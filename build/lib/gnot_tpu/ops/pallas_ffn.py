"""Pallas TPU kernel: fused gated soft-MoE expert FFN.

GNOT's FFN is a dense soft mixture (reference
``/root/reference/model.py:123-131``): E expert MLPs all run on every
token and a geometry gate combines them. The XLA path stacks the expert
parameters and runs batched GEMMs — good MXU mapping, but every layer
boundary materializes an ``[E, B, L, hidden]`` activation slab in HBM,
and at reference defaults those slabs are the single largest HBM stream
in the whole model (5 Linears x 2 FFNs x 4 blocks, E=3, hidden=256).

This kernel runs the ENTIRE expert MLP stack for one sequence tile in
VMEM: the full weight set (E x (num_layers+1) x [in, out] + biases —
~3.9 MB at defaults, fetched once and reused across the grid) stays
resident, each expert's hidden activations live and die in registers/
VMEM, and the gate-weighted sum folds into the accumulator. HBM traffic
drops to: x tile in, gate scores in, one output tile out.

The FFN is strictly rowwise, so sequence tiling needs no masking —
padded rows produce garbage that the wrapper slices off.

Backward recomputes the forward in einsum/jnp form and differentiates
that (rematerialization), keeping gradients identical to the XLA path.

Used when the weight set fits the VMEM budget (``fits_vmem``); callers
fall back to the XLA path otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

TILE = 256
VMEM_BYTES = 16 * 1024 * 1024  # per-core VMEM on current TPUs
VMEM_RESERVE = 2 * 1024 * 1024  # scheduler / spill slack


def _interpret_default() -> bool:
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "cpu":
        return True
    raise ValueError(
        f"ffn_impl='pallas' supports tpu (compiled) and cpu (interpreted) "
        f"backends, not {backend!r}; use ffn_impl='xla'"
    )


def fits_vmem(kernels: list[Array], biases: list[Array] | None = None) -> bool:
    """Whether the kernel's whole working set fits the VMEM budget.

    Budgets the resident weights AND biases plus the per-tile activation
    working set (double-buffered x/scores/out tiles, the live hidden
    buffer and its matmul input, the f32 accumulator), not just the
    kernels — a large hidden_dim can fail to compile or spill even when
    the weights alone fit.
    """
    weights = sum(4 * k.size for k in kernels)
    if biases is not None:
        weights += sum(4 * b.size for b in biases)
    else:
        weights += sum(4 * k.shape[-1] * k.shape[0] for k in kernels)
    d_in = kernels[0].shape[1]
    d_out = kernels[-1].shape[-1]
    n_expert = kernels[0].shape[0]
    widest = max(k.shape[-1] for k in kernels)
    # Live [TILE, *] f32 buffers: x + scores + out (x2 for pipeline
    # double-buffering), hidden in + hidden out, accumulator.
    act = 4 * TILE * (
        2 * (d_in + n_expert + d_out) + 2 * widest + d_out
    )
    return weights + act <= VMEM_BYTES - VMEM_RESERVE


def _erf_f32(x: Array) -> Array:
    """float32 erf as a rational polynomial (Eigen's
    ``generic_fast_erf_float``, ~1 ulp over the clamped range — the same
    approximation XLA lowers ``erf`` to for f32). Mosaic TPU has no
    ``erf``/``erfc`` primitive, so the exact-GELU inside the kernel
    needs its own erf."""
    x = jnp.clip(x, -3.832506856900711, 3.832506856900711)
    z = x * x
    alpha = jnp.float32(-2.72614225801306e-10)
    alpha = alpha * z + jnp.float32(2.77068142495902e-08)
    alpha = alpha * z + jnp.float32(-2.10102402082508e-06)
    alpha = alpha * z + jnp.float32(-5.69250639462346e-05)
    alpha = alpha * z + jnp.float32(-7.34990630326855e-04)
    alpha = alpha * z + jnp.float32(-2.95459980854025e-03)
    alpha = alpha * z + jnp.float32(-1.60960333262415e-02)
    beta = jnp.float32(-1.45660718464996e-05)
    beta = beta * z + jnp.float32(-2.13374055278905e-04)
    beta = beta * z + jnp.float32(-1.68282697438203e-03)
    beta = beta * z + jnp.float32(-7.37332916720468e-03)
    beta = beta * z + jnp.float32(-1.42647390514189e-02)
    return x * alpha / beta


def _gelu_exact(x: Array) -> Array:
    """Exact (erf-based) GELU — torch ``nn.GELU()`` default semantics
    (reference model.py MLP), usable inside Mosaic kernels."""
    inv_sqrt2 = jnp.float32(0.7071067811865476)
    return 0.5 * x * (1.0 + _erf_f32(x * inv_sqrt2))


def _gelu_tanh(x: Array) -> Array:
    """tanh-approximated GELU (``jax.nn.gelu(approximate=True)``) — the
    masked-mode default (config.gelu): ~2x cheaper than exact erf on the
    TPU VPU. Mosaic has a native ``tanh``."""
    c = jnp.float32(0.7978845608028654)  # sqrt(2/pi)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + jnp.float32(0.044715) * x * x * x)))


def _gelu(x: Array, gelu: str) -> Array:
    return _gelu_tanh(x) if gelu == "tanh" else _gelu_exact(x)


def _ffn_kernel(x_ref, s_ref, *refs, n_expert: int, n_linears: int, gelu: str):
    k_refs = refs[:n_linears]
    b_refs = refs[n_linears : 2 * n_linears]
    out_ref = refs[2 * n_linears]

    x = x_ref[0].astype(jnp.float32)  # [T, Din]
    scores = s_ref[0].astype(jnp.float32)  # [T, E]
    acc = jnp.zeros((x.shape[0], k_refs[-1].shape[-1]), jnp.float32)
    for e in range(n_expert):
        h = x
        for i in range(n_linears):
            h = (
                jnp.dot(
                    h,
                    k_refs[i][e].astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                + b_refs[i][e].astype(jnp.float32)  # [1, out] row broadcast
            )
            if i < n_linears - 1:
                h = _gelu(h, gelu)
        acc = acc + scores[:, e][:, None] * h
    out_ref[0] = acc.astype(out_ref.dtype)


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _ffn_call(x, scores, kernels, biases, interpret: bool, gelu: str):
    b, l, _ = x.shape
    n_expert = kernels[0].shape[0]
    n_linears = len(kernels)
    d_out = kernels[-1].shape[-1]
    tl = TILE if l >= TILE else _round_up(l, 8)
    lp = _round_up(l, tl)
    xp = jnp.pad(x, ((0, 0), (0, lp - l), (0, 0)))
    sp = jnp.pad(scores, ((0, 0), (0, lp - l), (0, 0)))
    b3 = [bb[:, None, :] for bb in biases]  # [E, 1, out] for 2D row adds

    weight_specs = [
        pl.BlockSpec(k.shape, lambda bi, li: (0, 0, 0)) for k in kernels
    ] + [pl.BlockSpec(bb.shape, lambda bi, li: (0, 0, 0)) for bb in b3]

    out = pl.pallas_call(
        functools.partial(
            _ffn_kernel, n_expert=n_expert, n_linears=n_linears, gelu=gelu
        ),
        grid=(b, lp // tl),
        in_specs=[
            pl.BlockSpec((1, tl, x.shape[-1]), lambda bi, li: (bi, li, 0)),
            pl.BlockSpec((1, tl, scores.shape[-1]), lambda bi, li: (bi, li, 0)),
            *weight_specs,
        ],
        out_specs=pl.BlockSpec((1, tl, d_out), lambda bi, li: (bi, li, 0)),
        out_shape=jax.ShapeDtypeStruct((b, lp, d_out), x.dtype),
        interpret=interpret,
    )(xp, sp, *kernels, *b3)
    return out[:, :l]


def _reference_impl(x, scores, kernels, biases, gelu: str = "erf"):
    """Einsum/jnp form with the kernel's f32 semantics (backward source
    + test oracle). Matches the XLA GatedExpertFfn math
    (models/layers.py) — per-expert MLP, gate-weighted sum — with the
    kernel's polynomial erf-GELU (``_gelu_exact``), so forward kernel
    and backward recompute are the same function (the polynomial is
    within ~4e-7 of ``jax.nn.gelu(approximate=False)``)."""
    h = jnp.broadcast_to(
        x[None].astype(jnp.float32), (kernels[0].shape[0], *x.shape)
    )  # [E, B, L, Din]
    n = len(kernels)
    for i, (k, bb) in enumerate(zip(kernels, biases)):
        h = (
            jnp.einsum("ebld,edo->eblo", h, k.astype(jnp.float32))
            + bb.astype(jnp.float32)[:, None, None, :]
        )
        if i < n - 1:
            h = _gelu(h, gelu)
    out = jnp.einsum("eblo,ble->blo", h, scores.astype(jnp.float32))
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_gated_ffn(x, scores, kernels, biases, interpret: bool | None = None, gelu: str = "erf"):
    """Fused gated expert FFN.

    Args:
      x: ``[B, L, Din]`` tokens.
      scores: ``[B, L, E]`` gate weights (softmaxed geometry gating).
      kernels: per-Linear stacked weights, each ``[E, in, out]``.
      biases: per-Linear stacked biases, each ``[E, out]``.
      interpret: force interpreter mode (None = auto).

    Returns:
      ``[B, L, Dout]`` gate-combined expert outputs.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _ffn_call(x, scores, list(kernels), list(biases), interpret, gelu)


def _fused_fwd(x, scores, kernels, biases, interpret, gelu):
    interpret = _interpret_default() if interpret is None else interpret
    out = _ffn_call(x, scores, list(kernels), list(biases), interpret, gelu)
    return out, (x, scores, kernels, biases)


def _fused_bwd(interpret, gelu, residuals, g):
    del interpret
    x, scores, kernels, biases = residuals
    _, vjp = jax.vjp(
        lambda x_, s_, k_, b_: _reference_impl(x_, s_, k_, b_, gelu),
        x,
        scores,
        kernels,
        biases,
    )
    return vjp(g)


fused_gated_ffn.defvjp(_fused_fwd, _fused_bwd)
