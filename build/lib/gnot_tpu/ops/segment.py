"""Masked per-graph (per-sample) reductions and losses.

The reference computes per-graph losses with DGL segment pooling over a
batched graph (``/root/reference/loss.py:4-23``): a segment-sum keyed by
graph membership after the padded batch has been unpadded and concatenated
(``/root/reference/main.py:87-98``).

TPU-native form: keep everything padded/dense ``[B, L, C]`` and fold the
ragged structure into a 0/1 node mask — mathematically identical (the
sum over a graph's nodes == the masked sum over its padded row) but with
static shapes and zero host round-trips. No graph library is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def masked_segment_sum(values: Array, mask: Array) -> Array:
    """Per-sample masked sum over the length axis.

    Args:
      values: ``[B, L, C]``.
      mask: ``[B, L]`` 0/1.
    Returns:
      ``[B, C]`` — equivalent of DGL ``SumPooling`` over each graph.
    """
    return jnp.einsum("blc,bl->bc", values, mask.astype(values.dtype))


def masked_segment_mean(values: Array, mask: Array) -> Array:
    """Per-sample masked mean over the length axis (DGL ``AvgPooling``)."""
    s = masked_segment_sum(values, mask)
    n = jnp.sum(mask, axis=1).astype(values.dtype)
    return s / n[:, None]


def rel_l2_loss(predictions: Array, targets: Array, mask: Array) -> Array:
    """Per-graph relative L2, averaged over graphs and channels.

    Matches ``RelL2Loss`` (reference loss.py:19-23):
    ``mean_{g,c} sqrt( sum_l (p-t)^2 / sum_l t^2 )``.
    """
    num = masked_segment_sum((predictions - targets) ** 2, mask)
    den = masked_segment_sum(targets**2, mask)
    return jnp.mean(jnp.sqrt(num / den))


def mse_loss(predictions: Array, targets: Array, mask: Array) -> Array:
    """Per-graph node-mean of squared error, then mean over graphs and
    channels. Matches ``MSELoss`` (reference loss.py:9-12)."""
    per_graph = masked_segment_mean((predictions - targets) ** 2, mask)
    return jnp.mean(per_graph)


def rel_l2_per_sample(predictions: Array, targets: Array, mask: Array) -> Array:
    """``[B]`` per-graph relative L2 (channel-averaged) — the per-sample
    decomposition of ``rel_l2_loss``: the batch mean of this vector is
    the scalar loss (up to fp reduction order). Used by the distributed
    ragged-tail eval, which pads the last test batch with repeats and
    must drop them from the metric on the host."""
    num = masked_segment_sum((predictions - targets) ** 2, mask)
    den = masked_segment_sum(targets**2, mask)
    return jnp.mean(jnp.sqrt(num / den), axis=1)


def mse_per_sample(predictions: Array, targets: Array, mask: Array) -> Array:
    """``[B]`` per-graph node-mean squared error (channel-averaged)."""
    per_graph = masked_segment_mean((predictions - targets) ** 2, mask)
    return jnp.mean(per_graph, axis=1)


LOSSES = {"rel_l2": rel_l2_loss, "mse": mse_loss}
PER_SAMPLE_LOSSES = {"rel_l2": rel_l2_per_sample, "mse": mse_per_sample}
