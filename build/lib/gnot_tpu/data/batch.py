"""Dense batch container + ragged->dense batching.

The reference carries ragged meshes as edge-less DGL graphs and pads them
inline in the train loop (``/root/reference/main.py:37-39,63-82``). The
TPU-native form is a single static-shaped pytree, ``MeshBatch``, with the
ragged structure folded into 0/1 masks — XLA-friendly (no recompiles per
shape when bucketing is on, no graph library, no host round trips).

Reference-faithful padding semantics preserved:
  * input functions are padded to the **single max length across ALL
    functions of ALL samples in the batch** (main.py:63 — one shared
    max, not per-function);
  * coords/targets are padded to the per-batch max node count
    (main.py:78-80);
  * zero padding at the tail of the length axis (utils.py:3-4).

On top, an optional bucketing scheme rounds pad lengths up to the next
bucket boundary so XLA compiles O(log L) programs instead of one per
distinct length. Bucketing changes numerics only in parity (unmasked)
mode, so parity runs disable it.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterator, Sequence

import flax.struct
import numpy as np


@flax.struct.dataclass
class MeshBatch:
    """One padded batch of ragged PDE meshes. All arrays are dense.

    Shapes: B batch, L max nodes, Lf max input-function points, F number
    of input functions, dx/df/dy coordinate/function/output dims, T theta.
    """

    coords: np.ndarray  # [B, L, dx] mesh point coordinates
    theta: np.ndarray  # [B, T] global (per-sample) parameters
    y: np.ndarray  # [B, L, dy] padded targets
    node_mask: np.ndarray  # [B, L] 1 for real nodes, 0 for padding
    funcs: np.ndarray | None = None  # [F, B, Lf, df] padded input functions
    func_mask: np.ndarray | None = None  # [F, B, Lf]

    @property
    def n_real_points(self) -> int:
        """Total un-padded mesh points — the throughput denominator."""
        return int(np.sum(np.asarray(self.node_mask)))


@dataclasses.dataclass
class MeshSample:
    """One ragged sample: ``[X, Y, theta, (f1, f2, ...)]`` — the pickle
    record schema of the reference (dataset.py:7)."""

    coords: np.ndarray  # [n, dx]
    y: np.ndarray  # [n, dy]
    theta: np.ndarray  # [T]
    funcs: tuple[np.ndarray, ...] = ()  # each [m_i, df]


def bucket_length(n: int, *, min_size: int = 64) -> int:
    """Round up to the next power-of-two-ish bucket (1, 1.5 mantissa)."""
    size = min_size
    while size < n:
        if int(size * 1.5) >= n and (size & (size - 1)) == 0:
            return int(size * 1.5)
        size *= 2
    return size


def fixed_pad_lengths(
    samples: Sequence[MeshSample], *, bucket: bool = True
) -> tuple[int, int]:
    """Dataset-wide ``(pad_nodes, pad_funcs)`` targets: the maxima over
    ALL samples (bucketed). With these, every batch has one static
    shape — multi-host SPMD safe, zero recompiles."""
    pn = max(s.coords.shape[0] for s in samples)
    pf = max((f.shape[0] for s in samples for f in s.funcs), default=0)
    if bucket:
        pn = bucket_length(pn)
        pf = bucket_length(pf) if pf else 0
    return pn, pf


def pad_rows(arr: np.ndarray, length: int) -> np.ndarray:
    """Zero-pad axis 0 to ``length`` (reference utils.py:3-4)."""
    if arr.shape[0] == length:
        return arr
    pad = [(0, length - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def collate(
    samples: Sequence[MeshSample],
    *,
    bucket: bool = True,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
) -> MeshBatch:
    """Pad and stack ragged samples into a dense MeshBatch.

    ``pad_nodes``/``pad_funcs`` force fixed pad lengths (0 = per-batch
    max, optionally bucketed). Fixed lengths give every batch one static
    shape — required for multi-host SPMD (every process must assemble
    identically-shaped global arrays regardless of its local samples)
    and they eliminate XLA recompiles outright.

    The packing hot loop runs in the native C++ packer
    (``gnot_tpu/native/ragged_pack.cpp``) when available: one
    memcpy+memset sweep per field with the mask written in the same
    pass; pure-numpy fallback otherwise (identical output)."""
    from gnot_tpu import native

    if pad_nodes:
        max_nodes = pad_nodes
    else:
        max_nodes = max(s.coords.shape[0] for s in samples)
        if bucket:
            max_nodes = bucket_length(max_nodes)

    coords, node_mask = native.pack_rows([s.coords for s in samples], max_nodes)
    y, _ = native.pack_rows([s.y for s in samples], max_nodes)
    theta = np.stack([np.atleast_1d(np.asarray(s.theta, np.float32)) for s in samples])

    n_funcs = len(samples[0].funcs)
    funcs = func_mask = None
    if n_funcs:
        if pad_funcs:
            max_f = pad_funcs
        else:
            # Single shared max across every function of every sample
            # (reference main.py:63).
            max_f = max(f.shape[0] for s in samples for f in s.funcs)
            if bucket:
                max_f = bucket_length(max_f)
        packed = [
            native.pack_rows([s.funcs[j] for s in samples], max_f)
            for j in range(n_funcs)
        ]
        funcs = np.stack([p[0] for p in packed])
        func_mask = np.stack([p[1] for p in packed])

    return MeshBatch(
        coords=coords,
        theta=theta,
        y=y,
        node_mask=node_mask,
        funcs=funcs,
        func_mask=func_mask,
    )


class Loader:
    """Epoch iterator: shuffle, batch, collate, background prefetch.

    Replaces the reference's ``DataLoader(batch_size=4, shuffle=True,
    collate_fn=unzip)`` (main.py:37-42) without a torch dependency.
    With ``prefetch > 0`` (default), collation runs in a background
    thread so the host packs batch N+1 while the device executes batch
    N — the host->device pipeline never stalls on the packer.
    """

    def __init__(
        self,
        samples: Sequence[MeshSample],
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        bucket: bool = True,
        drop_remainder: bool = False,
        prefetch: int = 2,
        pad_nodes: int = 0,
        pad_funcs: int = 0,
    ):
        self.samples = list(samples)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.bucket = bucket
        self.drop_remainder = drop_remainder
        self.prefetch = prefetch
        self.pad_nodes = pad_nodes
        self.pad_funcs = pad_funcs
        self.seed = seed
        # Epoch counter for shuffling: each epoch's order is a pure
        # function of (seed, epoch), so a resumed run at epoch N sees
        # exactly the batches the continuous run would have (a stateful
        # rng stream would restart from epoch 0's order after resume).
        # Advanced by __iter__; set_epoch() pins it (trainer resume,
        # torch DistributedSampler-style).
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        n = len(self.samples)
        if self.drop_remainder:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self) -> list[np.ndarray]:
        order = np.arange(len(self.samples))
        if self.shuffle:
            np.random.default_rng((self.seed, self._epoch)).shuffle(order)
        self._epoch += 1
        chunks = []
        for start in range(0, len(order), self.batch_size):
            idx = order[start : start + self.batch_size]
            if self.drop_remainder and len(idx) < self.batch_size:
                break
            chunks.append(idx)
        return chunks

    def _collate_at(self, idx: np.ndarray) -> MeshBatch:
        return collate(
            [self.samples[i] for i in idx],
            bucket=self.bucket,
            pad_nodes=self.pad_nodes,
            pad_funcs=self.pad_funcs,
        )

    def __iter__(self) -> Iterator[MeshBatch]:
        chunks = self._epoch_indices()
        if self.prefetch <= 0 or len(chunks) <= 1:
            for idx in chunks:
                yield self._collate_at(idx)
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for idx in chunks:
                    if not put(self._collate_at(idx)):
                        return  # consumer abandoned the epoch
                put(_END)
            except BaseException as e:  # surface worker errors to the consumer
                put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            t.join()
