"""Native (C++) runtime components, loaded via ctypes.

The reference has no native code of its own (SURVEY.md §2: all native
execution lives in the torch/DGL wheels), so this layer is a
capability superset: the host-side ragged->dense packer that feeds the
TPU. Built on first import with g++ (cached as a .so next to the
source); every entry point has a pure-numpy fallback so the framework
works with no toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "ragged_pack.cpp")
_SO = os.path.join(_HERE, "_ragged_pack.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _load():
    """Build (if stale) and dlopen the packer; returns None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
                _SRC
            ):
                # Per-process tmp name: concurrent first-builds must not
                # interleave writes; os.replace stays atomic.
                tmp = f"{_SO}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.gnot_pack_rows.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.gnot_pack_rows.restype = None
            _lib = lib
        except (OSError, subprocess.CalledProcessError):
            _load_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def pack_rows_numpy(
    arrs: list[np.ndarray], max_len: int
) -> tuple[np.ndarray, np.ndarray]:
    """Fallback: pad [len_i, dim] float32 blocks to [n, max_len, dim] +
    [n, max_len] mask (zero pad at the row tail, reference utils.py:3-4)."""
    n, dim = len(arrs), arrs[0].shape[1]
    out = np.zeros((n, max_len, dim), np.float32)
    mask = np.zeros((n, max_len), np.float32)
    for i, a in enumerate(arrs):
        out[i, : a.shape[0]] = a
        mask[i, : a.shape[0]] = 1.0
    return out, mask


def pack_rows(arrs: list[np.ndarray], max_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Pack ragged float32 row-blocks into a padded batch + mask, using
    the C++ packer when available."""
    dim = arrs[0].shape[1] if arrs[0].ndim == 2 else -1
    for a in arrs:
        if a.ndim != 2 or a.shape[1] != dim:
            raise ValueError(
                f"pack_rows needs uniform [len_i, {dim}] blocks, got {a.shape}"
            )
    too_long = max(a.shape[0] for a in arrs)
    if too_long > max_len:
        raise ValueError(f"row block of {too_long} rows exceeds max_len={max_len}")
    lib = _load()
    if lib is None:
        return pack_rows_numpy(arrs, max_len)
    n, dim = len(arrs), arrs[0].shape[1]
    contig = [np.ascontiguousarray(a, np.float32) for a in arrs]
    out = np.empty((n, max_len, dim), np.float32)
    mask = np.empty((n, max_len), np.float32)
    srcs = (ctypes.c_void_p * n)(
        *(a.ctypes.data_as(ctypes.c_void_p).value for a in contig)
    )
    lens = (ctypes.c_int64 * n)(*(a.shape[0] for a in contig))
    lib.gnot_pack_rows(
        ctypes.cast(srcs, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(lens, ctypes.POINTER(ctypes.c_int64)),
        n,
        dim,
        max_len,
        out.ctypes.data_as(ctypes.c_void_p),
        mask.ctypes.data_as(ctypes.c_void_p),
    )
    return out, mask
