// Native ragged->dense batch packer: the host-side hot loop of the data
// pipeline.
//
// The reference pads ragged meshes in Python inside the train loop
// (/root/reference/main.py:63-82, utils.py:3-4): one torch op per sample
// per field. The numpy fallback in gnot_tpu/data/batch.py is the same
// shape of work. This packer does the whole batch in one call: a single
// pass of memcpy per sample row-block, zero-fill for the pad tail, and
// the 0/1 mask written in the same sweep — no per-sample allocations, no
// interpreter in the loop. Threaded over samples for large batches.
//
// ABI: plain C symbols loaded via ctypes (no pybind11 dependency).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Pack n ragged [len_i, dim] float32 row-blocks into a dense
// [n, max_len, dim] tensor (zero pad at the row tail) and a [n, max_len]
// 0/1 mask. `srcs[i]` points at sample i's contiguous data.
void gnot_pack_rows(const float** srcs, const int64_t* lens, int64_t n,
                    int64_t dim, int64_t max_len, float* out, float* mask) {
  const int64_t row_bytes = dim * static_cast<int64_t>(sizeof(float));
  auto pack_one = [&](int64_t i) {
    const int64_t len = lens[i];
    float* dst = out + i * max_len * dim;
    std::memcpy(dst, srcs[i], static_cast<size_t>(len * row_bytes));
    std::memset(dst + len * dim, 0,
                static_cast<size_t>((max_len - len) * row_bytes));
    float* m = mask + i * max_len;
    for (int64_t r = 0; r < len; ++r) m[r] = 1.0f;
    std::memset(m + len, 0, static_cast<size_t>((max_len - len) * sizeof(float)));
  };

  // Threading pays only when there is real work per thread; the packer
  // is memcpy-bound, so use a coarse bytes threshold.
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i] * row_bytes;
  const unsigned hw = std::thread::hardware_concurrency();
  if (total < (1 << 22) || hw <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) pack_one(i);
    return;
  }
  const int64_t n_threads = std::min<int64_t>(n, hw);
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (int64_t t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t] {
      for (int64_t i = t; i < n; i += n_threads) pack_one(i);
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
