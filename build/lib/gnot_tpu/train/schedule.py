"""OneCycle learning-rate schedule, torch-formula-exact.

The reference uses ``torch.optim.lr_scheduler.OneCycleLR(optimizer,
max_lr=1e-3, steps_per_epoch=len(train_loader), epochs=args.epochs)``
(main.py:52) with all other arguments at torch defaults: cosine
annealing, ``pct_start=0.3``, ``div_factor=25``, ``final_div_factor=1e4``,
``three_phase=False``.

Crucially the reference calls ``scheduler.step()`` once per **epoch**
(main.py:106) even though the schedule is sized in per-batch steps, so
only ``epochs / (epochs * steps_per_epoch)`` of the cycle is traversed —
the LR never leaves the early warm-up ramp. ``OptimConfig.
parity_schedule_bug=True`` reproduces this by evaluating the schedule at
the *epoch* counter; ``False`` gives the correct per-update schedule.
"""

from __future__ import annotations

import math
from typing import Callable


def _cos_anneal(start: float, end: float, pct: float) -> float:
    """torch OneCycleLR cosine annealing between two bounds."""
    return end + (start - end) / 2.0 * (1.0 + math.cos(math.pi * pct))


def onecycle_lr(
    step: float,
    *,
    max_lr: float,
    total_steps: int,
    pct_start: float = 0.3,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
) -> float:
    """LR after ``step`` scheduler steps, matching torch OneCycleLR
    (cos anneal, three_phase=False)."""
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    phase1_end = pct_start * total_steps - 1
    phase2_end = total_steps - 1
    step = min(step, phase2_end)
    if step <= phase1_end:
        pct = step / max(phase1_end, 1e-12)
        return _cos_anneal(initial_lr, max_lr, pct)
    pct = (step - phase1_end) / max(phase2_end - phase1_end, 1e-12)
    return _cos_anneal(max_lr, min_lr, pct)


def make_lr_fn(optim_cfg, *, steps_per_epoch: int, epochs: int) -> Callable[[int, int], float]:
    """Returns ``lr(step, epoch)`` where ``step`` is the micro-step count.

    With the parity bug on, the schedule is evaluated at the epoch count
    (the reference's per-epoch ``scheduler.step()``); otherwise at the
    optimizer UPDATE count: with ``grad_accum = k > 1``, MultiSteps
    applies the LR sampled at every k-th micro-step, so the schedule is
    evaluated at ``step // k`` over a total horizon of updates — exactly
    torch's per-update ``scheduler.step()`` semantics, not a subsampling
    of a micro-step-sized cycle.
    """
    accum = max(1, getattr(optim_cfg, "grad_accum", 1))
    if optim_cfg.parity_schedule_bug:
        # The reference sizes the cycle in per-batch steps (main.py:52);
        # keep its construction verbatim in parity mode.
        total_steps = steps_per_epoch * epochs
    else:
        # True update count: MultiSteps windows are GLOBAL micro-step
        # windows (they straddle epoch boundaries), so divide the whole
        # micro-step horizon — per-epoch flooring would undercount
        # updates and park the tail of training at min_lr.
        total_steps = max(1, (steps_per_epoch * epochs) // accum)

    def lr(step: int, epoch: int) -> float:
        counter = epoch if optim_cfg.parity_schedule_bug else step // accum
        return onecycle_lr(
            counter,
            max_lr=optim_cfg.lr,
            total_steps=total_steps,
            pct_start=optim_cfg.pct_start,
            div_factor=optim_cfg.div_factor,
            final_div_factor=optim_cfg.final_div_factor,
        )

    return lr
