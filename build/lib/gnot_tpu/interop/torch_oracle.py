"""PyTorch-reference interop: the parity oracle.

BASELINE.json keeps the reference PyTorch implementation as the
*numerical oracle*: the JAX path must reproduce it to <1e-4 on Darcy2d.
This module (a) loads the reference ``model.py`` (torch-only, no DGL
needed) from ``GNOT_REFERENCE_PATH`` without copying any of its code, and
(b) maps a torch ``state_dict`` into this framework's Flax param pytree.

torch -> flax naming (see the reference model.py:142-152 for the torch
side and gnot_tpu/models for the flax side):

    x.layers.{2i}                      -> x_embed/dense_{i}
    gating.layers.{2i}                 -> gating/dense_{i}
    out.layers.{2i}                    -> out_mlp/dense_{i}
    input_func_mlps.{f}.layers.{2i}    -> input_func_mlps/dense_{i}  (stacked over f)
    blocks.{b}.cross_attention.query   -> block_{b}/cross_attention/query
    blocks.{b}.cross_attention.key.{f} -> block_{b}/cross_attention/key (stacked over f)
    blocks.{b}.self_attention.key      -> block_{b}/self_attention/key
    blocks.{b}.ffn{n}.{e}.layers.{2i}  -> block_{b}/ffn{n}/experts/dense_{i} (stacked over e)

torch Linear stores weight as [out, in]; flax Dense kernel is [in, out],
so every weight is transposed. ModuleList entries become the leading
stack axis of the corresponding vmapped flax layer.
"""

from __future__ import annotations

import importlib.util
import os
import sys

import numpy as np

from gnot_tpu.config import ModelConfig

DEFAULT_REFERENCE_PATH = os.environ.get("GNOT_REFERENCE_PATH", "/root/reference")


def load_reference_model_module(path: str | None = None):
    """Import the reference ``model.py`` as a module (torch-only file)."""
    path = path or DEFAULT_REFERENCE_PATH
    model_py = os.path.join(path, "model.py")
    if not os.path.exists(model_py):
        raise FileNotFoundError(
            f"reference model.py not found at {model_py}; set GNOT_REFERENCE_PATH"
        )
    spec = importlib.util.spec_from_file_location("gnot_reference_model", model_py)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def torch_rel_l2(pred, target, mask):
    """Masked per-sample relative L2 on padded torch tensors — the
    reference objective (loss.py:19-23) without the unpad/concat round
    trip: per-sample masked sums over the padded node axis are
    mathematically identical to DGL's per-graph pooling. The ONE
    torch-side oracle loss; the torch backend (main.py), the bench
    baseline (bench.py) and the quality gate all call this."""
    num = ((pred - target) ** 2 * mask[..., None]).sum(1)
    den = (target**2 * mask[..., None]).sum(1)
    return ((num / den) ** 0.5).mean()


def build_reference_model(cfg: ModelConfig, path: str | None = None):
    """Instantiate the reference torch GNOT with matching hyperparams."""
    mod = load_reference_model_module(path)
    return mod.GNOT(
        cfg.input_dim,
        cfg.theta_dim,
        cfg.input_func_dim,
        cfg.out_dim,
        cfg.n_attn_layers,
        cfg.n_attn_hidden_dim,
        cfg.n_mlp_num_layers,
        cfg.n_mlp_hidden_dim,
        cfg.n_input_hidden_dim,
        cfg.n_expert,
        cfg.n_head,
        cfg.n_input_functions,
    )


def _linear(sd, prefix: str) -> dict[str, np.ndarray]:
    w = np.asarray(sd[f"{prefix}.weight"].detach().cpu().numpy())
    b = np.asarray(sd[f"{prefix}.bias"].detach().cpu().numpy())
    return {"kernel": w.T.copy(), "bias": b}


def _stacked_linear(sd, prefixes: list[str]) -> dict[str, np.ndarray]:
    parts = [_linear(sd, p) for p in prefixes]
    return {
        "kernel": np.stack([p["kernel"] for p in parts]),
        "bias": np.stack([p["bias"] for p in parts]),
    }


def _mlp(sd, prefix: str, num_layers: int) -> dict:
    # torch MLP Sequential: Linears at even indices 0, 2, ..., 2*num_layers.
    return {
        f"dense_{i}": _linear(sd, f"{prefix}.layers.{2 * i}")
        for i in range(num_layers + 1)
    }


def _stacked_mlp(sd, prefixes: list[str], num_layers: int) -> dict:
    return {
        f"dense_{i}": _stacked_linear(
            sd, [f"{p}.layers.{2 * i}" for p in prefixes]
        )
        for i in range(num_layers + 1)
    }


def flax_to_state_dict(params, cfg: ModelConfig) -> dict:
    """Inverse of ``state_dict_to_flax``: map this framework's params to
    a reference-compatible torch ``state_dict`` (numpy tensors wrapped
    as ``torch.Tensor``). Lets models trained here run under the
    reference's torch code — interop in both directions."""
    import torch

    out: dict = {}

    def put_linear(prefix: str, leaf: dict) -> None:
        out[f"{prefix}.weight"] = torch.from_numpy(
            np.asarray(leaf["kernel"]).T.copy()
        )
        out[f"{prefix}.bias"] = torch.from_numpy(np.asarray(leaf["bias"]).copy())

    def put_mlp(prefix: str, tree: dict, num_layers: int) -> None:
        for i in range(num_layers + 1):
            put_linear(f"{prefix}.layers.{2 * i}", tree[f"dense_{i}"])

    def put_stacked_mlp(prefixes: list[str], tree: dict, num_layers: int) -> None:
        for s, prefix in enumerate(prefixes):
            for i in range(num_layers + 1):
                leaf = tree[f"dense_{i}"]
                put_linear(
                    f"{prefix}.layers.{2 * i}",
                    {"kernel": np.asarray(leaf["kernel"])[s], "bias": np.asarray(leaf["bias"])[s]},
                )

    n = cfg.n_mlp_num_layers
    put_mlp("x", params["x_embed"], n)
    put_mlp("gating", params["gating"], n)
    put_mlp("out", params["out_mlp"], n)
    if cfg.n_input_functions > 0:
        put_stacked_mlp(
            [f"input_func_mlps.{f}" for f in range(cfg.n_input_functions)],
            params["input_func_mlps"],
            n,
        )
    for b in range(cfg.n_attn_layers):
        pb, blk = f"blocks.{b}", params[f"block_{b}"]
        cross = blk["cross_attention"]
        put_linear(f"{pb}.cross_attention.query", cross["query"])
        put_linear(f"{pb}.cross_attention.fc_out", cross["fc_out"])
        if cfg.n_input_functions > 0:
            for f in range(cfg.n_input_functions):
                for kind in ("key", "value"):
                    leaf = cross[kind]
                    put_linear(
                        f"{pb}.cross_attention.{kind}.{f}",
                        {
                            "kernel": np.asarray(leaf["kernel"])[f],
                            "bias": np.asarray(leaf["bias"])[f],
                        },
                    )
        else:
            put_linear(f"{pb}.cross_attention.key", cross["key"])
            put_linear(f"{pb}.cross_attention.value", cross["value"])
        for k in ("query", "key", "value", "fc_out"):
            put_linear(f"{pb}.self_attention.{k}", blk["self_attention"][k])
        for ffn in ("ffn1", "ffn2"):
            put_stacked_mlp(
                [f"{pb}.{ffn}.{e}" for e in range(cfg.n_expert)],
                blk[ffn]["experts"],
                n,
            )
    return out


def state_dict_to_flax(state_dict, cfg: ModelConfig) -> dict:
    """Map a reference torch GNOT state_dict to this framework's params."""
    sd = state_dict
    n = cfg.n_mlp_num_layers
    params: dict = {
        "x_embed": _mlp(sd, "x", n),
        "gating": _mlp(sd, "gating", n),
        "out_mlp": _mlp(sd, "out", n),
    }
    if cfg.n_input_functions > 0:
        params["input_func_mlps"] = _stacked_mlp(
            sd,
            [f"input_func_mlps.{f}" for f in range(cfg.n_input_functions)],
            n,
        )
    for b in range(cfg.n_attn_layers):
        pb = f"blocks.{b}"
        cross: dict = {
            "query": _linear(sd, f"{pb}.cross_attention.query"),
            "fc_out": _linear(sd, f"{pb}.cross_attention.fc_out"),
        }
        if cfg.n_input_functions > 0:
            cross["key"] = _stacked_linear(
                sd,
                [f"{pb}.cross_attention.key.{f}" for f in range(cfg.n_input_functions)],
            )
            cross["value"] = _stacked_linear(
                sd,
                [
                    f"{pb}.cross_attention.value.{f}"
                    for f in range(cfg.n_input_functions)
                ],
            )
        else:
            cross["key"] = _linear(sd, f"{pb}.cross_attention.key")
            cross["value"] = _linear(sd, f"{pb}.cross_attention.value")
        params[f"block_{b}"] = {
            "cross_attention": cross,
            "self_attention": {
                k: _linear(sd, f"{pb}.self_attention.{k}")
                for k in ("query", "key", "value", "fc_out")
            },
            "ffn1": {
                "experts": _stacked_mlp(
                    sd, [f"{pb}.ffn1.{e}" for e in range(cfg.n_expert)], n
                )
            },
            "ffn2": {
                "experts": _stacked_mlp(
                    sd, [f"{pb}.ffn2.{e}" for e in range(cfg.n_expert)], n
                )
            },
        }
    return params
