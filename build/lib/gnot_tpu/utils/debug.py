"""Debug-build numeric guards (SURVEY.md §5 race-detection note: the
reference is single-threaded with nothing to race; the TPU-native
equivalent of sanitizers is ``checkify`` for NaN/inf/OOB inside jit).

``checked(fn)`` wraps a jittable function so NaN/inf inside it raises
with a location, instead of silently propagating through the compiled
program; pass ``errors=checkify.all_checks`` to add div-by-zero and
out-of-bounds index checks (expensive at trace time on large
programs). Debug builds only — the checks block fusion and cost real
throughput.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.experimental import checkify


def checked(fn: Callable, *, jit: bool = True, errors=None) -> Callable:
    """Returns ``fn`` instrumented with numeric checks; the wrapper
    raises ``checkify.JaxRuntimeError`` on the first violation.

    ``errors`` defaults to float checks (NaN/inf) — the practical guard
    for a training step. ``checkify.all_checks`` adds index/div checks
    but multiplies compile time on large models."""
    err_fn = checkify.checkify(
        fn, errors=checkify.float_checks if errors is None else errors
    )
    if jit:
        err_fn = jax.jit(err_fn)

    def wrapper(*args, **kwargs):
        err, out = err_fn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper
