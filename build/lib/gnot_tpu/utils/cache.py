"""Persistent XLA compile cache.

JAX ships a content-addressed compilation cache but leaves it OFF by
default; first compiles here are expensive (30-90s per program over a
remote-device tunnel), so the CLI enables it by default at a per-user
path. Per-user matters: a world-shared /tmp dir would fail for the
second user on a shared machine and mean executing artifacts another
user could write. The test suite (tests/conftest.py) uses the same
location, so CLI runs and tests share warm entries.
"""

from __future__ import annotations

import os
import tempfile


def default_cache_dir() -> str:
    home = os.path.expanduser("~")
    if os.path.isabs(home):
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME") or os.path.join(home, ".cache"),
            "gnot_jax_cache",
        )
    # Stripped container env without HOME: uid-scoped tmp fallback.
    return os.path.join(tempfile.gettempdir(), f"gnot_jax_cache_{os.getuid()}")


def enable_compile_cache(path: str | None = None) -> str:
    """Turn the persistent cache on (call before tracing). Returns the
    cache path in effect ("" when disabled).

    Resolution order for a default (``path=None``) call:
    ``GNOT_COMPILE_CACHE`` env (``off``/empty disables, a path
    overrides; ``GNOT_TEST_CACHE`` accepted as an alias) → an
    already-configured ``jax_compilation_cache_dir`` (e.g. a hermetic
    test path — in-process ``main()`` calls must not silently redirect
    it) → the per-user default. The env override is what makes
    ``GNOT_COMPILE_CACHE=off`` give genuinely clean-compile runs even
    through code paths that enable the cache themselves."""
    import jax

    if path is None:
        env = os.environ.get("GNOT_COMPILE_CACHE")
        if env is None:
            env = os.environ.get("GNOT_TEST_CACHE")
        if env is not None and env.strip() in ("off", ""):
            return ""
        if env:
            path = env
        else:
            existing = getattr(jax.config, "jax_compilation_cache_dir", None)
            if existing:
                return existing
            path = default_cache_dir()
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything that took meaningful compile time.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path
