"""Tracing / profiling hooks (SURVEY.md §5: the reference has none —
its only timing signal is per-epoch prints, ``/root/reference/main.py:105``).

TPU-native equivalent: ``jax.profiler`` traces viewable in
Perfetto/XProf/TensorBoard. ``trace_epoch`` wraps one epoch in a trace
when a profile directory is configured; ``annotate`` marks named spans
inside a traced region so train/eval phases are distinguishable on the
timeline.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace_epoch(profile_dir: str, epoch: int, *, trace_at: int = 1):
    """Trace epoch ``trace_at`` into ``profile_dir``. Callers pick
    ``trace_at`` past the first executed epoch when they can, to keep
    compile noise out of the trace (see Trainer.fit). No-op when
    ``profile_dir`` is empty."""
    if not profile_dir or epoch != trace_at:
        yield
        return
    with jax.profiler.trace(profile_dir):
        yield


def annotate(name: str):
    """Named span on the profiler timeline (context manager)."""
    return jax.profiler.TraceAnnotation(name)
