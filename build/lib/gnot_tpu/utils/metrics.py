"""Structured metrics sinks.

The reference logs via three ``print`` lines per epoch
(``/root/reference/main.py:105,147-148``). The trainer keeps those exact
console lines for diffability; this module adds structured JSONL metrics
(loss, LR, throughput, step time) on top — SURVEY.md §5 observability.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, TextIO

import numpy as np


class MetricsSink:
    """Append-only JSONL metrics writer."""

    def __init__(self, path: str):
        self.path = path
        if d := os.path.dirname(path):
            os.makedirs(d, exist_ok=True)
        self._fh: TextIO = open(path, "a", buffering=1)

    def log(self, **record: Any) -> None:
        record.setdefault("ts", time.time())
        # json.dumps would emit bare NaN/Infinity tokens (invalid JSON)
        # for non-finite floats — e.g. a diverged loss or the inf metric
        # of an empty test set — and rejects numpy scalars outright, so
        # coerce numpy scalars to Python first, then null non-finites.
        def coerce(v):
            if isinstance(v, np.floating):
                return float(v)
            if isinstance(v, np.integer):
                return int(v)
            if isinstance(v, np.bool_):
                return bool(v)
            return v

        record = {k: coerce(v) for k, v in record.items()}
        record = {
            k: (None if isinstance(v, float) and not math.isfinite(v) else v)
            for k, v in record.items()
        }
        self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        self._fh.close()
