"""Chaos suite for the inference-serving subsystem (gnot_tpu/serve/).

ISSUE 3 acceptance: on CPU, with deterministic fault injection, the
server demonstrates deadline shedding, queue-overflow fast-fail,
circuit-breaker trip + recovery, graceful drain completing in-flight
requests, and a hot reload that survives a corrupted checkpoint dir by
falling back — each asserted via MetricsSink events — with no
mixed-bucket batches and a compiled-program count bounded by the
bucket count (O(log L_max)) under a mixed small/large request storm.

Fast scenarios run in tier-1; the long storm carries ``-m slow``.
"""

import json
import math
import os
import signal
import sys
import time

import jax
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, make_config
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import MeshSample, bucket_length, collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.resilience.faults import FaultInjector
from gnot_tpu.resilience.preemption import PreemptionHandler
from gnot_tpu.serve import (
    AdmissionController,
    Batcher,
    CheckpointReloader,
    CircuitBreaker,
    InferenceEngine,
    InferenceServer,
)
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)

MAX_BATCH = 2  # one compiled (rows, L, Lf) shape shared by every test


def read_events(path):
    return [
        r
        for r in (json.loads(l) for l in open(path))
        if r.get("event")
    ]


@pytest.fixture(scope="module")
def setup():
    """Tiny model + params + 64-point Darcy traffic; the shared
    engine's (2, 64, 64) program compiles once for the whole module."""
    samples = datasets.synth_darcy2d(12, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(samples[:1], rows=MAX_BATCH)
    return model, params, samples, engine


def make_server(setup, tmp_path, **kw):
    """Server over the module's shared warmed engine (tests that swap
    weights pass their own via ``engine=``)."""
    engine = kw.pop("engine", None) or setup[3]
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    server = InferenceServer(
        engine,
        max_batch=MAX_BATCH,
        max_wait_ms=kw.pop("max_wait_ms", 5.0),
        sink=sink,
        **kw,
    )
    return server, sink, str(tmp_path / "serve.jsonl")


# --- policy objects -------------------------------------------------------


def test_admission_controller_bounds_and_releases():
    adm = AdmissionController(2)
    assert adm.try_admit() and adm.try_admit()
    assert not adm.try_admit()  # full -> fast-fail
    adm.release()
    assert adm.try_admit()
    with pytest.raises(ValueError):
        AdmissionController(0)


def test_circuit_breaker_trip_halfopen_recovery():
    clk = [0.0]
    cb = CircuitBreaker(threshold=2, cooldown_s=1.0, clock=lambda: clk[0])
    assert cb.allow() and cb.state == "closed"
    assert not cb.record_failure()
    assert cb.record_failure()  # threshold reached -> tripped
    assert cb.state == "open" and cb.trips == 1
    assert not cb.allow()  # still cooling
    clk[0] = 1.5
    assert cb.allow() and cb.state == "half_open"
    assert not cb.allow()  # one trial at a time
    assert cb.record_success()  # trial passed -> recovered
    assert cb.state == "closed"
    # A failed trial re-opens immediately.
    cb.record_failure()
    cb.record_failure()
    clk[0] = 3.0
    assert cb.allow()
    assert cb.record_failure()  # half-open trial failed
    assert cb.state == "open" and cb.trips == 3


# --- batcher --------------------------------------------------------------


def test_batcher_never_mixes_buckets():
    """THE invariant: a flushed batch holds requests from exactly one
    bucket, whatever the arrival interleaving."""
    b = Batcher(max_batch=3, max_wait_ms=50, key_fn=lambda r: r[0])
    rng = np.random.default_rng(0)
    keys = [("k", int(k)) for k in rng.integers(0, 4, size=40)]
    for i, k in enumerate(keys):
        b.add((k, i), now=0.001 * i)
    batches = b.pop_ready(1.0, flush_all=True)
    assert sum(len(reqs) for _, reqs in batches) == 40
    for key, reqs in batches:
        assert len(reqs) <= 3
        assert {r[0] for r in reqs} == {key}


def test_batcher_flush_on_size_and_age():
    b = Batcher(max_batch=2, max_wait_ms=100, key_fn=lambda r: r[0])
    b.add(("a", 1), now=0.0)
    assert b.pop_ready(0.01) == []  # neither full nor aged
    b.add(("a", 2), now=0.02)
    [(key, reqs)] = b.pop_ready(0.03)  # full -> immediate
    assert key == "a" and len(reqs) == 2
    b.add(("b", 3), now=0.0)
    assert b.pop_ready(0.05) == []
    assert b.next_flush_in(0.05) == pytest.approx(0.05)
    [(key, reqs)] = b.pop_ready(0.11)  # aged -> partial flush
    assert key == "b" and len(reqs) == 1
    assert len(b) == 0 and b.next_flush_in(0.2) is None


# --- engine ---------------------------------------------------------------


def test_engine_infer_matches_predict(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    key = engine.bucket_key(samples[0])
    out_infer = engine.infer(
        samples[:1], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
    )
    out_pred = engine.predict(samples[:1])
    np.testing.assert_allclose(out_infer[0], out_pred[0], rtol=1e-5)
    assert out_infer[0].shape[0] == samples[0].coords.shape[0]


def test_engine_swap_params_changes_outputs(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    key = engine.bucket_key(samples[0])
    a = engine.infer(samples[:1], pad_nodes=key[0], pad_funcs=key[1])[0]
    engine.swap_params(jax.tree.map(lambda x: x * 0.0, params))
    b = engine.infer(samples[:1], pad_nodes=key[0], pad_funcs=key[1])[0]
    assert not np.allclose(a, b)


def test_engine_validates_nonfinite_with_index(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    bad = MeshSample(
        coords=samples[1].coords.copy(),
        y=samples[1].y,
        theta=samples[1].theta,
        funcs=samples[1].funcs,
    )
    bad.coords[3, 0] = np.nan
    with pytest.raises(ValueError, match="sample 1.*non-finite"):
        engine.validate([samples[0], bad])


def test_trainer_predict_rejects_nonfinite_inputs():
    """Satellite: Trainer.predict (which the engine is extracted from)
    rejects non-finite coords/values with the offending sample index —
    previously only shape/pad mismatches were caught."""
    from gnot_tpu.train.trainer import Trainer

    train = datasets.synth_darcy2d(4, seed=0, grid_n=4)
    cfg = make_config(**{
        "data.n_train": 4, "data.n_test": 0, "train.epochs": 1,
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    trainer = Trainer(cfg, mc, train, [])
    bad = datasets.synth_darcy2d(3, seed=1, grid_n=4)
    bad[2].funcs[0][1, -1] = np.inf
    with pytest.raises(ValueError, match="sample 2.*non-finite"):
        trainer.predict(bad)
    bad[2].funcs[0][1, -1] = 1.0
    bad[1].theta[0] = np.nan
    with pytest.raises(ValueError, match="sample 1.*non-finite"):
        trainer.predict(bad)


# --- server: the chaos scenarios -----------------------------------------


def test_deadline_shedding_via_slow_request(setup, tmp_path):
    """slow_request@N stalls the victim's dispatch past its deadline:
    the victim (and its batchmates) shed BEFORE the forward, with a
    `shed` event naming the reason."""
    server, sink, path = make_server(
        setup,
        tmp_path,
        # max_wait > deadline: the bucket can only flush on SIZE, so
        # both requests ride one deterministic dispatch.
        max_wait_ms=10_000,
        default_deadline_ms=150.0,
        faults=FaultInjector.from_spec("slow_request@1"),
    )
    _, _, samples, _ = setup
    server.start()
    futs = [server.submit(s) for s in samples[:MAX_BATCH]]
    results = [f.result(timeout=30) for f in futs]
    summary = server.drain()
    sink.close()
    assert all(not r.ok and r.reason == "shed_deadline" for r in results)
    assert summary["shed"]["shed_deadline"] == MAX_BATCH
    sheds = [e for e in read_events(path) if e["event"] == "shed"]
    assert any(e["reason"] == "shed_deadline" for e in sheds)
    # The forward never ran: no dispatch (queue_depth) event was
    # emitted for the shed batch.
    assert not [
        e for e in read_events(path) if e["event"] == "queue_depth"
    ]


def test_queue_overflow_fast_fails(setup, tmp_path):
    """Bounded-queue admission: a storm beyond queue_limit fast-fails
    at submit() (shed_queue_full events), and the admitted remainder
    still completes."""
    server, sink, path = make_server(setup, tmp_path, queue_limit=4)
    _, _, samples, _ = setup
    # Worker not started yet: the storm piles into admission unserved —
    # the deterministic "overloaded backend" shape.
    futs = [server.submit(s) for s in samples[:10]]
    shed = [f for f in futs if f.done()]
    assert len(shed) == 6
    assert all(
        f.result().reason == "shed_queue_full" for f in shed
    )
    server.start()
    summary = server.drain()
    sink.close()
    assert summary["completed"] == 4  # admitted requests all served
    assert summary["shed"]["shed_queue_full"] == 6
    events = read_events(path)
    assert sum(e["reason"] == "shed_queue_full" for e in events
               if e["event"] == "shed") == 6
    assert any(e["event"] == "serve_summary" for e in events)


def test_breaker_trips_on_nan_outputs_and_recovers(setup, tmp_path):
    """nan_output@1,2 poisons two dispatches -> breaker opens
    (breaker_open event), requests get instant reject-with-reason
    responses, and after the cooldown a half-open trial closes it
    again (breaker_close event, served request)."""
    server, sink, path = make_server(
        setup,
        tmp_path,
        breaker_threshold=2,
        breaker_cooldown_s=0.4,
        faults=FaultInjector.from_spec("nan_output@1,nan_output@2"),
    )
    _, _, samples, _ = setup
    server.start()
    # Sequential submit-and-wait: each request is its own dispatch, so
    # nan_output@1 and @2 burn exactly the two failures the threshold
    # needs.
    r1 = [server.submit(s).result(timeout=30) for s in samples[:2]]
    assert [r.reason for r in r1] == ["error_nan_output"] * 2
    # Breaker is now open: instant rejection, no dispatch.
    r2 = server.submit(samples[0]).result(timeout=30)
    assert r2.reason == "rejected_breaker_open"
    time.sleep(0.5)  # past the cooldown -> half-open trial allowed
    r3 = server.submit(samples[1]).result(timeout=30)
    assert r3.ok and r3.reason == "ok"
    summary = server.drain()
    sink.close()
    assert summary["breaker_trips"] == 1
    events = read_events(path)
    assert any(e["event"] == "breaker_open" for e in events)
    assert any(e["event"] == "breaker_close" for e in events)


def test_graceful_drain_completes_inflight(setup, tmp_path):
    """drain() stops admission, flushes every queued request through a
    real dispatch, and emits serve_summary with latency percentiles."""
    server, sink, path = make_server(setup, tmp_path, max_wait_ms=10_000)
    _, _, samples, _ = setup
    server.start()
    futs = [server.submit(s) for s in samples[:5]]
    # With a 10 s max_wait and 5 requests (odd), at least one bucket
    # sits partial — only drain's flush_all can complete it.
    summary = server.drain()
    results = [f.result(timeout=1) for f in futs]
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["completed"] == 5
    # Post-drain submissions are rejected with a reason, never queued.
    late = server.submit(samples[0]).result(timeout=1)
    assert late.reason == "rejected_draining"
    sink.close()
    events = read_events(path)
    [summ] = [e for e in events if e["event"] == "serve_summary"]
    assert summ["completed"] == 5
    assert summ["latency_p50_ms"] <= summ["latency_p99_ms"]


def test_sigterm_drains_gracefully(setup, tmp_path):
    """SIGTERM (via resilience.preemption.PreemptionHandler) makes the
    worker drain: in-flight requests complete, nothing hangs."""
    with PreemptionHandler() as preempt:
        server, sink, path = make_server(
            setup, tmp_path, preempt=preempt, max_wait_ms=10_000
        )
        _, _, samples, _ = setup
        server.start()
        futs = [server.submit(s) for s in samples[:4]]
        os.kill(os.getpid(), signal.SIGTERM)
        results = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in results)
        summary = server.drain()
        sink.close()
    assert summary["completed"] == 4
    assert any(
        e["event"] == "serve_summary" for e in read_events(path)
    )


def test_hot_reload_swaps_weights_without_dropping(setup, tmp_path):
    """reload() atomically swaps weights from a checkpoint; requests
    submitted before/after keep resolving, and outputs change to the
    reloaded weights'."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck"))
    new_params = jax.tree.map(lambda x: x * 0.5, params)
    ck.save_latest(new_params, 3, 0.5)
    ck.wait()
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        reload_fn=CheckpointReloader(ck, params),
    )
    server.start()
    before = server.submit(samples[0]).result(timeout=30)
    assert before.ok
    assert server.reload()
    after = server.submit(samples[0]).result(timeout=30)
    assert after.ok
    assert not np.allclose(before.output, after.output)
    server.drain()
    sink.close()
    events = read_events(path)
    [rel] = [e for e in events if e["event"] == "reload"]
    assert rel["ok"] and rel["epoch"] == 3 and not rel["fallback"]


def test_hot_reload_survives_corrupt_dir_via_fallback(setup, tmp_path):
    """reload_corrupt@1 truncates the published 'latest' right before
    the reload reads it: the restore walks the fallback chain to
    'best', serving continues, and the reload event records the
    fallback."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck"))
    best_params = jax.tree.map(lambda x: x * 0.25, params)
    ck.save_best(best_params, 1, 0.5)
    ck.wait()
    ck.save_latest(jax.tree.map(lambda x: x * 2.0, params), 2, 0.5)
    ck.wait()
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        reload_fn=CheckpointReloader(ck, params),
        faults=FaultInjector.from_spec("reload_corrupt@1"),
    )
    server.start()
    assert server.submit(samples[0]).result(timeout=30).ok
    assert server.reload()  # survives the corruption via fallback
    got = server.submit(samples[1]).result(timeout=30)
    assert got.ok  # in-flight serving never stopped
    # The engine now serves the BEST weights (the fallback target).
    leaves_engine = jax.tree.leaves(engine.params)
    leaves_best = jax.tree.leaves(best_params)
    np.testing.assert_allclose(
        np.asarray(leaves_engine[0]), np.asarray(leaves_best[0]), rtol=1e-6
    )
    server.drain()
    sink.close()
    [rel] = [e for e in read_events(path) if e["event"] == "reload"]
    assert rel["ok"] and rel["fallback"]


def test_reload_failure_keeps_serving_old_weights(setup, tmp_path):
    """A reload with NOTHING restorable (empty checkpoint dir) fails
    loudly (event ok=False) but never kills serving."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck_empty"))
    server, sink, path = make_server(
        setup, tmp_path, reload_fn=CheckpointReloader(ck, params)
    )
    server.start()
    assert not server.reload()
    assert server.submit(samples[0]).result(timeout=30).ok
    server.drain()
    sink.close()
    [rel] = [e for e in read_events(path) if e["event"] == "reload"]
    assert not rel["ok"]


# --- mixed-bucket storm + compiled-program bound --------------------------


def _storm_asserts(events, engine, traffic):
    dispatches = [e for e in events if e["event"] == "queue_depth"]
    assert dispatches, "storm produced no dispatches"
    expected = {
        (
            bucket_length(s.coords.shape[0]),
            bucket_length(max(f.shape[0] for f in s.funcs)),
        )
        for s in traffic
    }
    seen = {(e["bucket_nodes"], e["bucket_funcs"]) for e in dispatches}
    assert seen <= expected  # no dispatch outside a real bucket
    l_max = max(bucket_length(s.coords.shape[0]) for s in traffic)
    # O(log L): ~2 bucket boundaries per octave above the 64 floor.
    bound = 2 * (int(math.log2(l_max / 64)) + 1)
    assert engine.compiled_shapes <= max(len(expected), bound)


def test_mixed_bucket_storm_bounded_compiles(setup, tmp_path):
    """Mixed Darcy64 / elasticity-sized traffic: every dispatch stays
    inside one bucket and the engine compiles at most one program per
    bucket — O(log L_max) programs under O(traffic) requests."""
    import serve_smoke

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(12, seed=1)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(traffic, rows=MAX_BATCH)
    server, sink, path = make_server(setup, tmp_path, engine=engine)
    server.start()
    futs = [server.submit(s) for s in traffic]
    results = [f.result(timeout=60) for f in futs]
    server.drain()
    sink.close()
    assert all(r.ok for r in results)
    _storm_asserts(read_events(path), engine, traffic)


def test_serve_smoke_tool(tmp_path):
    """Tier-1 wiring of tools/serve_smoke.py: the CLI smoke (mixed
    buckets, one injected straggler, asserted counters) passes."""
    import serve_smoke

    summary = serve_smoke.run(
        ["--n", "10", "--metrics_path", str(tmp_path / "smoke.jsonl")]
    )
    assert summary["failures"] == []
    assert summary["shed"].get("shed_deadline", 0) >= 1


@pytest.mark.slow
def test_long_mixed_storm_with_faults(setup, tmp_path):
    """The long storm: 80 mixed-bucket requests under queue pressure
    with a straggler AND two NaN dispatches — sheds, trips, recovers,
    drains; every request resolves; compiled programs stay bounded."""
    import serve_smoke

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(80, seed=2)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(traffic, rows=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        queue_limit=32,
        default_deadline_ms=10_000.0,
        breaker_threshold=2,
        breaker_cooldown_s=0.05,
        faults=FaultInjector.from_spec(
            "slow_request@79,nan_output@3,nan_output@4"
        ),
    )
    server.start()
    futs = [server.submit(s) for s in traffic]
    results = [f.result(timeout=120) for f in futs]
    summary = server.drain()
    sink.close()
    assert len(results) == 80  # every request resolved
    reasons = {r.reason for r in results}
    assert "ok" in reasons
    assert summary["completed"] + sum(summary["shed"].values()) == 80
    events = read_events(path)
    _storm_asserts(events, engine, traffic)
    assert summary["breaker_trips"] >= 1


# --- packed dispatch ("pack, don't pad" on the serving hot path) ----------


def _ragged_traffic(setup, sizes, seed=0):
    """Small ragged meshes in the module's Darcy schema (same theta /
    func feature dims, varying node and function-row counts)."""
    _, _, samples, _ = setup
    rng = np.random.default_rng(seed)
    f_dim = samples[0].funcs[0].shape[-1]
    out = []
    for i, m in enumerate(sizes):
        out.append(
            MeshSample(
                coords=rng.uniform(0, 1, size=(m, 2)).astype(np.float32),
                y=np.zeros((m, 1), np.float32),
                theta=samples[0].theta,
                funcs=(
                    rng.uniform(
                        0, 1, size=(max(4, m // 4), f_dim)
                    ).astype(np.float32),
                ),
            )
        )
    return out


def test_batcher_take_fn_prefix_capacity():
    """A take_fn bucket dispatches exactly the FIFO prefix the packer
    says fits: the bucket is FULL when the prefix-take is smaller than
    its queue, aged flushes still take whole dispatches, and other
    buckets keep the max_batch discipline."""
    def take(key, reqs):
        if key != "packed":
            return None
        return min(2, len(reqs))  # two requests per dispatch

    b = Batcher(max_batch=8, max_wait_ms=100, key_fn=lambda r: r[0], take_fn=take)
    b.add(("packed", 1), now=0.0)
    b.add(("packed", 2), now=0.01)
    # take == len(q): one whole dispatch is pending but nothing spills
    # yet — not full, not aged.
    assert b.pop_ready(0.02) == []
    b.add(("packed", 3), now=0.02)  # spills -> FULL
    [(key, reqs)] = b.pop_ready(0.03)
    assert key == "packed" and [r[1] for r in reqs] == [1, 2]
    # The leftover ages out as one whole dispatch.
    [(key, reqs)] = b.pop_ready(0.2)
    assert [r[1] for r in reqs] == [3]
    # A non-take_fn bucket is untouched by the packer.
    b.add(("pad", 4), now=0.0)
    assert b.pop_ready(0.01) == []
    [(key, reqs)] = b.pop_ready(0.2)
    assert key == "pad" and len(reqs) == 1
    # flush_all drains a take_fn bucket in dispatch-sized cuts.
    for i in range(5):
        b.add(("packed", i), now=0.5)
    batches = b.pop_ready(0.5, flush_all=True)
    assert [len(r) for _, r in batches] == [2, 2, 1]


def test_engine_infer_packed_matches_solo(setup):
    """Per-request outputs of ONE packed dispatch == each request's own
    padded dispatch (<= 1e-5, the ISSUE 6 bar), with exactly-per-request
    unpad shapes; repeat dispatches at different fills reuse the ONE
    compiled program."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    traffic = _ragged_traffic(setup, [16, 40, 24, 64, 8, 32])
    plan = PackPlan.from_samples(traffic, chunk=8, batch_size=8)
    assert all(plan.packable(s) for s in traffic)
    assert engine.warmup_packed(traffic, plan) == 1
    shapes_before = engine.compiled_shapes
    outs = engine.infer_packed(traffic, plan)
    assert engine.compiled_shapes == shapes_before  # warmed, no recompile
    for s, o in zip(traffic, outs):
        assert o.shape[0] == s.coords.shape[0]
        key = engine.bucket_key(s)
        solo = engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(o, solo, rtol=1e-5, atol=1e-5)
    # A different fill level of the same plan: same program.
    engine.infer_packed(traffic[:2], plan)
    assert engine.compiled_shapes == shapes_before


def test_packed_server_end_to_end(setup, tmp_path):
    """Packed dispatch through the whole server: plan-fitting requests
    ride pack-plan dispatches (packed=True queue_depth events), an
    oversize request falls back to the padded per-bucket path, every
    Future resolves with exactly its own nodes matching the solo
    dispatch <= 1e-5, and serve_summary reports per-bucket pad-waste
    with the packed bucket's fill above the padded path's for the same
    small-mesh traffic."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    small = _ragged_traffic(setup, [16, 40, 24, 64, 8, 32, 48, 16])
    plan = PackPlan.from_samples(small, chunk=8, batch_size=4)
    oversize = _ragged_traffic(setup, [plan.row_len + 8], seed=5)[0]
    engine.warmup(small + [oversize], rows=MAX_BATCH)
    engine.warmup_packed(small, plan)
    server, sink, path = make_server(setup, tmp_path, pack_plan=plan)
    with sink:
        server.start()
        futures = [server.submit(s) for s in small + [oversize]]
        results = [f.result(timeout=60) for f in futures]
        summary = server.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    for s, r in zip(small + [oversize], results):
        assert r.output.shape[0] == s.coords.shape[0]
        key = engine.bucket_key(s)
        solo = engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(r.output, solo, rtol=1e-5, atol=1e-5)
    events = read_events(path)
    dispatches = [e for e in events if e["event"] == "queue_depth"]
    packed_d = [e for e in dispatches if e["packed"]]
    padded_d = [e for e in dispatches if not e["packed"]]
    assert packed_d, "no packed dispatch happened"
    assert padded_d, "oversize request did not fall back to padded path"
    for e in dispatches:
        assert 0 < e["real_tokens"] <= e["capacity_tokens"]
    # The oversize fallback went to its own (pn, pf) bucket.
    ob = engine.bucket_key(oversize)
    assert any(
        (e["bucket_nodes"], e["bucket_funcs"]) == ob for e in padded_d
    )
    # Packing efficiency rollup: both bucket families present, fractions
    # coherent, and packing beat row-per-request padding for the smalls.
    pw = summary["pad_waste_by_bucket"]
    packed_key = f"packed:{plan.n_rows}x{plan.row_len}"
    assert packed_key in pw
    st = pw[packed_key]
    assert st["real_tokens"] == sum(s.coords.shape[0] for s in small)
    assert st["fill_frac"] == pytest.approx(
        st["real_tokens"] / st["capacity_tokens"]
    )
    padded_fill = sum(s.coords.shape[0] for s in small) / (
        len(small) * bucket_length(max(s.coords.shape[0] for s in small))
    )
    assert st["fill_frac"] > padded_fill, (
        f"packing ({st['fill_frac']:.2%}) should beat row-per-request "
        f"padding ({padded_fill:.2%}) on small-mesh traffic"
    )


def test_packed_server_deadline_shed_repack(setup, tmp_path):
    """A deadline shed between batcher cut and dispatch shrinks the
    live set; the dispatch path re-packs what remains and every
    surviving request still resolves correctly."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    small = _ragged_traffic(setup, [16, 24, 32, 8])
    plan = PackPlan.from_samples(small, chunk=8, batch_size=4)
    engine.warmup_packed(small, plan)
    server, sink, path = make_server(
        setup, tmp_path, pack_plan=plan,
        faults=FaultInjector.from_spec("slow_request@1"),
        default_deadline_ms=150.0,
    )
    with sink:
        server.start()
        futures = [server.submit(s) for s in small]
        results = [f.result(timeout=60) for f in futures]
        server.drain()
    shed = [r for r in results if not r.ok]
    ok = [r for r in results if r.ok]
    assert shed, "the injected straggler should shed at least one deadline"
    for s, r in zip(small, results):
        if r.ok:
            assert r.output.shape[0] == s.coords.shape[0]
