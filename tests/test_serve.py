"""Chaos suite for the inference-serving subsystem (gnot_tpu/serve/).

ISSUE 3 acceptance: on CPU, with deterministic fault injection, the
server demonstrates deadline shedding, queue-overflow fast-fail,
circuit-breaker trip + recovery, graceful drain completing in-flight
requests, and a hot reload that survives a corrupted checkpoint dir by
falling back — each asserted via MetricsSink events — with no
mixed-bucket batches and a compiled-program count bounded by the
bucket count (O(log L_max)) under a mixed small/large request storm.

Fast scenarios run in tier-1; the long storm carries ``-m slow``.
"""

import json
import math
import os
import signal
import sys
import time

import jax
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, make_config
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import MeshSample, bucket_length, collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.resilience.faults import FaultInjector
from gnot_tpu.resilience.preemption import PreemptionHandler
from gnot_tpu.serve import (
    AdmissionController,
    Batcher,
    CheckpointReloader,
    CircuitBreaker,
    InferenceEngine,
    InferenceServer,
)
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")
)

MAX_BATCH = 2  # one compiled (rows, L, Lf) shape shared by every test


def read_events(path):
    return [
        r
        for r in (json.loads(l) for l in open(path))
        if r.get("event")
    ]


@pytest.fixture(scope="module")
def setup():
    """Tiny model + params + 64-point Darcy traffic; the shared
    engine's (2, 64, 64) program compiles once for the whole module."""
    samples = datasets.synth_darcy2d(12, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(samples[:1], rows=MAX_BATCH)
    return model, params, samples, engine


def make_server(setup, tmp_path, **kw):
    """Server over the module's shared warmed engine (tests that swap
    weights pass their own via ``engine=``)."""
    engine = kw.pop("engine", None) or setup[3]
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    server = InferenceServer(
        engine,
        max_batch=MAX_BATCH,
        max_wait_ms=kw.pop("max_wait_ms", 5.0),
        sink=sink,
        **kw,
    )
    return server, sink, str(tmp_path / "serve.jsonl")


# --- policy objects -------------------------------------------------------


def test_admission_controller_bounds_and_releases():
    adm = AdmissionController(2)
    assert adm.try_admit() and adm.try_admit()
    assert not adm.try_admit()  # full -> fast-fail
    adm.release()
    assert adm.try_admit()
    with pytest.raises(ValueError):
        AdmissionController(0)


def test_circuit_breaker_trip_halfopen_recovery():
    clk = [0.0]
    cb = CircuitBreaker(threshold=2, cooldown_s=1.0, clock=lambda: clk[0])
    assert cb.allow() and cb.state == "closed"
    assert not cb.record_failure()
    assert cb.record_failure()  # threshold reached -> tripped
    assert cb.state == "open" and cb.trips == 1
    assert not cb.allow()  # still cooling
    clk[0] = 1.5
    assert cb.allow() and cb.state == "half_open"
    assert not cb.allow()  # one trial at a time
    assert cb.record_success()  # trial passed -> recovered
    assert cb.state == "closed"
    # A failed trial re-opens immediately.
    cb.record_failure()
    cb.record_failure()
    clk[0] = 3.0
    assert cb.allow()
    assert cb.record_failure()  # half-open trial failed
    assert cb.state == "open" and cb.trips == 3


# --- batcher --------------------------------------------------------------


def test_batcher_never_mixes_buckets():
    """THE invariant: a flushed batch holds requests from exactly one
    bucket, whatever the arrival interleaving."""
    b = Batcher(max_batch=3, max_wait_ms=50, key_fn=lambda r: r[0])
    rng = np.random.default_rng(0)
    keys = [("k", int(k)) for k in rng.integers(0, 4, size=40)]
    for i, k in enumerate(keys):
        b.add((k, i), now=0.001 * i)
    batches = b.pop_ready(1.0, flush_all=True)
    assert sum(len(reqs) for _, reqs in batches) == 40
    for key, reqs in batches:
        assert len(reqs) <= 3
        assert {r[0] for r in reqs} == {key}


def test_batcher_flush_on_size_and_age():
    b = Batcher(max_batch=2, max_wait_ms=100, key_fn=lambda r: r[0])
    b.add(("a", 1), now=0.0)
    assert b.pop_ready(0.01) == []  # neither full nor aged
    b.add(("a", 2), now=0.02)
    [(key, reqs)] = b.pop_ready(0.03)  # full -> immediate
    assert key == "a" and len(reqs) == 2
    b.add(("b", 3), now=0.0)
    assert b.pop_ready(0.05) == []
    assert b.next_flush_in(0.05) == pytest.approx(0.05)
    [(key, reqs)] = b.pop_ready(0.11)  # aged -> partial flush
    assert key == "b" and len(reqs) == 1
    assert len(b) == 0 and b.next_flush_in(0.2) is None


# --- engine ---------------------------------------------------------------


def test_engine_infer_matches_predict(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    key = engine.bucket_key(samples[0])
    out_infer = engine.infer(
        samples[:1], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
    )
    out_pred = engine.predict(samples[:1])
    np.testing.assert_allclose(out_infer[0], out_pred[0], rtol=1e-5)
    assert out_infer[0].shape[0] == samples[0].coords.shape[0]


def test_engine_swap_params_changes_outputs(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    key = engine.bucket_key(samples[0])
    a = engine.infer(samples[:1], pad_nodes=key[0], pad_funcs=key[1])[0]
    engine.swap_params(jax.tree.map(lambda x: x * 0.0, params))
    b = engine.infer(samples[:1], pad_nodes=key[0], pad_funcs=key[1])[0]
    assert not np.allclose(a, b)


def test_engine_validates_nonfinite_with_index(setup):
    model, params, samples, _ = setup
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    bad = MeshSample(
        coords=samples[1].coords.copy(),
        y=samples[1].y,
        theta=samples[1].theta,
        funcs=samples[1].funcs,
    )
    bad.coords[3, 0] = np.nan
    with pytest.raises(ValueError, match="sample 1.*non-finite"):
        engine.validate([samples[0], bad])


def test_trainer_predict_rejects_nonfinite_inputs():
    """Satellite: Trainer.predict (which the engine is extracted from)
    rejects non-finite coords/values with the offending sample index —
    previously only shape/pad mismatches were caught."""
    from gnot_tpu.train.trainer import Trainer

    train = datasets.synth_darcy2d(4, seed=0, grid_n=4)
    cfg = make_config(**{
        "data.n_train": 4, "data.n_test": 0, "train.epochs": 1,
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    trainer = Trainer(cfg, mc, train, [])
    bad = datasets.synth_darcy2d(3, seed=1, grid_n=4)
    bad[2].funcs[0][1, -1] = np.inf
    with pytest.raises(ValueError, match="sample 2.*non-finite"):
        trainer.predict(bad)
    bad[2].funcs[0][1, -1] = 1.0
    bad[1].theta[0] = np.nan
    with pytest.raises(ValueError, match="sample 1.*non-finite"):
        trainer.predict(bad)


# --- server: the chaos scenarios -----------------------------------------


def test_deadline_shedding_via_slow_request(setup, tmp_path):
    """slow_request@N stalls the victim's dispatch past its deadline:
    the victim (and its batchmates) shed BEFORE the forward, with a
    `shed` event naming the reason."""
    server, sink, path = make_server(
        setup,
        tmp_path,
        # max_wait > deadline: the bucket can only flush on SIZE, so
        # both requests ride one deterministic dispatch.
        max_wait_ms=10_000,
        default_deadline_ms=150.0,
        faults=FaultInjector.from_spec("slow_request@1"),
    )
    _, _, samples, _ = setup
    server.start()
    futs = [server.submit(s) for s in samples[:MAX_BATCH]]
    results = [f.result(timeout=30) for f in futs]
    summary = server.drain()
    sink.close()
    assert all(not r.ok and r.reason == "shed_deadline" for r in results)
    assert summary["shed"]["shed_deadline"] == MAX_BATCH
    sheds = [e for e in read_events(path) if e["event"] == "shed"]
    assert any(e["reason"] == "shed_deadline" for e in sheds)
    # The forward never ran: no dispatch (queue_depth) event was
    # emitted for the shed batch.
    assert not [
        e for e in read_events(path) if e["event"] == "queue_depth"
    ]


def test_queue_overflow_fast_fails(setup, tmp_path):
    """Bounded-queue admission: a storm beyond queue_limit fast-fails
    at submit() (shed_queue_full events), and the admitted remainder
    still completes."""
    server, sink, path = make_server(setup, tmp_path, queue_limit=4)
    _, _, samples, _ = setup
    # Worker not started yet: the storm piles into admission unserved —
    # the deterministic "overloaded backend" shape.
    futs = [server.submit(s) for s in samples[:10]]
    shed = [f for f in futs if f.done()]
    assert len(shed) == 6
    assert all(
        f.result().reason == "shed_queue_full" for f in shed
    )
    server.start()
    summary = server.drain()
    sink.close()
    assert summary["completed"] == 4  # admitted requests all served
    assert summary["shed"]["shed_queue_full"] == 6
    events = read_events(path)
    assert sum(e["reason"] == "shed_queue_full" for e in events
               if e["event"] == "shed") == 6
    assert any(e["event"] == "serve_summary" for e in events)


def test_breaker_trips_on_nan_outputs_and_recovers(setup, tmp_path):
    """nan_output@1,2 poisons two dispatches -> breaker opens
    (breaker_open event), requests get instant reject-with-reason
    responses, and after the cooldown a half-open trial closes it
    again (breaker_close event, served request)."""
    server, sink, path = make_server(
        setup,
        tmp_path,
        breaker_threshold=2,
        breaker_cooldown_s=0.4,
        faults=FaultInjector.from_spec("nan_output@1,nan_output@2"),
    )
    _, _, samples, _ = setup
    server.start()
    # Sequential submit-and-wait: each request is its own dispatch, so
    # nan_output@1 and @2 burn exactly the two failures the threshold
    # needs.
    r1 = [server.submit(s).result(timeout=30) for s in samples[:2]]
    assert [r.reason for r in r1] == ["error_nan_output"] * 2
    # Breaker is now open: instant rejection, no dispatch.
    r2 = server.submit(samples[0]).result(timeout=30)
    assert r2.reason == "rejected_breaker_open"
    time.sleep(0.5)  # past the cooldown -> half-open trial allowed
    r3 = server.submit(samples[1]).result(timeout=30)
    assert r3.ok and r3.reason == "ok"
    summary = server.drain()
    sink.close()
    assert summary["breaker_trips"] == 1
    events = read_events(path)
    assert any(e["event"] == "breaker_open" for e in events)
    assert any(e["event"] == "breaker_close" for e in events)


def test_graceful_drain_completes_inflight(setup, tmp_path):
    """drain() stops admission, flushes every queued request through a
    real dispatch, and emits serve_summary with latency percentiles."""
    server, sink, path = make_server(setup, tmp_path, max_wait_ms=10_000)
    _, _, samples, _ = setup
    server.start()
    futs = [server.submit(s) for s in samples[:5]]
    # With a 10 s max_wait and 5 requests (odd), at least one bucket
    # sits partial — only drain's flush_all can complete it.
    summary = server.drain()
    results = [f.result(timeout=1) for f in futs]
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["completed"] == 5
    # Post-drain submissions are rejected with a reason, never queued.
    late = server.submit(samples[0]).result(timeout=1)
    assert late.reason == "rejected_draining"
    sink.close()
    events = read_events(path)
    [summ] = [e for e in events if e["event"] == "serve_summary"]
    assert summ["completed"] == 5
    assert summ["latency_p50_ms"] <= summ["latency_p99_ms"]


def test_sigterm_drains_gracefully(setup, tmp_path):
    """SIGTERM (via resilience.preemption.PreemptionHandler) makes the
    worker drain: in-flight requests complete, nothing hangs."""
    with PreemptionHandler() as preempt:
        server, sink, path = make_server(
            setup, tmp_path, preempt=preempt, max_wait_ms=10_000
        )
        _, _, samples, _ = setup
        server.start()
        futs = [server.submit(s) for s in samples[:4]]
        os.kill(os.getpid(), signal.SIGTERM)
        results = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in results)
        summary = server.drain()
        sink.close()
    assert summary["completed"] == 4
    assert any(
        e["event"] == "serve_summary" for e in read_events(path)
    )


def test_hot_reload_swaps_weights_without_dropping(setup, tmp_path):
    """reload() atomically swaps weights from a checkpoint; requests
    submitted before/after keep resolving, and outputs change to the
    reloaded weights'."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck"))
    new_params = jax.tree.map(lambda x: x * 0.5, params)
    ck.save_latest(new_params, 3, 0.5)
    ck.wait()
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        reload_fn=CheckpointReloader(ck, params),
    )
    server.start()
    before = server.submit(samples[0]).result(timeout=30)
    assert before.ok
    assert server.reload()
    after = server.submit(samples[0]).result(timeout=30)
    assert after.ok
    assert not np.allclose(before.output, after.output)
    server.drain()
    sink.close()
    events = read_events(path)
    [rel] = [e for e in events if e["event"] == "reload"]
    assert rel["ok"] and rel["epoch"] == 3 and not rel["fallback"]


def test_hot_reload_survives_corrupt_dir_via_fallback(setup, tmp_path):
    """reload_corrupt@1 truncates the published 'latest' right before
    the reload reads it: the restore walks the fallback chain to
    'best', serving continues, and the reload event records the
    fallback."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck"))
    best_params = jax.tree.map(lambda x: x * 0.25, params)
    ck.save_best(best_params, 1, 0.5)
    ck.wait()
    ck.save_latest(jax.tree.map(lambda x: x * 2.0, params), 2, 0.5)
    ck.wait()
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        reload_fn=CheckpointReloader(ck, params),
        faults=FaultInjector.from_spec("reload_corrupt@1"),
    )
    server.start()
    assert server.submit(samples[0]).result(timeout=30).ok
    assert server.reload()  # survives the corruption via fallback
    got = server.submit(samples[1]).result(timeout=30)
    assert got.ok  # in-flight serving never stopped
    # The engine now serves the BEST weights (the fallback target).
    leaves_engine = jax.tree.leaves(engine.params)
    leaves_best = jax.tree.leaves(best_params)
    np.testing.assert_allclose(
        np.asarray(leaves_engine[0]), np.asarray(leaves_best[0]), rtol=1e-6
    )
    server.drain()
    sink.close()
    [rel] = [e for e in read_events(path) if e["event"] == "reload"]
    assert rel["ok"] and rel["fallback"]


def test_reload_failure_keeps_serving_old_weights(setup, tmp_path):
    """A reload with NOTHING restorable (empty checkpoint dir) fails
    loudly (event ok=False) but never kills serving."""
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck_empty"))
    server, sink, path = make_server(
        setup, tmp_path, reload_fn=CheckpointReloader(ck, params)
    )
    server.start()
    assert not server.reload()
    assert server.submit(samples[0]).result(timeout=30).ok
    server.drain()
    sink.close()
    [rel] = [e for e in read_events(path) if e["event"] == "reload"]
    assert not rel["ok"]


# --- mixed-bucket storm + compiled-program bound --------------------------


def _storm_asserts(events, engine, traffic):
    dispatches = [e for e in events if e["event"] == "queue_depth"]
    assert dispatches, "storm produced no dispatches"
    expected = {
        (
            bucket_length(s.coords.shape[0]),
            bucket_length(max(f.shape[0] for f in s.funcs)),
        )
        for s in traffic
    }
    seen = {(e["bucket_nodes"], e["bucket_funcs"]) for e in dispatches}
    assert seen <= expected  # no dispatch outside a real bucket
    l_max = max(bucket_length(s.coords.shape[0]) for s in traffic)
    # O(log L): ~2 bucket boundaries per octave above the 64 floor.
    bound = 2 * (int(math.log2(l_max / 64)) + 1)
    assert engine.compiled_shapes <= max(len(expected), bound)


def test_mixed_bucket_storm_bounded_compiles(setup, tmp_path):
    """Mixed Darcy64 / elasticity-sized traffic: every dispatch stays
    inside one bucket and the engine compiles at most one program per
    bucket — O(log L_max) programs under O(traffic) requests."""
    import serve_smoke

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(12, seed=1)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(traffic, rows=MAX_BATCH)
    server, sink, path = make_server(setup, tmp_path, engine=engine)
    server.start()
    futs = [server.submit(s) for s in traffic]
    results = [f.result(timeout=60) for f in futs]
    server.drain()
    sink.close()
    assert all(r.ok for r in results)
    _storm_asserts(read_events(path), engine, traffic)


def test_serve_smoke_tool(tmp_path):
    """Tier-1 wiring of tools/serve_smoke.py: the CLI smoke (mixed
    buckets, one injected straggler, asserted counters) passes."""
    import serve_smoke

    summary = serve_smoke.run(
        ["--n", "10", "--metrics_path", str(tmp_path / "smoke.jsonl")]
    )
    assert summary["failures"] == []
    assert summary["shed"].get("shed_deadline", 0) >= 1


@pytest.mark.slow
def test_serve_bench_quick_smoke(tmp_path):
    """tools/serve_bench.py --quick end-to-end (in-process — the XLA
    thread-pinning flags don't apply with jax already initialized, so
    this checks structure and bookkeeping, NOT the committed artifact's
    speedup bar, which test_artifacts pins)."""
    import serve_bench

    out = str(tmp_path / "bench.jsonl")
    summary = serve_bench.run(
        [
            "--quick", "--replicas", "2", "--out", out,
            "--n_traffic", "8", "--duration_s", "1.0",
            "--hidden", "16", "--layers", "1",
            "--mesh_lo", "100", "--mesh_hi", "200",
        ]
    )
    recs = [json.loads(l) for l in open(out) if l.strip()]
    runs = [r for r in recs if "arm" in r]
    assert {r["arm"] for r in runs} == {"replicas_1", "replicas_2"}
    for r in runs:
        assert r["completed"] + sum(r["shed"].values()) == r["submitted"]
    assert summary["quick"] is True
    assert summary["max_abs_diff"] <= 1e-5


@pytest.mark.slow
def test_long_mixed_storm_with_faults(setup, tmp_path):
    """The long storm: 80 mixed-bucket requests under queue pressure
    with a straggler AND two NaN dispatches — sheds, trips, recovers,
    drains; every request resolves; compiled programs stay bounded."""
    import serve_smoke

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(80, seed=2)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(traffic, rows=MAX_BATCH)
    server, sink, path = make_server(
        setup,
        tmp_path,
        engine=engine,
        queue_limit=32,
        default_deadline_ms=10_000.0,
        breaker_threshold=2,
        breaker_cooldown_s=0.05,
        faults=FaultInjector.from_spec(
            "slow_request@79,nan_output@3,nan_output@4"
        ),
    )
    server.start()
    futs = [server.submit(s) for s in traffic]
    results = [f.result(timeout=120) for f in futs]
    summary = server.drain()
    sink.close()
    assert len(results) == 80  # every request resolved
    reasons = {r.reason for r in results}
    assert "ok" in reasons
    assert summary["completed"] + sum(summary["shed"].values()) == 80
    events = read_events(path)
    _storm_asserts(events, engine, traffic)
    assert summary["breaker_trips"] >= 1


# --- packed dispatch ("pack, don't pad" on the serving hot path) ----------


def _ragged_traffic(setup, sizes, seed=0):
    """Small ragged meshes in the module's Darcy schema (same theta /
    func feature dims, varying node and function-row counts)."""
    _, _, samples, _ = setup
    rng = np.random.default_rng(seed)
    f_dim = samples[0].funcs[0].shape[-1]
    out = []
    for i, m in enumerate(sizes):
        out.append(
            MeshSample(
                coords=rng.uniform(0, 1, size=(m, 2)).astype(np.float32),
                y=np.zeros((m, 1), np.float32),
                theta=samples[0].theta,
                funcs=(
                    rng.uniform(
                        0, 1, size=(max(4, m // 4), f_dim)
                    ).astype(np.float32),
                ),
            )
        )
    return out


def test_batcher_take_fn_prefix_capacity():
    """A take_fn bucket dispatches exactly the FIFO prefix the packer
    says fits: the bucket is FULL when the prefix-take is smaller than
    its queue, aged flushes still take whole dispatches, and other
    buckets keep the max_batch discipline."""
    def take(key, reqs):
        if key != "packed":
            return None
        return min(2, len(reqs))  # two requests per dispatch

    b = Batcher(max_batch=8, max_wait_ms=100, key_fn=lambda r: r[0], take_fn=take)
    b.add(("packed", 1), now=0.0)
    b.add(("packed", 2), now=0.01)
    # take == len(q): one whole dispatch is pending but nothing spills
    # yet — not full, not aged.
    assert b.pop_ready(0.02) == []
    b.add(("packed", 3), now=0.02)  # spills -> FULL
    [(key, reqs)] = b.pop_ready(0.03)
    assert key == "packed" and [r[1] for r in reqs] == [1, 2]
    # The leftover ages out as one whole dispatch.
    [(key, reqs)] = b.pop_ready(0.2)
    assert [r[1] for r in reqs] == [3]
    # A non-take_fn bucket is untouched by the packer.
    b.add(("pad", 4), now=0.0)
    assert b.pop_ready(0.01) == []
    [(key, reqs)] = b.pop_ready(0.2)
    assert key == "pad" and len(reqs) == 1
    # flush_all drains a take_fn bucket in dispatch-sized cuts.
    for i in range(5):
        b.add(("packed", i), now=0.5)
    batches = b.pop_ready(0.5, flush_all=True)
    assert [len(r) for _, r in batches] == [2, 2, 1]


def test_engine_infer_packed_matches_solo(setup):
    """Per-request outputs of ONE packed dispatch == each request's own
    padded dispatch (<= 1e-5, the ISSUE 6 bar), with exactly-per-request
    unpad shapes; repeat dispatches at different fills reuse the ONE
    compiled program."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    traffic = _ragged_traffic(setup, [16, 40, 24, 64, 8, 32])
    plan = PackPlan.from_samples(traffic, chunk=8, batch_size=8)
    assert all(plan.packable(s) for s in traffic)
    assert engine.warmup_packed(traffic, plan) == 1
    shapes_before = engine.compiled_shapes
    outs = engine.infer_packed(traffic, plan)
    assert engine.compiled_shapes == shapes_before  # warmed, no recompile
    for s, o in zip(traffic, outs):
        assert o.shape[0] == s.coords.shape[0]
        key = engine.bucket_key(s)
        solo = engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(o, solo, rtol=1e-5, atol=1e-5)
    # A different fill level of the same plan: same program.
    engine.infer_packed(traffic[:2], plan)
    assert engine.compiled_shapes == shapes_before


def test_packed_server_end_to_end(setup, tmp_path):
    """Packed dispatch through the whole server: plan-fitting requests
    ride pack-plan dispatches (packed=True queue_depth events), an
    oversize request falls back to the padded per-bucket path, every
    Future resolves with exactly its own nodes matching the solo
    dispatch <= 1e-5, and serve_summary reports per-bucket pad-waste
    with the packed bucket's fill above the padded path's for the same
    small-mesh traffic."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    small = _ragged_traffic(setup, [16, 40, 24, 64, 8, 32, 48, 16])
    plan = PackPlan.from_samples(small, chunk=8, batch_size=4)
    oversize = _ragged_traffic(setup, [plan.row_len + 8], seed=5)[0]
    engine.warmup(small + [oversize], rows=MAX_BATCH)
    engine.warmup_packed(small, plan)
    server, sink, path = make_server(setup, tmp_path, pack_plan=plan)
    with sink:
        server.start()
        futures = [server.submit(s) for s in small + [oversize]]
        results = [f.result(timeout=60) for f in futures]
        summary = server.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    for s, r in zip(small + [oversize], results):
        assert r.output.shape[0] == s.coords.shape[0]
        key = engine.bucket_key(s)
        solo = engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(r.output, solo, rtol=1e-5, atol=1e-5)
    events = read_events(path)
    dispatches = [e for e in events if e["event"] == "queue_depth"]
    packed_d = [e for e in dispatches if e["packed"]]
    padded_d = [e for e in dispatches if not e["packed"]]
    assert packed_d, "no packed dispatch happened"
    assert padded_d, "oversize request did not fall back to padded path"
    for e in dispatches:
        assert 0 < e["real_tokens"] <= e["capacity_tokens"]
    # The oversize fallback went to its own (pn, pf) bucket.
    ob = engine.bucket_key(oversize)
    assert any(
        (e["bucket_nodes"], e["bucket_funcs"]) == ob for e in padded_d
    )
    # Packing efficiency rollup: both bucket families present, fractions
    # coherent, and packing beat row-per-request padding for the smalls.
    pw = summary["pad_waste_by_bucket"]
    packed_key = f"packed:{plan.n_rows}x{plan.row_len}"
    assert packed_key in pw
    st = pw[packed_key]
    assert st["real_tokens"] == sum(s.coords.shape[0] for s in small)
    assert st["fill_frac"] == pytest.approx(
        st["real_tokens"] / st["capacity_tokens"]
    )
    padded_fill = sum(s.coords.shape[0] for s in small) / (
        len(small) * bucket_length(max(s.coords.shape[0] for s in small))
    )
    assert st["fill_frac"] > padded_fill, (
        f"packing ({st['fill_frac']:.2%}) should beat row-per-request "
        f"padding ({padded_fill:.2%}) on small-mesh traffic"
    )


# --- replicated serving: replicas + compile-affinity router ---------------


def _make_replicas(setup, n, **kw):
    from gnot_tpu.serve import build_replicas

    model, params, _, _ = setup
    # One device per replica: MAX_BATCH=2 rows don't shard over the
    # wider slices an even 8-device split would produce.
    kw.setdefault("devices", jax.devices()[:n])
    return build_replicas(model, params, n, batch_size=MAX_BATCH, **kw)


def _read_all(path):
    return [json.loads(l) for l in open(path) if l.strip()]


def test_serve_config_validates_replica_knobs():
    with pytest.raises(ValueError, match="replicas"):
        make_config(**{"serve.replicas": 0})
    with pytest.raises(ValueError, match="route_policy"):
        make_config(**{"serve.route_policy": "sticky"})
    with pytest.raises(ValueError, match="wedge_after_s"):
        make_config(**{"serve.wedge_after_s": 0.0})
    cfg = make_config(**{"serve.replicas": 4, "serve.route_policy": "round_robin"})
    assert cfg.serve.replicas == 4


def test_replica_health_policy_verdicts():
    from gnot_tpu.serve import ReplicaHealthPolicy

    hp = ReplicaHealthPolicy(wedge_after_s=1.0)
    ok = hp.assess(
        breaker_state="closed", warming=False, progress_age_s=0.1, depth=3
    )
    assert ok.healthy and ok.reason == "ok"
    assert hp.assess(
        breaker_state="open", warming=False, progress_age_s=0.0, depth=0
    ).reason == "breaker_open"
    # Post-cooldown open breaker: routable again (reason "trial") so
    # the half-open trial dispatch can actually happen.
    trial = hp.assess(
        breaker_state="open", warming=False, progress_age_s=0.0,
        depth=0, breaker_trial_due=True,
    )
    assert trial.healthy and trial.reason == "trial"
    assert hp.assess(
        breaker_state="closed", warming=True, progress_age_s=0.0, depth=0
    ).reason == "warming"
    # Wedged needs BOTH a stalled loop and work in the system — an idle
    # replica with an old stamp is just idle.
    assert hp.assess(
        breaker_state="closed", warming=False, progress_age_s=5.0, depth=2
    ).reason == "wedged"
    assert hp.assess(
        breaker_state="closed", warming=False, progress_age_s=5.0, depth=0
    ).healthy
    assert hp.assess(
        breaker_state="closed", warming=False, progress_age_s=0.0,
        depth=0, worker_alive=False,
    ).reason == "dead"
    with pytest.raises(ValueError):
        ReplicaHealthPolicy(wedge_after_s=0.0)


def test_replica_engines_match_default_engine(setup):
    """Every mesh-sliced replica engine produces the default engine's
    outputs (the replicated-vs-solo acceptance invariant), and a
    swap_params with HOST arrays keeps the replica's placement (no
    recompile — the place_params hook)."""
    model, params, samples, engine = setup
    replicas = _make_replicas(setup, 2)
    key = engine.bucket_key(samples[0])
    ref = engine.infer(
        samples[:1], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
    )[0]
    for r in replicas:
        out = r.engine.infer(
            samples[:1], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
    # Host-array reload keeps placement: same outputs, same program.
    r0 = replicas[0]
    host = jax.tree.map(lambda x: np.array(jax.device_get(x)), params)
    before = r0.engine.compiled_shapes
    r0.engine.swap_params(host)
    out = r0.engine.infer(
        samples[:1], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
    )[0]
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
    assert r0.engine.compiled_shapes == before


def test_build_replicas_validates():
    from gnot_tpu.serve import build_replicas

    with pytest.raises(ValueError, match="n_replicas"):
        build_replicas(None, None, 0, batch_size=2)
    with pytest.raises(ValueError, match="at least one device"):
        build_replicas(None, None, 10_000, batch_size=2)
    # 8 devices / 2 replicas = 4-device slices; 2 rows don't shard.
    if len(jax.devices()) >= 8:
        with pytest.raises(ValueError, match="divide"):
            build_replicas(None, None, 2, batch_size=2)


def test_router_affinity_cold_assign_sticks(setup, tmp_path):
    """A bucket seen for the first time is assigned to ONE replica
    (cold_assign) and every later request of that bucket follows it
    (affinity): exactly one replica compiles the bucket's program."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    sizes = [100, 100, 100, 100]  # one un-warmed 128-bucket
    traffic = _ragged_traffic(setup, sizes, seed=3)
    replicas = _make_replicas(setup, 2)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, max_wait_ms=5.0
        ).start()
        results = [
            router.submit(s).result(timeout=60) for s in traffic
        ]
        summary = router.drain()
    assert all(r.ok for r in results)
    routes = [
        e for e in _read_all(str(tmp_path / "serve.jsonl"))
        if e.get("event") == "route"
    ]
    assert [r["reason"] for r in routes] == [
        "cold_assign", "affinity", "affinity", "affinity"
    ]
    assert len({r["replica"] for r in routes}) == 1  # it stuck
    compiled = [r.engine.compiled_shapes for r in replicas]
    assert sorted(compiled) == [0, 1]  # exactly one replica compiled
    assert summary["routing"]["policy"] == "affinity"
    assert set(summary["per_replica"]) == {"0", "1"}


def test_router_routes_around_open_breaker(setup, tmp_path):
    """An open breaker on one replica drains its NEW traffic to the
    sibling (replica_health event) instead of shedding it; the pool
    completes everything."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
            breaker_cooldown_s=0.3,
        ).start()
        # Trip replica 0's breaker directly (threshold default 3).
        for _ in range(3):
            replicas[0].server.breaker.record_failure()
        assert replicas[0].server.breaker.state == "open"
        results = [
            router.submit(s).result(timeout=60) for s in samples[:6]
        ]
        # Past the cooldown the router must route a trial back to
        # replica 0 — a drained replica never dispatches, so without
        # this the breaker could NEVER recover.
        time.sleep(0.4)
        trial = router.submit(samples[0]).result(timeout=60)
        assert trial.ok
        assert replicas[0].server.breaker.state == "closed"
        after = [
            router.submit(s).result(timeout=60) for s in samples[:4]
        ]
        router.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert all(r.ok for r in after)
    events = _read_all(str(tmp_path / "serve.jsonl"))
    routes = [e for e in events if e.get("event") == "route"]
    assert {r["replica"] for r in routes[:6]} == {1}
    # The trial request landed on replica 0, and replica 0 is routable
    # again afterwards (idle-tie-break prefers the lowest id, so it may
    # legitimately absorb all of the light post-recovery traffic).
    assert routes[6]["replica"] == 0
    assert 0 in {r["replica"] for r in routes[7:]}
    health = [e for e in events if e.get("event") == "replica_health"]
    assert any(
        e["replica"] == 0 and not e["healthy"]
        and e["reason"] == "breaker_open"
        for e in health
    )
    # ... and the recovery edge back to routable.
    reasons0 = [e["reason"] for e in health if e["replica"] == 0]
    assert "trial" in reasons0 or "ok" in reasons0[1:]


def test_router_wedged_replica_drains_to_siblings(setup, tmp_path):
    """A worker stalled inside a dispatch (injected straggler) with
    work in-system reads as wedged after wedge_after_s: new traffic
    routes to the sibling while the victim stalls."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            wedge_after_s=0.2,
            # The straggler stalls replica 0's FIRST dispatch past the
            # victim's deadline (deterministic wedge).
            faults={0: FaultInjector.from_spec("slow_request@1")},
        ).start()
        victim = router.submit(samples[0], deadline_ms=1_500)
        time.sleep(0.5)  # worker 0 now mid-stall, loop silent
        late = [router.submit(s) for s in samples[1:5]]
        results = [f.result(timeout=60) for f in late]
        victim_result = victim.result(timeout=60)
        router.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert victim_result.reason == "shed_deadline"
    events = _read_all(str(tmp_path / "serve.jsonl"))
    routes = [e for e in events if e.get("event") == "route"]
    # The first request landed on replica 0; the post-stall ones on 1.
    assert routes[0]["replica"] == 0
    assert all(r["replica"] == 1 for r in routes[1:])
    assert any(
        e.get("event") == "replica_health" and e["reason"] == "wedged"
        for e in events
    )


def test_router_spill_when_affinity_target_full(setup, tmp_path):
    """A full affinity target spills to the least-loaded sibling
    instead of shedding at its door."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, queue_limit=2
        )
        # Workers NOT started: queues only fill. Pre-assign the bucket
        # to replica 0, then overfill it.
        key, _ = router._bucket_of(samples[0])
        replicas[0].note_bucket(key)
        futs = [router.submit(s) for s in samples[:3]]
        events_now = [
            e for e in _read_all(str(tmp_path / "serve.jsonl"))
            if e.get("event") == "route"
        ]
        assert [e["reason"] for e in events_now] == [
            "affinity", "affinity", "spill"
        ]
        assert [e["replica"] for e in events_now] == [0, 0, 1]
        summary = router.drain()
        for f in futs:
            assert f.result(timeout=5).reason == "rejected_draining"
    assert summary["routing"]["spills"] == 1


def test_rolling_reload_corrupt_replica_keeps_pool_serving(setup, tmp_path):
    """THE rolling-reload chaos scenario (ISSUE 9 satellite):
    reload_corrupt hits one replica mid-rollout — that replica's
    restore walks the fallback chain (old weights never stop serving),
    at most one replica warms at a time, and the pool completes EVERY
    request submitted during the rollout: zero shed requests
    attributable to the reload."""
    import threading

    from gnot_tpu.serve import CheckpointReloader, ReplicaRouter
    from gnot_tpu.train.checkpoint import Checkpointer

    model, params, samples, _ = setup
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save_best(jax.tree.map(lambda x: x * 0.25, params), 1, 0.5)
    ck.wait()
    ck.save_latest(jax.tree.map(lambda x: x * 0.5, params), 2, 0.4)
    ck.wait()
    replicas = _make_replicas(setup, 3)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            reload_fn=CheckpointReloader(ck, params),
            # Replica 1's FIRST reload truncates the published 'latest'
            # right before reading it — mid-rollout corruption.
            faults={1: FaultInjector.from_spec("reload_corrupt@1")},
        ).start()
        futures = []
        stop = threading.Event()

        def storm():
            i = 0
            while not stop.is_set():
                futures.append(router.submit(samples[i % len(samples)]))
                i += 1
                time.sleep(0.005)

        t = threading.Thread(target=storm)
        t.start()
        try:
            time.sleep(0.05)  # traffic flowing before the rollout
            ok_n = router.reload()
            time.sleep(0.05)  # and after
        finally:
            stop.set()
            t.join()
        results = [f.result(timeout=60) for f in futures]

        # Weight provenance right after the corrupted rollout (see the
        # mixed-pool assertions below).
        def first_leaf(r):
            return np.array(
                np.asarray(jax.tree.leaves(r.engine.params)[0])
            )

        after_rollout1 = [first_leaf(r) for r in replicas]
        # A second, clean rollout ('latest' is still corrupt — the
        # fallback is sticky and loud, not an error).
        ok_n2 = router.reload()
        after_rollout2 = [first_leaf(r) for r in replicas]
        summary = router.drain()
    assert ok_n == 3  # corrupt replica recovered via fallback
    assert ok_n2 == 3
    assert results, "storm submitted nothing"
    assert all(r.ok for r in results), (
        f"reload shed requests: "
        f"{[r.reason for r in results if not r.ok]}"
    )
    assert summary["shed"] == {}  # zero shed, full stop
    assert summary["reloads"] == 6  # two full rollouts of 3
    events = _read_all(str(tmp_path / "serve.jsonl"))
    rolling = [e for e in events if e.get("event") == "rolling_reload"]
    assert [(e["rollout"], e["step"], e["replica"], e["ok"]) for e in rolling] == [
        (1, 1, 0, True), (1, 2, 1, True), (1, 3, 2, True),
        (2, 1, 0, True), (2, 2, 1, True), (2, 3, 2, True),
    ]
    assert all(e["n_replicas"] == 3 for e in rolling)
    # The corrupted replica's reload records the fallback walk.
    reloads = [e for e in events if e.get("event") == "reload"]
    assert [e["replica"] for e in reloads][:3] == [0, 1, 2]
    assert reloads[1]["fallback"] and reloads[1]["ok"]
    # Warming edges: each replica drained while ITS weights swapped.
    warm_edges = [
        e for e in events
        if e.get("event") == "replica_health" and e["reason"] == "warming"
    ]
    assert {e["replica"] for e in warm_edges} == {0, 1, 2}
    # Weight provenance after the corrupted rollout: replica 0
    # reloaded BEFORE the fault (it serves 'latest' = 0.5x), replicas
    # 1 and 2 hit the corrupted 'latest' and fell back to 'best'
    # (0.25x) — the pool is deliberately MIXED rather than stalled.
    ref = np.asarray(jax.tree.leaves(params)[0])
    np.testing.assert_allclose(after_rollout1[0], ref * 0.5, rtol=1e-6)
    for leaf in after_rollout1[1:]:
        np.testing.assert_allclose(leaf, ref * 0.25, rtol=1e-6)
    # The second, clean rollout converged every replica onto 'best'.
    for leaf in after_rollout2:
        np.testing.assert_allclose(leaf, ref * 0.25, rtol=1e-6)


def test_replicated_serve_cli_guards_and_packed_alignment(tmp_path):
    """--serve_replicas guards the layouts it can't serve (scan_layers
    / flat_params fail with the flag to flip, not a flax structure
    error), and packed replicated serving aligns the PackPlan row grid
    to the replica slice so packed rows shard evenly."""
    from gnot_tpu import main as main_mod

    tiny = [
        "--synthetic", "elasticity", "--synth_size", "64",
        "--n_train", "4", "--n_test", "6", "--epochs", "1",
        "--n_attn_layers", "2", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
    ]
    with pytest.raises(ValueError, match="scan_layers"):
        main_mod.main(
            ["--serve", "--serve_replicas", "2", "--scan_layers", *tiny]
        )
    with pytest.raises(ValueError, match="flat_params"):
        main_mod.main(
            ["--serve", "--serve_replicas", "2", "--flat_params", *tiny]
        )
    # Packed + replicated end-to-end: the plan's n_rows is aligned up
    # to the 4-device slice (8 devices / 2 replicas), so warm and
    # every packed dispatch shard cleanly.
    frac = main_mod.main(
        [
            "--serve", "--serve_replicas", "2", "--serve_packed",
            "--serve_pack_chunk", "16",
            "--metrics_path", str(tmp_path / "m.jsonl"), *tiny,
        ]
    )
    assert frac == 1.0
    events = [
        json.loads(l) for l in open(tmp_path / "m.jsonl") if l.strip()
    ]
    packed_d = [
        e for e in events
        if e.get("event") == "queue_depth" and e.get("packed")
    ]
    assert packed_d, "no packed dispatch rode the replicated pool"


def test_serve_smoke_tool_replicas(tmp_path):
    """Tier-1 wiring of tools/serve_smoke.py --replicas: the mixed-
    bucket storm through the 2-replica router passes every assertion
    (per-replica compile bounds, route events, per-replica rollup)."""
    import serve_smoke

    summary = serve_smoke.run(
        [
            "--n", "10", "--replicas", "2", "--inject_fault", "none",
            "--metrics_path", str(tmp_path / "smoke.jsonl"),
        ]
    )
    assert summary["failures"] == []
    assert summary["routing"]["replicas"] == 2


def test_packed_server_deadline_shed_repack(setup, tmp_path):
    """A deadline shed between batcher cut and dispatch shrinks the
    live set; the dispatch path re-packs what remains and every
    surviving request still resolves correctly."""
    from gnot_tpu.data.batch import PackPlan

    model, params, samples, engine = setup
    small = _ragged_traffic(setup, [16, 24, 32, 8])
    plan = PackPlan.from_samples(small, chunk=8, batch_size=4)
    engine.warmup_packed(small, plan)
    server, sink, path = make_server(
        setup, tmp_path, pack_plan=plan,
        faults=FaultInjector.from_spec("slow_request@1"),
        default_deadline_ms=150.0,
    )
    with sink:
        server.start()
        futures = [server.submit(s) for s in small]
        results = [f.result(timeout=60) for f in futures]
        server.drain()
    shed = [r for r in results if not r.ok]
    ok = [r for r in results if r.ok]
    assert shed, "the injected straggler should shed at least one deadline"
    for s, r in zip(small, results):
        if r.ok:
            assert r.output.shape[0] == s.coords.shape[0]


# --- deploy-time AOT prewarm + warm-replica snapshots (ISSUE 10) ----------


def _prewarm_manifest(setup, tmp_path, n=2, traffic=None):
    """Deploy-time pass for an n-replica topology: AOT-compile +
    snapshot the program family, return (manifest, traffic)."""
    from gnot_tpu.serve import aot

    if traffic is None:
        import serve_smoke

        traffic = serve_smoke.mixed_traffic(8)
    deploy = _make_replicas(setup, n)
    manifest = aot.prewarm_deployment(
        [(r.replica_id, r.engine) for r in deploy],
        traffic,
        rows=MAX_BATCH,
        snapshot_dir=str(tmp_path / "snap"),
    )
    return manifest, traffic


def test_aot_manifest_roundtrip_and_params_guard(setup, tmp_path):
    """The deploy manifest round-trips through disk (version-checked),
    and a snapshot compiled for a DIFFERENT param structure refuses to
    hydrate — the engine stays on the (correct, cold) jit path instead
    of feeding a foreign executable a mismatched tree mid-traffic."""
    from gnot_tpu.serve import aot

    model, params, samples, _ = setup
    manifest, traffic = _prewarm_manifest(setup, tmp_path, n=1)
    path = str(tmp_path / "manifest.json")
    aot.save_manifest(path, manifest)
    loaded = aot.load_manifest(path)
    assert loaded["program_keys"] == manifest["program_keys"]
    assert loaded["per_replica"]["0"]["params_sig"]
    # Unknown schema versions are rejected loudly.
    bad = dict(loaded, version=99)
    aot.save_manifest(str(tmp_path / "bad.json"), bad)
    with pytest.raises(ValueError, match="version"):
        aot.load_manifest(str(tmp_path / "bad.json"))
    # A params-structure mismatch skips every snapshot.
    (twin,) = _make_replicas(setup, 1)
    block = loaded["per_replica"]["0"]
    stats = aot.hydrate(
        twin.engine, block["programs"], loaded["snapshot_dir"],
        params_sig="definitely-not-this-model",
    )
    assert stats == {
        "installed": 0,
        "skipped": len(block["programs"]),
        "seconds": stats["seconds"],
        "keys": [],
        "reason": "params_mismatch",
    }
    # A mismatch surfaced through a replica's warm_stats carries the
    # reason (the router/CLI print it instead of silently serving cold).
    (guarded,) = _make_replicas(setup, 1)
    doctored = dict(loaded)
    doctored["per_replica"] = {
        "0": {**block, "params_sig": "some-other-model"}
    }
    ws = guarded.prewarm_from(doctored)
    assert ws["reason"] == "params_mismatch" and ws["programs"] == 0
    assert ws["source"] == "none"  # a refused hydration is NOT "snapshot"
    # Scale-out past the manifest's topology: degrade-to-cold, no crash.
    nb = guarded.prewarm_from({"per_replica": {}, "snapshot_dir": "/x"})
    assert nb["reason"] == "no_manifest_block" and nb["source"] == "none"
    # The honest signature hydrates everything.
    ok = aot.hydrate(
        twin.engine, block["programs"], loaded["snapshot_dir"],
        params_sig=block["params_sig"],
    )
    assert ok["installed"] == len(block["programs"]) and ok["skipped"] == 0
    assert twin.engine.aot_programs == len(block["programs"])


def test_router_prewarm_first_request_never_compiles(setup, tmp_path):
    """ISSUE 10 acceptance: a prewarmed replica's first request never
    waits on a compile — hydration + the whole first-request path make
    ZERO compile-cache consultations and zero jit dispatches — and its
    time-to-ready beats a cold twin warming the same program family
    against an empty cache."""
    import serve_smoke

    from gnot_tpu.serve import ReplicaRouter
    from gnot_tpu.utils.cache import compile_cache_probe, enable_compile_cache

    manifest, traffic = _prewarm_manifest(setup, tmp_path, n=2)
    replicas = _make_replicas(setup, 2)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    router = ReplicaRouter(
        replicas, max_batch=MAX_BATCH, max_wait_ms=2.0, sink=sink
    )
    with compile_cache_probe() as probe:
        t0 = time.perf_counter()
        stats = router.prewarm_from(manifest)
        prewarm_s = time.perf_counter() - t0
        router.start()
        futs = [router.submit(s) for s in traffic]
        results = [f.result(timeout=60) for f in futs]
    summary = router.drain()
    sink.close()
    assert all(r.ok for r in results)
    # Zero cache misses — in fact zero cache REQUESTS: snapshots never
    # reach the compile path at all.
    assert probe["requests"] == 0 and probe["misses"] == 0
    for r in replicas:
        assert r.engine.dispatch_counts["jit"] == 0
        assert r.warm_stats["source"] == "snapshot"
        assert r.warm_stats["misses"] == 0 and not r.warm_stats["skipped"]
    assert sum(r.engine.dispatch_counts["aot"] for r in replicas) > 0
    assert set(stats) == {0, 1}
    # Event stream: one replica_warm per replica, snapshot provenance,
    # and the per-replica serve_summary rollup carries warmup_cache.
    warms = [
        e for e in _read_all(str(tmp_path / "serve.jsonl"))
        if e.get("event") == "replica_warm"
    ]
    assert {e["replica"] for e in warms} == {0, 1}
    assert all(e["source"] == "snapshot" and e["misses"] == 0 for e in warms)
    for rid in ("0", "1"):
        assert summary["per_replica"][rid]["warmup_cache"]["source"] == "snapshot"
    # Bounded time-to-ready vs a cold twin: the cold arm traces AND
    # compiles every program against an empty cache; hydration does
    # neither.
    before = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        enable_compile_cache(str(tmp_path / "cold_cache"))
        (cold,) = _make_replicas(setup, 1)
        t0 = time.perf_counter()
        cold.warm(traffic, rows=MAX_BATCH)
        cold_s = time.perf_counter() - t0
    finally:
        if before:
            enable_compile_cache(before)
    assert cold.warm_stats["source"] == "compile"
    assert cold.warm_stats["misses"] > 0
    assert prewarm_s < cold_s, (prewarm_s, cold_s)


def test_rolling_reload_of_prewarmed_pool_sheds_nothing(setup, tmp_path):
    """Rolling hot-reload across a PREWARMED pool under a live submit
    storm: zero requests shed, the swapped params keep dispatching
    through the hydrated AOT executables (the re-placed tree has the
    same structure/sharding, so no jit fallback and no recompile)."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, _ = setup
    manifest, traffic = _prewarm_manifest(setup, tmp_path, n=2)
    replicas = _make_replicas(setup, 2)
    host_params = jax.tree.map(np.array, jax.device_get(params))
    reloads = []

    def reload_fn(deadline_ms=None):
        reloads.append(1)
        return host_params, {"epoch": len(reloads)}

    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    router = ReplicaRouter(
        replicas, max_batch=MAX_BATCH, max_wait_ms=2.0, sink=sink,
        reload_fn=reload_fn,
    )
    router.prewarm_from(manifest)
    router.start()
    futs = [router.submit(s) for s in traffic]
    assert router.reload() == 2  # both replicas swapped mid-storm
    futs += [router.submit(s) for s in traffic]
    results = [f.result(timeout=60) for f in futs]
    summary = router.drain()
    sink.close()
    assert all(r.ok for r in results)
    assert summary["shed"] == {}
    assert summary["reloads"] == 2
    for r in replicas:
        # Post-reload dispatches still ride the snapshot executables.
        assert r.engine.dispatch_counts["jit"] == 0
    events = _read_all(str(tmp_path / "serve.jsonl"))
    steps = [e for e in events if e.get("event") == "rolling_reload"]
    assert [e["ok"] for e in steps] == [True, True]


def test_router_add_replica_scale_out(setup, tmp_path):
    """Live scale-out: a snapshot-hydrated replica joins a serving
    pool via add_replica and takes traffic — no shed, a replica_warm
    event with snapshot provenance, and both replicas in the rollup."""
    from gnot_tpu.serve import ReplicaRouter, build_replica

    model, params, samples, _ = setup
    manifest, traffic = _prewarm_manifest(setup, tmp_path, n=2)
    (r0,) = _make_replicas(setup, 1)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    router = ReplicaRouter(
        [r0], max_batch=MAX_BATCH, max_wait_ms=2.0, sink=sink
    )
    router.prewarm_from(manifest)
    router.start()
    futs = [router.submit(s) for s in traffic]
    r1 = build_replica(
        model, params, 1, jax.devices()[1:2], batch_size=MAX_BATCH
    )
    r1.prewarm_from(manifest)
    router.add_replica(r1)
    with pytest.raises(ValueError, match="already in the pool"):
        router.add_replica(r1)
    probe = r1.server.submit(traffic[0])
    assert probe.result(timeout=60).ok
    futs += [router.submit(s) for s in traffic]
    results = [f.result(timeout=60) for f in futs]
    summary = router.drain()
    sink.close()
    assert all(r.ok for r in results)
    assert summary["shed"] == {}
    assert set(summary["per_replica"]) == {"0", "1"}
    assert summary["per_replica"]["1"]["warmup_cache"]["source"] == "snapshot"
    events = _read_all(str(tmp_path / "serve.jsonl"))
    warms = [e for e in events if e.get("event") == "replica_warm"]
    assert {e["replica"] for e in warms} == {0, 1}
    routed_to_new = [
        e for e in events
        if e.get("event") == "route" and e["replica"] == 1
    ]
    assert routed_to_new, "scale-out replica never took routed traffic"


def test_serve_smoke_tool_prewarm(tmp_path):
    """Tier-1 wiring of serve_smoke --prewarm (ISSUE 10 CI criterion):
    the mixed-bucket storm over a snapshot-hydrated replica pool passes
    with ZERO per-replica compiles — the smoke asserts zero
    compile-cache consultations and zero jit-fallback dispatches."""
    import serve_smoke

    summary = serve_smoke.run(
        [
            "--n", "10", "--replicas", "2", "--prewarm",
            "--inject_fault", "slow_request@3",
            "--metrics_path", str(tmp_path / "smoke.jsonl"),
        ]
    )
    assert summary["failures"] == []
    assert summary["shed"].get("shed_deadline", 0) >= 1


@pytest.mark.slow
def test_coldstart_ab_quick_smoke(tmp_path):
    """tools/coldstart_ab.py --quick end-to-end (in-process: structure
    and bookkeeping, not the committed artifact's 5x bar, which
    test_artifacts pins): both arms scale out 1->2, the prewarmed arm
    sheds nothing, and the speedup is positive."""
    import coldstart_ab

    out = str(tmp_path / "ab.jsonl")
    summary = coldstart_ab.run(["--quick", "--out", out])
    assert summary["failures"] == []
    recs = [json.loads(l) for l in open(out) if l.strip()]
    arms = {r["arm"] for r in recs if "arm" in r}
    assert arms == {"deploy", "cold", "prewarmed"}
    assert summary["shed_prewarmed"] == 0
    assert summary["speedup"] > 1.0


# --- low-precision serving (ISSUE 12): serve.dtype = bf16 -----------------


def test_bf16_server_storm_end_to_end(setup, tmp_path):
    """A bf16 server serves the same traffic the f32 server does: every
    request completes, responses are f32 (the policy head) and within
    the parity bar of the f32 engine's answers, the summary names its
    dtype, and the compiled-program bound holds (bf16 programs are
    dtype-keyed, not extra shapes)."""
    import serve_smoke

    model, params, samples, f32_engine = setup
    engine = InferenceEngine(
        model, params, batch_size=MAX_BATCH, dtype="bfloat16"
    )
    traffic = serve_smoke.mixed_traffic(8, seed=3)
    engine.warmup(traffic, rows=MAX_BATCH)
    server, sink, path = make_server(setup, tmp_path, engine=engine)
    server.start()
    futures = [server.submit(s) for s in traffic]
    results = [f.result(timeout=60) for f in futures]
    summary = server.drain()
    sink.close()
    assert all(r.ok for r in results)
    assert summary["dtype"] == "bfloat16"
    f32_engine.warmup(traffic, rows=MAX_BATCH)
    for s, r in zip(traffic, results):
        assert r.output.dtype == np.float32
        key = f32_engine.bucket_key(s)
        ref = f32_engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        rel = np.linalg.norm(r.output - ref) / max(
            np.linalg.norm(ref), 1e-12
        )
        assert rel < 2e-2, f"bf16 response drifted {rel} from f32"
    buckets = {f32_engine.bucket_key(s) for s in traffic}
    assert summary["compiled_shapes"] <= len(buckets)


def test_bf16_aot_roundtrip_serves_with_zero_jit_fallbacks(setup, tmp_path):
    """ISSUE 12 acceptance: AOT prewarm/hydrate round-trips dtype-keyed
    programs — a bf16 deployment hydrates a bf16 manifest (keys carry
    the @bf16 tag) and serves its first requests entirely through the
    installed executables: zero jit fallbacks."""
    import serve_smoke

    from gnot_tpu.serve import aot

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(6, seed=4)
    deploy = InferenceEngine(
        model, params, batch_size=MAX_BATCH, dtype="bfloat16"
    )
    manifest = aot.prewarm_deployment(
        [(0, deploy)], traffic, rows=MAX_BATCH,
        snapshot_dir=str(tmp_path / "snap"),
    )
    assert manifest["dtype"] == "bfloat16"
    assert all(k.endswith("@bf16") for k in manifest["program_keys"])
    fresh = InferenceEngine(
        model, params, batch_size=MAX_BATCH, dtype="bfloat16"
    )
    stats = aot.hydrate_block(fresh, manifest, 0)
    assert stats["installed"] == len(manifest["program_keys"])
    assert stats["skipped"] == 0
    for s in traffic:
        key = fresh.bucket_key(s)
        out = fresh.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        assert out.shape[0] == s.coords.shape[0]
    counts = fresh.dispatch_counts
    assert counts["jit"] == 0 and counts["aot"] == len(traffic)


def test_dtype_mismatched_snapshots_are_refused_wholesale(setup, tmp_path):
    """A bf16 deployment handed an f32 manifest (or vice versa) must
    refuse EVERY snapshot with the named reason and serve cold — an
    f32 executable at a bf16 deployment's shapes is the wrong program,
    not a warm one. The replica warm_stats surface the refusal."""
    import serve_smoke

    from gnot_tpu.serve import aot

    model, params, _, _ = setup
    traffic = serve_smoke.mixed_traffic(4, seed=5)
    f32_manifest, _ = _prewarm_manifest(
        setup, tmp_path, n=1, traffic=traffic
    )
    assert f32_manifest["dtype"] == "float32"
    assert all(k.endswith("@f32") for k in f32_manifest["program_keys"])
    bf16_engine = InferenceEngine(
        model, params, batch_size=MAX_BATCH, dtype="bfloat16"
    )
    stats = aot.hydrate_block(bf16_engine, f32_manifest, 0)
    assert stats["installed"] == 0
    assert stats["skipped"] == len(f32_manifest["program_keys"])
    assert stats["reason"] == "dtype_mismatch"
    assert bf16_engine.aot_programs == 0
    # A v1-era manifest (predates serving dtypes) cannot even load.
    stale = dict(f32_manifest, version=1)
    aot.save_manifest(str(tmp_path / "stale.json"), stale)
    # save_manifest re-stamps the current version; doctor it back.
    doc = json.load(open(str(tmp_path / "stale.json")))
    doc["version"] = 1
    json.dump(doc, open(str(tmp_path / "stale.json"), "w"))
    with pytest.raises(ValueError, match="version"):
        aot.load_manifest(str(tmp_path / "stale.json"))
    # The reverse direction refuses too (f32 engine, bf16 manifest).
    deploy = InferenceEngine(
        model, params, batch_size=MAX_BATCH, dtype="bfloat16"
    )
    bf16_manifest = aot.prewarm_deployment(
        [(0, deploy)], traffic, rows=MAX_BATCH,
        snapshot_dir=str(tmp_path / "snap2"),
    )
    (twin,) = _make_replicas(setup, 1)
    ws = twin.prewarm_from(bf16_manifest)
    assert ws["reason"] == "dtype_mismatch" and ws["source"] == "none"


def test_router_reports_dtype_on_routes_and_summary(setup, tmp_path):
    """The replica/router plumbing names the serving dtype: every route
    event and the pool serve_summary carry it (the A/B artifact's
    attribution chain)."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, _ = setup
    replicas = _make_replicas(setup, 2, dtype="bfloat16")
    for r in replicas:
        assert r.engine.dtype == "bfloat16"
        r.warm(samples[:2], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    router = ReplicaRouter(
        replicas, max_batch=MAX_BATCH, max_wait_ms=2.0, sink=sink
    )
    router.start()
    futures = [router.submit(s) for s in samples[:6]]
    for f in futures:
        assert f.result(timeout=60).ok
    summary = router.drain()
    sink.close()
    assert summary["dtype"] == "bfloat16"
    events = read_events(str(tmp_path / "serve.jsonl"))
    routes = [e for e in events if e.get("event") == "route"]
    assert routes and all(e["dtype"] == "bfloat16" for e in routes)
    pool = [
        e for e in events
        if e.get("event") == "serve_summary" and "routing" in e
    ]
    assert pool and pool[0]["dtype"] == "bfloat16"


@pytest.mark.slow
def test_lowprec_ab_quick_smoke(tmp_path):
    """tools/lowprec_ab.py --quick end-to-end (in-process: structure
    and bookkeeping, not the committed artifact's bars, which
    test_artifacts pins): parity within the bar on the quick dataset,
    both serve arms measured, the host-phase arms recorded."""
    import lowprec_ab

    out = str(tmp_path / "ab.jsonl")
    summary = lowprec_ab.run(["--quick", "--out", out])
    assert summary["quick"] is True
    assert summary["parity_max_delta"] <= summary["parity_bar"]
    recs = [json.loads(l) for l in open(out) if l.strip()]
    arms = {r.get("arm") for r in recs if "arm" in r}
    assert {"serve_f32", "serve_bf16", "host_python", "host_native"} <= arms
    assert summary["bf16_dispatch_slowdown_cpu"] > 0


# --- rollout serving: stateful sessions (ISSUE 13) ------------------------


def _offline(engine, sample, steps):
    from gnot_tpu.serve import offline_rollout

    return offline_rollout(engine, sample, steps, rows=MAX_BATCH)


def test_serve_config_validates_rollout_knobs():
    with pytest.raises(ValueError, match="rollout_steps"):
        make_config(**{"serve.rollout_steps": -1})
    with pytest.raises(ValueError, match="session_snapshot_every"):
        make_config(**{"serve.session_snapshot_every": 0})
    cfg = make_config(
        **{"serve.rollout_steps": 8, "serve.session_snapshot_every": 2}
    )
    assert cfg.serve.rollout_steps == 8


def test_rollout_session_completes_streams_and_matches_offline(
    setup, tmp_path
):
    """THE basic rollout contract: K chained dispatches, each step
    streamed (iterator AND callback) exactly once in order, carry
    advanced between steps, rollout_step/session_snapshot events at
    the configured cadence, a sessions rollup in serve_summary — and
    the served trajectory matches the offline engine-only loop."""
    model, params, samples, engine = setup
    K = 4
    server, sink, path = make_server(
        setup, tmp_path, session_snapshot_every=2
    )
    pushed = []
    with sink:
        server.start()
        fut = server.submit_rollout(
            samples[0], K, on_step=lambda sid, k, out: pushed.append(k)
        )
        streamed = list(fut.iter_steps(timeout=30))
        res = fut.result(timeout=30)
        summary = server.drain()
    assert res.ok and res.reason == "ok"
    assert res.steps == K and res.steps_completed == K
    assert [k for k, _ in streamed] == [1, 2, 3, 4] == pushed
    ref = _offline(engine, samples[0], K)
    for got, want in zip(res.outputs, ref):
        np.testing.assert_allclose(got, want, atol=1e-5)
    for (_, out), want in zip(streamed, ref):
        np.testing.assert_allclose(out, want, atol=1e-5)
    events = read_events(path)
    steps = [e for e in events if e["event"] == "rollout_step"]
    assert [e["step"] for e in steps] == [1, 2, 3, 4]
    assert all(
        e["session"] == res.session and e["steps"] == K for e in steps
    )
    # Snapshot cadence 2: snapshots at steps 2 and 4... but the final
    # step completes the session (no snapshot needed), so exactly the
    # step-2 rolling snapshot lands.
    snaps = [e for e in events if e["event"] == "session_snapshot"]
    assert [e["step"] for e in snaps] == [2]
    sess = summary["sessions"]
    assert sess["started"] == 1 and sess["completed"] == 1
    assert sess["steps"] == K
    assert sess["step_latency_p50_ms"] <= sess["step_latency_p99_ms"]


def test_rollout_drain_resolves_partial_with_marker(setup, tmp_path):
    """ISSUE 13 satellite: drain mid-rollout resolves the session
    future with the completed prefix plus a terminal drained_at_step
    marker and a shed event carrying the session id — never a hang.
    (The one-shot drain guarantee, extended to multi-step sessions.)"""
    model, params, samples, engine = setup
    server, sink, path = make_server(setup, tmp_path)
    with sink:
        server.start()
        fut = server.submit_rollout(samples[0], 50)
        it = fut.iter_steps(timeout=30)
        next(it)
        next(it)  # at least two steps committed
        server.drain()
        res = fut.result(timeout=5)  # resolved, no hang
    assert not res.ok and res.reason == "drained"
    assert 2 <= res.steps_completed < 50
    assert res.drained_at_step == res.steps_completed
    assert len(res.outputs) == res.steps_completed
    # The completed prefix is still the true trajectory prefix.
    ref = _offline(engine, samples[0], res.steps_completed)
    for got, want in zip(res.outputs, ref):
        np.testing.assert_allclose(got, want, atol=1e-5)
    events = read_events(path)
    sheds = [
        e for e in events
        if e["event"] == "shed" and e.get("session") == res.session
    ]
    assert sheds and sheds[-1]["reason"] == "drained"
    # The drain persisted a final snapshot at the stop point.
    snaps = [e for e in events if e["event"] == "session_snapshot"]
    assert snaps[-1]["step"] == res.drained_at_step
    sess = [
        e for e in events if e["event"] == "serve_summary"
    ][0]["sessions"]
    assert sess["drained"] == 1 and sess["completed"] == 0


def test_rollout_sigterm_drain_resolves_every_session(setup, tmp_path):
    """ISSUE 13 acceptance: SIGTERM during a rollout storm resolves
    EVERY session future — completed or partial-with-marker, no hangs,
    no orphaned sessions left resident."""
    with PreemptionHandler() as preempt:
        server, sink, path = make_server(
            setup, tmp_path, preempt=preempt, max_wait_ms=2.0
        )
        _, _, samples, _ = setup
        server.start()
        futs = [server.submit_rollout(s, 25) for s in samples[:4]]
        time.sleep(0.1)  # some steps commit
        os.kill(os.getpid(), signal.SIGTERM)
        results = [f.result(timeout=30) for f in futs]
        summary = server.drain()
        sink.close()
    for r in results:
        assert r.ok or (
            r.reason == "drained" and r.drained_at_step is not None
        ), (r.reason, r.detail)
    sess = summary["sessions"]
    assert sess["resident"] == 0  # no orphaned device/session state
    assert sess["completed"] + sess["drained"] + sess["shed"] == 4
    # Streams all terminated too (no consumer left blocked).
    for f in futs:
        assert list(f.iter_steps(timeout=1)) is not None


def test_rollout_per_step_deadline_shed(setup, tmp_path):
    """ISSUE 13 satellite: a per-step deadline expiry (injected
    straggler stalling step 1) sheds the SESSION with the correct
    reason — partial outputs, shed event carrying the session id."""
    server, sink, path = make_server(
        setup,
        tmp_path,
        default_deadline_ms=150.0,
        faults=FaultInjector.from_spec("slow_request@1"),
    )
    _, _, samples, _ = setup
    with sink:
        server.start()
        fut = server.submit_rollout(samples[0], 4)
        res = fut.result(timeout=30)
        server.drain()
    assert not res.ok and res.reason == "shed_deadline"
    assert res.steps_completed == 0 and res.outputs == []
    sheds = [
        e for e in read_events(path)
        if e["event"] == "shed" and e.get("session") == res.session
    ]
    assert sheds and sheds[-1]["reason"] == "shed_deadline"


def test_rollout_whole_budget_shed(setup, tmp_path):
    """The whole-rollout deadline bounds the trajectory: a generous
    per-step budget still ends the session when the rollout budget
    runs out (reason shed_deadline, partial prefix intact)."""
    model, params, samples, engine = setup
    server, sink, path = make_server(setup, tmp_path)
    with sink:
        server.start()
        fut = server.submit_rollout(
            samples[0], 500, rollout_deadline_ms=250.0
        )
        res = fut.result(timeout=30)
        server.drain()
    assert not res.ok and res.reason == "shed_deadline"
    assert 0 < res.steps_completed < 500
    ref = _offline(engine, samples[0], min(res.steps_completed, 3))
    for got, want in zip(res.outputs[:3], ref):
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_rollout_replica_kill_migrates_and_matches_offline(setup, tmp_path):
    """THE ISSUE 13 chaos scenario: replica 0 dies mid-rollout
    (replica_kill) — every orphaned session migrates to the sibling
    from its snapshot, replays forward, and completes with outputs
    matching the offline engine-only rollout; zero lost sessions; the
    dead replica's health edge lands in the event stream."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, engine = setup
    K = 4
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            session_snapshot_every=2,
            faults={0: FaultInjector.from_spec("replica_kill@2")},
        ).start()
        futs = [router.submit_rollout(s, K) for s in samples[:4]]
        results = [f.result(timeout=60) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.reason, r.detail) for r in results
    ]
    sess = summary["sessions"]
    assert sess["lost"] == 0 and sess["completed"] == 4
    assert sess["migrated"] >= 1
    for s, r in zip(samples[:4], results):
        ref = _offline(engine, s, K)
        assert len(r.outputs) == K
        for got, want in zip(r.outputs, ref):
            np.testing.assert_allclose(got, want, atol=1e-5)
    events = _read_all(str(tmp_path / "serve.jsonl"))
    migs = [e for e in events if e.get("event") == "session_migrate"]
    assert migs and all(
        e["from_replica"] == 0 and e["to_replica"] == 1
        and e["reason"] == "error_replica_dead"
        and e["replay_from"] <= e["at_step"]
        for e in migs
    )
    assert any(
        e.get("event") == "replica_health" and e["reason"] == "dead"
        and e["replica"] == 0
        for e in events
    )
    # Migrated sessions committed each step exactly once client-side:
    # rollout_step coverage per session is exactly 1..K.
    by_session: dict = {}
    for e in events:
        if e.get("event") == "rollout_step":
            by_session.setdefault(e["session"], set()).add(e["step"])
    for r in results:
        assert by_session[r.session] == set(range(1, K + 1))


def test_rollout_breaker_trip_mid_session_migrates(setup, tmp_path):
    """Breaker trip mid-session (rollout_nan trips a threshold-1
    breaker on the owner): the session is handed to a sibling instead
    of dying behind the sick backend, and its trajectory still matches
    the offline loop — the poisoned step was replayed, never
    committed."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, engine = setup
    K = 4
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            breaker_threshold=1,
            breaker_cooldown_s=30.0,  # stays open for the whole test
            faults={0: FaultInjector.from_spec("rollout_nan@2")},
        ).start()
        futs = [router.submit_rollout(s, K) for s in samples[:4]]
        results = [f.result(timeout=60) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.reason, r.detail) for r in results
    ]
    assert summary["sessions"]["lost"] == 0
    assert summary["sessions"]["migrated"] >= 1
    assert summary["breaker_trips"] >= 1
    for s, r in zip(samples[:4], results):
        ref = _offline(engine, s, K)
        for got, want in zip(r.outputs, ref):
            np.testing.assert_allclose(got, want, atol=1e-5)
    events = _read_all(str(tmp_path / "serve.jsonl"))
    migs = [e for e in events if e.get("event") == "session_migrate"]
    assert migs and all(e["to_replica"] == 1 for e in migs)
    assert any(e.get("event") == "breaker_open" for e in events)


def test_rollout_stale_session_replays_from_snapshot(setup, tmp_path):
    """stale_session: the resident carry is lost under a live session —
    the step fails error_stale_session, the session restores from its
    snapshot on a sibling, and the trajectory is still exact."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, engine = setup
    K = 4
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            session_snapshot_every=1,
            faults={0: FaultInjector.from_spec("stale_session@2")},
        ).start()
        futs = [router.submit_rollout(s, K) for s in samples[:2]]
        results = [f.result(timeout=60) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.reason, r.detail) for r in results
    ]
    assert summary["sessions"]["lost"] == 0
    for s, r in zip(samples[:2], results):
        ref = _offline(engine, s, K)
        for got, want in zip(r.outputs, ref):
            np.testing.assert_allclose(got, want, atol=1e-5)
    events = _read_all(str(tmp_path / "serve.jsonl"))
    migs = [e for e in events if e.get("event") == "session_migrate"]
    assert migs and migs[0]["reason"] == "error_stale_session"
    # snapshot_every=1: the replay resumed from the failure point, no
    # committed step was re-run.
    assert migs[0]["replay_from"] == migs[0]["at_step"]


def test_rollout_rolling_reload_keeps_sessions_serving(setup, tmp_path):
    """ISSUE 13 satellite: a rolling hot-reload with live sessions —
    the warming replica keeps serving ITS resident sessions to
    completion (only NEW placements drain to siblings), every session
    completes, zero lost/shed."""
    from gnot_tpu.serve import ReplicaRouter

    model, params, samples, _ = setup
    host_params = jax.tree.map(np.array, jax.device_get(params))
    reloads = []

    def reload_fn(deadline_ms=None):
        reloads.append(1)
        return host_params, {"epoch": len(reloads)}

    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            reload_fn=reload_fn,
        ).start()
        futs = [router.submit_rollout(s, 8) for s in samples[:4]]
        assert router.reload() == 2  # rolling, mid-storm
        results = [f.result(timeout=60) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.reason, r.detail) for r in results
    ]
    sess = summary["sessions"]
    assert sess["completed"] == 4 and sess["lost"] == 0
    assert summary["shed"] == {}
    events = _read_all(str(tmp_path / "serve.jsonl"))
    steps = [e for e in events if e.get("event") == "rolling_reload"]
    assert [e["ok"] for e in steps] == [True, True]


def test_rollout_mixed_with_oneshot_keeps_bucket_discipline(
    setup, tmp_path
):
    """Concurrent one-shot + rollout traffic: bucket discipline holds
    (no dispatch outside a real bucket), both kinds resolve, and the
    summary carries both the request counters and the sessions
    rollup."""
    model, params, samples, engine = setup
    server, sink, path = make_server(setup, tmp_path, max_wait_ms=2.0)
    with sink:
        server.start()
        one_shot = [server.submit(s) for s in samples[:4]]
        sessions = [server.submit_rollout(s, 3) for s in samples[4:6]]
        ones = [f.result(timeout=30) for f in one_shot]
        rolls = [f.result(timeout=30) for f in sessions]
        summary = server.drain()
    assert all(r.ok for r in ones)
    assert all(r.ok for r in rolls)
    # One-shot answers are unaffected by the session traffic sharing
    # their buckets/dispatches.
    for s, r in zip(samples[:4], ones):
        key = engine.bucket_key(s)
        solo = engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(r.output, solo, rtol=1e-5, atol=1e-5)
    events = read_events(path)
    dispatches = [e for e in events if e["event"] == "queue_depth"]
    keys = {engine.bucket_key(s) for s in samples[:6]}
    assert {
        (e["bucket_nodes"], e["bucket_funcs"]) for e in dispatches
    } <= keys
    assert summary["sessions"]["completed"] == 2
    assert summary["completed"] == 4 + 2 * 3  # requests + steps


def test_router_load_accounting_counts_resident_sessions(setup, tmp_path):
    """ISSUE 13 satellite (the load-accounting audit): a replica
    holding a resident session must not be preferred for new
    placements even when its visible queue depth ties the sibling's."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        # Workers NOT started: queues only fill, state is frozen.
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH,
            route_policy="least_loaded",
        )
        f0 = router.submit_rollout(samples[0], 5)  # -> replica 0
        assert replicas[0].server.resident_sessions() == 1
        # Both replicas now hold ONE in-system request each (the
        # session's queued step vs nothing yet on 1): the next two
        # placements must both prefer replica 1 — depth ties at the
        # second, and only the session accounting breaks it.
        f1 = router.submit(samples[1])  # depths 1 vs 0 -> replica 1
        f2 = router.submit(samples[2])  # 1+1session vs 1 -> replica 1
        routes = [
            e for e in _read_all(str(tmp_path / "serve.jsonl"))
            if e.get("event") == "route"
        ]
        assert [e["replica"] for e in routes] == [0, 1, 1]
        assert routes[0].get("session")  # session placement is tagged
        router.drain()
        for f in (f1, f2):
            assert f.result(timeout=5).reason == "rejected_draining"
        assert f0.result(timeout=5).reason in ("drained",)


def test_serve_smoke_tool_rollout(tmp_path):
    """Tier-1 wiring of tools/serve_smoke.py --rollout: the K-step
    session storm through the 2-replica router passes every session
    assertion (one rollout_step per step, affinity honored, zero lost
    sessions)."""
    import serve_smoke

    summary = serve_smoke.run(
        [
            "--n", "6", "--rollout", "3", "--replicas", "2",
            "--metrics_path", str(tmp_path / "smoke.jsonl"),
        ]
    )
    assert summary["failures"] == []
    assert summary["sessions"]["completed"] == 6
    assert summary["sessions"]["lost"] == 0


@pytest.mark.slow
def test_rollout_ab_quick_smoke(tmp_path):
    """tools/rollout_ab.py --quick end-to-end (in-process: structure
    and bookkeeping, not the committed artifact's bars, which
    test_artifacts pins): migration arm loses nothing, the twin loses
    measurably, parity within the bar."""
    import rollout_ab

    out = str(tmp_path / "ab.jsonl")
    summary = rollout_ab.run(["--quick", "--out", out])
    assert summary["failures"] == []
    assert summary["lost_migration"] == 0
    assert summary["lost_no_migration"] >= 1
    assert summary["max_abs_diff"] <= summary["bar_numeric"]


def test_rollout_whole_pool_death_resolves_lost_not_hang(setup, tmp_path):
    """Code-review regression: when EVERY replica dies mid-rollout the
    router must resolve the orphaned sessions as lost — re-placing onto
    a dead sibling would swallow the step into a queue nobody drains
    and hang the future forever."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            faults={
                0: FaultInjector.from_spec("replica_kill@1"),
                1: FaultInjector.from_spec("replica_kill@1"),
            },
        ).start()
        futs = [router.submit_rollout(s, 6) for s in samples[:4]]
        # The futures MUST resolve (lost), well inside the timeout.
        results = [f.result(timeout=30) for f in futs]
        summary = router.drain()
    assert all(not r.ok for r in results)
    assert {r.reason for r in results} == {"error_replica_dead"}
    sess = summary["sessions"]
    assert sess["lost"] == 4 and sess["completed"] == 0
    # Streams terminated too — no consumer left blocked.
    for f in futs:
        list(f.iter_steps(timeout=1))


# --- multi-tenant isolation (docs/serving.md "Multi-tenant isolation") ----


def _tenant_policy(**kw):
    from gnot_tpu.serve import TenantPolicy

    kw.setdefault("weights", "interactive:3,batch:1")
    return TenantPolicy.from_specs(**kw)


def test_tenant_spec_parsing_and_config_validation():
    from gnot_tpu.config import parse_tenant_spec
    from gnot_tpu.serve import TenantPolicy

    assert parse_tenant_spec("interactive:3, batch:1", what="weight") == {
        "interactive": "3",
        "batch": "1",
    }
    assert parse_tenant_spec("") == {}
    with pytest.raises(ValueError, match="weight"):
        parse_tenant_spec("interactive", what="weight")
    with pytest.raises(ValueError, match="duplicate"):
        parse_tenant_spec("a:1,a:2")
    with pytest.raises(ValueError, match="tenant weight"):
        make_config(**{"serve.tenant_weights": "a:0"})
    with pytest.raises(ValueError, match="tenant quota"):
        make_config(**{"serve.tenant_quotas": "a:none"})
    with pytest.raises(ValueError, match="tenant priority"):
        make_config(**{"serve.tenant_priorities": "a:urgent"})
    cfg = make_config(
        **{
            "serve.tenant_weights": "interactive:3,batch:1",
            "serve.tenant_quotas": "batch:4",
        }
    )
    assert cfg.serve.tenant_weights == "interactive:3,batch:1"
    # All-empty specs mean the plane is OFF, not a vacuous policy.
    assert TenantPolicy.from_specs() is None
    pol = _tenant_policy(quotas="batch:2")
    assert pol.tenants == ["batch", "interactive"]
    assert pol.weight("interactive") == 3 and pol.weight("unlisted") == 1
    # Unlisted tenants are interactive-class — except one literally
    # named "batch", so the README example reads the way it behaves.
    assert pol.priority("interactive") == "interactive"
    assert pol.priority("unlisted") == "interactive"
    assert pol.priority("batch") == "batch"
    assert pol.quota("interactive") is None and pol.quota("batch") == 2
    assert pol.try_admit("batch") and pol.try_admit("batch")
    assert not pol.try_admit("batch")  # quota full -> O(1) fast-fail
    assert pol.try_admit("interactive")  # un-quota'd: never limited
    pol.release("batch")
    assert pol.try_admit("batch")


def test_batcher_wfq_weighted_drain_and_fifo_within_tenant():
    """The WFQ drain contract: within one bucket and one priority tier,
    a 3:1-weighted pair of tenants shares each dispatch 3:1 (deficit
    round robin), and each tenant's own requests dispatch in arrival
    order (FIFO within tenant)."""
    pol = _tenant_policy(
        weights="alice:3,bob:1", priorities="alice:interactive,bob:interactive"
    )
    b = Batcher(
        max_batch=4, max_wait_ms=50, key_fn=lambda r: "k",
        tenants=pol, tenant_fn=lambda r: r[0],
    )
    # Interleave arrivals bob-first so weight (not arrival order) must
    # explain the drain mix.
    for i in range(8):
        b.add(("bob", i), now=0.001 * (2 * i))
        b.add(("alice", i), now=0.001 * (2 * i + 1))
    batches = b.pop_ready(1.0, flush_all=True)
    assert [len(reqs) for _, reqs in batches] == [4, 4, 4, 4]
    # 3:1 per dispatch while both queues are non-empty: alice's 8 ride
    # the first three cuts (3+3+2), bob backfills the remainder.
    mixes = [
        [t for t, _ in reqs].count("alice") for _, reqs in batches
    ]
    assert mixes == [3, 3, 2, 0]
    for tenant in ("alice", "bob"):
        served = [
            i for _, reqs in batches for t, i in reqs if t == tenant
        ]
        assert served == sorted(served)  # FIFO within tenant


def test_batcher_priority_tier_drains_interactive_first():
    """Strict priority tiers: every interactive-class request in a
    bucket dispatches before ANY batch-class one — even when the batch
    work arrived first — and an in-flight inversion is bounded by ONE
    dispatch (the cut that left before the interactive work existed)."""
    pol = _tenant_policy(quotas="")
    b = Batcher(
        max_batch=2, max_wait_ms=50, key_fn=lambda r: "k",
        tenants=pol, tenant_fn=lambda r: r[0],
    )
    b.add(("batch", 0), now=0.0)
    b.add(("batch", 1), now=0.0)
    # The pre-existing inversion: a full batch-class cut leaves while
    # no interactive work exists. That one dispatch is the bound.
    [(_, first)] = b.pop_ready(0.001)
    assert [t for t, _ in first] == ["batch", "batch"]
    # Now both classes queue together: interactive preempts everything
    # still queued, batch backfills only after it drains.
    for i in range(2, 6):
        b.add(("batch", i), now=0.002)
    for i in range(4):
        b.add(("interactive", i), now=0.003)
    batches = b.pop_ready(1.0, flush_all=True)
    order = [t for _, reqs in batches for t, _ in reqs]
    assert order == ["interactive"] * 4 + ["batch"] * 4


def test_batcher_tenant_aged_flush_is_per_request():
    """Satellite regression (max_wait audit): the age clock is the
    OLDEST ARRIVAL ANYWHERE in the bucket — not the head of whichever
    sub-queue WFQ favors — so a lone lowest-weight request's wait is
    bounded by max_wait_ms even while a heavier sibling keeps arriving,
    and the aged flush takes the whole bucket (the victim rides it)."""
    pol = _tenant_policy(weights="alice:9,bob:1")
    b = Batcher(
        max_batch=8, max_wait_ms=100, key_fn=lambda r: "k",
        tenants=pol, tenant_fn=lambda r: r[0],
    )
    b.add(("bob", 0), now=0.0)  # the lowest-weight victim
    b.add(("alice", 0), now=0.09)  # newer, heavier sibling
    # Not aged yet at t=0.05; the flush countdown reads BOB's arrival.
    assert b.pop_ready(0.05) == []
    assert b.next_flush_in(0.05) == pytest.approx(0.05)
    # At t=0.1 bob's budget is spent: the partial bucket flushes WHOLE
    # (both tenants), so bob's worst-case wait == max_wait_ms exactly.
    [(_, reqs)] = b.pop_ready(0.1)
    assert {t for t, _ in reqs} == {"alice", "bob"}
    assert len(b) == 0 and b.next_flush_in(0.2) is None
    # Leftovers from a size-based cut keep their TRUE arrival time: a
    # bob request surviving a full cut must not have its clock reset.
    for i in range(9):
        b.add(("alice", i), now=0.2)
    b.add(("bob", 1), now=0.2)
    batches = b.pop_ready(0.201)  # one full 8-wide cut leaves
    assert [len(reqs) for _, reqs in batches] == [8]
    # 2 remain (alice's 9th + bob's); their age still counts from 0.2.
    assert b.next_flush_in(0.25) == pytest.approx(0.05)
    [(_, rest)] = b.pop_ready(0.301)
    assert {t for t, _ in rest} == {"alice", "bob"}


def test_batcher_untagged_mode_unchanged_by_tenant_code():
    """Default-path pin at the batcher level: with ``tenants=None`` the
    structure never consults tenant_fn and behaves exactly like the
    single-FIFO batcher, whatever tenant attributes requests carry."""
    seen = []

    def tenant_fn(r):
        seen.append(r)
        return "x"

    b = Batcher(
        max_batch=2, max_wait_ms=100, key_fn=lambda r: r[0],
        tenant_fn=tenant_fn,
    )
    b.add(("a", 1), now=0.0)
    b.add(("a", 2), now=0.01)
    [(key, reqs)] = b.pop_ready(0.02)
    assert key == "a" and [i for _, i in reqs] == [1, 2]
    assert seen == []  # tenant plumbing never ran


def test_tenant_quota_exhaustion_never_blocks_sibling(setup, tmp_path):
    """Chaos: one tenant exhausts its quota while the queue is stalled
    (nothing dispatches before drain) — its overflow FAST-fails with
    tenant-tagged events, the sibling's admissions are untouched, and
    the per-tenant summary rollup matches number-for-number."""
    server, sink, path = make_server(
        setup, tmp_path, max_wait_ms=10_000, tenants=_tenant_policy(
            quotas="batch:2"
        ),
    )
    _, _, samples, _ = setup
    with sink:
        server.start()
        held = [
            server.submit(samples[i], tenant="batch") for i in range(2)
        ]
        overflow = [
            server.submit(samples[2 + i], tenant="batch") for i in range(3)
        ]
        # Fast-fail means NOW — the queue is stalled (10 s max_wait),
        # yet the over-quota futures resolve immediately.
        for f in overflow:
            assert f.result(timeout=1).reason == "shed_tenant_quota"
        # The sibling admits freely past the batch quota wall.
        inter = [
            server.submit(samples[5 + i], tenant="interactive")
            for i in range(4)
        ]
        summary = server.drain()
        assert all(f.result(timeout=5).ok for f in held + inter)
    roll = summary["tenants"]
    assert roll["batch"] == {
        "requests": 5, "completed": 2,
        "shed": {"shed_tenant_quota": 3},
        "latency_p50_ms": roll["batch"]["latency_p50_ms"],
        "latency_p99_ms": roll["batch"]["latency_p99_ms"],
    }
    assert roll["interactive"]["requests"] == 4
    assert roll["interactive"]["completed"] == 4
    assert roll["interactive"]["shed"] == {}
    events = read_events(path)
    qevs = [e for e in events if e["event"] == "tenant_quota_shed"]
    assert len(qevs) == 3
    assert all(
        e["tenant"] == "batch" and e["quota"] == 2 and e["in_system"] >= 2
        for e in qevs
    )
    # Quota releases on completion: after drain the tenant re-admits.
    assert server.tenants.try_admit("batch")
    server.tenants.release("batch")


def test_tenant_sigterm_drain_resolves_with_tenant_summaries(
    setup, tmp_path
):
    """Chaos: SIGTERM mid-storm — every tagged future resolves through
    the graceful drain and the serve_summary tenants rollup attributes
    each completion to the right tenant."""
    with PreemptionHandler() as preempt:
        server, sink, path = make_server(
            setup, tmp_path, preempt=preempt, max_wait_ms=10_000,
            tenants=_tenant_policy(),
        )
        _, _, samples, _ = setup
        server.start()
        futs = [
            server.submit(s, tenant=("interactive", "batch")[i % 2])
            for i, s in enumerate(samples[:6])
        ]
        os.kill(os.getpid(), signal.SIGTERM)
        results = [f.result(timeout=30) for f in futs]
        summary = server.drain()
        sink.close()
    assert all(r.ok for r in results)
    roll = summary["tenants"]
    assert roll["interactive"]["completed"] == 3
    assert roll["batch"]["completed"] == 3
    [summ] = [
        e for e in read_events(path) if e["event"] == "serve_summary"
    ]
    assert summ["tenants"]["batch"]["requests"] == 3


def test_untagged_traffic_coexists_with_policy(setup, tmp_path):
    """With a policy ACTIVE, untagged requests still flow (they ride
    the DEFAULT_TENANT WFQ sub-queue — interactive class, no quota) and
    the tenants rollup charges only the traffic that carried a tag."""
    server, sink, path = make_server(
        setup, tmp_path, tenants=_tenant_policy(quotas="batch:1")
    )
    _, _, samples, _ = setup
    with sink:
        server.start()
        futs = [server.submit(s) for s in samples[:3]]
        tagged = [
            server.submit(s, tenant="batch") for s in samples[3:4]
        ]
        assert all(f.result(timeout=30).ok for f in futs + tagged)
        summary = server.drain()
    roll = summary["tenants"]
    assert set(roll) == {"batch"}  # untagged traffic stays anonymous
    assert roll["batch"]["completed"] == 1
    assert summary["completed"] == 4  # global counters cover everyone


def test_tenant_summary_absent_without_policy(setup, tmp_path):
    """Default-path pin at the server level: no policy, no tags ->
    ZERO tenant footprint in the summary and the event stream."""
    server, sink, path = make_server(setup, tmp_path)
    _, _, samples, _ = setup
    with sink:
        server.start()
        futs = [server.submit(s) for s in samples[:3]]
        assert all(f.result(timeout=30).ok for f in futs)
        summary = server.drain()
    assert "tenants" not in summary
    for e in read_events(path):
        assert "tenant" not in e and "tenants" not in e
        assert "tenant" not in e["event"]


def test_rollout_session_tenant_inherited_across_migration(
    setup, tmp_path
):
    """Chaos: a tagged rollout session survives its owner's death and
    the migrated session keeps charging the SAME tenant — accounting
    follows the session, not the replica."""
    from gnot_tpu.serve import ReplicaRouter

    _, _, samples, _ = setup
    K = 4
    pol = _tenant_policy()
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas,
            sink=sink,
            max_batch=MAX_BATCH,
            max_wait_ms=2.0,
            session_snapshot_every=1,
            tenants=pol,
            faults={0: FaultInjector.from_spec("replica_kill@2")},
        ).start()
        futs = [
            router.submit_rollout(s, K, tenant="alice")
            for s in samples[:3]
        ]
        results = [f.result(timeout=60) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["sessions"]["migrated"] >= 1
    roll = summary["tenants"]
    # Every session accepted (including re-accepted migrants) and every
    # committed step landed under alice — nothing leaked to an
    # anonymous bucket on the migration path.
    assert set(roll) == {"alice"}
    assert roll["alice"]["requests"] >= 3
    assert roll["alice"]["completed"] >= 3 * K
    assert roll["alice"]["latency_p50_ms"] is not None


def test_rollout_session_tenant_survives_store_resume(setup, tmp_path):
    """A drained tagged session resumes from the SessionStore on a
    fresh server with its tenant intact (snapshot_state carries it)."""
    from gnot_tpu.serve import SessionStore

    _, _, samples, engine = setup
    store = SessionStore(str(tmp_path / "sessions"))
    pol = _tenant_policy()
    server = InferenceServer(
        engine, max_batch=MAX_BATCH, max_wait_ms=2.0,
        session_snapshot_every=1, session_store=store, tenants=pol,
    ).start()
    fut = server.submit_rollout(
        samples[0], 6, name="tagged-run", tenant="alice"
    )
    it = fut.iter_steps(timeout=60)
    for _ in range(2):  # mid-rollout by construction
        next(it)
    server.drain(10.0)
    first = fut.result(timeout=10)
    assert not first.ok and first.reason == "drained"
    server2 = InferenceServer(
        engine, max_batch=MAX_BATCH, max_wait_ms=2.0,
        session_snapshot_every=1, session_store=store, tenants=pol,
    ).start()
    fut2 = server2.resume_rollout("tagged-run")
    assert fut2.result(timeout=60).ok
    summary = server2.drain()
    # The resumed server never saw an explicit tag — the tenant came
    # back from the persisted session state.
    assert summary["tenants"]["alice"]["requests"] >= 1
    assert summary["tenants"]["alice"]["completed"] >= 1


def test_loadgen_multi_stream_deterministic_and_independent():
    """Satellite: the merged multi-tenant trace is a pure function of
    (streams, duration, seed); per-stream seeding is positional, so
    reshaping one tenant's stream never perturbs a sibling's arrivals."""
    import loadgen

    streams = {
        "interactive": {"pattern": "steady", "base_rps": 40.0},
        "batch": {"pattern": "bursty", "base_rps": 80.0, "bursts": 1},
    }
    a = loadgen.multi_stream_times(streams, duration_s=2.0, seed=7)
    b = loadgen.multi_stream_times(streams, duration_s=2.0, seed=7)
    assert a == b and len(a) > 50
    assert a == sorted(a)
    assert {t for _, t in a} == {"interactive", "batch"}
    # Per-tenant sub-trace == that tenant's solo trace_times (stream
    # seed = master seed + insertion index).
    solo = loadgen.trace_times(
        "steady", base_rps=40.0, duration_s=2.0, seed=7
    )
    assert [t for t, who in a if who == "interactive"] == solo
    # Changing BATCH's shape leaves interactive's arrivals untouched.
    streams2 = dict(streams)
    streams2["batch"] = {"pattern": "steady", "base_rps": 10.0}
    c = loadgen.multi_stream_times(streams2, duration_s=2.0, seed=7)
    assert [t for t, w in c if w == "interactive"] == solo
    with pytest.raises(ValueError, match="at least one"):
        loadgen.multi_stream_times({}, duration_s=1.0)


def test_serve_smoke_tool_tenants(tmp_path):
    """Tier-1 wiring of tools/serve_smoke.py --tenants: the two-tenant
    storm's isolation assertions (quota fast-fail, tenant-tagged
    events, WFQ drain fairness, per-tenant rollup) all hold."""
    import serve_smoke

    summary = serve_smoke.run(
        [
            "--tenants", "--n", "24",
            "--metrics_path", str(tmp_path / "serve.jsonl"),
        ]
    )
    assert summary["failures"] == []
    assert summary["tenants"]["batch"]["shed"]["shed_tenant_quota"] >= 1
