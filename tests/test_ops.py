"""Unit tests for the core ops against naive per-sample oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.ops.attention import (
    feature_softmax,
    merge_heads,
    normalized_linear_attention,
    split_heads,
)
from gnot_tpu.ops.segment import masked_segment_mean, masked_segment_sum, mse_loss, rel_l2_loss


def naive_normalized_attention(q, k, v):
    """O(L^2) per-sample oracle: explicit attention weights.

    alpha * q @ (k^T v) == (q k^T / normalizer) @ v — the linear form is
    just a reassociation of an explicit (unnormalized-softmax-free)
    attention matrix; verify against that direct form.
    """
    b, h, lq, d = q.shape
    out = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            qm, km, vm = q[bi, hi], k[bi, hi], v[bi, hi]
            attn = qm @ km.T  # [Lq, Lk]
            norm = attn.sum(axis=1, keepdims=True)
            out[bi, hi] = (attn / norm) @ vm
    return out


def test_attention_matches_quadratic_oracle():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(2, 3, 17, 8)).astype(np.float32)
    k = rng.normal(size=(2, 3, 29, 8)).astype(np.float32)
    v = rng.normal(size=(2, 3, 29, 8)).astype(np.float32)
    qs = np.asarray(feature_softmax(jnp.asarray(q)))
    ks = np.asarray(feature_softmax(jnp.asarray(k)))
    got = normalized_linear_attention(jnp.asarray(qs), jnp.asarray(ks), jnp.asarray(v))
    want = naive_normalized_attention(qs, ks, v)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_attention_mask_equals_shorter_sequence():
    """Masked attention over padded k/v == unmasked over the real rows."""
    rng = np.random.default_rng(1)
    lk_real, lk_pad = 13, 24
    q = feature_softmax(jnp.asarray(rng.normal(size=(2, 2, 11, 8)), jnp.float32))
    k_real = rng.normal(size=(2, 2, lk_real, 8)).astype(np.float32)
    v_real = rng.normal(size=(2, 2, lk_real, 8)).astype(np.float32)
    k_pad = np.concatenate(
        [k_real, rng.normal(size=(2, 2, lk_pad - lk_real, 8)).astype(np.float32)], axis=2
    )
    v_pad = np.concatenate(
        [v_real, rng.normal(size=(2, 2, lk_pad - lk_real, 8)).astype(np.float32)], axis=2
    )
    mask = np.zeros((2, lk_pad), np.float32)
    mask[:, :lk_real] = 1.0
    want = normalized_linear_attention(
        q, feature_softmax(jnp.asarray(k_real)), jnp.asarray(v_real)
    )
    got = normalized_linear_attention(
        q,
        feature_softmax(jnp.asarray(k_pad)),
        jnp.asarray(v_pad),
        kv_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_feature_softmax_axis():
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 2, 5, 8)), jnp.float32)
    s = feature_softmax(x)
    np.testing.assert_allclose(np.asarray(s.sum(axis=-1)), 1.0, rtol=1e-6)


def test_split_merge_heads_roundtrip():
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 7, 24)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(merge_heads(split_heads(x, 4))), np.asarray(x))


def test_segment_reductions_match_manual_segments():
    rng = np.random.default_rng(4)
    lengths = [5, 9, 3]
    l_max = 12
    vals = rng.normal(size=(3, l_max, 2)).astype(np.float32)
    mask = np.zeros((3, l_max), np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0
    got_sum = np.asarray(masked_segment_sum(jnp.asarray(vals), jnp.asarray(mask)))
    got_mean = np.asarray(masked_segment_mean(jnp.asarray(vals), jnp.asarray(mask)))
    for i, n in enumerate(lengths):
        np.testing.assert_allclose(got_sum[i], vals[i, :n].sum(0), rtol=1e-5)
        np.testing.assert_allclose(got_mean[i], vals[i, :n].mean(0), rtol=1e-5)


def test_losses_match_dgl_style_pooling():
    """rel-L2 / MSE equal the reference formulas computed segment-wise:
    mean over graphs AND channels of per-graph pooled values
    (reference loss.py:9-12,19-23)."""
    rng = np.random.default_rng(5)
    lengths = [6, 4]
    l_max = 8
    p = rng.normal(size=(2, l_max, 3)).astype(np.float32)
    t = rng.normal(size=(2, l_max, 3)).astype(np.float32)
    mask = np.zeros((2, l_max), np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0

    rel, mse = [], []
    for i, n in enumerate(lengths):
        num = ((p[i, :n] - t[i, :n]) ** 2).sum(0)
        den = (t[i, :n] ** 2).sum(0)
        rel.append(np.sqrt(num / den))
        mse.append(((p[i, :n] - t[i, :n]) ** 2).mean(0))
    np.testing.assert_allclose(
        float(rel_l2_loss(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask))),
        np.mean(rel),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        float(mse_loss(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask))),
        np.mean(mse),
        rtol=1e-6,
    )


def test_loss_grads_finite():
    p = jnp.ones((2, 4, 1)) * 0.5
    t = jnp.ones((2, 4, 1))
    mask = jnp.ones((2, 4))
    g = jax.grad(lambda x: rel_l2_loss(x, t, mask))(p)
    assert np.isfinite(np.asarray(g)).all()
