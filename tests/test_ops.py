"""Unit tests for the core ops against naive per-sample oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.ops.attention import (
    feature_softmax,
    merge_heads,
    normalized_linear_attention,
    split_heads,
)
from gnot_tpu.ops.segment import masked_segment_mean, masked_segment_sum, mse_loss, rel_l2_loss


def naive_normalized_attention(q, k, v):
    """O(L^2) per-sample oracle: explicit attention weights.

    alpha * q @ (k^T v) == (q k^T / normalizer) @ v — the linear form is
    just a reassociation of an explicit (unnormalized-softmax-free)
    attention matrix; verify against that direct form.
    """
    b, h, lq, d = q.shape
    out = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            qm, km, vm = q[bi, hi], k[bi, hi], v[bi, hi]
            attn = qm @ km.T  # [Lq, Lk]
            norm = attn.sum(axis=1, keepdims=True)
            out[bi, hi] = (attn / norm) @ vm
    return out


def test_attention_matches_quadratic_oracle():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(2, 3, 17, 8)).astype(np.float32)
    k = rng.normal(size=(2, 3, 29, 8)).astype(np.float32)
    v = rng.normal(size=(2, 3, 29, 8)).astype(np.float32)
    qs = np.asarray(feature_softmax(jnp.asarray(q)))
    ks = np.asarray(feature_softmax(jnp.asarray(k)))
    got = normalized_linear_attention(jnp.asarray(qs), jnp.asarray(ks), jnp.asarray(v))
    want = naive_normalized_attention(qs, ks, v)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_attention_mask_equals_shorter_sequence():
    """Masked attention over padded k/v == unmasked over the real rows."""
    rng = np.random.default_rng(1)
    lk_real, lk_pad = 13, 24
    q = feature_softmax(jnp.asarray(rng.normal(size=(2, 2, 11, 8)), jnp.float32))
    k_real = rng.normal(size=(2, 2, lk_real, 8)).astype(np.float32)
    v_real = rng.normal(size=(2, 2, lk_real, 8)).astype(np.float32)
    k_pad = np.concatenate(
        [k_real, rng.normal(size=(2, 2, lk_pad - lk_real, 8)).astype(np.float32)], axis=2
    )
    v_pad = np.concatenate(
        [v_real, rng.normal(size=(2, 2, lk_pad - lk_real, 8)).astype(np.float32)], axis=2
    )
    mask = np.zeros((2, lk_pad), np.float32)
    mask[:, :lk_real] = 1.0
    want = normalized_linear_attention(
        q, feature_softmax(jnp.asarray(k_real)), jnp.asarray(v_real)
    )
    got = normalized_linear_attention(
        q,
        feature_softmax(jnp.asarray(k_pad)),
        jnp.asarray(v_pad),
        kv_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_feature_softmax_axis():
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 2, 5, 8)), jnp.float32)
    s = feature_softmax(x)
    np.testing.assert_allclose(np.asarray(s.sum(axis=-1)), 1.0, rtol=1e-6)


def test_split_merge_heads_roundtrip():
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 7, 24)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(merge_heads(split_heads(x, 4))), np.asarray(x))


def test_segment_reductions_match_manual_segments():
    rng = np.random.default_rng(4)
    lengths = [5, 9, 3]
    l_max = 12
    vals = rng.normal(size=(3, l_max, 2)).astype(np.float32)
    mask = np.zeros((3, l_max), np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0
    got_sum = np.asarray(masked_segment_sum(jnp.asarray(vals), jnp.asarray(mask)))
    got_mean = np.asarray(masked_segment_mean(jnp.asarray(vals), jnp.asarray(mask)))
    for i, n in enumerate(lengths):
        np.testing.assert_allclose(got_sum[i], vals[i, :n].sum(0), rtol=1e-5)
        np.testing.assert_allclose(got_mean[i], vals[i, :n].mean(0), rtol=1e-5)


def test_losses_match_dgl_style_pooling():
    """rel-L2 / MSE equal the reference formulas computed segment-wise:
    mean over graphs AND channels of per-graph pooled values
    (reference loss.py:9-12,19-23)."""
    rng = np.random.default_rng(5)
    lengths = [6, 4]
    l_max = 8
    p = rng.normal(size=(2, l_max, 3)).astype(np.float32)
    t = rng.normal(size=(2, l_max, 3)).astype(np.float32)
    mask = np.zeros((2, l_max), np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0

    rel, mse = [], []
    for i, n in enumerate(lengths):
        num = ((p[i, :n] - t[i, :n]) ** 2).sum(0)
        den = (t[i, :n] ** 2).sum(0)
        rel.append(np.sqrt(num / den))
        mse.append(((p[i, :n] - t[i, :n]) ** 2).mean(0))
    np.testing.assert_allclose(
        float(rel_l2_loss(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask))),
        np.mean(rel),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        float(mse_loss(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask))),
        np.mean(mse),
        rtol=1e-6,
    )


def test_loss_grads_finite():
    p = jnp.ones((2, 4, 1)) * 0.5
    t = jnp.ones((2, 4, 1))
    mask = jnp.ones((2, 4))
    g = jax.grad(lambda x: rel_l2_loss(x, t, mask))(p)
    assert np.isfinite(np.asarray(g)).all()


def test_packed_attention_matches_per_segment():
    """packed_normalized_linear_attention == the unpacked op run on
    each segment separately: no cross-segment leakage, exact
    per-sample math (fp summation order aside). Covers ragged segment
    tails (intra-chunk masking), pad chunks, a pad segment slot, and
    DIFFERENT query/key packings (the cross-attention case)."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.ops.attention import (
        feature_softmax,
        normalized_linear_attention,
        packed_normalized_linear_attention,
        segment_one_hot,
    )

    rng = np.random.RandomState(0)
    H, D, C = 2, 8, 4
    n_seg = 3
    # Segment token counts (queries and keys differ per segment).
    q_lens = [6, 9, 4]
    k_lens = [5, 3, 7]

    def pack(lens, rows, row_chunks):
        """Place segment s's tokens contiguously, chunk-aligned, into
        the given (row, start_chunk) slots; return arrays + seg map."""
        L = row_chunks * C
        x = np.zeros((len(rows and [0]) and max(r for r, _ in rows) + 1, L, H * D), np.float32)
        seg = np.full((x.shape[0], row_chunks), n_seg, np.int32)
        mask = np.zeros((x.shape[0], L), np.float32)
        chunks_used = {}
        tokens = []
        for s, (ln, (r, c0)) in enumerate(zip(lens, rows)):
            t = rng.randn(ln, H * D).astype(np.float32)
            tokens.append(t)
            x[r, c0 * C : c0 * C + ln] = t
            mask[r, c0 * C : c0 * C + ln] = 1.0
            nch = -(-ln // C)
            seg[r, c0 : c0 + nch] = s
        return x, seg, mask, tokens

    # queries: seg0 row0@0, seg1 row0@2 (after seg0's 2 chunks), seg2 row1@0
    qx, q_seg, q_mask, q_toks = pack(q_lens, [(0, 0), (0, 2), (1, 0)], 5)
    # keys: different packing entirely
    kx, k_seg, k_mask, k_toks = pack(k_lens, [(1, 0), (0, 0), (0, 1)], 3)
    vx = rng.randn(*kx.shape).astype(np.float32)

    def heads(a):
        b, l, e = a.shape
        return jnp.asarray(a).reshape(b, l, H, D).transpose(0, 2, 1, 3)

    q = feature_softmax(heads(qx))
    k = feature_softmax(heads(kx))
    # Zero padded q/k rows' softmax garbage where it matters: the op
    # masks k itself; q pad rows produce outputs we never compare.
    v = heads(vx)

    out = packed_normalized_linear_attention(
        q, k, v,
        q_seg_oh=segment_one_hot(jnp.asarray(q_seg), n_seg),
        kv_seg_oh=segment_one_hot(jnp.asarray(k_seg), n_seg),
        kv_mask=jnp.asarray(k_mask),
    )  # [Bq, H, Lq, D]

    # Reference: run each segment through the unpacked op alone.
    q_rows = {0: (0, 0), 1: (0, 2), 2: (1, 0)}
    k_rows = {0: (1, 0), 1: (0, 0), 2: (0, 1)}
    for s in range(n_seg):
        qs = feature_softmax(heads(q_toks[s][None]))
        ks = feature_softmax(heads(k_toks[s][None]))
        r, c0 = k_rows[s]
        vs = heads(vx[None, r, c0 * C : c0 * C + k_lens[s]])
        ref = normalized_linear_attention(qs, ks, vs)  # [1,H,Lq_s,D]
        r, c0 = q_rows[s]
        got = out[r, :, c0 * C : c0 * C + q_lens[s]]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref[0]), rtol=2e-5, atol=2e-6,
            err_msg=f"segment {s} diverges from its solo attention",
        )
