"""BASELINE.json quality gate: <=1% rel-L2 gap vs the PyTorch reference.

test_parity_training.py checks per-step loss parity over a few steps;
this file runs the full reference regime in miniature — multiple epochs,
OneCycle schedule with the reference's per-epoch stepping, per-epoch
eval, best-metric tracking — on BOTH backends from the same initial
weights and batch order, and asserts the best eval metrics agree to
well under the 1% gate.

All five BASELINE.json benchmark configs run through the gate:
darcy2d's regular grid gives uniform lengths (no padding); elasticity,
inductor2d and heatsink3d are genuinely ragged, so pad rows pollute
attention unmasked on both sides (parity mode) while the loss stays
pad-free. The full-scale (64x64, 100-epoch, reference-default
architecture) darcy2d run is recorded in docs/performance.md.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, OptimConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader, collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.train.schedule import make_lr_fn
from gnot_tpu.train.trainer import (
    TrainState,
    make_eval_step,
    make_optimizer,
    make_train_step,
)

pytestmark = pytest.mark.skipif(
    not os.path.exists("/root/reference/model.py"),
    reason="reference checkout not available",
)

SMALL_ARCH = dict(
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=2,
    n_head=4,
)
EPOCHS = 6
BATCH = 4

# Generator size kwargs keep every config fast while preserving its
# defining trait (ragged lengths, multiple input functions, 3D coords).
GEN_KWARGS = {
    "darcy2d": {"grid_n": 8},
    "ns2d": {"n_points": 48},
    "elasticity": {"base_points": 96},
    "inductor2d": {"base_points": 64},
    "heatsink3d": {"base_points": 64},
}


from gnot_tpu.interop.torch_oracle import torch_rel_l2 as _torch_rel_l2


@pytest.mark.parametrize("config", sorted(GEN_KWARGS))
def test_quality_gate(config):
    import torch

    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

    gen = datasets.SYNTHETIC[config]
    train = gen(16, seed=11, **GEN_KWARGS[config])
    test = gen(8, seed=12, **GEN_KWARGS[config])
    mc = ModelConfig(
        **SMALL_ARCH, **datasets.infer_model_dims(train), attention_mode="parity"
    )

    # Identical batch composition per epoch on both sides.
    rng = np.random.default_rng(7)
    epoch_batches = []
    for _ in range(EPOCHS):
        order = rng.permutation(len(train))
        epoch_batches.append(
            [
                collate([train[i] for i in order[s : s + BATCH]], bucket=False)
                for s in range(0, len(train), BATCH)
            ]
        )
    test_batches = list(Loader(test, BATCH, bucket=False, prefetch=0))

    optim = OptimConfig()  # reference regime: AdamW 1e-3, per-epoch OneCycle
    lr_fn = make_lr_fn(optim, steps_per_epoch=len(epoch_batches[0]), epochs=EPOCHS)

    def tt(b):
        return (
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        )

    # --- torch side -------------------------------------------------------
    torch.manual_seed(0)
    tmodel = build_reference_model(mc)
    topt = torch.optim.AdamW(tmodel.parameters(), lr=optim.lr)
    t_best = float("inf")
    for epoch in range(EPOCHS):
        lr = lr_fn(0, epoch)
        for g in topt.param_groups:
            g["lr"] = lr
        for b in epoch_batches[epoch]:
            loss = _torch_rel_l2(
                tmodel(*tt(b)), torch.from_numpy(b.y), torch.from_numpy(b.node_mask)
            )
            topt.zero_grad()
            loss.backward()
            topt.step()
        with torch.no_grad():
            metrics = [
                float(
                    _torch_rel_l2(
                        tmodel(*tt(b)),
                        torch.from_numpy(b.y),
                        torch.from_numpy(b.node_mask),
                    )
                )
                for b in test_batches
            ]
        t_best = min(t_best, float(np.mean(metrics)))

    # --- jax side, same initial weights -----------------------------------
    torch.manual_seed(0)
    params = jax.tree.map(
        jnp.asarray, state_dict_to_flax(build_reference_model(mc).state_dict(), mc)
    )
    model = GNOT(mc)
    tx = make_optimizer(optim, optim.lr)
    state = TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )
    step_fn = make_train_step(model, optim, "rel_l2")
    eval_fn = make_eval_step(model, "rel_l2")
    j_best = float("inf")
    for epoch in range(EPOCHS):
        lr = jnp.asarray(lr_fn(0, epoch), jnp.float32)
        for b in epoch_batches[epoch]:
            state, _ = step_fn(state, b, lr)
        metrics = [float(eval_fn(state.params, b)) for b in test_batches]
        j_best = min(j_best, float(np.mean(metrics)))

    gap = abs(j_best - t_best) / t_best
    assert gap < 0.01, f"quality gate: torch best {t_best}, jax best {j_best}, gap {gap:.4f}"
    # In practice the trajectories track far tighter than the 1% gate.
    assert gap < 2e-3, f"trajectory drift unexpectedly large: {gap:.5f}"


ARTIFACTS = os.path.join(os.path.dirname(__file__), "..", "docs", "artifacts")


def _load_ab(name, base=None):
    import json

    path = os.path.join(base or ARTIFACTS, name)
    if not os.path.exists(path):
        pytest.skip(f"artifact {name} not present")
    by = {}
    for line in open(path):
        r = json.loads(line)
        by.setdefault((r["backend"], r["variant"]), {})[r["epoch"]] = r["test_metric"]
    return by


def _series(by, backend, variant):
    assert (backend, variant) in by, (
        f"artifact incomplete: missing the ({backend}, {variant}) series "
        f"(have {sorted(by)}); tools/quality_ab.py runs one backend per "
        "invocation — regenerate the missing one"
    )
    return by[(backend, variant)]


# Every committed full-scale A/B artifact: the jax variants it must
# record beyond the parity series, and the variant-vs-oracle bound.
# darcy64 (round 4) recorded the full variant ablation; the round-5
# configs record the TPU-default masked/tanh/bf16 variant, which
# doubles as the full-scale bf16 + masked-numerics gate under genuine
# raggedness (elasticity / inductor2d) and 3D gating (heatsink3d) —
# VERDICT r4 weak #1/#5. The bound catches real regressions (a broken
# mask/bf16 path lands 2x+ off), not trajectory noise. It is 1.1x
# except ns2d: its recorded masked variants spread 0.2455-0.2670
# AMONG THEMSELVES (erf-f32 / tanh-bf16 / tanh-f32 — not monotonic in
# dtype, so this is 24-epoch trajectory noise at 32 samples, not a
# numerics defect; masked lands BETTER than the oracle on the other
# four configs), and the worst recorded ratio is already 1.196, so a
# noise-tolerant bound must sit clear of the measured ±8% scatter —
# 1.3x. The BASELINE <=1% gate is the parity series above, never the
# variant bound.
FULL_SCALE_ARTIFACTS = {
    "darcy64": (("masked_erf_f32", "masked_tanh_f32", "masked_tanh_bf16"), 1.1),
    "elasticity": (("masked_tanh_bf16",), 1.1),
    "inductor2d": (("masked_tanh_bf16",), 1.1),
    "ns2d": (("masked_erf_f32", "masked_tanh_f32", "masked_tanh_bf16"), 1.3),
    "heatsink3d": (("masked_tanh_bf16",), 1.1),
}


@pytest.mark.parametrize("config", sorted(FULL_SCALE_ARTIFACTS))
def test_full_scale_quality_ab_artifact(config):
    """The committed full-scale A/B artifacts (reference-default
    architecture, >=24 epochs, tools/quality_ab.py --config) keep
    torch-CPU and jax-TPU inside the BASELINE 1% gate on ALL FIVE
    benchmark configs — the parity curves actually track to ~0.01%
    epoch by epoch — and the recorded TPU-native variants land in the
    same quality regime as the oracle."""
    by = _load_ab(f"quality_ab_{config}.jsonl")
    torch_curve = _series(by, "torch", "parity_f32")
    jax_curve = _series(by, "jax", "parity_f32")
    common = sorted(set(torch_curve) & set(jax_curve))
    assert len(common) >= 20, f"A/B artifact too short: {len(common)} epochs"
    for e in common:
        gap = abs(jax_curve[e] - torch_curve[e]) / torch_curve[e]
        assert gap < 0.01, f"epoch {e}: torch {torch_curve[e]} vs jax {jax_curve[e]}"
    t_best = min(torch_curve[e] for e in common)
    j_best = min(jax_curve[e] for e in common)
    assert abs(j_best - t_best) / t_best < 0.01
    variants, bound = FULL_SCALE_ARTIFACTS[config]
    for variant in variants:
        v_best = min(_series(by, "jax", variant).values())
        assert v_best <= t_best * bound, (variant, v_best, t_best)


def test_bf16_quality_gate_artifact():
    """100-epoch bf16-vs-f32 gate at the reference-default architecture
    (licenses the bf16 headline throughput): bf16 must not DEGRADE the
    best metric by more than 1%. The recorded run has bf16 slightly
    BETTER (0.0631 vs 0.0698 — late-training trajectory wobble at the
    noisy optimum swamps dtype effects), which passes trivially; the
    gate exists to catch a real bf16 quality loss."""
    by = _load_ab("bf16_gate_darcy64.jsonl")
    f32 = min(_series(by, "jax", "masked_tanh_f32").values())
    bf16 = min(_series(by, "jax", "masked_tanh_bf16").values())
    assert bf16 <= f32 * 1.01, f"bf16 {bf16} degrades vs f32 {f32}"


@pytest.mark.skipif(
    not os.environ.get("RUN_SLOW_AB"),
    reason="full-scale A/B re-run takes ~hours of torch-CPU; set RUN_SLOW_AB=1",
)
def test_full_scale_quality_ab_rerun(tmp_path):
    """End-to-end re-run of the full-scale A/B (torch CPU + jax) at a
    reduced epoch count; asserts the <=1% gap the committed artifact
    records at 24 epochs."""
    import argparse
    import sys

    tools_dir = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools_dir)
    try:
        import quality_ab

        out = str(tmp_path / "ab.jsonl")
        base = dict(
            config="darcy2d", size=None, grid_n=64,
            n_train=8, n_test=8, epochs=4, batch=4, out=out,
        )
        quality_ab.run_torch(
            argparse.Namespace(backend="torch", variant="parity_f32", **base)
        )
        quality_ab.run_jax(
            argparse.Namespace(backend="jax", variant="parity_f32", **base)
        )
    finally:
        sys.path.remove(tools_dir)
    by = _load_ab("ab.jsonl", base=str(tmp_path))
    t_best = min(_series(by, "torch", "parity_f32").values())
    j_best = min(_series(by, "jax", "parity_f32").values())
    assert abs(j_best - t_best) / t_best < 0.01


def test_ns2d_60_epoch_artifact_resolves_variant_noise():
    """Round 5 follow-up to the ns2d 24-epoch scatter: at 60 epochs
    (docs/artifacts/quality_ab_ns2d_60ep.jsonl, same protocol) every
    masked TPU variant beats the torch oracle outright and the parity
    series still tracks it — the 24-epoch straddle was trajectory
    noise, not a numerics defect."""
    by = _load_ab("quality_ab_ns2d_60ep.jsonl")
    # Every series must be complete — a truncated oracle would make
    # the beats-the-oracle assertions below trivially true.
    for backend, variant in (("torch", "parity_f32"), ("jax", "parity_f32")):
        assert len(_series(by, backend, variant)) >= 60
    torch_best = min(_series(by, "torch", "parity_f32").values())
    parity_best = min(_series(by, "jax", "parity_f32").values())
    # Parity class stays inside the BASELINE 1% gate at 2.5x the
    # gate's horizon (divergence grows with steps; it still doesn't).
    assert abs(parity_best - torch_best) / torch_best < 0.01
    for variant in ("masked_erf_f32", "masked_tanh_f32", "masked_tanh_bf16"):
        v = min(_series(by, "jax", variant).values())
        assert v < torch_best, (
            f"{variant} best {v} did not beat the 60-epoch oracle {torch_best}"
        )
        assert len(_series(by, "jax", variant)) >= 60
