"""Span tracing (gnot_tpu/obs/tracing.py): fake-clock nesting and
parenting, queue-wait arithmetic through the real serving stack,
deterministic head sampling, thread-safety under the serve worker
pool, and Chrome trace-event JSON schema validity of exported files.
"""

import json
import os
import threading

import numpy as np
import pytest

from gnot_tpu.data import datasets
from gnot_tpu.obs.tracing import (
    SERVE_OPTIONAL_SPANS,
    SERVE_SPANS,
    Tracer,
    percentiles,
)
from gnot_tpu.serve import InferenceEngine, InferenceServer
from gnot_tpu.utils.metrics import MetricsSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    """Deterministic monotonic clock: reads are stable, ticks explicit."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def fake_server(tracer=None, sink=None, max_batch=2, **kw):
    """Real InferenceServer over a stubbed forward (no XLA compile —
    these tests exercise the span plumbing, not the model)."""
    fake_forward = lambda params, batch: np.zeros(
        (batch.coords.shape[0], batch.coords.shape[1], 1)
    )
    engine = InferenceEngine(None, None, batch_size=max_batch, forward=fake_forward)
    return InferenceServer(
        engine, max_batch=max_batch, max_wait_ms=5.0, sink=sink,
        tracer=tracer, **kw,
    )


# --- nesting / parenting (fake clock) --------------------------------------


def test_span_nesting_and_parenting_fake_clock():
    clk = FakeClock()
    tr = Tracer(clock=clk)
    t = tr.start_trace()
    with tr.span("epoch", trace=t) as root:
        clk.tick(1.0)
        with tr.span("step", trace=t) as step:
            assert step.parent_id == root.span_id  # ambient parent
            clk.tick(0.5)
        clk.tick(0.25)
    spans = {s.name: s for s in tr.snapshot()}
    assert spans["epoch"].trace_id == spans["step"].trace_id == t
    assert spans["step"].parent_id == spans["epoch"].span_id
    assert spans["epoch"].parent_id is None
    assert (spans["step"].start, spans["step"].end) == (1.0, 1.5)
    assert (spans["epoch"].start, spans["epoch"].end) == (0.0, 1.75)
    assert spans["step"].duration_ms == pytest.approx(500.0)


def test_span_inherits_ambient_trace():
    tr = Tracer(clock=FakeClock())
    t = tr.start_trace()
    with tr.span("outer", trace=t):
        with tr.span("inner"):  # no explicit trace: inherits ambient's
            pass
    inner = next(s for s in tr.snapshot() if s.name == "inner")
    assert inner.trace_id == t


def test_span_without_trace_records_nothing():
    tr = Tracer(clock=FakeClock())
    with tr.span("orphan") as s:  # no ambient, no trace -> no-op
        assert s is None
    with tr.span("unsampled", trace=None) as s:
        assert s is None
    assert tr.snapshot() == []


def test_threads_keep_separate_ambient_chains():
    """Two threads nest concurrently on one tracer: each child parents
    under ITS thread's enclosing span, never the other's."""
    tr = Tracer()
    errs = []

    def worker(label):
        try:
            t = tr.start_trace()
            with tr.span("outer", trace=t, args={"label": label}) as o:
                with tr.span("inner") as i:
                    assert i.parent_id == o.span_id
                    assert i.trace_id == t
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=worker, args=(k,)) for k in range(8)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert errs == []
    spans = tr.snapshot()
    assert len(spans) == 16
    # Every inner's parent is the outer OF THE SAME trace.
    outers = {s.trace_id: s.span_id for s in spans if s.name == "outer"}
    for s in spans:
        if s.name == "inner":
            assert s.parent_id == outers[s.trace_id]


# --- sampling --------------------------------------------------------------


def test_head_sampling_deterministic():
    tr = Tracer(sample_rate=0.25)
    picks = [tr.start_trace() is not None for _ in range(12)]
    # floor(n/4) increments at n = 4, 8, 12: exactly every 4th trace.
    assert picks == [False, False, False, True] * 3
    # A fresh tracer with the same rate decides identically (no RNG).
    tr2 = Tracer(sample_rate=0.25)
    assert [tr2.start_trace() is not None for _ in range(12)] == picks


def test_stream_sampling_is_independent():
    # Aux lifecycles (serve reloads, stream "r") sample on their own
    # counter: interleaving them must not shift which requests the
    # "t" stream keeps, and ids carry the stream prefix.
    tr = Tracer(sample_rate=0.25)
    reqs, rels = [], []
    for _ in range(8):
        reqs.append(tr.start_trace())
        rels.append(tr.start_trace(stream="r"))
    assert reqs == [None] * 3 + ["t000001"] + [None] * 3 + ["t000002"]
    assert rels == [None] * 3 + ["r000001"] + [None] * 3 + ["r000002"]


def test_sampling_edge_rates():
    assert all(
        Tracer(sample_rate=1.0).start_trace() is not None for _ in range(1)
    )
    tr1 = Tracer(sample_rate=1.0)
    assert [tr1.start_trace() for _ in range(5)] == [
        f"t{i:06d}" for i in range(1, 6)
    ]
    tr0 = Tracer(sample_rate=0.0)
    assert [tr0.start_trace() for _ in range(5)] == [None] * 5
    with pytest.raises(ValueError, match="sample_rate"):
        Tracer(sample_rate=1.5)


def test_unsampled_spans_cost_nothing():
    tr = Tracer(sample_rate=0.0)
    t = tr.start_trace()
    assert t is None
    assert tr.add_span("queue_wait", 0.0, 1.0, trace=t) is None
    with tr.span("x", trace=t) as s:
        assert s is None
    assert tr.snapshot() == []


# --- buffer bound / add_span arithmetic ------------------------------------


def test_bounded_buffer_counts_drops():
    tr = Tracer(max_spans=2, clock=FakeClock())
    t = tr.start_trace()
    for i in range(5):
        tr.add_span("s", 0.0, 1.0, trace=t)
    assert len(tr.snapshot()) == 2
    assert tr.dropped == 3
    assert tr.export()["otherData"]["spans_dropped"] == 3


def test_add_span_exact_durations():
    tr = Tracer(clock=FakeClock())
    t = tr.start_trace()
    sid = tr.add_span(
        "queue_wait", 2.0, 5.0, trace=t, args={"bucket": "64x64"}
    )
    (s,) = tr.snapshot()
    assert s.span_id == sid
    assert s.duration_ms == pytest.approx(3000.0)
    assert s.args["bucket"] == "64x64"


def test_percentiles_helper():
    assert percentiles([]) == {"p50_ms": None, "p99_ms": None}
    out = percentiles([1.0, 2.0, 3.0, 4.0])
    assert out["p50_ms"] == 2.0 and out["p99_ms"] == 4.0


# --- the serving stack: chains + queue-wait arithmetic ---------------------


def test_serve_request_chain_and_queue_wait_arithmetic(tmp_path):
    """Every completed request gets the full admission->resolve chain
    under ONE trace_id, and the span arithmetic closes: queue_wait
    starts at submit, ends where dispatch begins, and queue_wait +
    dispatch duration equals the reported request latency."""
    tracer = Tracer(path=str(tmp_path / "trace.json"))
    mp = str(tmp_path / "serve.jsonl")
    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    with MetricsSink(mp) as sink:
        server = fake_server(tracer=tracer, sink=sink).start()
        futs = [server.submit(s) for s in samples]
        results = [f.result(timeout=60) for f in futs]
        summary = server.drain()
    assert all(r.ok for r in results)
    by_trace = {}
    for s in tracer.snapshot():
        by_trace.setdefault(s.trace_id, {})[s.name] = s
    assert len(by_trace) == len(samples)
    for t, chain in by_trace.items():
        # The guaranteed chain is exactly SERVE_SPANS; a fresh-signature
        # jit dispatch may additionally carry the optional `compile`
        # span (SERVE_OPTIONAL_SPANS) over its device window.
        assert set(SERVE_SPANS) <= set(chain), (t, sorted(chain))
        extra = set(chain) - set(SERVE_SPANS)
        assert extra <= set(SERVE_OPTIONAL_SPANS), (t, sorted(extra))
        qw, disp = chain["queue_wait"], chain["dispatch"]
        assert chain["admission"].start == qw.start  # both from submit
        assert qw.end == disp.start  # dispatch pop closes the queue
        # batch phases nest inside dispatch; resolve follows device.
        assert disp.start <= chain["batch_assembly"].start
        assert chain["device"].end <= disp.end + 1e-9
        assert chain["resolve"].start >= chain["device"].end - 1e-9
        assert "member_trace_ids" in disp.args
        assert t in disp.args["member_trace_ids"]
    # queue_wait + dispatch == reported latency (same clock, same ends).
    for r, t in zip(results, sorted(by_trace, key=lambda t: by_trace[t]["admission"].start)):
        chain = by_trace[t]
        assert chain["queue_wait"].duration_ms + chain["dispatch"].duration_ms == pytest.approx(
            r.latency_ms, rel=1e-6, abs=1e-6
        )
    # serve_summary carries the span-derived per-bucket breakdown.
    assert summary["queue_device_by_bucket"]
    for st in summary["queue_device_by_bucket"].values():
        assert st["n"] >= 1 and st["queue_p50_ms"] is not None
        assert st["device_p50_ms"] is not None


def test_serve_sampled_out_requests_trace_nothing(tmp_path):
    tracer = Tracer(sample_rate=0.5)
    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    server = fake_server(tracer=tracer).start()
    futs = [server.submit(s) for s in samples]
    assert all(f.result(timeout=60).ok for f in futs)
    server.drain()
    traced = {s.trace_id for s in tracer.snapshot()}
    assert len(traced) == 2  # every 2nd request at rate 0.5


def test_serve_shed_chain_and_event_trace_id(tmp_path):
    """A deadline-shed request's chain ends at queue_wait with the shed
    reason, and its shed event carries the trace_id."""
    clk = FakeClock()
    tracer = Tracer(clock=clk)
    mp = str(tmp_path / "serve.jsonl")
    samples = datasets.synth_darcy2d(2, seed=0, grid_n=8)
    with MetricsSink(mp) as sink:
        server = fake_server(
            tracer=tracer, sink=sink, max_batch=4, clock=clk,
        )
        # No worker thread: drive the internals directly so the fake
        # clock controls the deadline arithmetic exactly.
        fut = server.submit(samples[0], deadline_ms=10.0)
        (req,) = list(server._inbound.queue)
        server._inbound.get_nowait()
        clk.tick(0.050)  # 50 ms >> the 10 ms deadline
        server._dispatch(server.engine.bucket_key(samples[0]) , [req])
        assert fut.result(timeout=5).reason == "shed_deadline"
    qw = next(s for s in tracer.snapshot() if s.name == "queue_wait")
    assert qw.args["reason"] == "shed_deadline"
    assert qw.duration_ms == pytest.approx(50.0)
    shed_events = [
        json.loads(l) for l in open(mp) if '"shed"' in l
    ]
    assert shed_events and shed_events[0]["trace_id"] == qw.trace_id
    assert shed_events[0]["waited_ms"] == pytest.approx(50.0)


def test_drain_sweep_ends_chain_with_terminal_span():
    """A traced request swept by drain()'s final pass (worker never
    ran) still gets a terminal queue_wait span with the reject reason —
    no trace dangles at an 'admitted' admission span."""
    tracer = Tracer()
    samples = datasets.synth_darcy2d(1, seed=0, grid_n=8)
    server = fake_server(tracer=tracer)  # .start() never called
    fut = server.submit(samples[0])
    server.drain(timeout_s=1.0)
    assert fut.result(timeout=5).reason == "rejected_draining"
    spans = {
        s.name: (s.args or {}).get("reason") for s in tracer.snapshot()
    }
    assert spans["admission"] == "admitted"
    assert spans["queue_wait"] == "rejected_draining"


def test_serve_thread_safety_under_client_storm(tmp_path):
    """Many client threads submitting concurrently against the worker:
    no span is lost or cross-linked, ids stay unique, every completed
    request's chain is whole."""
    tracer = Tracer()
    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    server = fake_server(tracer=tracer, max_batch=4, queue_limit=256).start()
    results = []
    lock = threading.Lock()

    def client(k):
        futs = [server.submit(samples[i % 4]) for i in range(8)]
        rs = [f.result(timeout=60) for f in futs]
        with lock:
            results.extend(rs)

    threads = [threading.Thread(target=client, args=(k,)) for k in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    server.drain()
    n_ok = sum(r.ok for r in results)
    assert len(results) == 32 and n_ok == 32
    spans = tracer.snapshot()
    ids = [s.span_id for s in spans]
    assert len(ids) == len(set(ids))  # unique under concurrency
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s.name)
    assert len(by_trace) == 32
    for names in by_trace.values():
        assert set(SERVE_SPANS) <= set(names)
        assert set(names) - set(SERVE_SPANS) <= set(SERVE_OPTIONAL_SPANS)


# --- Chrome trace-event JSON schema ----------------------------------------


def test_exported_file_is_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "trace.json")
    clk = FakeClock()
    tr = Tracer(path=path, clock=clk)
    t = tr.start_trace()
    with tr.span("epoch", trace=t, args={"epoch": 0}):
        clk.tick(0.001)
        with tr.span("step"):
            clk.tick(0.002)
    mp = str(tmp_path / "m.jsonl")
    with MetricsSink(mp) as sink:
        assert tr.flush(sink=sink) == path
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ms"
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert e["ph"] == "X"
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert "trace_id" in e["args"] and "span_id" in e["args"]
    od = doc["otherData"]
    assert od["sample_rate"] == 1.0 and od["traces_kept"] == 1
    # The flush is announced on the metrics stream (registry-valid).
    from gnot_tpu.obs import events as events_registry

    recs = [json.loads(l) for l in open(mp)]
    flushes = [r for r in recs if r.get("event") == "trace_flush"]
    assert len(flushes) == 1 and flushes[0]["path"] == path
    assert flushes[0]["spans"] == 2
    for r in recs:
        assert events_registry.validate_record(r) == [], r


def test_flush_without_path_is_noop():
    tr = Tracer()
    assert tr.flush() is None


# --- slow_step <-> span correlation ----------------------------------------


def test_slow_step_event_carries_span_id(tmp_path):
    from gnot_tpu.obs import events as events_registry
    from gnot_tpu.obs.telemetry import TelemetryBuffer

    class AlwaysSlow:
        def observe(self, dt):
            return {"step_time_s": dt, "median_s": 0.01, "slowdown": 9.0}

    import jax.numpy as jnp

    mp = str(tmp_path / "m.jsonl")
    with MetricsSink(mp) as sink:
        # log_every=2: both appends land in ONE drain window (an
        # every-step drain would reset the interval clock between
        # appends and no dt would ever exist).
        buf = TelemetryBuffer(sink, log_every=2, slow_step=AlwaysSlow())
        for step, sid in ((1, None), (2, "s000042")):
            buf.append(
                steps=[step], epoch=0, lrs=[1e-3],
                loss=jnp.asarray(1.0), telem={}, batches=[None],
                span_ids=[sid],
            )
        buf.drain()
    recs = [json.loads(l) for l in open(mp)]
    slow = [r for r in recs if r.get("event") == "slow_step"]
    # dt exists only from the 2nd append; its span id is attached.
    assert len(slow) == 1 and slow[0]["span_id"] == "s000042"
    for r in recs:
        assert events_registry.validate_record(r) == [], r


# --- trace_report ----------------------------------------------------------


def _tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"gnot_tool_{name}", os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_on_synthetic_serve_trace(tmp_path):
    """Known durations in -> exact percentiles and critical path out."""
    trace_report = _tool("trace_report")
    path = str(tmp_path / "trace.json")
    tr = Tracer(path=path, clock=FakeClock())
    for i, (queue_s, device_s) in enumerate([(0.010, 0.004), (0.030, 0.004)]):
        t = tr.start_trace()
        t0 = float(i)
        tr.add_span("admission", t0, t0 + 0.001, trace=t)
        tr.add_span(
            "queue_wait", t0, t0 + queue_s, trace=t,
            args={"bucket": "64x64"},
        )
        tr.add_span(
            "dispatch", t0 + queue_s, t0 + queue_s + device_s + 0.002,
            trace=t, args={"bucket": "64x64"},
        )
        tr.add_span(
            "device", t0 + queue_s + 0.001, t0 + queue_s + 0.001 + device_s,
            trace=t, args={"bucket": "64x64"},
        )
        tr.add_span(
            "resolve", t0 + queue_s + device_s + 0.002,
            t0 + queue_s + device_s + 0.003, trace=t,
        )
    tr.flush()
    rep = trace_report.report(path)
    assert rep["spans"] == 10
    assert rep["kinds"]["queue_wait"]["count"] == 2
    assert rep["kinds"]["queue_wait"]["p50_ms"] == pytest.approx(10.0)
    assert rep["kinds"]["queue_wait"]["p99_ms"] == pytest.approx(30.0)
    bb = rep["buckets"]["64x64"]
    assert bb["requests"] == 2
    assert bb["queue_p99_ms"] == pytest.approx(30.0)
    assert bb["device_p50_ms"] == pytest.approx(4.0)
    cp = rep["critical_path"]
    assert cp["kind"] == "request" and cp["trace_id"] == "t000002"
    assert [p["name"] for p in cp["phases"]][0] in ("admission", "queue_wait")
    assert cp["total_ms"] == pytest.approx(37.0)
    # Queue-wait dominates the slowest request's critical path.
    qw = next(p for p in cp["phases"] if p["name"] == "queue_wait")
    assert qw["share"] > 0.8


def test_trace_report_replica_breakdown(tmp_path):
    """Spans tagged with a ``replica`` arg (replicated serving) roll up
    into the per-replica queue-vs-device table; untagged spans (single
    server) leave it empty; dispatch spans repeated per traced member
    count once per dispatch ordinal."""
    trace_report = _tool("trace_report")
    path = str(tmp_path / "trace.json")
    tr = Tracer(path=path, clock=FakeClock())
    for rep, queue_s in ((0, 0.010), (0, 0.020), (1, 0.002)):
        t = tr.start_trace()
        args = {"bucket": "64x64", "replica": rep}
        tr.add_span("queue_wait", 0.0, queue_s, trace=t, args=args)
        tr.add_span(
            "dispatch", queue_s, queue_s + 0.005, trace=t,
            args={**args, "dispatch": 1},  # same dispatch for both r0
        )
        tr.add_span(
            "device", queue_s, queue_s + 0.004, trace=t, args=args
        )
    tr.flush()
    rep = trace_report.report(path)
    rb = rep["replicas"]
    assert set(rb) == {"0", "1"}
    assert rb["0"]["requests"] == 2 and rb["1"]["requests"] == 1
    assert rb["0"]["dispatches"] == 1  # two member spans, one ordinal
    assert rb["0"]["queue_p99_ms"] == pytest.approx(20.0)
    assert rb["0"]["device_p50_ms"] == pytest.approx(4.0)
    assert rb["1"]["queue_p50_ms"] == pytest.approx(2.0)
    # No replica args -> empty table (single-server traces).
    tr2 = Tracer(path=str(tmp_path / "t2.json"), clock=FakeClock())
    t = tr2.start_trace()
    tr2.add_span("queue_wait", 0.0, 0.01, trace=t, args={"bucket": "64x64"})
    tr2.flush()
    assert trace_report.report(str(tmp_path / "t2.json"))["replicas"] == {}


def test_trace_report_cli_and_train_critical_path(tmp_path, capsys):
    """Train-shaped trace: the critical path picks the slowest step and
    its phase children; the CLI prints without error."""
    trace_report = _tool("trace_report")
    path = str(tmp_path / "train_trace.json")
    clk = FakeClock()
    tr = Tracer(path=path, clock=clk)
    t = tr.start_trace()
    with tr.span("epoch", trace=t):
        for step, cost in ((1, 0.010), (2, 0.050)):
            with tr.span("step", args={"step": step}):
                with tr.span("host_to_device"):
                    clk.tick(0.001)
                with tr.span("step_dispatch"):
                    clk.tick(cost)
    tr.flush()
    rep = trace_report.report(path)
    cp = rep["critical_path"]
    assert cp["kind"] == "step"
    assert cp["total_ms"] == pytest.approx(51.0)
    names = [p["name"] for p in cp["phases"]]
    assert names[0] == "step" and "step_dispatch" in names
    assert trace_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "critical path" in out and "step_dispatch" in out
    assert trace_report.main([str(tmp_path / "missing.json")]) == 2


# --- end-to-end: CLI + smoke tool ------------------------------------------


def test_train_cli_writes_trace(tmp_path):
    """--trace_path on a tiny training run: epoch/step phase spans land
    in a valid trace; the trainer path stays numerically untouched."""
    from gnot_tpu.main import main

    trace_report = _tool("trace_report")
    tp = str(tmp_path / "trace.json")
    main([
        "--n_attn_layers", "1", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--epochs", "1", "--n_train", "4", "--n_test", "2",
        "--synthetic", "ns2d", "--trace_path", tp,
    ])
    rep = trace_report.report(tp)
    assert {"epoch", "step", "step_dispatch", "host_to_device",
            "data_iter", "eval"} <= set(rep["kinds"])
    assert rep["kinds"]["step"]["count"] == 1  # 4 train / batch 4
    assert rep["critical_path"]["kind"] == "step"


def test_serve_smoke_tool_with_tracing(tmp_path):
    """The ISSUE 5 acceptance run: serve smoke with --trace_path
    produces a Chrome trace where every completed request has the full
    chain, and the smoke's own trace assertions all hold (exit 0)."""
    serve_smoke = _tool("serve_smoke")
    tp = str(tmp_path / "smoke_trace.json")
    summary = serve_smoke.run([
        "--n", "8", "--trace_path", tp,
        "--metrics_path", str(tmp_path / "serve.jsonl"),
    ])
    assert summary["failures"] == []
    assert summary["queue_device_by_bucket"]
    assert os.path.exists(tp)
