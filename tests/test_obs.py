"""Observability subsystem (gnot_tpu/obs/): telemetry record schema,
run manifests, health monitors, and the trainer/CLI integration."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, make_config
from gnot_tpu.data import datasets
from gnot_tpu.train.trainer import Trainer
from gnot_tpu.utils.metrics import MetricsSink

TINY_ARGS = [
    "--n_attn_layers", "2", "--n_attn_hidden_dim", "16",
    "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
    "--n_input_hidden_dim", "16", "--n_expert", "3", "--n_head", "2",
    "--epochs", "2", "--n_train", "8", "--n_test", "4",
    "--synthetic", "ns2d",
]


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# --- gate stats (models/layers.py) ----------------------------------------


def test_gate_stats_uniform_scores():
    from gnot_tpu.models.layers import gate_stats

    e = 4
    scores = jnp.full((2, 8, e), 1.0 / e)
    out = gate_stats(scores, None)
    np.testing.assert_allclose(np.asarray(out["gate_load"]), np.full(e, 1 / e), rtol=1e-6)
    np.testing.assert_allclose(float(out["gate_entropy"]), np.log(e), rtol=1e-6)


def test_gate_stats_masked_tokens_excluded():
    from gnot_tpu.models.layers import gate_stats

    # Real token gates expert 0; the padded token gates expert 1 and
    # must not contribute.
    scores = jnp.asarray([[[1.0, 0.0], [0.0, 1.0]]])
    mask = jnp.asarray([[1.0, 0.0]])
    out = gate_stats(scores, mask)
    np.testing.assert_allclose(np.asarray(out["gate_load"]), [1.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(float(out["gate_entropy"]), 0.0, atol=1e-5)
    # Collapsed gate signature: one load ~1, entropy ~0.


# --- telemetry records via the CLI (the acceptance-criteria test) ---------


def test_telemetry_run_produces_manifest_and_step_records(tmp_path):
    """--telemetry --metrics_path: run.json manifest + JSONL step
    records with grad-norm and per-layer gate-load stats (ISSUE 1
    acceptance criterion)."""
    from gnot_tpu.main import main

    mp = str(tmp_path / "metrics.jsonl")
    best = main(TINY_ARGS + ["--metrics_path", mp, "--log_every", "2", "--telemetry"])
    assert np.isfinite(best)

    # Manifest: next to the metrics file, with the provenance fields.
    man_path = str(tmp_path / "run.json")
    assert os.path.exists(man_path)
    man = json.load(open(man_path))
    assert man["config"]["train"]["telemetry"] is True
    assert man["config"]["train"]["metrics_path"] == mp
    assert man["model_config"]["n_expert"] == 3
    assert "jax" in man["versions"]
    assert man["devices"]["n_devices"] >= 1 and man["devices"]["platform"] == "cpu"
    assert "rev" in man["git"] and "dirty" in man["git"]
    assert "dir" in man["compile_cache"]

    recs = read_jsonl(mp)
    step_recs = [r for r in recs if "grad_norm" in r]
    assert step_recs, "no telemetry step records written"
    for r in step_recs:
        # 8 train / batch 4 = 2 steps/epoch; log_every=2 -> even steps.
        assert r["step"] % 2 == 0
        for key in ("loss", "lr", "grad_norm", "param_norm", "update_norm",
                    "padding_waste", "ts"):
            assert isinstance(r[key], float), (key, r[key])
        for layer in range(2):  # per-layer gate stats, n_attn_layers=2
            load = r[f"gate_load/block_{layer}"]
            assert isinstance(load, list) and len(load) == 3  # n_expert
            np.testing.assert_allclose(sum(load), 1.0, rtol=1e-4)
            assert isinstance(r[f"gate_entropy/block_{layer}"], float)
    # Per-epoch records still written alongside.
    assert [r for r in recs if "test_metric" in r]


def test_telemetry_off_by_default(tmp_path):
    from gnot_tpu.config import Config
    from gnot_tpu.main import main

    assert Config().train.telemetry is False
    mp = str(tmp_path / "metrics.jsonl")
    main(TINY_ARGS[:-2] + ["--epochs", "1", "--metrics_path", mp, "--log_every", "2"])
    assert not any("grad_norm" in r for r in read_jsonl(mp))


def test_telemetry_does_not_change_training(capsys):
    """The instrumented step is the same train_step_body math: console
    epoch losses match the plain run's."""
    from helpers import assert_epoch_lines_close
    from gnot_tpu.main import build_parser, config_from_args, model_config

    def run(extra):
        args = build_parser().parse_args(TINY_ARGS + extra)
        cfg = config_from_args(args)
        train, test = datasets.load(cfg.data)
        mc = model_config(cfg, args, train)
        best = Trainer(cfg, mc, train, test).fit()
        return best, capsys.readouterr().out

    b_plain, out_plain = run([])
    b_tel, out_tel = run(["--telemetry"])
    np.testing.assert_allclose(b_plain, b_tel, rtol=1e-5)
    assert_epoch_lines_close(out_plain, out_tel, rtol=1e-5)


def test_telemetry_steps_per_dispatch(tmp_path):
    """The scanned K-step dispatch path stacks telemetry per step: every
    step gets its record, same schema."""
    from gnot_tpu.main import main

    mp = str(tmp_path / "metrics.jsonl")
    main(TINY_ARGS + ["--epochs", "1", "--batch_size", "2",
                      "--metrics_path", mp, "--log_every", "1",
                      "--telemetry", "--steps_per_dispatch", "2"])
    step_recs = [r for r in read_jsonl(mp) if "grad_norm" in r]
    assert [r["step"] for r in step_recs] == [1, 2, 3, 4]
    assert all(len(r["gate_load/block_0"]) == 3 for r in step_recs)


def test_telemetry_sharded_mesh(tmp_path):
    """GSPMD path: telemetry outputs come back replicated; records carry
    the same schema; the manifest names the mesh."""
    from gnot_tpu.main import main

    mp = str(tmp_path / "metrics.jsonl")
    main(TINY_ARGS + ["--epochs", "1", "--metrics_path", mp,
                      "--log_every", "1", "--telemetry",
                      "--distributed", "--mesh_data", "4", "--mesh_model", "2"])
    step_recs = [r for r in read_jsonl(mp) if "grad_norm" in r]
    assert step_recs and all(len(r["gate_load/block_0"]) == 3 for r in step_recs)
    man = json.load(open(tmp_path / "run.json"))
    assert man["mesh"]["data"] == 4 and man["mesh"]["model"] == 2


def test_telemetry_rejects_pipeline_mesh():
    cfg = make_config(**{
        "train.telemetry": True, "train.distributed": True,
        "mesh.pipe": 2, "mesh.data": 4,
    })
    train = datasets.synth_ns2d(8, n_points=16, seed=0)
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    with pytest.raises(ValueError, match="telemetry"):
        Trainer(cfg, mc, train, [])


def test_manifest_does_not_clobber_other_runs(tmp_path):
    """Two runs sharing a directory: the second manifest falls back to
    <metrics-stem>.run.json; a re-run of the SAME metrics file keeps
    run.json."""
    from gnot_tpu.obs import manifest as manifest_lib

    mp1 = str(tmp_path / "train.jsonl")
    p1 = manifest_lib.manifest_path_for(mp1)
    assert os.path.basename(p1) == "run.json"
    manifest_lib.write_manifest(p1, extra={"metrics_path": mp1, "kind": "train"})

    # Same metrics file again -> same manifest path (re-run).
    assert manifest_lib.manifest_path_for(mp1) == p1

    # A different run in the same dir -> fallback name, original intact.
    mp2 = str(tmp_path / "bench.jsonl")
    p2 = manifest_lib.manifest_path_for(mp2)
    assert os.path.basename(p2) == "bench.run.json"
    manifest_lib.write_manifest(p2, extra={"metrics_path": mp2, "kind": "bench"})
    assert json.load(open(p1))["kind"] == "train"
    assert json.load(open(p2))["kind"] == "bench"


# --- NaN watchdog ---------------------------------------------------------


def test_nan_watchdog_localizes_and_records(tmp_path):
    """First non-finite loss: checkify re-run names the producing op,
    the sink gets the event record, the run stops."""
    train = datasets.synth_ns2d(8, n_points=16, seed=0)
    train[2].coords[0, 0] = np.nan  # poison one sample of batch 0
    test = datasets.synth_ns2d(4, n_points=16, seed=1)
    mp = str(tmp_path / "metrics.jsonl")
    cfg = make_config(**{
        "data.n_train": 8, "data.n_test": 4, "train.epochs": 1,
        "train.telemetry": True, "train.log_every": 2,
        "data.shuffle_train": False, "train.metrics_path": mp,
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    with MetricsSink(mp) as sink:
        trainer = Trainer(cfg, mc, train, test, metrics_sink=sink)
        with pytest.raises(FloatingPointError, match="epoch 0"):
            trainer.fit()
    events = [r for r in read_jsonl(mp) if r.get("event") == "non_finite_loss"]
    assert len(events) == 1
    assert events[0]["step"] == 1 and events[0]["loss"] is None
    assert "nan" in events[0]["detail"]  # checkify localization
    # Every record — event or metric — validates against the central
    # registry (obs/events.py): required payload fields all present.
    from gnot_tpu.obs import events as events_registry

    for rec in read_jsonl(mp):
        assert events_registry.validate_record(rec) == [], rec


# --- event registry (obs/events.py) ---------------------------------------


def test_event_registry_validate_record():
    from gnot_tpu.obs import events

    assert events.validate_record({"step": 1, "loss": 0.5}) == []  # metric
    assert events.validate_record(
        {"event": "rollback", "epoch": 0, "step": 3, "to_step": 1,
         "rollbacks_used": 1, "ts": 0.0}
    ) == []
    missing = events.validate_record({"event": "rollback", "epoch": 0})
    assert len(missing) == 3  # step, to_step, rollbacks_used
    assert events.validate_record({"event": "not_a_kind"}) == [
        "unknown event kind 'not_a_kind'"
    ]


def test_event_registry_matches_serving_reasons():
    """The `shed` family's reason strings are serve/server.py REASONS —
    the registry requires the `reason` field, the server provides it
    from its own closed vocabulary."""
    from gnot_tpu.obs import events
    from gnot_tpu.serve.server import REASONS

    assert "reason" in events.EVENTS["shed"].fields
    assert "ok" in REASONS and "shed_deadline" in REASONS


def test_event_table_in_docs_is_generated():
    """docs/observability.md embeds events.markdown_table() VERBATIM:
    adding or changing a kind without regenerating the docs table
    fails here (and GL005 catches the registry/docs direction)."""
    from gnot_tpu.obs import events

    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "observability.md",
    )
    with open(doc_path) as f:
        doc = f.read()
    assert events.markdown_table() in doc


def test_serve_events_validate_against_registry(tmp_path):
    """A serving run's event stream (dispatch, shed, summary) validates
    against the registry specs. The forward is a stub — the events
    under test come from the server/batcher machinery, and skipping the
    XLA compile keeps this inside the tier-1 time budget."""
    from gnot_tpu.obs import events as events_registry
    from gnot_tpu.serve import InferenceEngine, InferenceServer

    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    fake_forward = lambda params, batch: np.zeros(
        (batch.coords.shape[0], batch.coords.shape[1], 1)
    )
    engine = InferenceEngine(
        None, None, batch_size=2, forward=fake_forward
    )
    mp = str(tmp_path / "serve.jsonl")
    with MetricsSink(mp) as sink:
        server = InferenceServer(
            engine, max_batch=2, max_wait_ms=5.0, sink=sink
        ).start()
        futs = [server.submit(s) for s in samples]
        for f in futs:
            assert f.result(timeout=60).ok
        server.drain()
    recs = read_jsonl(mp)
    assert any(r.get("event") == "serve_summary" for r in recs)
    assert any(r.get("event") == "queue_depth" for r in recs)
    for rec in recs:
        assert events_registry.validate_record(rec) == [], rec


def test_router_events_validate_against_registry(tmp_path):
    """A replicated serving run's event stream (route, replica_health,
    rolling_reload, per-replica + pool serve_summary) validates against
    the registry specs. Stub forwards — the events come from the
    router/server machinery, no XLA compile."""
    from gnot_tpu.obs import events as events_registry
    from gnot_tpu.serve import EngineReplica, InferenceEngine, ReplicaRouter

    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    fake_forward = lambda params, batch: np.zeros(
        (batch.coords.shape[0], batch.coords.shape[1], 1)
    )
    replicas = [
        EngineReplica(
            i, InferenceEngine(None, None, batch_size=2, forward=fake_forward)
        )
        for i in range(2)
    ]
    mp = str(tmp_path / "serve.jsonl")
    with MetricsSink(mp) as sink:
        router = ReplicaRouter(
            replicas,
            max_batch=2,
            max_wait_ms=5.0,
            sink=sink,
            # Reload source that always succeeds with fresh "params".
            reload_fn=lambda deadline_ms=None: ({"w": np.ones(2)}, {}),
        ).start()
        futs = [router.submit(s) for s in samples]
        for f in futs:
            assert f.result(timeout=60).ok
        assert router.reload() == 2
        summary = router.drain()
    recs = read_jsonl(mp)
    kinds = {r.get("event") for r in recs}
    assert {"route", "rolling_reload", "replica_health",
            "serve_summary"} <= kinds
    for rec in recs:
        assert events_registry.validate_record(rec) == [], rec
    # Per-server events carry the replica tag; the pool summary rolls
    # per-replica summaries up.
    assert all(
        "replica" in r for r in recs if r.get("event") == "queue_depth"
    )
    assert set(summary["per_replica"]) == {"0", "1"}
    [pool] = [
        r for r in recs
        if r.get("event") == "serve_summary" and "per_replica" in r
    ]
    assert pool["requests"] == len(samples)


def test_serve_manifest_records_warmup_cache(tmp_path):
    """--serve --serve_replicas 2: run.json gains the warmup_cache
    block (programs warmed per pool + persistent-compile-cache
    hit/miss counts) — the ROADMAP cold-start number."""
    from gnot_tpu import main as main_mod

    mp = str(tmp_path / "serve.jsonl")
    main_mod.main([
        "--serve", "--serve_replicas", "2",
        "--synthetic", "darcy2d", "--synth_size", "4",
        "--n_train", "4", "--n_test", "4", "--epochs", "1",
        "--n_attn_layers", "1", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--metrics_path", mp,
    ])
    man = json.load(open(tmp_path / "run.json"))
    assert man["kind"] == "serve"
    wc = man["warmup_cache"]
    assert wc["replicas"] == 2
    assert wc["programs_warmed"] >= 2  # >= one program per replica
    if wc["requests"] is not None:  # monitoring API present
        assert wc["hits"] + wc["misses"] == wc["requests"]
        assert wc["requests"] >= wc["programs_warmed"]
    # The replicated run's events (route included) validate too.
    from gnot_tpu.obs import events as events_registry

    recs = read_jsonl(mp)
    assert any(r.get("event") == "route" for r in recs)
    for rec in recs:
        assert events_registry.validate_record(rec) == [], rec


def test_metrics_snapshot_and_slo_alert_validate_against_registry(tmp_path):
    """The live metrics plane's event kinds (ISSUE 14): REAL
    metrics_snapshot records (from a publisher polling a serving
    registry) and REAL slo_alert fire/clear edges (from a breached
    objective) validate against the central registry specs."""
    from gnot_tpu.obs.metrics import (
        MetricsPublisher,
        MetricsRegistry,
        SLOEvaluator,
        SLOObjective,
    )

    clock = {"t": 0.0}
    reg = MetricsRegistry()
    reqs = reg.counter("serve_requests_total")
    shed = reg.counter("serve_shed_total", reason="shed_deadline")
    mp = str(tmp_path / "m.jsonl")
    with MetricsSink(mp) as sink:
        pub = MetricsPublisher(
            reg, interval_s=1.0, sink=sink,
            series_path=str(tmp_path / "m.series.jsonl"),
            exposition_path=str(tmp_path / "m.prom"),
            evaluator=SLOEvaluator([
                SLOObjective("shed_fraction", "shed_frac", 0.1,
                             fast_window_s=1.0, slow_window_s=2.0),
            ]),
            clock=lambda: clock["t"],
        )
        for i in range(4):
            reqs.inc(10)
            if i == 2:
                shed.inc(10)  # breach -> fire, then clear next window
            pub.tick()
            clock["t"] += 1.0
    recs = read_jsonl(mp)
    kinds = [r.get("event") for r in recs]
    assert kinds.count("metrics_snapshot") == 4
    states = [r["state"] for r in recs if r.get("event") == "slo_alert"]
    assert states == ["fire", "clear"]
    from gnot_tpu.obs import events as events_registry

    for rec in recs:
        assert events_registry.validate_record(rec) == [], rec
    # Snapshot pool block mirrors the registry totals.
    last = [r for r in recs if r.get("event") == "metrics_snapshot"][-1]
    assert last["pool"]["requests"] == 40 and last["pool"]["shed"] == 10


# --- health monitors ------------------------------------------------------


def test_recompile_monitor_detects_new_trace():
    from gnot_tpu.obs.health import RecompileMonitor

    f = jax.jit(lambda x: x * 2)
    f(jnp.ones((2,)))
    mon = RecompileMonitor()
    mon.register("f", f)
    assert mon.check() == {}  # baseline
    assert mon.check() == {}  # no new traces
    f(jnp.ones((3,)))  # shape leak -> recompile
    assert mon.check() == {"f": 1}


def test_recompile_monitor_ignores_uncountable_fns():
    from gnot_tpu.obs.health import RecompileMonitor

    mon = RecompileMonitor()
    mon.register("not_jitted", lambda x: x)
    mon.register("none", None)
    assert mon.check() == {}


def test_slow_step_monitor_flags_outliers():
    from gnot_tpu.obs.health import SlowStepMonitor

    mon = SlowStepMonitor(factor=3.0, warmup=5)
    # A huge spike during warmup must NOT flag (compiles live there).
    assert mon.observe(50.0) is None
    for _ in range(10):
        assert mon.observe(0.1) is None
    out = mon.observe(1.0)  # 10x the median
    assert out is not None and out["slowdown"] > 3.0
    assert out["median_s"] == pytest.approx(0.1)
    assert mon.observe(0.1) is None  # back to normal


def test_localize_nan_reports_clean_run():
    from gnot_tpu.obs.health import localize_nan

    loss_fn = lambda p, b: jnp.sum(jnp.sqrt(p))
    assert localize_nan(loss_fn, jnp.asarray([4.0]), None) is None
    detail = localize_nan(loss_fn, jnp.asarray([-4.0]), None)
    assert detail is not None and "nan" in detail


def test_per_host_gauge_single_process():
    from gnot_tpu.parallel import multihost

    out = multihost.per_host_gauge(0.25)
    np.testing.assert_allclose(out, [0.25])


# --- telemetry buffer -----------------------------------------------------


def test_telemetry_buffer_drains_on_window_and_flush(tmp_path):
    from gnot_tpu.obs.telemetry import TelemetryBuffer

    mp = str(tmp_path / "m.jsonl")
    with MetricsSink(mp) as sink:
        buf = TelemetryBuffer(sink, log_every=2)
        for s in range(1, 4):
            buf.append(steps=[s], epoch=0, lrs=[1e-3],
                       loss=jnp.asarray(float(s)),
                       telem={"grad_norm": jnp.asarray(0.5)},
                       batches=[None])
        # 3 appended, window=2: steps 1-2 drained, step 3 pending.
        assert [r["step"] for r in read_jsonl(mp)] == [2]
        buf.drain()  # epoch-end flush
        recs = read_jsonl(mp)
        assert [r["step"] for r in recs] == [2]  # step 3 not a multiple
        assert recs[0]["loss"] == 2.0 and recs[0]["grad_norm"] == 0.5


# --- satellites: sink context manager, bench --metrics_path ---------------


def test_metrics_sink_context_manager_closes_on_error(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with pytest.raises(RuntimeError):
        with MetricsSink(path) as sink:
            sink.log(a=1)
            raise RuntimeError("mid-run crash")
    assert sink._fh.closed
    assert read_jsonl(path)[0]["a"] == 1  # record survived the crash
    sink.close()  # idempotent


def test_metrics_sink_coerces_arrays_and_nonfinite(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsSink(path) as sink:
        sink.log(
            vec=np.asarray([1.0, np.nan, np.inf]),
            scalar0d=np.asarray(2.5),
            jarr=jnp.asarray([0.5, 1.5]),
            nested=[np.float32(1.0), float("nan")],
        )
    rec = read_jsonl(path)[0]
    assert rec["vec"] == [1.0, None, None]
    assert rec["scalar0d"] == 2.5
    assert rec["jarr"] == [0.5, 1.5]
    assert rec["nested"] == [1.0, None]


def test_bench_metrics_path_emits_sink_schema(tmp_path, monkeypatch, capsys):
    """bench.py --metrics_path writes the MetricsSink JSONL schema plus
    a run.json manifest — one report tool reads bench and trainer."""
    import bench

    mp = str(tmp_path / "bench.jsonl")
    monkeypatch.setattr("sys.argv", [
        "bench.py", "--timing", "persstep", "--steps", "2", "--warmup", "1",
        "--repeats", "1", "--cpu_steps", "0", "--n_points", "64",
        "--batch_size", "2", "--dtype", "float32", "--metrics_path", mp,
    ])
    bench.main()
    out = capsys.readouterr().out
    printed = json.loads(out.strip().splitlines()[-1])
    recs = read_jsonl(mp)
    assert len(recs) == 1 and recs[0]["kind"] == "bench"
    assert recs[0]["metric"] == printed["metric"]
    assert recs[0]["value"] == printed["value"]
    assert "ts" in recs[0]  # the sink's timestamp, same as trainer records
    man = json.load(open(tmp_path / "run.json"))
    assert man["kind"] == "bench" and man["config"]["n_points"] == 64
