"""Data layer tests: reference-faithful padding semantics, pickle
round-trip, bucketing, synthetic generators."""

import numpy as np
import pytest

from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader, MeshSample, bucket_length, collate, pad_rows


def ragged_samples():
    rng = np.random.default_rng(0)
    out = []
    for n, m1, m2 in [(5, 3, 7), (9, 4, 2), (6, 8, 5)]:
        out.append(
            MeshSample(
                coords=rng.normal(size=(n, 2)).astype(np.float32),
                y=rng.normal(size=(n, 1)).astype(np.float32),
                theta=np.array([1.0], np.float32),
                funcs=(
                    rng.normal(size=(m1, 3)).astype(np.float32),
                    rng.normal(size=(m2, 3)).astype(np.float32),
                ),
            )
        )
    return out


def test_collate_shared_func_max():
    """Input functions pad to ONE max across all functions of all samples
    (reference main.py:63), not per-function maxima."""
    batch = collate(ragged_samples(), bucket=False)
    assert batch.funcs.shape == (2, 3, 8, 3)  # shared max = 8
    assert batch.coords.shape == (3, 9, 2)  # per-batch node max = 9
    assert batch.func_mask.shape == (2, 3, 8)
    # masks count real lengths
    np.testing.assert_array_equal(batch.node_mask.sum(1), [5, 9, 6])
    np.testing.assert_array_equal(batch.func_mask[0].sum(1), [3, 4, 8])
    np.testing.assert_array_equal(batch.func_mask[1].sum(1), [7, 2, 5])


def test_collate_zero_tail_padding():
    batch = collate(ragged_samples(), bucket=False)
    assert (batch.coords[0, 5:] == 0).all()  # zero pad at tail (utils.py:3-4)
    assert (batch.y[2, 6:] == 0).all()


def test_bucketing_bounds_recompiles():
    ls = [bucket_length(n) for n in range(1, 3000, 37)]
    assert len(set(ls)) <= 12  # O(log L) distinct shapes
    for n in range(1, 3000, 37):
        assert bucket_length(n) >= n


def test_bucket_length_invariants():
    """The serving layer's compiled-program bound rests on these
    (docs/serving.md): monotone (a longer mesh never gets a shorter
    bucket), idempotent on bucket boundaries (bucketing a bucket is a
    no-op — so re-bucketing collated data can't drift shapes), and the
    min_size floor absorbs every tiny mesh into ONE shape."""
    prev = 0
    for n in range(1, 5000):
        b = bucket_length(n)
        assert b >= n  # never truncates
        assert b >= prev  # monotone in n
        prev = b
    # Idempotent on boundaries: every emitted bucket maps to itself.
    for n in range(1, 5000, 7):
        b = bucket_length(n)
        assert bucket_length(b) == b
    # min_size floor: everything at-or-below min_size shares one bucket.
    for n in range(1, 65):
        assert bucket_length(n) == 64
    assert bucket_length(1, min_size=16) == 16
    assert bucket_length(17, min_size=16) == 24  # 16 * 1.5 mantissa step
    # Bucket count over a full range stays O(log L): ~2 per octave.
    distinct = {bucket_length(n) for n in range(1, 65537)}
    import math

    assert len(distinct) <= 2 * (int(math.log2(65536 / 64)) + 1)


def test_validate_samples_names_offender():
    """validate_samples (shared by Trainer.predict and the serving
    engine) rejects oversize and non-finite inputs naming the sample
    index and field."""
    from gnot_tpu.data.batch import validate_samples

    def mk(n=8, m=4):
        return MeshSample(
            coords=np.zeros((n, 2), np.float32),
            y=np.zeros((n, 1), np.float32),
            theta=np.zeros((1,), np.float32),
            funcs=(np.zeros((m, 3), np.float32),),
        )

    good = mk()
    validate_samples([good, mk()])  # clean inputs pass
    big = mk(n=32)
    with pytest.raises(ValueError, match="sample 1.*fixed pad length"):
        validate_samples([good, big], pad_nodes=16)
    bigf = mk(m=64)
    with pytest.raises(ValueError, match="sample 1 input function 0"):
        validate_samples([good, bigf], pad_nodes=64, pad_funcs=16)
    for field, poison in (
        ("coordinates", lambda s: s.coords.__setitem__((0, 0), np.nan)),
        ("theta", lambda s: s.theta.__setitem__(0, np.inf)),
        ("target", lambda s: s.y.__setitem__((1, 0), np.nan)),
        ("input function", lambda s: s.funcs[0].__setitem__((2, 1), np.nan)),
    ):
        bad = mk()
        poison(bad)
        with pytest.raises(ValueError, match=f"sample 2.*{field}"):
            validate_samples([good, mk(), bad])
    # check_finite=False restores the old shape-only behavior.
    bad = mk()
    bad.coords[0, 0] = np.nan
    validate_samples([bad], check_finite=False)


def test_pad_rows_noop_when_equal():
    x = np.ones((4, 2), np.float32)
    assert pad_rows(x, 4) is x


def test_pickle_roundtrip(tmp_path):
    samples = ragged_samples()
    path = str(tmp_path / "data.pkl")
    datasets.save_pickle(samples, path)
    loaded = datasets.load_pickle(path)
    assert len(loaded) == len(samples)
    for a, b in zip(samples, loaded):
        np.testing.assert_array_equal(a.coords, b.coords)
        np.testing.assert_array_equal(a.y, b.y)
        np.testing.assert_array_equal(a.theta, b.theta)
        for fa, fb in zip(a.funcs, b.funcs):
            np.testing.assert_array_equal(fa, fb)


@pytest.mark.parametrize("name", sorted(datasets.SYNTHETIC))
def test_synthetic_generators(name):
    samples = datasets.SYNTHETIC[name](4, seed=1)
    assert len(samples) == 4
    dims = datasets.infer_model_dims(samples)
    assert dims["out_dim"] >= 1
    batch = collate(samples)
    assert np.isfinite(batch.coords).all() and np.isfinite(batch.y).all()
    # determinism
    again = datasets.SYNTHETIC[name](4, seed=1)
    np.testing.assert_array_equal(samples[0].coords, again[0].coords)


def test_infer_dims_matches_reference_shape_inference():
    """Shape inference from sample 0 (reference main.py:30-35)."""
    samples = ragged_samples()
    dims = datasets.infer_model_dims(samples)
    assert dims == dict(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1, n_input_functions=2
    )


def test_loader_shuffle_deterministic_by_seed():
    samples = ragged_samples() * 4
    l1 = [b.coords.sum() for b in Loader(samples, 4, shuffle=True, seed=7)]
    l2 = [b.coords.sum() for b in Loader(samples, 4, shuffle=True, seed=7)]
    assert l1 == l2
    # different epochs reshuffle
    loader = Loader(samples, 4, shuffle=True, seed=7)
    e1 = [b.coords.sum() for b in loader]
    e2 = [b.coords.sum() for b in loader]
    assert e1 != e2


def test_loader_prefetch_matches_sync():
    """Prefetching yields identical batches in identical order."""
    samples = ragged_samples() * 6
    sync = list(Loader(samples, 4, shuffle=True, seed=3, prefetch=0))
    pre = list(Loader(samples, 4, shuffle=True, seed=3, prefetch=2))
    assert len(sync) == len(pre)
    for a, b in zip(sync, pre):
        np.testing.assert_array_equal(a.coords, b.coords)
        np.testing.assert_array_equal(a.node_mask, b.node_mask)
        np.testing.assert_array_equal(a.funcs, b.funcs)


def test_loader_prefetch_abandoned_epoch_no_deadlock():
    samples = ragged_samples() * 20
    loader = Loader(samples, 2, prefetch=1)
    it = iter(loader)
    next(it)
    it.close()  # abandon mid-epoch; producer must shut down cleanly
    # a fresh epoch still works
    assert len(list(loader)) == len(loader)


def test_loader_prefetch_propagates_worker_errors():
    samples = ragged_samples()
    loader = Loader(samples, 2)
    broken = Loader(samples, 2)
    broken._collate_at = lambda idx: (_ for _ in ()).throw(RuntimeError("boom"))
    import pytest

    with pytest.raises(RuntimeError, match="boom"):
        list(broken)
    assert len(list(loader)) == len(loader)


def test_fixed_pad_lengths_static_shapes():
    """Fixed pads give every batch one shape regardless of composition."""
    from gnot_tpu.data.batch import fixed_pad_lengths

    samples = datasets.synth_elasticity(12, base_points=64, seed=9)
    pn, pf = fixed_pad_lengths(samples)
    shapes = set()
    for b in Loader(samples, 4, pad_nodes=pn, pad_funcs=pf, prefetch=0):
        shapes.add((b.coords.shape, b.funcs.shape))
        assert b.coords.shape[1] == pn and b.funcs.shape[2] == pf
    assert len(shapes) == 1
    # masks still reflect the true lengths
    total = sum(s.coords.shape[0] for s in samples)
    masked = sum(b.n_real_points for b in Loader(samples, 4, pad_nodes=pn, pad_funcs=pf))
    assert masked == total


def test_loader_epoch_shuffle_resumable():
    """Epoch order is a pure function of (seed, epoch): a loader pinned
    to epoch N via set_epoch reproduces the order a continuous run saw
    at epoch N (resume fidelity)."""
    samples = datasets.synth_ns2d(12, n_points=8)
    cont = Loader(samples, 4, shuffle=True, seed=7, prefetch=0)
    orders = []
    for _ in range(3):  # epochs 0..2
        orders.append([b.theta.tobytes() for b in cont])

    resumed = Loader(samples, 4, shuffle=True, seed=7, prefetch=0)
    resumed.set_epoch(2)
    assert [b.theta.tobytes() for b in resumed] == orders[2]
    # and epochs actually differ from each other
    assert orders[0] != orders[1]


def make_reference_contract_records():
    """Records EXACTLY as the reference's NS2dDataset ingests them
    (/root/reference/dataset.py:7,30-38): X/Y numpy (any float dtype —
    the reference casts with .float()), theta kept raw (scalar, 0-d, or
    array), input functions tuple- OR list-wrapped (truthiness-checked
    there), torch tensors accepted anywhere np.asarray is (from_numpy
    sources are numpy, but torch-written pickles carry tensors)."""
    import torch

    rng = np.random.default_rng(11)

    def xy(n, d=2, c=1, dtype=np.float64):
        return (
            rng.normal(size=(n, d)).astype(dtype),
            rng.normal(size=(n, c)).astype(dtype),
        )

    x0, y0 = xy(7)
    x1, y1 = xy(5)
    x2, y2 = xy(9, dtype=np.float32)
    x3, y3 = xy(4)
    return [
        # tuple-wrapped float64 funcs, scalar python-float theta
        [x0, y0, 0.25, (rng.normal(size=(6, 3)), rng.normal(size=(8, 3)))],
        # list-wrapped funcs, 0-d numpy theta
        [x1, y1, np.float64(1.5), [rng.normal(size=(3, 3)).astype(np.float32)]],
        # torch-tensor X/Y/funcs, 1-d theta
        [
            torch.from_numpy(x2),
            torch.from_numpy(y2),
            np.array([0.1, 0.2]),
            (torch.from_numpy(rng.normal(size=(5, 3)).astype(np.float32)),),
        ],
        # empty input functions (reference: `if input_function:` is False)
        [x3, y3, np.array([0.3]), ()],
    ]


def test_load_pickle_reference_contract(tmp_path):
    import pickle

    records = make_reference_contract_records()
    p = tmp_path / "ref_contract.pkl"
    with open(p, "wb") as f:
        pickle.dump(records, f)

    samples = datasets.load_pickle(str(p))
    assert len(samples) == 4
    for s, rec in zip(samples, records):
        assert s.coords.dtype == np.float32 and s.coords.ndim == 2
        assert s.y.dtype == np.float32 and s.y.shape[0] == s.coords.shape[0]
        assert s.theta.dtype == np.float32 and s.theta.ndim == 1
        np.testing.assert_allclose(
            s.coords, np.asarray(rec[0], np.float32), rtol=1e-6
        )
        assert isinstance(s.funcs, tuple)
        for fi, raw in zip(s.funcs, rec[3]):
            assert fi.dtype == np.float32 and fi.ndim == 2
            np.testing.assert_allclose(fi, np.asarray(raw, np.float32), rtol=1e-6)
    assert samples[3].funcs == ()
    assert float(samples[0].theta[0]) == pytest.approx(0.25)


@pytest.mark.parametrize(
    "record,match",
    [
        (["just-one-entry"], "at least 3 entries"),
        (None, "must be \\[X, Y, theta"),
        ([np.zeros((4, 2)), np.zeros((5, 1)), 0.0, ()], "matching n"),
        ([np.zeros(4), np.zeros((4, 1)), 0.0, ()], "X \\(4,\\)"),
        ([np.zeros((4, 2)), np.zeros((4, 1)), "nan?", ()], "non-numeric"),
        ([np.zeros((4, 2)), np.zeros((4, 1)), 0.0, (np.zeros(3),)], "must be"),
        # ndarray funcs container: a clear message, not an
        # ambiguous-truthiness error
        ([np.zeros((4, 2)), np.zeros((4, 1)), 0.0, np.ones((5, 3))], "tuple or list"),
        # 2-d theta would break query_features' broadcast deep inside
        ([np.zeros((4, 2)), np.zeros((4, 1)), np.zeros((1, 2)), ()], "theta"),
    ],
)
def test_load_pickle_malformed_record_messages(record, match, tmp_path):
    """Malformed records raise a ValueError naming the record and the
    schema — not an IndexError / broadcast error from deep inside."""
    import pickle

    good = [np.zeros((4, 2), np.float32), np.zeros((4, 1), np.float32), 0.0, ()]
    p = tmp_path / "bad.pkl"
    with open(p, "wb") as f:
        pickle.dump([good, record], f)
    with pytest.raises(ValueError, match=match) as exc:
        datasets.load_pickle(str(p))
    assert "record 1" in str(exc.value)


def test_load_pickle_non_list_toplevel(tmp_path):
    import pickle

    p = tmp_path / "notalist.pkl"
    with open(p, "wb") as f:
        pickle.dump({"x": 1}, f)
    with pytest.raises(ValueError, match="pickled list"):
        datasets.load_pickle(str(p))


def test_packed_loader_covers_every_sample_each_epoch():
    """Open-bin first-fit packing: every sample appears exactly once
    per epoch (any shuffle), placements are chunk-aligned and
    non-overlapping, and fill beats the naive bound."""
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import PackedLoader

    samples = datasets.synth_elasticity(37, seed=2)
    loader = PackedLoader(samples, batch_size=8, chunk=128, shuffle=True, seed=1)
    for epoch in (0, 1):
        loader.set_epoch(epoch)
        dispatches = loader._epoch_dispatches()
        seen = sorted(i for idx, _ in dispatches for i in idx)
        assert seen == list(range(len(samples)))
        total_real = 0
        for d in dispatches:
            b = loader._collate_at(d)
            total_real += b.n_real_points
            # No token is claimed by two samples: per-row masks of
            # distinct slots are disjoint by construction; check the
            # aggregate instead — mask count equals the sum of lengths.
        assert total_real == sum(s.coords.shape[0] for s in samples)
    # Fill: real tokens / allocated tokens comfortably above the ~70%
    # bucket-padding utilization this feature exists to beat.
    rows = sum(len(loader._epoch_dispatches()) for _ in (0,)) * loader.n_rows
    fill = total_real / (rows * loader.row_len)
    assert fill > 0.7, f"fill {fill:.2%}"
    # len() is EXACT for the canonical (unshuffled) stream — eval-side
    # truncation by a wrong count would silently drop samples.
    unshuffled = PackedLoader(samples, batch_size=8, chunk=128)
    assert len(list(unshuffled)) == len(unshuffled)


def _mesh(n, seed=0, n_func=12):
    rng = np.random.default_rng(seed + n)
    return MeshSample(
        coords=rng.uniform(0, 1, size=(n, 2)).astype(np.float32),
        y=np.zeros((n, 1), np.float32),
        theta=np.zeros((1,), np.float32),
        funcs=(rng.uniform(0, 1, size=(n_func, 3)).astype(np.float32),),
    )


def test_pack_plan_from_samples_invariants():
    """The serve-side PackPlan derives a static dispatch shape from
    representative traffic: chunk-aligned row_len, slot capacity no
    packing can overflow, bucketed pad_funcs covering every function."""
    from gnot_tpu.data.batch import PackPlan, bucket_length

    samples = [_mesh(n) for n in (40, 90, 130, 64, 200)]
    plan = PackPlan.from_samples(samples, chunk=64, batch_size=4)
    assert plan.row_len % plan.chunk == 0
    assert plan.n_slots == plan.n_rows * (plan.row_len // plan.chunk)
    assert plan.pad_funcs == bucket_length(12)
    # Every sample in the representative set is packable by its own plan.
    assert all(plan.packable(s) for s in samples)
    # Oversize (aligned span exceeds a row) and over-long functions are not.
    assert not plan.packable(_mesh(plan.row_len + 1))
    assert not plan.packable(_mesh(40, n_func=plan.pad_funcs + 1))
    with pytest.raises(ValueError, match="at least one sample"):
        PackPlan.from_samples([], chunk=64)
    with pytest.raises(ValueError, match="multiple of chunk"):
        PackPlan(row_len=100, chunk=64, n_rows=1, n_slots=1, pad_funcs=0)


def test_pack_prefix_is_fifo_prefix():
    """pack_prefix packs an ARRIVAL-ORDER PREFIX: it stops at the first
    sample that fits nowhere — a newer small request never overtakes an
    older big one (the Batcher's FIFO/monotone queue-wait contract
    depends on this). Placements are chunk-aligned, in-bounds and
    non-overlapping."""
    from gnot_tpu.data.batch import PackPlan, pack_prefix

    plan = PackPlan(row_len=256, chunk=64, n_rows=2, n_slots=8, pad_funcs=64)
    # 200 -> 256 aligned fills row 0; 200 again fills row 1; the 250
    # fits NOWHERE, so packing stops there even though the trailing 10
    # would fit — prefix discipline.
    sizes = [200, 200, 250, 10]
    placed = pack_prefix(sizes, plan)
    assert len(placed) == 2
    used: set = set()
    for (r, off), n in zip(placed, sizes):
        assert 0 <= r < plan.n_rows and off % plan.chunk == 0
        span = range(off, off + plan.aligned(n))
        assert off + plan.aligned(n) <= plan.row_len
        assert not (used & set((r, t) for t in span))
        used |= set((r, t) for t in span)
    # Small meshes pack many-per-row, capped by the slot budget.
    placed_small = pack_prefix([10] * 20, plan)
    assert len(placed_small) == plan.n_slots
    # Everything fitting -> everything placed.
    assert len(pack_prefix([64, 64, 64], plan)) == 3
