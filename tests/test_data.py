"""Data layer tests: reference-faithful padding semantics, pickle
round-trip, bucketing, synthetic generators."""

import numpy as np
import pytest

from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader, MeshSample, bucket_length, collate, pad_rows


def ragged_samples():
    rng = np.random.default_rng(0)
    out = []
    for n, m1, m2 in [(5, 3, 7), (9, 4, 2), (6, 8, 5)]:
        out.append(
            MeshSample(
                coords=rng.normal(size=(n, 2)).astype(np.float32),
                y=rng.normal(size=(n, 1)).astype(np.float32),
                theta=np.array([1.0], np.float32),
                funcs=(
                    rng.normal(size=(m1, 3)).astype(np.float32),
                    rng.normal(size=(m2, 3)).astype(np.float32),
                ),
            )
        )
    return out


def test_collate_shared_func_max():
    """Input functions pad to ONE max across all functions of all samples
    (reference main.py:63), not per-function maxima."""
    batch = collate(ragged_samples(), bucket=False)
    assert batch.funcs.shape == (2, 3, 8, 3)  # shared max = 8
    assert batch.coords.shape == (3, 9, 2)  # per-batch node max = 9
    assert batch.func_mask.shape == (2, 3, 8)
    # masks count real lengths
    np.testing.assert_array_equal(batch.node_mask.sum(1), [5, 9, 6])
    np.testing.assert_array_equal(batch.func_mask[0].sum(1), [3, 4, 8])
    np.testing.assert_array_equal(batch.func_mask[1].sum(1), [7, 2, 5])


def test_collate_zero_tail_padding():
    batch = collate(ragged_samples(), bucket=False)
    assert (batch.coords[0, 5:] == 0).all()  # zero pad at tail (utils.py:3-4)
    assert (batch.y[2, 6:] == 0).all()


def test_bucketing_bounds_recompiles():
    ls = [bucket_length(n) for n in range(1, 3000, 37)]
    assert len(set(ls)) <= 12  # O(log L) distinct shapes
    for n in range(1, 3000, 37):
        assert bucket_length(n) >= n


def test_pad_rows_noop_when_equal():
    x = np.ones((4, 2), np.float32)
    assert pad_rows(x, 4) is x


def test_pickle_roundtrip(tmp_path):
    samples = ragged_samples()
    path = str(tmp_path / "data.pkl")
    datasets.save_pickle(samples, path)
    loaded = datasets.load_pickle(path)
    assert len(loaded) == len(samples)
    for a, b in zip(samples, loaded):
        np.testing.assert_array_equal(a.coords, b.coords)
        np.testing.assert_array_equal(a.y, b.y)
        np.testing.assert_array_equal(a.theta, b.theta)
        for fa, fb in zip(a.funcs, b.funcs):
            np.testing.assert_array_equal(fa, fb)


@pytest.mark.parametrize("name", sorted(datasets.SYNTHETIC))
def test_synthetic_generators(name):
    samples = datasets.SYNTHETIC[name](4, seed=1)
    assert len(samples) == 4
    dims = datasets.infer_model_dims(samples)
    assert dims["out_dim"] >= 1
    batch = collate(samples)
    assert np.isfinite(batch.coords).all() and np.isfinite(batch.y).all()
    # determinism
    again = datasets.SYNTHETIC[name](4, seed=1)
    np.testing.assert_array_equal(samples[0].coords, again[0].coords)


def test_infer_dims_matches_reference_shape_inference():
    """Shape inference from sample 0 (reference main.py:30-35)."""
    samples = ragged_samples()
    dims = datasets.infer_model_dims(samples)
    assert dims == dict(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1, n_input_functions=2
    )


def test_loader_shuffle_deterministic_by_seed():
    samples = ragged_samples() * 4
    l1 = [b.coords.sum() for b in Loader(samples, 4, shuffle=True, seed=7)]
    l2 = [b.coords.sum() for b in Loader(samples, 4, shuffle=True, seed=7)]
    assert l1 == l2
    # different epochs reshuffle
    loader = Loader(samples, 4, shuffle=True, seed=7)
    e1 = [b.coords.sum() for b in loader]
    e2 = [b.coords.sum() for b in loader]
    assert e1 != e2


def test_loader_prefetch_matches_sync():
    """Prefetching yields identical batches in identical order."""
    samples = ragged_samples() * 6
    sync = list(Loader(samples, 4, shuffle=True, seed=3, prefetch=0))
    pre = list(Loader(samples, 4, shuffle=True, seed=3, prefetch=2))
    assert len(sync) == len(pre)
    for a, b in zip(sync, pre):
        np.testing.assert_array_equal(a.coords, b.coords)
        np.testing.assert_array_equal(a.node_mask, b.node_mask)
        np.testing.assert_array_equal(a.funcs, b.funcs)


def test_loader_prefetch_abandoned_epoch_no_deadlock():
    samples = ragged_samples() * 20
    loader = Loader(samples, 2, prefetch=1)
    it = iter(loader)
    next(it)
    it.close()  # abandon mid-epoch; producer must shut down cleanly
    # a fresh epoch still works
    assert len(list(loader)) == len(loader)


def test_loader_prefetch_propagates_worker_errors():
    samples = ragged_samples()
    loader = Loader(samples, 2)
    broken = Loader(samples, 2)
    broken._collate_at = lambda idx: (_ for _ in ()).throw(RuntimeError("boom"))
    import pytest

    with pytest.raises(RuntimeError, match="boom"):
        list(broken)
    assert len(list(loader)) == len(loader)


def test_fixed_pad_lengths_static_shapes():
    """Fixed pads give every batch one shape regardless of composition."""
    from gnot_tpu.data.batch import fixed_pad_lengths

    samples = datasets.synth_elasticity(12, base_points=64, seed=9)
    pn, pf = fixed_pad_lengths(samples)
    shapes = set()
    for b in Loader(samples, 4, pad_nodes=pn, pad_funcs=pf, prefetch=0):
        shapes.add((b.coords.shape, b.funcs.shape))
        assert b.coords.shape[1] == pn and b.funcs.shape[2] == pf
    assert len(shapes) == 1
    # masks still reflect the true lengths
    total = sum(s.coords.shape[0] for s in samples)
    masked = sum(b.n_real_points for b in Loader(samples, 4, pad_nodes=pn, pad_funcs=pf))
    assert masked == total


def test_loader_epoch_shuffle_resumable():
    """Epoch order is a pure function of (seed, epoch): a loader pinned
    to epoch N via set_epoch reproduces the order a continuous run saw
    at epoch N (resume fidelity)."""
    samples = datasets.synth_ns2d(12, n_points=8)
    cont = Loader(samples, 4, shuffle=True, seed=7, prefetch=0)
    orders = []
    for _ in range(3):  # epochs 0..2
        orders.append([b.theta.tobytes() for b in cont])

    resumed = Loader(samples, 4, shuffle=True, seed=7, prefetch=0)
    resumed.set_epoch(2)
    assert [b.theta.tobytes() for b in resumed] == orders[2]
    # and epochs actually differ from each other
    assert orders[0] != orders[1]
