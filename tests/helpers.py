"""Shared assertions for the test suite (not collected by pytest)."""

import numpy as np


def skip_if_pipe_tp_unsupported(mesh_cfg) -> None:
    """Skip composed pipe x TP mesh tests on jax 0.4.x: its XLA rejects
    the pipeline's manual shard_map ``pipe`` axis composing with a
    GSPMD-auto ``model`` axis — every program compiles to
    "UNIMPLEMENTED: PartitionId instruction is not supported for SPMD
    partitioning". An upstream XLA limitation of the 0.4.37 toolchain
    (the pinned jax ~= 0.9 compiles these fine); skipping keeps tier-1
    signal clean without hiding real regressions on either axis alone."""
    import jax
    import pytest

    if (
        jax.__version__.startswith("0.4.")
        and getattr(mesh_cfg, "model", 1) > 1
        and getattr(mesh_cfg, "pipe", 1) > 1
    ):
        pytest.skip(
            "jax 0.4.x XLA cannot compose the manual shard_map pipe axis "
            "with a GSPMD model axis ('PartitionId not supported for "
            "SPMD' — upstream limitation, fixed in newer jax/XLA)"
        )


def assert_epoch_lines_close(out_a: str, out_b: str, rtol: float) -> None:
    """Compare two runs' reference-format console outputs line by line:
    same Epoch-line structure, numeric values equal to ``rtol``. The
    values come from different compiled programs, which may fuse float
    reductions differently — compare parsed floats, not reprs."""
    lines_a = [l for l in out_a.splitlines() if l.startswith("Epoch")]
    lines_b = [l for l in out_b.splitlines() if l.startswith("Epoch")]
    assert len(lines_a) == len(lines_b) and lines_a
    for a, b in zip(lines_a, lines_b):
        prefix_a, val_a = a.rsplit(": ", 1)
        prefix_b, val_b = b.rsplit(": ", 1)
        assert prefix_a == prefix_b
        np.testing.assert_allclose(
            float(val_a), float(val_b), rtol=rtol,
            err_msg=f"console outputs diverge: {a!r} vs {b!r}",
        )
