"""Shared assertions for the test suite (not collected by pytest)."""

import numpy as np


def assert_epoch_lines_close(out_a: str, out_b: str, rtol: float) -> None:
    """Compare two runs' reference-format console outputs line by line:
    same Epoch-line structure, numeric values equal to ``rtol``. The
    values come from different compiled programs, which may fuse float
    reductions differently — compare parsed floats, not reprs."""
    lines_a = [l for l in out_a.splitlines() if l.startswith("Epoch")]
    lines_b = [l for l in out_b.splitlines() if l.startswith("Epoch")]
    assert len(lines_a) == len(lines_b) and lines_a
    for a, b in zip(lines_a, lines_b):
        prefix_a, val_a = a.rsplit(": ", 1)
        prefix_b, val_b = b.rsplit(": ", 1)
        assert prefix_a == prefix_b
        np.testing.assert_allclose(
            float(val_a), float(val_b), rtol=rtol,
            err_msg=f"console outputs diverge: {a!r} vs {b!r}",
        )
