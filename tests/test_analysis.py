"""graftlint (gnot_tpu/analysis/): per-rule known-bad/clean fixtures,
suppression handling, config, the CLI, and THE tier-1 gate — zero
findings over the real gnot_tpu/ tree.

Fixture discipline: every rule gets one minimal offender and one clean
twin, so a rule regression (stops firing, or starts over-firing) is
caught independently of the codebase scan.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from gnot_tpu.analysis import LintConfig, run_analysis
from gnot_tpu.analysis.core import FileContext, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: A minimal event registry for fixture sandboxes (GL005 resolves
#: kinds against the tree it lints, not this repo).
MINI_REGISTRY = '''
GOOD = "good_event"
EVENTS = {
    "good_event": None,
}
'''


def lint_source(tmp_path, source, *, rules=None, registry=False, config=None):
    """Write ``source`` into a sandbox tree and run the analysis on it.
    Returns (findings, stats)."""
    cfg = config or LintConfig()
    if rules:
        cfg.enable = list(rules)
    root = str(tmp_path)
    target = tmp_path / "mod.py"
    target.write_text(textwrap.dedent(source))
    if registry:
        reg = tmp_path / "gnot_tpu" / "obs"
        reg.mkdir(parents=True, exist_ok=True)
        (reg / "events.py").write_text(MINI_REGISTRY)
        (tmp_path / "docs").mkdir(exist_ok=True)
        (tmp_path / "docs" / "observability.md").write_text("`good_event`\n")
        (tmp_path / "docs" / "robustness.md").write_text("")
    return run_analysis(["mod.py"], root=root, config=cfg)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# --- GL001 use-after-donate ------------------------------------------------

GL001_BAD = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return state + batch

    def train(state, batches):
        for b in batches:
            out = step(state, b)
        return out
"""

GL001_CLEAN = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return state, state + batch

    def train(state, batches):
        for b in batches:
            state, out = step(state, b)
        return state, out
"""


def test_gl001_fires_on_use_after_donate(tmp_path):
    findings, _ = lint_source(tmp_path, GL001_BAD, rules=["GL001"])
    assert rule_ids(findings) == ["GL001"]
    assert "donated" in findings[0].message


def test_gl001_silent_on_rebind(tmp_path):
    findings, _ = lint_source(tmp_path, GL001_CLEAN, rules=["GL001"])
    assert findings == []


def test_gl001_read_after_call_same_block(tmp_path):
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state):
            return state

        def run(state):
            new = step(state)
            return state.params, new
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1 and "state" in findings[0].message


def test_gl001_attribute_never_rebound_in_nested_helper(tmp_path):
    """The PR-2 bug shape: the donating call sits in a nested helper
    that never rebinds the donated `self.state` — the later readers
    live past the def boundary, so the absence of a rebind IS the
    finding (a scan of the helper alone would see no use at all)."""
    src = """
        class T:
            def fit(self):
                def run_single(batch):
                    out = self.train_step(self.state, batch, lr)
                    losses.append(out)
                for b in batches:
                    run_single(b)
                return self.state
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1 and "self.state" in findings[0].message


def test_gl001_configured_callable_names(tmp_path):
    src = """
        class T:
            def fit(self):
                self.state, loss = self.train_step(self.state, b, lr)
                return loss

            def bad(self):
                out = self.train_step(self.state, b, lr)
                return self.state, out
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1
    assert "self.state" in findings[0].message


# --- GL001 via the donation call graph -------------------------------------


def test_gl001_call_graph_wrapper_donor(tmp_path):
    """A helper that feeds its parameter into a donating call becomes
    a donor itself (the run_single-wrapper shape): callers that fail
    to rebind are flagged, callers that rebind are clean."""
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, batch):
            return state, batch

        def run_one(state, batch):
            state, out = step(state, batch)
            return state, out

        def bad(state, batches):
            out = run_one(state, batches[0])
            return state, out

        def good(state, batches):
            state, out = run_one(state, batches[0])
            return state, out
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1
    assert "run_one" in findings[0].message


def test_gl001_factory_assigned_step(tmp_path):
    """`step = make_train_step(...)` binds a donating callable: the
    factory's returned jit (donate_argnums=(0,)) flows to the local
    name — the literal shape of the historical test-side bugs."""
    src = """
        import functools
        import jax

        def make_train_step(model):
            @functools.partial(jax.jit, donate_argnums=(0,))
            def train_step(state, batch, lr):
                return state, 0.0
            return train_step

        def bad(model, state, b, lr):
            step = make_train_step(model)
            out = step(state, b, lr)
            return state, out

        def good(model, state, b, lr):
            step = make_train_step(model)
            state, out = step(state, b, lr)
            return state, out
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1
    assert "`state`" in findings[0].message


def test_gl001_factory_returning_jit_expression(tmp_path):
    """Factories that `return jax.jit(step, ..., donate_argnums=(0,))`
    directly (make_sharded_train_step's shape) are recognized too."""
    src = """
        import jax

        def make_sharded_train_step(body):
            def step(state, batch, lr):
                return body(state, (batch, lr))
            return jax.jit(step, donate_argnums=(0,))

        def bad(body, state, b, lr):
            step = make_sharded_train_step(body)
            out = step(state, b, lr)
            return state, out
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL001"])
    assert len(findings) == 1


# --- GL002 host-sync-in-hot-path ------------------------------------------

GL002_BAD = """
    import jax
    import numpy as np

    @jax.jit
    def hot(x):
        return float(x) + x.item()

    def body(carry, x):
        np.asarray(carry)
        return carry, x

    def run(xs):
        return jax.lax.scan(body, 0.0, xs)
"""

GL002_CLEAN = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def hot(x):
        return jnp.sum(x)

    def cold(x):
        return float(np.asarray(x))  # host-side caller: fine
"""


def test_gl002_fires_in_jitted_and_scanned_bodies(tmp_path):
    findings, _ = lint_source(tmp_path, GL002_BAD, rules=["GL002"])
    assert rule_ids(findings) == ["GL002"]
    msgs = " ".join(f.message for f in findings)
    assert ".item()" in msgs and "float" in msgs and "asarray" in msgs
    assert len(findings) == 3


def test_gl002_silent_outside_hot_code(tmp_path):
    findings, _ = lint_source(tmp_path, GL002_CLEAN, rules=["GL002"])
    assert findings == []


def test_gl002_hot_container_nested_body(tmp_path):
    src = """
        def train_step_body(cfg):
            def body(state, xs):
                return state, float(xs)
            return body
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL002"])
    assert len(findings) == 1 and "body" in findings[0].message


GL002_TRACER_BAD = """
    import jax

    def train_step_body(cfg, tracer):
        def body(state, xs):
            with tracer.span("step"):
                state = state + xs
            return state, xs
        return body

    @jax.jit
    def hot(x, tracer):
        tracer.add_span("device", 0.0, 1.0, trace="t1")
        return x
"""

GL002_TRACER_CLEAN = """
    import jax

    @jax.jit
    def step(state, xs):
        return state + xs, xs

    def run_single(batch, state, tracer, trace):
        # Host-side span AROUND the dispatch: the trainer's pattern.
        with tracer.span("step_dispatch", trace=trace):
            state, out = step(state, batch)
        return state, out

    def spanner(tracer):
        tracer.start_trace()
        tracer.flush()
"""


def test_gl002_tracer_calls_in_hot_code_flagged(tmp_path):
    """Tracing must stay host-side by construction: a Tracer span site
    inside a compiled step body is flagged like any other host op (it
    would run once at trace time and time nothing real)."""
    findings, _ = lint_source(tmp_path, GL002_TRACER_BAD, rules=["GL002"])
    assert rule_ids(findings) == ["GL002"]
    msgs = " ".join(f.message for f in findings)
    assert "Tracer.span" in msgs and "Tracer.add_span" in msgs
    assert len(findings) == 2


def test_gl002_tracer_host_side_clean(tmp_path):
    """Spans AROUND dispatch (the trainer/server pattern) are host-side
    and clean — only tracer calls INSIDE hot bodies fire."""
    findings, _ = lint_source(tmp_path, GL002_TRACER_CLEAN, rules=["GL002"])
    assert findings == []


# --- GL003 recompile-hazard -----------------------------------------------

GL003_BAD = """
    import functools
    import jax

    def run(fs, x):
        outs = []
        for f in fs:
            outs.append(jax.jit(f)(x))
        return outs

    @functools.partial(jax.jit, static_argnums=(1,))
    def step(x, cfg=[1, 2]):
        return x
"""

GL003_CLEAN = """
    import functools
    import jax

    def run(fs, x):
        jitted = [jax.jit(f) for f in fs]  # comprehension: builder, once
        return [f(x) for f in jitted]

    @functools.partial(jax.jit, static_argnums=(1,))
    def step(x, cfg=(1, 2)):
        return x
"""


def test_gl003_fires_on_loop_jit_and_mutable_static(tmp_path):
    findings, _ = lint_source(tmp_path, GL003_BAD, rules=["GL003"])
    assert rule_ids(findings) == ["GL003"]
    msgs = " ".join(f.message for f in findings)
    assert "inside a loop" in msgs and "non-hashable" in msgs
    assert len(findings) == 2


def test_gl003_silent_on_hoisted_and_hashable(tmp_path):
    findings, _ = lint_source(tmp_path, GL003_CLEAN, rules=["GL003"])
    assert findings == []


# --- GL004 lock-discipline -------------------------------------------------

GL004_BAD = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._completed = 0  #: guarded_by _lock

        def finish(self):
            self._completed += 1

        def stats(self):
            return self._completed
"""

GL004_CLEAN = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._completed = 0  #: guarded_by _lock
            self._unguarded = 0  # plain field: no annotation, no rule

        def finish(self):
            with self._lock:
                self._completed += 1
            self._unguarded += 1

        def stats(self):
            with self._lock:
                return self._completed
"""


def test_gl004_fires_on_unguarded_access(tmp_path):
    findings, _ = lint_source(tmp_path, GL004_BAD, rules=["GL004"])
    assert rule_ids(findings) == ["GL004"]
    assert len(findings) == 2  # the write and the read
    assert "written" in findings[0].message or "read" in findings[0].message


def test_gl004_silent_under_lock(tmp_path):
    findings, _ = lint_source(tmp_path, GL004_CLEAN, rules=["GL004"])
    assert findings == []


def test_gl004_init_exempt(tmp_path):
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  #: guarded_by _lock
                self._n = self._n + 1  # construction: not shared yet
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL004"])
    assert findings == []


# --- GL005 registry-drift --------------------------------------------------

GL005_BAD = """
    def emit(sink):
        sink.log(event="unregistered_kind", x=1)
"""

GL005_CLEAN = """
    def emit(sink):
        sink.log(event="good_event", x=1)
        sink.log(step=3, loss=0.5)  # metric record: no event key
"""


def test_gl005_fires_on_unregistered_kind(tmp_path):
    findings, _ = lint_source(
        tmp_path, GL005_BAD, rules=["GL005"], registry=True
    )
    assert rule_ids(findings) == ["GL005"]
    assert "unregistered_kind" in findings[0].message


def test_gl005_silent_on_registered_kind(tmp_path):
    findings, _ = lint_source(
        tmp_path, GL005_CLEAN, rules=["GL005"], registry=True
    )
    assert findings == []


def test_gl005_docs_coverage(tmp_path):
    """A registered-but-undocumented kind is a project-level finding."""
    (tmp_path / "gnot_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "gnot_tpu" / "obs" / "events.py").write_text(
        'EVENTS = {"undocumented_kind": None}\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text("nothing here\n")
    (tmp_path / "docs" / "robustness.md").write_text("")
    (tmp_path / "mod.py").write_text("x = 1\n")
    cfg = LintConfig(enable=["GL005"])
    findings, _ = run_analysis(["mod.py"], root=str(tmp_path), config=cfg)
    assert len(findings) == 1
    assert "undocumented_kind" in findings[0].message
    assert findings[0].path == "gnot_tpu/obs/events.py"


#: A minimal wire-message registry for fixture sandboxes (the GL005
#: wire-site check resolves ``wire(X, ...)`` against the MESSAGES dict
#: of the tree it lints, exactly like EVENTS for emit sites).
MINI_MESSAGES = '''
GOOD_MSG = "good_msg"
MESSAGES = {
    "good_msg": None,
}
'''


def _messages_sandbox(tmp_path, *, serving_doc="`good_msg`\n"):
    """Registry + docs scaffolding for the wire-site fixtures (events
    side included so the project pass has nothing else to report)."""
    reg = tmp_path / "gnot_tpu" / "serve"
    reg.mkdir(parents=True, exist_ok=True)
    (reg / "federation.py").write_text(MINI_MESSAGES)
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "serving.md").write_text(serving_doc)


GL005_WIRE_BAD = """
    def ping(link):
        link.send(wire("bogus_kind", x=1))
"""

GL005_WIRE_CLEAN = """
    GOOD_MSG = "good_msg"

    def ping(link):
        link.send(wire("good_msg", x=1))
        link.send(wire(GOOD_MSG, x=2))  # constant form resolves too
"""


def test_gl005_fires_on_unregistered_wire_kind(tmp_path):
    _messages_sandbox(tmp_path)
    findings, _ = lint_source(
        tmp_path, GL005_WIRE_BAD, rules=["GL005"], registry=True
    )
    assert rule_ids(findings) == ["GL005"]
    assert len(findings) == 1
    assert "bogus_kind" in findings[0].message
    assert "MESSAGES" in findings[0].message


def test_gl005_silent_on_registered_wire_kind(tmp_path):
    _messages_sandbox(tmp_path)
    findings, _ = lint_source(
        tmp_path, GL005_WIRE_CLEAN, rules=["GL005"], registry=True
    )
    assert findings == []


def test_gl005_messages_docs_coverage(tmp_path):
    """A MESSAGES kind missing its code-token mention in
    docs/serving.md is a project-level finding anchored at the wire
    registry — the federation protocol table must stay complete."""
    _messages_sandbox(tmp_path, serving_doc="prose only, no token\n")
    findings, _ = lint_source(tmp_path, "x = 1\n", rules=["GL005"],
                              registry=True)
    assert len(findings) == 1
    assert "'good_msg'" in findings[0].message
    assert findings[0].path == "gnot_tpu/serve/federation.py"


def test_gl005_unparseable_messages_is_a_finding(tmp_path):
    """A wire registry that EXISTS but whose MESSAGES is not a literal
    dict must fail loudly, mirroring the EVENTS loudness contract."""
    _messages_sandbox(tmp_path)
    (tmp_path / "gnot_tpu" / "serve" / "federation.py").write_text(
        "MESSAGES = dict(good_msg=None)\n"
    )
    findings, _ = lint_source(tmp_path, "x = 1\n", rules=["GL005"],
                              registry=True)
    assert len(findings) == 1
    assert "MESSAGES is not parseable" in findings[0].message


# --- GL006 aliased-host-view ------------------------------------------------

#: The PR-7 historical bug, reconstructed pre-fix
#: (test_sharded_multi_step_matches_single_device): a zero-copy
#: device_get snapshot taken BEFORE a loop of donating sharded steps
#: built via the factory, read after — measured 1.8e-3 silent drift.
GL006_PR7_PREFIX = """
    import jax
    import numpy as np

    def make_sharded_train_step(body):
        def step(state, batch, lr):
            return body(state, (batch, lr))
        return jax.jit(step, donate_argnums=(0,))

    def test_sharded_multi_step_matches_single_device(body, state, batches, lrs):
        host = jax.device_get(state.params)
        step = make_sharded_train_step(body)
        for b, lr in zip(batches, lrs):
            state, _ = step(state, b, lr)
        s2 = rebuild_from(host)
        return s2
"""

#: The PR-10 historical bug, reconstructed pre-fix
#: (test_convert_state_layout_roundtrip_resumes_training): the
#: mid-training snapshot `s_mid` was a device_get view of the state a
#: donating single-device step then advanced (~2.4e-2 loss drift).
GL006_PR10_PREFIX = """
    import jax
    import numpy as np

    def test_convert_state_layout_roundtrip_resumes_training(s_ref, batches, lr):
        s_mid = jax.device_get(s_ref)
        s_ref, _ = train_step(s_ref, batches[0], lr)
        stacked = stack_params(s_mid)
        return stacked
"""

#: The PR-6 historical bug, reconstructed pre-fix
#: (test_multi_step_dispatch_matches_single_steps): the "reference
#: start params" were np.asarray views over device_get, silently
#: advanced by the donating steps inside trainer.fit — resolvable only
#: through the project call graph (fit -> _run_epoch -> run_single ->
#: self.train_step donates self.state).
GL006_PR6_TRAINER = """
    class T:
        def fit(self, batches):
            self._run_epoch(batches)

        def _run_epoch(self, batches):
            def run_single(b):
                self.state, out = self.train_step(self.state, b, 0.1)
                return out
            for b in batches:
                run_single(b)
"""

GL006_PR6_PREFIX = """
    import jax
    import numpy as np

    def test_multi_step_dispatch_matches_single_steps(t, batches):
        ref = jax.tree.map(np.asarray, jax.device_get(t.state.params))
        t.fit(batches)
        np.testing.assert_allclose(ref[0], 1.0)
"""


def test_gl006_fires_on_pr7_shape(tmp_path):
    findings, _ = lint_source(tmp_path, GL006_PR7_PREFIX, rules=["GL006"])
    assert rule_ids(findings) == ["GL006"]
    assert len(findings) == 1
    assert "`host`" in findings[0].message
    assert "state.params" in findings[0].message


def test_gl006_fires_on_pr10_shape(tmp_path):
    findings, _ = lint_source(tmp_path, GL006_PR10_PREFIX, rules=["GL006"])
    assert len(findings) == 1
    assert "`s_mid`" in findings[0].message


def test_gl006_fires_on_pr6_shape_through_call_graph(tmp_path):
    """The fit-indirection form: no donating callable is named in the
    test at all — the project call graph must resolve t.fit() down to
    the donated self.state."""
    (tmp_path / "trainer_mod.py").write_text(
        textwrap.dedent(GL006_PR6_TRAINER)
    )
    (tmp_path / "mod.py").write_text(textwrap.dedent(GL006_PR6_PREFIX))
    findings, _ = run_analysis(
        ["."], root=str(tmp_path), config=LintConfig(enable=["GL006"])
    )
    gl6 = [f for f in findings if f.rule == "GL006"]
    assert len(gl6) == 1
    assert "`ref`" in gl6[0].message
    assert "t.state.params" in gl6[0].message
    assert "fit" in gl6[0].message


def test_gl006_clean_twins_of_all_three(tmp_path):
    """The committed fixes — copy-by-value snapshots — silence every
    historical shape (zero false positives on the fixed forms)."""
    fixes = [
        GL006_PR7_PREFIX.replace(
            "host = jax.device_get(state.params)",
            "host = jax.tree.map(np.array, jax.device_get(state.params))",
        ),
        GL006_PR10_PREFIX.replace(
            "s_mid = jax.device_get(s_ref)",
            "s_mid = jax.tree.map(np.array, jax.device_get(s_ref))",
        ),
    ]
    for src in fixes:
        findings, _ = lint_source(tmp_path, src, rules=["GL006"])
        assert findings == [], "\n".join(f.format() for f in findings)
    (tmp_path / "trainer_mod.py").write_text(
        textwrap.dedent(GL006_PR6_TRAINER)
    )
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            GL006_PR6_PREFIX.replace(
                "ref = jax.tree.map(np.asarray, jax.device_get(t.state.params))",
                "ref = jax.tree.map(np.array, jax.device_get(t.state.params))",
            )
        )
    )
    findings, _ = run_analysis(
        ["."], root=str(tmp_path), config=LintConfig(enable=["GL006"])
    )
    assert [f for f in findings if f.rule == "GL006"] == []


def test_gl006_rebound_source_breaks_the_link(tmp_path):
    """Rebinding the SOURCE before the donation detaches the view: it
    aliases the old buffers, which the donating call never touches."""
    src = """
        import jax
        import numpy as np

        def run(state, fresh, b, lr):
            host = jax.device_get(state.params)
            state = fresh()
            state, _ = train_step(state, b, lr)
            return host
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert findings == []


def test_gl006_read_in_donating_statement_is_clean(tmp_path):
    """Arguments of the donating call itself are evaluated before the
    donation — `step(state, host)` must not flag `host`."""
    src = """
        import jax

        def run(state, lr):
            host = jax.device_get(state.params)
            state, _ = train_step(state, host, lr)
            return state
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert findings == []


def test_gl006_np_asarray_seeds_alias(tmp_path):
    """np.asarray over a device value is the same zero-copy hazard as
    device_get (the forward-flow form in the parity ledger)."""
    src = """
        import jax
        import numpy as np

        def run(state, b, lr):
            host = np.asarray(state.params)
            state, _ = train_step(state, b, lr)
            return host
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert len(findings) == 1 and "`host`" in findings[0].message


def test_gl006_alias_propagation_and_chaining(tmp_path):
    """Name-to-name propagation (`h2 = host`) and the chained
    `np.asarray(jax.device_get(...))` form both keep the alias link."""
    src = """
        import jax
        import numpy as np

        def run(state, b, lr):
            host = np.asarray(jax.device_get(state.params))
            h2 = host
            state, _ = train_step(state, b, lr)
            return h2
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert len(findings) == 1 and "`h2`" in findings[0].message


def test_gl006_rebound_alias_after_donation_is_clean(tmp_path):
    """Rebinding the VIEW after the donation clears the poison — the
    read sees the fresh value, not the stale buffers."""
    src = """
        import jax
        import numpy as np

        def run(state, b, lr):
            host = jax.device_get(state.params)
            state, _ = train_step(state, b, lr)
            host = np.array([1.0])
            return host
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert findings == []


def test_gl006_sees_match_case_bodies(tmp_path):
    """Donations inside `match` arms must poison like any other
    compound statement (ast.Match keeps its arms under `cases`, not
    `body` — a walker that skips them is silently blind)."""
    src = """
        import jax

        def run(state, b, lr, mode):
            host = jax.device_get(state.params)
            match mode:
                case "train":
                    state, _ = train_step(state, b, lr)
                case _:
                    pass
            return host
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL006"])
    assert len(findings) == 1 and "`host`" in findings[0].message


def test_gl006_suppression(tmp_path):
    src = GL006_PR10_PREFIX.replace(
        "stacked = stack_params(s_mid)",
        "stacked = stack_params(s_mid)  "
        "# graftlint: disable=GL006 — fixture: stale on purpose",
    )
    findings, stats = lint_source(tmp_path, src, rules=["GL006"])
    assert findings == []
    assert stats["suppressed"] == 1


# --- suppressions ----------------------------------------------------------


def test_line_suppression_with_justification(tmp_path):
    src = GL004_BAD.replace(
        "self._completed += 1",
        "self._completed += 1  # graftlint: disable=GL004 — test-only path",
    ).replace(
        "return self._completed",
        "return self._completed  # graftlint: disable=GL004 — post-join read",
    )
    findings, stats = lint_source(tmp_path, src, rules=["GL004"])
    assert findings == []
    assert stats["suppressed"] == 2


def test_file_suppression(tmp_path):
    src = "# graftlint: disable-file=GL002\n" + textwrap.dedent(GL002_BAD)
    findings, _ = lint_source(tmp_path, src, rules=["GL002"])
    assert findings == []


def test_suppression_without_dash_justification(tmp_path):
    """The id capture is anchored to rule-id tokens: a justification
    NOT separated by a dash must not be swallowed into the id list."""
    src = GL004_BAD.replace(
        "self._completed += 1",
        "self._completed += 1  # graftlint: disable=GL004 worker only",
    )
    findings, stats = lint_source(tmp_path, src, rules=["GL004"])
    assert len(findings) == 1  # only the un-suppressed read remains
    assert stats["suppressed"] == 1


def test_suppression_ignored_inside_docstrings(tmp_path):
    """A docstring DOCUMENTING the suppression syntax must not
    suppress anything — only real comment tokens count."""
    src = '''
        """Module doc.

        Use ``# graftlint: disable-file=GL002`` to silence a file.
        """
        import jax

        @jax.jit
        def hot(x):
            return float(x)
    '''
    findings, _ = lint_source(tmp_path, src, rules=["GL002"])
    assert len(findings) == 1


def test_suppression_is_rule_specific(tmp_path):
    src = GL004_BAD.replace(
        "self._completed += 1",
        "self._completed += 1  # graftlint: disable=GL001",
    )
    findings, _ = lint_source(tmp_path, src, rules=["GL004"])
    assert len(findings) == 2  # wrong rule id: nothing suppressed


# --- config ----------------------------------------------------------------


def test_config_rule_selection_and_exclude(tmp_path):
    cfg = LintConfig(enable=["GL004"], exclude=["skipme/"])
    (tmp_path / "skipme").mkdir()
    (tmp_path / "skipme" / "bad.py").write_text(textwrap.dedent(GL004_BAD))
    (tmp_path / "mod.py").write_text(textwrap.dedent(GL004_BAD))
    findings, stats = run_analysis(["."], root=str(tmp_path), config=cfg)
    assert stats["files"] == 1  # skipme/ excluded
    assert all(f.path == "mod.py" for f in findings)


def test_pyproject_config_parses_without_tomllib():
    """The repo's [tool.graftlint] section round-trips through the
    fallback parser (this image's python predates tomllib)."""
    cfg = load_config(REPO)
    assert cfg.enable == [
        "GL001", "GL002", "GL003", "GL004", "GL005",
        "GL006", "GL007", "GL008", "GL009", "GL010",
    ]
    assert cfg.paths == ["gnot_tpu", "tests", "tools"]
    assert "gnot_tpu/native/" in cfg.exclude
    assert "build/" in cfg.exclude
    assert "train_step" in cfg.donate_callables
    assert "train_step_body" in cfg.hot_containers


def test_pyproject_fallback_parser_handles_inline_comments(tmp_path):
    """An inline comment after an array value must not derail the
    tomllib-less parser into swallowing the rest of the file (which
    would silently disable every rule)."""
    (tmp_path / "pyproject.toml").write_text(
        "[tool.graftlint]\n"
        'enable = ["GL004"]  # keep minimal\n'
        'exclude = [\n    "a/",  # dir a\n    "b/",\n]  # done\n'
        "[tool.other]\n"
        'x = ["GL001"]\n'
    )
    cfg = load_config(str(tmp_path))
    assert cfg.enable == ["GL004"]
    assert cfg.exclude == ["a/", "b/"]


def test_syntax_error_reports_gl000(tmp_path):
    (tmp_path / "mod.py").write_text("def broken(:\n")
    findings, _ = run_analysis(
        ["mod.py"], root=str(tmp_path), config=LintConfig(enable=["GL002"])
    )
    assert len(findings) == 1 and findings[0].rule == "GL000"


def test_unreadable_bytes_report_gl000_not_crash(tmp_path):
    """Null bytes / non-UTF8 content must yield a GL000 finding, not an
    uncaught UnicodeDecodeError/ValueError killing the gate."""
    (tmp_path / "nul.py").write_bytes(b"x = 1\x00\n")
    (tmp_path / "latin.py").write_bytes(b"# caf\xe9\nx = 1\n")
    findings, _ = run_analysis(
        ["."], root=str(tmp_path), config=LintConfig(enable=["GL002"])
    )
    assert sorted(f.rule for f in findings) == ["GL000", "GL000"]


def test_gl005_unparseable_registry_is_a_finding(tmp_path):
    """A registry that EXISTS but whose EVENTS is not a literal dict
    must fail loudly — not silently vacate every emit-site check."""
    (tmp_path / "gnot_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "gnot_tpu" / "obs" / "events.py").write_text(
        "EVENTS = dict(slow_step=None)\n"
    )
    (tmp_path / "mod.py").write_text("x = 1\n")
    findings, _ = run_analysis(
        ["mod.py"], root=str(tmp_path), config=LintConfig(enable=["GL005"])
    )
    assert len(findings) == 1 and "not parseable" in findings[0].message


def test_gl005_prose_mention_does_not_count_as_documented(tmp_path):
    """Docs coverage requires the code-token form (`kind` or `kind@`);
    a bare prose mention must not satisfy it."""
    (tmp_path / "gnot_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "gnot_tpu" / "obs" / "events.py").write_text(
        'EVENTS = {"reload": None}\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(
        "the server reload path retries\n"  # prose, not a code token
    )
    (tmp_path / "docs" / "robustness.md").write_text("")
    (tmp_path / "mod.py").write_text("x = 1\n")
    findings, _ = run_analysis(
        ["mod.py"], root=str(tmp_path), config=LintConfig(enable=["GL005"])
    )
    assert len(findings) == 1 and "'reload'" in findings[0].message


def test_cli_rules_flag_overrides_config_disable(tmp_path, capsys):
    """--rules must force-run the requested rule even when pyproject
    disables it (a zero-rule run exiting 0 would be a false clean)."""
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\ndisable = ["GL004"]\n'
    )
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GL004_BAD))
    rc = _lint_main()(
        [str(bad), "--rules", "GL004", "--root", str(tmp_path)]
    )
    assert rc == 1
    assert "GL004" in capsys.readouterr().out


# --- the CLI ---------------------------------------------------------------


def test_cli_json_format_and_exit_code(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GL004_BAD))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         str(bad), "--format", "json", "--rules", "GL004",
         "--root", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out["stats"]["findings"] == len(out["findings"]) == 2
    assert all(f["rule"] == "GL004" for f in out["findings"])
    assert all("line" in f and "hint" in f for f in out["findings"])


def _lint_main():
    """tools/lint.py's main(), loaded in-process (one subprocess test
    above covers the real CLI; these stay cheap)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gnot_lint_cli", os.path.join(REPO, "tools", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_cli_clean_exits_zero(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    rc = _lint_main()(
        [str(good), "--rules", "GL004", "--root", str(tmp_path)]
    )
    assert rc == 0, capsys.readouterr().out


def test_cli_missing_path_exits_two(tmp_path, capsys):
    rc = _lint_main()([str(tmp_path / "nope.py")])
    assert rc == 2


def _git(root, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=root, check=True, capture_output=True,
    )


def test_cli_changed_mode_scopes_and_baselines(tmp_path, capsys):
    """--changed lints only git-modified files under the configured
    roots; the committed baseline masks known findings; a fresh
    violation still fails (the pre-commit contract)."""
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\nenable = ["GL004"]\npaths = ["pkg"]\n'
    )
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n")
    (tmp_path / "scratch.py").write_text(textwrap.dedent(GL004_BAD))
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    main = _lint_main()

    # Clean working tree: nothing to lint, exit 0.
    assert main(["--changed", "--root", str(tmp_path)]) == 0
    assert "no changes" in capsys.readouterr().out

    # A violation outside the lint roots is not gated.
    (tmp_path / "scratch.py").write_text(
        textwrap.dedent(GL004_BAD) + "\n# touched\n"
    )
    assert main(["--changed", "--root", str(tmp_path)]) == 0
    capsys.readouterr()

    # A violation in a changed file under the roots fails...
    (pkg / "mod.py").write_text(textwrap.dedent(GL004_BAD))
    assert main(["--changed", "--root", str(tmp_path)]) == 1
    capsys.readouterr()

    # ...unless the committed baseline tolerates it (counted per
    # (rule, path) — line drift must not un-suppress)...
    (tmp_path / "tools").mkdir()
    assert main(["--update-baseline", "--root", str(tmp_path)]) == 0
    capsys.readouterr()
    assert main(["--changed", "--root", str(tmp_path)]) == 0
    assert "baseline-masked" in capsys.readouterr().out

    # ...and a NEW finding beyond the baseline allowance still fails.
    (pkg / "mod.py").write_text(
        textwrap.dedent(GL004_BAD)
        + "\n"
        + textwrap.dedent(GL004_BAD).replace("class Server", "class Server2")
    )
    assert main(["--changed", "--root", str(tmp_path)]) == 1


def test_cli_changed_mode_reports_project_findings_for_doc_edits(
    tmp_path, capsys
):
    """A docs-only change can CAUSE a project-level GL005 drift
    finding; --changed must report it even though no .py changed."""
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\nenable = ["GL005"]\npaths = ["gnot_tpu"]\n'
    )
    reg = tmp_path / "gnot_tpu" / "obs"
    reg.mkdir(parents=True)
    (reg / "events.py").write_text(MINI_REGISTRY)
    (tmp_path / "gnot_tpu" / "mod.py").write_text("x = 1\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text("`good_event`\n")
    (docs / "robustness.md").write_text("")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    main = _lint_main()
    assert main(["--changed", "--root", str(tmp_path)]) == 0
    capsys.readouterr()

    # Remove the kind's doc row — no .py touched, drift introduced.
    (docs / "observability.md").write_text("nothing here\n")
    rc = main(["--changed", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "good_event" in out


def test_cli_changed_mode_keeps_cross_file_call_graph(tmp_path, capsys):
    """--changed scopes the REPORT, not the analysis: a changed test
    whose bug only resolves through an UNCHANGED trainer's donation
    chain must still be caught (the PR6 fit-indirection shape), and an
    unchanged file's findings must stay out of the report."""
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\nenable = ["GL006"]\npaths = ["pkg"]\n'
    )
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "trainer_mod.py").write_text(textwrap.dedent(GL006_PR6_TRAINER))
    # An UNCHANGED file carrying its own violation: scanned for the
    # graph, but never reported in --changed mode.
    (pkg / "old_bug.py").write_text(textwrap.dedent(GL006_PR10_PREFIX))
    (pkg / "mod.py").write_text("x = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    main = _lint_main()

    # Change ONLY the test file, introducing the call-graph-resolved
    # bug: trainer_mod.py (the donor source) is untouched.
    (pkg / "mod.py").write_text(textwrap.dedent(GL006_PR6_PREFIX))
    rc = main(["--changed", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "pkg/mod.py" in out and "fit" in out
    assert "old_bug.py" not in out  # unchanged file: scanned, not reported


# --- THE gate: the real tree is clean --------------------------------------


def test_repo_tree_is_clean():
    """`python tools/lint.py` exits 0 on this tree: every GL001-GL006
    invariant holds (or carries a justified suppression) across train,
    serve, resilience, obs, parallel — AND tests/ + tools/, where
    every historical use-after-donate instance actually lived (ISSUE
    11). Run in-process."""
    cfg = load_config(REPO)
    findings, stats = run_analysis(cfg.paths, root=REPO, config=cfg)
    assert stats["rules"] == [
        "GL001", "GL002", "GL003", "GL004", "GL005",
        "GL006", "GL007", "GL008", "GL009", "GL010",
    ]
    assert stats["files"] > 90  # gnot_tpu + tests + tools, not a subset
    assert findings == [], "\n".join(f.format() for f in findings)


def test_changed_baseline_is_in_sync():
    """The committed --changed baseline must stay empty while the tree
    is clean: a baseline that silently tolerates findings would let
    pre-commit pass what the tier-1 gate rejects."""
    with open(os.path.join(REPO, "tools", "lint_baseline.json")) as f:
        baseline = json.load(f)
    assert baseline["version"] == 1
    assert baseline["findings"] == []


def test_rule_registry_complete():
    from gnot_tpu.analysis import RULES

    assert sorted(RULES) == [
        "GL001", "GL002", "GL003", "GL004", "GL005",
        "GL006", "GL007", "GL008", "GL009", "GL010",
    ]
    for rid, cls in RULES.items():
        assert cls.id == rid and cls.title and cls.hint


# --- GL007: native ABI drift (ctypes bindings vs extern "C" decls) --------


_GL007_CPP = '''
// comment mentioning void gnot_commented_out(int64_t fake) is ignored
extern "C" {
void gnot_pack_rows(const float** srcs, const int64_t* lens, int64_t n,
                    int64_t dim, int64_t max_len, float* out, float* mask) {}
void gnot_unpad_rows(const char* src, const int64_t* rows,
                     const int64_t* offs, const int64_t* lens, int64_t n,
                     int64_t row_bytes, int64_t tok_bytes, char** dsts) {}
}
'''

_GL007_PY_CLEAN = '''
import ctypes
def _bind(lib):
    lib.gnot_pack_rows.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.gnot_unpad_rows.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p),
    ]
'''


def _gl007_sandbox(tmp_path, py_src, cpp_src=_GL007_CPP):
    (tmp_path / "gnot_tpu" / "native").mkdir(parents=True, exist_ok=True)
    (tmp_path / "gnot_tpu" / "native" / "__init__.py").write_text(py_src)
    (tmp_path / "gnot_tpu" / "native" / "ragged_pack.cpp").write_text(cpp_src)
    cfg = LintConfig(enable=["GL007"])
    return run_analysis(["gnot_tpu"], root=str(tmp_path), config=cfg)[0]


def test_gl007_clean_bindings_pass(tmp_path):
    assert _gl007_sandbox(tmp_path, _GL007_PY_CLEAN) == []


def test_gl007_arity_drift_is_caught(tmp_path):
    drifted = _GL007_PY_CLEAN.replace(
        "        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,\n"
        "        ctypes.c_void_p, ctypes.c_void_p,",
        "        ctypes.c_int64, ctypes.c_int64,\n"
        "        ctypes.c_void_p, ctypes.c_void_p,",
    )
    assert drifted != _GL007_PY_CLEAN
    findings = _gl007_sandbox(tmp_path, drifted)
    assert len(findings) == 1 and findings[0].rule == "GL007"
    assert "arity drift" in findings[0].message
    assert findings[0].project_level  # --changed must never scope it out


def test_gl007_dtype_tag_drift_is_caught(tmp_path):
    drifted = _GL007_PY_CLEAN.replace(
        "ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),",
        "ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),",
    )
    assert drifted != _GL007_PY_CLEAN
    findings = _gl007_sandbox(tmp_path, drifted)
    assert [f.rule for f in findings] == ["GL007"]
    assert "dtype-tag drift at arg 1" in findings[0].message
    assert "POINTER(c_int64)" in findings[0].message


def test_gl007_unbound_export_and_missing_symbol(tmp_path):
    # Binding a symbol the .cpp never declares...
    phantom = _GL007_PY_CLEAN + (
        "    lib.gnot_phantom.argtypes = [ctypes.c_int64]\n"
    )
    findings = _gl007_sandbox(tmp_path, phantom)
    assert any("no such extern" in f.message for f in findings)
    # ...and an extern "C" export with no binding, both drift.
    extra_cpp = _GL007_CPP.replace(
        "}\n'",
        "void gnot_orphan(int64_t n) {}\n}\n'",
    )
    extra_cpp = _GL007_CPP.rstrip()[:-1] + "void gnot_orphan(int64_t n) {}\n}\n"
    findings = _gl007_sandbox(tmp_path, _GL007_PY_CLEAN, extra_cpp)
    assert any("no ctypes binding" in f.message for f in findings)


def test_gl007_real_tree_bindings_agree():
    """The live bindings and the live .cpp agree right now (the same
    check test_repo_tree_is_clean enforces, isolated here so a drift
    failure names the rule instead of the whole gate)."""
    cfg = load_config(REPO)
    cfg.enable = ["GL007"]
    findings, _ = run_analysis(["gnot_tpu"], root=REPO, config=cfg)
    assert findings == [], "\n".join(f.format() for f in findings)


# --- GL008: lock-order inversion (the concurrency plane) ------------------


# Mutation-style reconstruction of the pre-fix autoscaler<->router
# shape GL008 exists to forbid: the controller ticks into the pool
# under its tick lock, and the pool — in this mutated twin — calls
# back into the controller while still holding the pool lock.
GL008_BAD = """
    import threading

    class Router:
        def __init__(self):
            self._lock = threading.Lock()
            self.scaler = None

        def pool(self):
            with self._lock:
                return 1

        def remove(self):
            with self._lock:
                self.scaler.assess()

    class Controller:
        def __init__(self, router):
            self._tick_lock = threading.Lock()
            self.router = router

        def tick(self):
            with self._tick_lock:
                self.router.pool()

        def assess(self):
            with self._tick_lock:
                return 2
"""

# The shipped shape: calls into the other class happen with the
# caller's lock held in ONE direction only.
GL008_CLEAN = GL008_BAD.replace(
    """        def remove(self):
            with self._lock:
                self.scaler.assess()
""",
    """        def remove(self):
            with self._lock:
                n = 1
            self.scaler.assess()
""",
)


def test_gl008_lock_order_cycle_is_caught(tmp_path):
    findings, _ = lint_source(tmp_path, GL008_BAD, rules=["GL008"])
    assert [f.rule for f in findings] == ["GL008"]
    f = findings[0]
    assert "lock-order cycle" in f.message
    assert f.project_level  # --changed must never scope it out
    # Both witness paths, each a file:line hop chain through the call.
    assert "Controller._tick_lock" in f.message
    assert "Router._lock" in f.message
    assert f.message.count("mod.py:") >= 4


def test_gl008_consistent_order_is_clean(tmp_path):
    findings, _ = lint_source(tmp_path, GL008_CLEAN, rules=["GL008"])
    assert findings == []


def test_gl008_suppression_on_the_edge_acquisition(tmp_path):
    suppressed = GL008_BAD.replace(
        "self.scaler.assess()",
        "self.scaler.assess()  # graftlint: disable=GL008 — fixture: "
        "callback is documented reentrancy-safe",
    )
    findings, stats = lint_source(tmp_path, suppressed, rules=["GL008"])
    assert findings == []


def test_gl008_self_deadlock_is_caught(tmp_path):
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self):
                with self._lock:
                    return self.census()

            def census(self):
                with self._lock:
                    return 0
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL008"])
    assert [f.rule for f in findings] == ["GL008"]
    assert "self-deadlock" in findings[0].message


def test_gl008_rlock_reentrancy_is_not_a_finding(tmp_path):
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.RLock()

            def fetch(self):
                with self._lock:
                    return self.census()

            def census(self):
                with self._lock:
                    return 0
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL008"])
    assert findings == []


def test_gl008_single_lock_class_is_clean(tmp_path):
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL008"])
    assert findings == []


# --- GL009: blocking call under a held lock -------------------------------


def test_gl009_unbounded_future_result_under_lock(tmp_path):
    src = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.fut = None

            def wait_result(self):
                with self._lock:
                    return self.fut.result()
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL009"])
    assert [f.rule for f in findings] == ["GL009"]
    assert "result()" in findings[0].message
    assert "_lock" in findings[0].message


def test_gl009_bounded_wait_and_unlocked_wait_are_clean(tmp_path):
    src = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.fut = None

            def wait_bounded(self):
                with self._lock:
                    return self.fut.result(timeout=1.0)

            def wait_unlocked(self):
                return self.fut.result()
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL009"])
    assert findings == []


def test_gl009_socket_and_slow_callable_under_lock(tmp_path):
    src = """
        import threading

        class Host:
            def __init__(self):
                self._lock = threading.Lock()
                self.sock = None
                self.engine = None

            def pump(self):
                with self._lock:
                    data = self.sock.recv(65536)
                return data

            def warm(self):
                with self._lock:
                    self.engine.warmup()

            def run(self):
                with self._lock:
                    self.engine.infer_packed(None)
    """
    findings, _ = lint_source(tmp_path, src, rules=["GL009"])
    assert len(findings) == 3
    msgs = " | ".join(f.message for f in findings)
    assert "recv" in msgs and "warmup" in msgs and "infer_packed" in msgs


def test_gl009_allowed_blocking_annotation_contract(tmp_path):
    justified = """
        import threading

        class Host:
            def __init__(self):
                self._lock = threading.Lock()
                self.engine = None

            def warm(self):
                with self._lock:
                    #: allowed_blocking — startup path, no traffic yet
                    self.engine.warmup()
    """
    findings, _ = lint_source(tmp_path, justified, rules=["GL009"])
    assert findings == []
    # The annotation WITHOUT a reason is itself a finding: the
    # contract is a justification, not a mute button.
    bare = justified.replace(
        "#: allowed_blocking — startup path, no traffic yet",
        "#: allowed_blocking",
    )
    findings, _ = lint_source(tmp_path, bare, rules=["GL009"])
    assert [f.rule for f in findings] == ["GL009"]
    assert "missing its justification" in findings[0].message


# --- GL010: config drift (dataclass <-> CLI <-> docs) ---------------------


_GL010_CONFIG = """
    from dataclasses import dataclass

    @dataclass
    class TrainConfig:
        epochs: int = 1
        snapshot_every: int = 50

    @dataclass
    class ServeConfig:
        max_batch: int = 4
"""

_GL010_CLI = """
    import argparse

    def build_parser():
        p = argparse.ArgumentParser()
        p.add_argument("--epochs", type=int, default=1)
        p.add_argument("--snapshot_every", type=int, default=50)
        p.add_argument("--serve_max_batch", type=int, default=4)
        return p

    def config_from_args(args):
        return {
            "train.epochs": args.epochs,
            "train.snapshot_every": args.snapshot_every,
            "serve.max_batch": args.serve_max_batch,
        }
"""

_GL010_DOC = "`epochs` and `--snapshot_every` and `serve.max_batch`\n"


def _gl010_sandbox(tmp_path, cfg_src=_GL010_CONFIG, cli_src=_GL010_CLI,
                   doc=_GL010_DOC):
    import textwrap as _tw

    pkg = tmp_path / "gnot_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "config.py").write_text(_tw.dedent(cfg_src))
    (pkg / "main.py").write_text(_tw.dedent(cli_src))
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "serving.md").write_text(doc)
    (docs / "robustness.md").write_text("")
    (docs / "observability.md").write_text("")
    cfg = LintConfig(enable=["GL010"])
    return run_analysis(["gnot_tpu"], root=str(tmp_path), config=cfg)[0]


def test_gl010_fully_wired_config_is_clean(tmp_path):
    assert _gl010_sandbox(tmp_path) == []


def test_gl010_unwired_field_is_caught(tmp_path):
    cli = _GL010_CLI.replace(
        '            "train.snapshot_every": args.snapshot_every,\n', ""
    )
    findings = _gl010_sandbox(tmp_path, cli_src=cli)
    assert [f.rule for f in findings] == ["GL010"]
    assert "train.snapshot_every has no CLI wiring" in findings[0].message
    assert findings[0].path == "gnot_tpu/config.py"
    assert findings[0].project_level


def test_gl010_mapping_reads_undeclared_flag(tmp_path):
    cli = _GL010_CLI.replace(
        '        p.add_argument("--snapshot_every", type=int, default=50)\n',
        "",
    )
    findings = _gl010_sandbox(tmp_path, cli_src=cli)
    msgs = " | ".join(f.message for f in findings)
    assert "reads args.snapshot_every but no --snapshot_every flag" in msgs


def test_gl010_undocumented_field_is_caught(tmp_path):
    findings = _gl010_sandbox(
        tmp_path, doc="`epochs` and `--snapshot_every`\n"
    )
    assert [f.rule for f in findings] == ["GL010"]
    assert "serve.max_batch is not documented" in findings[0].message


def test_gl010_ghost_mapping_key_is_caught(tmp_path):
    cli = _GL010_CLI.replace(
        '"serve.max_batch": args.serve_max_batch,',
        '"serve.max_batch": args.serve_max_batch,\n'
        '            "serve.ghost": args.serve_max_batch,',
    )
    findings = _gl010_sandbox(tmp_path, cli_src=cli)
    msgs = " | ".join(f.message for f in findings)
    assert "'serve.ghost' does not match any field" in msgs
    assert all(f.path == "gnot_tpu/main.py" for f in findings)


def test_gl010_suppression_at_field_declaration(tmp_path):
    cfg = _GL010_CONFIG.replace(
        "max_batch: int = 4",
        "max_batch: int = 4  # graftlint: disable=GL010 — fixture: "
        "library-only knob",
    )
    cli = _GL010_CLI.replace(
        '            "serve.max_batch": args.serve_max_batch,\n', ""
    )
    findings = _gl010_sandbox(tmp_path, cfg_src=cfg, cli_src=cli)
    assert findings == []


def test_gl010_real_tree_config_is_wired():
    """Every TrainConfig/ServeConfig field reaches a --flag and a doc
    mention right now (isolated from the whole-tree gate so a drift
    failure names the rule)."""
    cfg = load_config(REPO)
    cfg.enable = ["GL010"]
    findings, _ = run_analysis(["gnot_tpu"], root=REPO, config=cfg)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_gl008_real_tree_lock_graph_is_acyclic():
    """The live acquires-while-holding graph: cycle-free, and big
    enough that an accidentally-neutered resolver would fail loudly
    (the lockmap artifact pins the same numbers)."""
    from gnot_tpu.analysis.core import FileContext, iter_python_files
    from gnot_tpu.analysis.lockorder import build_lock_graph

    cfg = load_config(REPO)
    contexts = []
    for rel in iter_python_files(cfg.paths, REPO, cfg):
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            contexts.append(FileContext(REPO, rel, fh.read(), cfg))
    nodes, edges, cycles = build_lock_graph(contexts)
    assert cycles == []
    assert len(nodes) >= 20  # the serving/obs/federation lock census
    assert len(edges) >= 10
    # The chains the serving layer actually relies on are resolved —
    # a resolver regression that silently dropped call-mediated edges
    # would make the cycle check vacuous.
    assert ("AutoscaleController._tick_lock", "ReplicaRouter._lock") in edges
    assert ("ReplicaRouter._reload_lock", "ReplicaRouter._lock") in edges
    # The federation discipline, verified statically: the cluster
    # RLock is NEVER held across a link send or host call-out.
    assert not any(a == "ClusterRouter._lock" for a, _ in edges)
