"""Model-level tests: shapes, masked-mode pad invariance, and the
torch-oracle parity gate (BASELINE.json: JAX must reproduce the PyTorch
reference to <1e-4; the forward gate here is tighter, <1e-5)."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.models.gnot import GNOT

SMALL = dict(
    input_dim=2,
    theta_dim=2,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=2,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=3,
    n_head=4,
)


def make_inputs(rng, b=3, l=20, lf=12, cfg=None):
    c = cfg or SMALL
    coords = rng.normal(size=(b, l, c["input_dim"])).astype(np.float32)
    theta = rng.normal(size=(b, c["theta_dim"])).astype(np.float32)
    funcs = rng.normal(size=(c["n_input_functions"], b, lf, c["input_func_dim"])).astype(
        np.float32
    )
    return coords, theta, funcs


def init_and_apply(mc, coords, theta, funcs, node_mask=None, func_mask=None, seed=0):
    model = GNOT(mc)
    params = model.init(
        jax.random.key(seed), coords, theta, funcs, node_mask=node_mask, func_mask=func_mask
    )["params"]
    out = model.apply(
        {"params": params},
        coords,
        theta,
        funcs,
        node_mask=node_mask,
        func_mask=func_mask,
    )
    return params, out


def test_output_shape():
    mc = ModelConfig(**SMALL)
    coords, theta, funcs = make_inputs(np.random.default_rng(0))
    _, out = init_and_apply(mc, coords, theta, funcs)
    assert out.shape == (3, 20, SMALL["out_dim"])
    assert np.isfinite(np.asarray(out)).all()


def test_no_input_functions_selfattention_mode():
    """n_input_functions=0 degrades cross-attn to self-attn
    (reference model.py:49-51,88-104 via the constructor branch)."""
    cfg = dict(SMALL, n_input_functions=0)
    mc = ModelConfig(**cfg)
    coords, theta, _ = make_inputs(np.random.default_rng(1), cfg=cfg)
    _, out = init_and_apply(mc, coords, theta, None)
    assert out.shape == (3, 20, 1)


def test_masked_mode_pad_invariance():
    """In masked mode, outputs at real rows must not change when pad
    length changes — the property parity mode deliberately lacks."""
    mc = ModelConfig(**SMALL, attention_mode="masked")
    rng = np.random.default_rng(2)
    b, l_real, lf_real = 2, 10, 7
    coords, theta, funcs = make_inputs(rng, b=b, l=l_real, lf=lf_real)
    node_mask = np.ones((b, l_real), np.float32)
    func_mask = np.ones((SMALL["n_input_functions"], b, lf_real), np.float32)

    model = GNOT(mc)
    params = model.init(
        jax.random.key(0), coords, theta, funcs, node_mask=node_mask, func_mask=func_mask
    )["params"]
    out_short = model.apply(
        {"params": params}, coords, theta, funcs, node_mask=node_mask, func_mask=func_mask
    )

    # Pad everything with garbage rows and mask them out.
    pad_l, pad_f = 6, 9
    coords_p = np.concatenate(
        [coords, rng.normal(size=(b, pad_l, coords.shape[-1])).astype(np.float32)], axis=1
    )
    funcs_p = np.concatenate(
        [funcs, rng.normal(size=funcs.shape[:2] + (pad_f, funcs.shape[-1])).astype(np.float32)],
        axis=2,
    )
    node_mask_p = np.concatenate([node_mask, np.zeros((b, pad_l), np.float32)], axis=1)
    func_mask_p = np.concatenate(
        [func_mask, np.zeros(func_mask.shape[:2] + (pad_f,), np.float32)], axis=2
    )
    out_padded = model.apply(
        {"params": params},
        coords_p,
        theta,
        funcs_p,
        node_mask=node_mask_p,
        func_mask=func_mask_p,
    )
    np.testing.assert_allclose(
        np.asarray(out_padded[:, :l_real]), np.asarray(out_short), rtol=2e-5, atol=2e-5
    )


def test_parity_mode_ignores_masks():
    """parity mode must produce identical results with and without masks
    passed (masks are dropped, pollution preserved)."""
    mc = ModelConfig(**SMALL, attention_mode="parity")
    coords, theta, funcs = make_inputs(np.random.default_rng(3))
    model = GNOT(mc)
    params = model.init(jax.random.key(0), coords, theta, funcs)["params"]
    out1 = model.apply({"params": params}, coords, theta, funcs)
    mask = np.ones(coords.shape[:2], np.float32)
    fmask = np.ones(funcs.shape[:3], np.float32)
    out2 = model.apply(
        {"params": params}, coords, theta, funcs, node_mask=mask, func_mask=fmask
    )
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


@pytest.mark.skipif(
    not os.path.exists(os.environ.get("GNOT_REFERENCE_PATH", "/root/reference")),
    reason="reference implementation not available",
)
class TestTorchParity:
    """Forward parity vs the reference PyTorch implementation."""

    def _parity_case(self, cfg_overrides=None, seed=0, b=2, l=18, lf=11):
        import torch

        from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

        cfg = dict(SMALL, **(cfg_overrides or {}))
        mc = ModelConfig(**cfg, attention_mode="parity")
        torch.manual_seed(seed)
        ref = build_reference_model(mc)
        ref.eval()

        rng = np.random.default_rng(seed)
        coords, theta, funcs = make_inputs(rng, b=b, l=l, lf=lf, cfg=cfg)

        with torch.no_grad():
            tfuncs = (
                [torch.from_numpy(funcs[i]) for i in range(funcs.shape[0])]
                if cfg["n_input_functions"]
                else None
            )
            want = ref(
                torch.from_numpy(coords), torch.from_numpy(theta), tfuncs
            ).numpy()

        params = state_dict_to_flax(ref.state_dict(), mc)
        model = GNOT(mc)
        got = np.asarray(
            model.apply(
                {"params": params},
                coords,
                theta,
                funcs if cfg["n_input_functions"] else None,
            )
        )
        return got, want

    def test_forward_parity_cross_attention(self):
        got, want = self._parity_case()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_forward_parity_single_function(self):
        got, want = self._parity_case({"n_input_functions": 1, "theta_dim": 1})
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_forward_parity_no_functions(self):
        got, want = self._parity_case({"n_input_functions": 0})
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_forward_parity_default_size_darcy(self):
        """Reference-default architecture (main.py:16-22) at Darcy-like
        dims — the <1e-4 BASELINE gate, forward direction."""
        got, want = self._parity_case(
            {
                "n_attn_layers": 4,
                "n_attn_hidden_dim": 256,
                "n_mlp_num_layers": 4,
                "n_mlp_hidden_dim": 256,
                "n_input_hidden_dim": 256,
                "n_expert": 3,
                "n_head": 8,
                "theta_dim": 1,
                "n_input_functions": 1,
            },
            b=2,
            l=64,
            lf=32,
        )
        assert float(np.max(np.abs(got - want))) < 1e-4
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(
    not os.path.exists(os.environ.get("GNOT_REFERENCE_PATH", "/root/reference")),
    reason="reference implementation not available",
)
def test_forward_parity_ragged_padding_pollution():
    """The reference's defining quirk: padding is UNMASKED, so pad rows
    pass through biased MLPs and pollute ``k_sum``/``k^T v`` — results
    depend on batch composition (reference main.py:63-82, model.py:77-80).
    This test feeds a genuinely ragged batch (elasticity-style lengths,
    nonzero pad rows on every sample but the longest) through both sides
    from the same imported weights and asserts parity holds anyway.
    """
    import torch

    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

    cfg = dict(
        SMALL,
        theta_dim=2,
        n_input_functions=1,
        out_dim=2,
        n_attn_layers=2,
        n_expert=2,
    )
    mc = ModelConfig(**cfg, attention_mode="parity")
    torch.manual_seed(7)
    ref = build_reference_model(mc)
    ref.eval()

    samples = datasets.synth_elasticity(4, seed=11, base_points=96)
    lengths = [s.coords.shape[0] for s in samples]
    flengths = [s.funcs[0].shape[0] for s in samples]
    assert len(set(lengths)) > 1, "samples must be genuinely ragged"
    assert len(set(flengths)) > 1

    # Our collate(bucket=False) must byte-match the reference's inline
    # padding (main.py:63-82 + utils.py:3-4): input functions padded to
    # the single shared max across ALL functions of ALL samples, coords
    # to the per-batch max, zero pad at the tail of axis 0.
    b = collate(samples, bucket=False)
    ref_max_f = max(f.shape[0] for s in samples for f in s.funcs)
    ref_max_l = max(lengths)

    def ref_pad(a, n):  # utils.py:3-4 semantics
        return np.pad(a, [(0, n - a.shape[0])] + [(0, 0)] * (a.ndim - 1))

    ref_funcs = np.stack(
        [np.stack([ref_pad(s.funcs[0], ref_max_f) for s in samples])]
    )
    ref_x = np.stack([ref_pad(s.coords, ref_max_l) for s in samples])
    np.testing.assert_array_equal(b.funcs, ref_funcs)
    np.testing.assert_array_equal(b.coords, ref_x)
    assert b.coords.shape[1] == ref_max_l and b.funcs.shape[2] == ref_max_f
    # Pad rows exist (ragged batch, no bucketing).
    assert float(b.node_mask.min()) == 0.0 and float(b.func_mask.min()) == 0.0

    with torch.no_grad():
        want = ref(
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        ).numpy()

    params = state_dict_to_flax(ref.state_dict(), mc)
    got = np.asarray(GNOT(mc).apply({"params": params}, b.coords, b.theta, b.funcs))
    # Parity holds at EVERY row, including pad rows (pollution included).
    assert float(np.max(np.abs(got - want))) < 1e-4
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # Prove the pad rows are nonzero contributors: masked-mode output at
    # the real rows must DIFFER from the parity output — if padding were
    # inert the two modes would coincide and this test would prove
    # nothing about pollution.
    mc_masked = ModelConfig(**cfg, attention_mode="masked")
    got_masked = np.asarray(
        GNOT(mc_masked).apply(
            {"params": params},
            b.coords,
            b.theta,
            b.funcs,
            node_mask=b.node_mask,
            func_mask=b.func_mask,
        )
    )
    real = np.asarray(b.node_mask, bool)
    pollution = float(np.max(np.abs(got[real] - got_masked[real])))
    parity_err = float(np.max(np.abs(got - want)))
    # Pollution is larger than both the achieved parity error and the
    # 1e-4 gate itself: had parity mode not replicated it, the gate
    # above would fail.
    assert pollution > 1e-4 and pollution > parity_err


def test_remat_same_outputs_and_grads():
    """remat must be numerics-neutral: same forward, same grads — it only
    changes what the backward rematerializes."""
    mc = ModelConfig(**SMALL)
    mc_r = dataclasses.replace(mc, remat=True)
    coords, theta, funcs = make_inputs(np.random.default_rng(5))
    model, model_r = GNOT(mc), GNOT(mc_r)
    params = model.init(jax.random.key(0), coords, theta, funcs)["params"]

    out = model.apply({"params": params}, coords, theta, funcs)
    out_r = model_r.apply({"params": params}, coords, theta, funcs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_r))

    def loss(m):
        def f(p):
            return jnp.sum(m.apply({"params": p}, coords, theta, funcs) ** 2)
        return f

    g = jax.jit(jax.grad(loss(model)))(params)
    g_r = jax.jit(jax.grad(loss(model_r)))(params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


from gnot_tpu.interop.torch_oracle import DEFAULT_REFERENCE_PATH


@pytest.mark.skipif(
    not os.path.exists(DEFAULT_REFERENCE_PATH),
    reason="reference implementation not available",
)
def test_forward_parity_darcy_full_resolution():
    """BASELINE configs[0] at its literal resolution: Darcy2d 64x64
    regular grid (4096 mesh points), small GNOT, CPU reference run —
    the <1e-4 parity gate."""
    import torch

    from gnot_tpu.data import datasets
    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

    cfg = dict(
        SMALL,
        theta_dim=1,
        n_input_functions=1,
        n_attn_layers=2,
        n_expert=2,
    )
    mc = ModelConfig(**cfg, attention_mode="parity")
    torch.manual_seed(4)
    ref = build_reference_model(mc)
    ref.eval()

    samples = datasets.synth_darcy2d(2, seed=9, grid_n=64)  # 4096 points
    from gnot_tpu.data.batch import collate

    b = collate(samples, bucket=False)
    with torch.no_grad():
        want = ref(
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        ).numpy()

    params = state_dict_to_flax(ref.state_dict(), mc)
    got = np.asarray(
        GNOT(mc).apply({"params": params}, b.coords, b.theta, b.funcs)
    )
    assert float(np.max(np.abs(got - want))) < 1e-4


def test_empty_input_function_is_finite():
    """A record with an *empty* input function — its func_mask row is all
    zeros — must give finite outputs and gradients. k_sum is exactly zero
    there, so without the denominator guard (ops/attention.py) the
    normalizer would be 1/0 -> inf and the (zero) numerator would turn it
    into nan. The guarded contribution is a clean 0."""
    mc = ModelConfig(**SMALL)
    rng = np.random.default_rng(3)
    coords, theta, funcs = make_inputs(rng)
    node_mask = np.ones(coords.shape[:2], np.float32)
    func_mask = np.ones((SMALL["n_input_functions"],) + funcs.shape[1:3], np.float32)
    func_mask[1, 0, :] = 0.0  # sample 0's second input function is empty

    params, out = init_and_apply(
        mc, coords, theta, funcs, node_mask=node_mask, func_mask=func_mask
    )
    assert np.isfinite(np.asarray(out)).all()

    def loss(p):
        y = GNOT(mc).apply(
            {"params": p}, coords, theta, funcs,
            node_mask=node_mask, func_mask=func_mask,
        )
        return jnp.mean(y * y)

    g = jax.grad(loss)(params)
    assert all(
        np.isfinite(np.asarray(x)).all() for x in jax.tree_util.tree_leaves(g)
    ), "all-masked input function produced non-finite gradients"


def test_gelu_config_validation():
    """config.gelu: auto-resolution and the parity/erf enforcement."""
    assert ModelConfig(**SMALL).gelu == "tanh"  # masked default
    assert ModelConfig(**SMALL, attention_mode="parity").gelu == "erf"
    assert ModelConfig(**SMALL, gelu="erf").gelu == "erf"
    with pytest.raises(ValueError, match="parity"):
        ModelConfig(**SMALL, attention_mode="parity", gelu="tanh")
    with pytest.raises(ValueError, match="unknown gelu"):
        ModelConfig(**SMALL, gelu="relu")


def test_gelu_tanh_vs_erf_forward_close():
    """The tanh approximation changes activations by ~1e-3 — the two
    flavors must stay close on the same weights (the quality gates
    prove the training-level equivalence; this pins the op level)."""
    mc_t = ModelConfig(**SMALL)            # tanh
    mc_e = ModelConfig(**SMALL, gelu="erf")
    coords, theta, funcs = make_inputs(np.random.default_rng(5))
    model_t, model_e = GNOT(mc_t), GNOT(mc_e)
    params = model_t.init(jax.random.key(0), coords, theta, funcs)["params"]
    out_t = np.asarray(model_t.apply({"params": params}, coords, theta, funcs))
    out_e = np.asarray(model_e.apply({"params": params}, coords, theta, funcs))
    assert np.max(np.abs(out_t - out_e)) < 0.05
    assert np.max(np.abs(out_t - out_e)) > 0  # genuinely different ops


def test_packed_forward_matches_per_sample():
    """GNOT packed forward ("pack, don't pad") == per-sample unpacked
    masked forward, same params: segments in a packed row never mix and
    theta/function routing per slot is exact."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import PackedLoader, collate
    from gnot_tpu.models.gnot import GNOT

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=2, n_attn_hidden_dim=32,
        n_mlp_num_layers=2, n_mlp_hidden_dim=32, n_input_hidden_dim=32,
        n_expert=2, n_head=4,
    )
    model = GNOT(mc)
    samples = datasets.synth_elasticity(6, seed=1)
    loader = PackedLoader(samples, batch_size=6, chunk=64)

    # Init on a standard batch (params are shape-independent in L).
    std = collate(samples[:2], bucket=False)
    params = model.init(
        jax.random.key(0), std.coords, std.theta, std.funcs,
        node_mask=std.node_mask, func_mask=std.func_mask,
    )["params"]

    # Every sample appears exactly once across the epoch's dispatches,
    # and no points are lost to packing.
    dispatches = loader._epoch_dispatches()
    seen = sorted(i for idx, _ in dispatches for i in idx)
    assert seen == list(range(len(samples)))

    chunk = loader.chunk
    checked = 0
    for dispatch in dispatches:
        idx, _ = dispatch
        packed = loader._collate_at(dispatch)
        assert packed.n_real_points == sum(
            samples[i].coords.shape[0] for i in idx
        )
        out = model.apply(
            {"params": params}, packed.coords, packed.theta, packed.funcs,
            node_mask=packed.node_mask, func_mask=packed.func_mask,
            node_seg=packed.node_seg, func_seg=packed.func_seg,
            n_seg=packed.n_seg,
        )  # [R, L, out]
        # Reference: each sample alone through the unpacked masked forward.
        for slot, i in enumerate(idx):
            s = samples[i]
            pos = np.argwhere(np.asarray(packed.node_seg) == slot)
            r = int(pos[0][0])
            off = int(pos[0][1]) * chunk
            n = s.coords.shape[0]
            solo = collate([s], bucket=False)
            ref = model.apply(
                {"params": params}, solo.coords, solo.theta, solo.funcs,
                node_mask=solo.node_mask, func_mask=solo.func_mask,
            )
            np.testing.assert_allclose(
                np.asarray(out[r, off : off + n]),
                np.asarray(ref[0, :n]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"sample {i} (slot {slot}) diverges from solo",
            )
            checked += 1
    assert checked == len(samples)


def test_packed_forward_rejects_parity():
    import jax
    import pytest as _pytest

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import PackedLoader
    from gnot_tpu.models.gnot import GNOT

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=1, n_attn_hidden_dim=16,
        n_mlp_num_layers=1, n_mlp_hidden_dim=16, n_input_hidden_dim=16,
        n_expert=2, n_head=2, attention_mode="parity",
    )
    model = GNOT(mc)
    samples = datasets.synth_elasticity(2, seed=0)
    packed = next(iter(PackedLoader(samples, batch_size=2)))
    with _pytest.raises(ValueError, match="packed"):
        model.init(
            jax.random.key(0), packed.coords, packed.theta, packed.funcs,
            node_mask=packed.node_mask, func_mask=packed.func_mask,
            node_seg=packed.node_seg, func_seg=packed.func_seg,
            n_seg=packed.n_seg,
        )


def test_packed_composes_with_remat():
    """nn.remat traces every block call argument; the one-hot segment
    maps are arrays (computed outside the remat boundary), so packed +
    remat must produce the same outputs AND gradients as packed alone."""
    import jax
    import jax.numpy as jnp
    import dataclasses

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import PackedLoader
    from gnot_tpu.models.gnot import GNOT

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=2, n_attn_hidden_dim=32,
        n_mlp_num_layers=1, n_mlp_hidden_dim=32, n_input_hidden_dim=32,
        n_expert=2, n_head=4,
    )
    samples = datasets.synth_elasticity(4, seed=0)
    packed = PackedLoader(samples, batch_size=4, chunk=64).probe_batch()

    def run(cfg):
        model = GNOT(cfg)
        params = model.init(
            jax.random.key(0), packed.coords, packed.theta, packed.funcs,
            node_mask=packed.node_mask, func_mask=packed.func_mask,
            node_seg=packed.node_seg, func_seg=packed.func_seg,
            n_seg=packed.n_seg,
        )["params"]

        def loss(p):
            out = model.apply(
                {"params": p}, packed.coords, packed.theta, packed.funcs,
                node_mask=packed.node_mask, func_mask=packed.func_mask,
                node_seg=packed.node_seg, func_seg=packed.func_seg,
                n_seg=packed.n_seg,
            )
            return jnp.sum(out**2)

        return jax.value_and_grad(loss)(params)

    l0, g0 = run(mc)
    l1, g1 = run(dataclasses.replace(mc, remat=True))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    import jax as _jax

    _jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g0,
        g1,
    )
