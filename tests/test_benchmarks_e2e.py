"""End-to-end trainer smoke over ALL five benchmark configs
(BASELINE.json `configs`): each exercises a different structural stress
(regular grid, ~1k mesh, ragged lengths + 2 output channels, multiple
input functions, 3D coords). Tiny models, 2 epochs — the point is that
the full pipeline (synthetic data -> collate/mask -> model -> loss ->
AdamW -> eval) runs and produces finite, improvable losses everywhere.
"""

import dataclasses
import os

import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, make_config
from gnot_tpu.data import datasets
from gnot_tpu.train.trainer import Trainer

TINY = dict(
    n_attn_layers=1,
    n_attn_hidden_dim=16,
    n_mlp_num_layers=1,
    n_mlp_hidden_dim=16,
    n_input_hidden_dim=16,
    n_expert=2,
    n_head=2,
)


def tiny_setup(name: str, n_train=8, n_test=4, epochs=2):
    cfg = make_config(**{
        "data.synthetic": name,
        "data.n_train": n_train,
        "data.n_test": n_test,
        "train.epochs": epochs,
    })
    # Keep heatsink3d point counts test-sized.
    gen_kwargs = {"heatsink3d": {"base_points": 256}, "elasticity": {"base_points": 128},
                  "inductor2d": {"base_points": 128}, "ns2d": {"n_points": 128},
                  "darcy2d": {"grid_n": 8}}[name]
    train = datasets.SYNTHETIC[name](n_train, seed=0, **gen_kwargs)
    test = datasets.SYNTHETIC[name](n_test, seed=1, **gen_kwargs)
    mc = ModelConfig(**TINY, **datasets.infer_model_dims(train))
    return cfg, mc, train, test


@pytest.mark.parametrize("name", sorted(datasets.SYNTHETIC))
def test_benchmark_config_trains(name):
    cfg, mc, train, test = tiny_setup(name)
    trainer = Trainer(cfg, mc, train, test)
    best = trainer.fit()
    assert np.isfinite(best), f"{name}: non-finite best metric"


def test_predict_returns_unpadded_per_sample_outputs():
    cfg, mc, train, test = tiny_setup("elasticity")  # ragged lengths
    trainer = Trainer(cfg, mc, train, test)
    trainer.initialize()
    outs = trainer.predict(test)
    assert len(outs) == len(test)
    for o, s in zip(outs, test):
        assert o.shape == (s.coords.shape[0], s.y.shape[1])
        assert np.all(np.isfinite(o))


def test_predict_matches_direct_apply():
    """predict()'s padded/masked batching must not change the numbers:
    compare against a direct single-sample forward."""
    import jax

    cfg, mc, train, test = tiny_setup("elasticity")
    trainer = Trainer(cfg, mc, train, test)
    trainer.initialize()
    outs = trainer.predict(test[:1])

    from gnot_tpu.data.batch import collate

    b = collate(test[:1], bucket=False)
    direct = trainer.model.apply(
        {"params": trainer.state.params},
        b.coords,
        b.theta,
        b.funcs,
        node_mask=b.node_mask,
        func_mask=b.func_mask,
    )
    np.testing.assert_allclose(
        outs[0], np.asarray(direct)[0, : test[0].coords.shape[0]],
        rtol=1e-5, atol=1e-6,
    )


def test_log_every_writes_step_records(tmp_path):
    from gnot_tpu.utils.metrics import MetricsSink

    cfg, mc, train, test = tiny_setup("darcy2d")
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train,
            log_every=1,
            metrics_path=str(tmp_path / "m.jsonl"),
        ),
    )
    sink = MetricsSink(cfg.train.metrics_path)
    Trainer(cfg, mc, train, test, metrics_sink=sink).fit()
    sink.close()

    import json

    records = [json.loads(l) for l in open(tmp_path / "m.jsonl")]
    step_records = [r for r in records if "step" in r]
    n_steps = cfg.train.epochs * ((len(train) + 3) // 4)
    assert len(step_records) == n_steps
    assert all(np.isfinite(r["loss"]) for r in step_records)


@pytest.mark.skipif(
    not os.path.exists("/root/reference/model.py"),
    reason="reference checkout not available",
)
def test_cli_export_torch(tmp_path):
    """--export_torch writes a state_dict the reference model loads."""
    pytest.importorskip("torch")
    from gnot_tpu.main import main

    out = tmp_path / "model.pth"
    main(
        [
            "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
            "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
            "--n_head", "2", "--epochs", "1", "--n_train", "8", "--n_test", "4",
            "--synthetic", "darcy2d", "--export_torch", str(out),
        ]
    )
    import torch

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.interop.torch_oracle import build_reference_model

    sd = torch.load(out, weights_only=True)
    dims = datasets.infer_model_dims(datasets.synth_darcy2d(1, seed=0))
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2, **dims,
    )
    tmodel = build_reference_model(mc)
    tmodel.load_state_dict(sd)  # raises on mismatch


def test_cli_predict_out_roundtrips(tmp_path):
    """--predict_out writes reference-schema records that load_pickle
    reads back, with per-sample unpadded prediction shapes."""
    from gnot_tpu.main import main

    out = tmp_path / "preds.pkl"
    main(
        [
            "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
            "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
            "--n_head", "2", "--epochs", "1", "--n_train", "8", "--n_test", "5",
            "--synthetic", "elasticity", "--predict_out", str(out),
        ]
    )
    preds = datasets.load_pickle(str(out))
    ref = datasets.synth_elasticity(5, seed=1)
    assert len(preds) == 5
    for p, s in zip(preds, ref):
        assert p.y.shape == s.y.shape
        assert np.all(np.isfinite(p.y))


def test_empty_test_set_trains_without_nan():
    cfg, mc, train, _ = tiny_setup("darcy2d")
    trainer = Trainer(cfg, mc, train, [])
    best = trainer.fit()
    assert best == float("inf")  # no eval, but training completed


@pytest.mark.slow  # wall-clock timing comparison: the ISSUE 6 median
# deflake narrowed but could not close the flake window on loaded CI
# boxes (host scheduling can still starve one arm's 3-sample median),
# so the comparison runs outside tier-1 where a loaded box can't turn
# scheduler noise into a red gate (ISSUE 10 satellite).
def test_bench_scan_marginal_matches_persstep_on_cpu():
    """The bench's scan_marginal estimator (two K-step scanned windows,
    marginal difference) must agree with the per-step dispatch loop on a
    locally-attached device, where per-step timing is trustworthy — the
    evidence that the marginal is per-step device time, not a
    scan artifact. Tiny model so the check stays fast."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp

    import bench
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_state, make_train_step

    samples = datasets.synth_ns2d(2, n_points=64)
    batch = next(iter(Loader(samples, 2)))
    mc = ModelConfig(**TINY, **datasets.infer_model_dims(samples))
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    step = make_train_step(model, optim, "rel_l2")
    dev = jax.devices()[0]
    lr = jnp.asarray(1e-3, jnp.float32)

    # Deflaked (ISSUE 6 satellite): a single wall-clock sample of either
    # estimator is at the mercy of host scheduling on a loaded CI box —
    # compare MEDIANS over independent estimates, and tolerate the
    # occasional degenerate marginal (noise swallowing T(k2)-T(k1)),
    # which time_scan_marginal reports as a RuntimeError by design.
    scans, steps = [], []
    for _ in range(3):
        try:
            scans.append(
                bench.time_scan_marginal(step, state, batch, lr, dev, 4, 16, 2)
            )
        except RuntimeError:
            pass  # degenerate window; the median of the rest decides
        steps.append(bench.time_steps(step, state, batch, lr, 2, 16, dev, repeats=2))
    assert scans, "every scan-marginal window degenerated — workload too small"
    per_scan = float(np.median(scans))
    per_step = float(np.median(steps))
    assert per_scan > 0 and np.isfinite(per_scan)
    assert per_step > 0 and np.isfinite(per_step)
    # Same device work; generous ratio slack for host-loop overhead and
    # CI noise.
    assert 0.2 < per_scan / per_step < 5.0


def _load_jsonl_artifact(name):
    import json

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "artifacts", name)
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_reference_scale_demo_artifact():
    """The committed --train_data demonstration at the reference's true
    data scale (1100 samples x ~10k-point meshes, the shape-of-record
    in /root/reference/model.py:110-116 and main.py:28-29 —
    tools/reference_scale_demo.py): the real pickle-loading CLI path
    trained on chip and converged."""
    records = _load_jsonl_artifact("reference_scale_demo.jsonl")
    epochs = [r for r in records if "train_loss" in r]
    summary = next(r for r in records if r.get("kind") == "summary")
    assert summary["n_train"] == 1100
    assert len(epochs) == summary["epochs"] >= 5
    assert all(np.isfinite(r["train_loss"]) for r in epochs)
    # Converged: best metric well below the first epoch's.
    assert summary["best_metric"] < 0.5 * epochs[0]["test_metric"]
    # Steady-state end-to-end throughput (post-compile epochs) is
    # recorded and nontrivial.
    steady = [r["points_per_sec"] for r in epochs[1:]]
    assert steady and min(steady) > 1e5


def test_heatsink3d_16k_long_context_artifact():
    """Long-context training artifact (SURVEY.md §5 stretch goal /
    VERDICT r4 #8): heatsink3d synthetic at L>=16k points per cloud,
    --remat --dtype bfloat16, 40 epochs on one chip — the long-context
    levers TRAIN to convergence, not just step."""
    epochs = [
        r for r in _load_jsonl_artifact("heatsink3d_16k_convergence.jsonl")
        if "train_loss" in r
    ]
    assert len(epochs) >= 40
    assert all(np.isfinite(r["train_loss"]) for r in epochs)
    assert epochs[-1]["test_metric"] < 0.2 * epochs[0]["test_metric"]


def test_heatsink3d_64k_long_context_artifact():
    """L=65536 single-chip convergence (round 5): 4x the 16k artifact's
    sequence length, B=1 --remat --dtype bfloat16 — the remat memory
    lever (3.1x activation reduction measured at exactly this shape)
    carries a REAL training run, not just a memory analysis."""
    epochs = [
        r for r in _load_jsonl_artifact("heatsink3d_64k_convergence.jsonl")
        if "train_loss" in r
    ]
    assert len(epochs) >= 40
    assert all(np.isfinite(r["train_loss"]) for r in epochs)
    assert min(r["test_metric"] for r in epochs) < 0.2 * epochs[0]["test_metric"]


def test_packed_quality_ab_artifact():
    """On-chip 24-epoch elasticity A/B (same regime, B=16, bf16):
    packed training reaches the padded path's quality — the throughput
    win does not trade away convergence. Recorded by two CLI runs with
    --metrics_path; docs/performance.md 'Pack, don't pad'."""
    records = _load_jsonl_artifact("packed_quality_ab.jsonl")
    best = {}
    for r in records:
        if r.get("test_metric") is not None:
            m = r["mode"]
            best[m] = min(best.get(m, float("inf")), r["test_metric"])
    assert set(best) == {"padded", "packed"}
    assert np.isfinite(best["packed"]) and np.isfinite(best["padded"])
    # Parity-or-better with trajectory-noise headroom.
    assert best["packed"] < best["padded"] * 1.1, best
