"""The live metrics plane (gnot_tpu/obs/metrics.py, ISSUE 14):
histogram record/merge/percentile-estimate bounds, bounded reservoirs,
registry semantics, publisher cadence + atomic writes, SLO burn-rate
fire/clear edge semantics, and the serve-tier wiring — per-server
counters matching serve_summary, router pool-merge equal to the sum of
replicas, and drain-time/final-snapshot agreement."""

import json
import os

import numpy as np
import pytest

from gnot_tpu.data import datasets
from gnot_tpu.obs import events as events_registry
from gnot_tpu.obs.metrics import (
    DEFAULT_BOUNDS,
    REL_ERROR,
    LogHistogram,
    MetricsPublisher,
    MetricsRegistry,
    Reservoir,
    SLOEvaluator,
    SLOObjective,
    default_objectives,
    exposition_text,
    pool_block,
    summary_agrees,
)
from gnot_tpu.utils.metrics import MetricsSink


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# --- LogHistogram ----------------------------------------------------------


def test_histogram_percentile_bound_under_10k_storm():
    """The retention-bug satellite's pinned tolerance: percentile
    estimates from the log-bucketed histogram stay within the
    DOCUMENTED relative error bound (REL_ERROR, sqrt of the bucket
    growth factor minus one) of the exact nearest-rank values over a
    10k-observation latency storm."""
    rng = np.random.default_rng(0)
    # Lognormal latencies spanning ~3 decades — the shape a mixed-
    # bucket serve storm actually produces.
    values = np.exp(rng.normal(loc=1.5, scale=1.0, size=10_000)).astype(float)
    h = LogHistogram()
    for v in values:
        h.record(v)
    v_sorted = np.sort(values)
    for q in (0.50, 0.90, 0.99, 1.0):
        exact = float(v_sorted[max(0, int(np.ceil(q * len(values))) - 1)])
        est = h.percentile(q)
        assert est is not None
        assert abs(est - exact) / exact <= REL_ERROR, (
            f"p{int(q * 100)}: estimate {est} vs exact {exact} beyond "
            f"the documented bound {REL_ERROR}"
        )


def test_histogram_merge_is_lossless():
    rng = np.random.default_rng(1)
    values = rng.uniform(0.1, 5000.0, size=2000)
    whole = LogHistogram()
    a, b = LogHistogram(), LogHistogram()
    for i, v in enumerate(values):
        whole.record(v)
        (a if i % 2 else b).record(v)
    merged = LogHistogram().merge(a).merge(b)
    assert merged.state() == whole.state()
    assert merged.percentile(0.99) == whole.percentile(0.99)


def test_histogram_empty_and_extremes():
    h = LogHistogram()
    assert h.percentile(0.5) is None and h.count == 0
    h.record(1e-9)  # underflow bucket
    h.record(1e9)  # overflow bucket
    assert h.count == 2
    # Estimates clamp to the OBSERVED range: the overflow estimate is
    # the tracked exact max, the underflow at most the lowest bound.
    assert h.percentile(1.0) == 1e9
    assert h.percentile(0.5) <= DEFAULT_BOUNDS[0]
    with pytest.raises(ValueError):
        h.percentile(0.0)


def test_histogram_state_roundtrip_and_delta():
    h = LogHistogram()
    for v in (1.0, 2.0, 400.0):
        h.record(v)
    st1 = h.state()
    for v in (3.0, 5.0):
        h.record(v)
    st2 = h.state()
    # Roundtrip preserves the full distribution.
    assert LogHistogram.from_state(st2).state() == st2
    # Windowed delta holds exactly the observations between snapshots.
    win = LogHistogram.delta(st2, st1)
    assert win.count == 2
    assert win.percentile(1.0) <= 5.0 * (1 + REL_ERROR)
    assert LogHistogram.delta(st2, None).count == 5


def test_reservoir_bounded_and_exact_below_capacity():
    r = Reservoir(size=100, seed=0)
    for v in range(50):
        r.add(float(v))
    assert sorted(r.values()) == [float(v) for v in range(50)]  # exact
    for v in range(50, 10_000):
        r.add(float(v))
    assert len(r.values()) == 100 and r.seen == 10_000  # bounded


# --- MetricsRegistry -------------------------------------------------------


def test_registry_get_or_create_identity_and_kinds():
    reg = MetricsRegistry()
    c1 = reg.counter("reqs", replica=0)
    c2 = reg.counter("reqs", replica=0)
    assert c1 is c2  # one series, every caller sees the same object
    assert reg.counter("reqs", replica=1) is not c1
    with pytest.raises(ValueError):
        reg.gauge("reqs", replica=0)  # kind clash on the same key
    c1.inc(3)
    assert reg.aggregate_counter("reqs") == 3


def test_registry_snapshot_and_aggregate_histogram():
    reg = MetricsRegistry()
    reg.histogram("lat", replica=0).record(10.0)
    reg.histogram("lat", replica=1).record(1000.0)
    reg.gauge("depth", fn=lambda: 7.0)
    snap = reg.snapshot()
    assert snap["lat{replica=0}"]["count"] == 1
    assert snap["depth"]["value"] == 7.0
    agg = reg.aggregate_histogram("lat")
    assert agg.count == 2
    # Pool merge is lossless: the merged p100 estimate sits within the
    # documented bucket-width bound of the true max.
    assert agg.percentile(1.0) == pytest.approx(1000.0, rel=REL_ERROR)


def test_exposition_text_prometheus_format():
    reg = MetricsRegistry()
    reg.counter("serve_requests_total", replica=0).inc(5)
    reg.gauge("serve_queue_depth").set(3)
    reg.histogram("serve_request_latency_ms").record(12.0)
    text = exposition_text(reg.snapshot())
    assert '# TYPE serve_requests_total counter' in text
    assert 'serve_requests_total{replica="0"} 5' in text
    assert "serve_queue_depth 3.0" in text
    assert '# TYPE serve_request_latency_ms histogram' in text
    assert 'serve_request_latency_ms_bucket{le="+Inf"} 1' in text
    assert "serve_request_latency_ms_count 1" in text
    # le buckets are CUMULATIVE: the +Inf sample equals the count.
    lines = [l for l in text.splitlines() if l.startswith(
        "serve_request_latency_ms_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in lines]
    assert counts == sorted(counts)


# --- MetricsPublisher ------------------------------------------------------


def test_publisher_tick_writes_series_exposition_events(tmp_path):
    clock = {"t": 100.0}
    reg = MetricsRegistry()
    c = reg.counter("serve_requests_total")
    series = str(tmp_path / "m.series.jsonl")
    expo = str(tmp_path / "m.prom")
    sink_path = str(tmp_path / "events.jsonl")
    with MetricsSink(sink_path) as sink:
        pub = MetricsPublisher(
            reg, interval_s=1.0, sink=sink, series_path=series,
            exposition_path=expo, clock=lambda: clock["t"],
        )
        c.inc(4)
        row1 = pub.tick()
        clock["t"] += 1.0
        c.inc(2)
        row2 = pub.close()
    rows = read_jsonl(series)
    assert [r["seq"] for r in rows] == [1, 2] == [row1["seq"], row2["seq"]]
    assert rows[0]["series"]["serve_requests_total"]["value"] == 4
    assert rows[1]["series"]["serve_requests_total"]["value"] == 6
    assert rows[1]["t"] - rows[0]["t"] == pytest.approx(1.0)
    # The exposition file reflects the LAST snapshot (atomic rewrite —
    # no .tmp straggler left behind).
    assert "serve_requests_total 6" in open(expo).read()
    assert not os.path.exists(expo + ".tmp")
    # Every published event validates against the central registry.
    events = read_jsonl(sink_path)
    snaps = [e for e in events if e.get("event") == "metrics_snapshot"]
    assert [e["seq"] for e in snaps] == [1, 2]
    for e in events:
        assert events_registry.validate_record(e) == [], e


def test_publisher_thread_cadence(tmp_path):
    reg = MetricsRegistry()
    reg.counter("serve_requests_total").inc()
    pub = MetricsPublisher(
        reg, interval_s=0.03,
        series_path=str(tmp_path / "s.jsonl"),
    )
    import time

    pub.start()
    time.sleep(0.25)
    final = pub.close()
    # ~8 intervals elapsed; the thread must have ticked repeatedly and
    # close() takes the final snapshot on top.
    assert final["seq"] >= 4
    assert len(read_jsonl(str(tmp_path / "s.jsonl"))) == final["seq"]
    with pytest.raises(ValueError):
        MetricsPublisher(reg, interval_s=0.0)


# --- SLO evaluation --------------------------------------------------------


def _snap(reg):
    return reg.snapshot()


def test_slo_fire_and_clear_edges_no_flapping():
    """The burn-rate contract: FIRE only when burn > 1 in BOTH the
    fast and slow windows, exactly once per breach; sustained violation
    stays silent (edge already emitted); CLEAR exactly once when the
    fast window recovers; a later second breach fires a NEW pair."""
    reg = MetricsRegistry()
    reqs = reg.counter("serve_requests_total")
    shed = reg.counter("serve_shed_total", reason="shed_deadline")
    ev = SLOEvaluator([
        SLOObjective("shed_fraction", "shed_frac", 0.10,
                     fast_window_s=2.0, slow_window_s=6.0),
    ])
    edges = []
    t = 0.0
    # Healthy traffic: 10 req/s, zero shed — never an edge.
    for _ in range(6):
        reqs.inc(10)
        edges += ev.observe(t, _snap(reg))
        t += 1.0
    assert edges == []
    # Total outage for 2 ticks: everything sheds.
    for _ in range(2):
        reqs.inc(10)
        shed.inc(10)
        edges += ev.observe(t, _snap(reg))
        t += 1.0
    assert [e["state"] for e in edges] == ["fire"]
    assert edges[0]["objective"] == "shed_fraction"
    assert edges[0]["burn_fast"] > 1.0 and edges[0]["burn_slow"] > 1.0
    # Violation persists one more tick: NO second fire (edges, not
    # levels).
    reqs.inc(10)
    shed.inc(10)
    edges += ev.observe(t, _snap(reg))
    t += 1.0
    assert [e["state"] for e in edges] == ["fire"]
    # Recovery: clean traffic until the shed burst leaves the fast
    # window -> exactly one clear.
    for _ in range(4):
        reqs.inc(10)
        edges += ev.observe(t, _snap(reg))
        t += 1.0
    assert [e["state"] for e in edges] == ["fire", "clear"]
    # A second breach fires a NEW pair (fresh edge, not flapping).
    for _ in range(3):
        reqs.inc(10)
        shed.inc(10)
        edges += ev.observe(t, _snap(reg))
        t += 1.0
    assert [e["state"] for e in edges] == ["fire", "clear", "fire"]


def test_slo_one_interval_blip_does_not_fire():
    """The slow window's job: a single-interval spike whose slow-window
    burn stays under 1.0 never fires — no paging on blips."""
    reg = MetricsRegistry()
    reqs = reg.counter("serve_requests_total")
    shed = reg.counter("serve_shed_total", reason="shed_deadline")
    ev = SLOEvaluator([
        SLOObjective("shed_fraction", "shed_frac", 0.20,
                     fast_window_s=1.0, slow_window_s=10.0),
    ])
    edges = []
    t = 0.0
    for i in range(12):
        reqs.inc(100)
        if i == 6:
            shed.inc(30)  # one bad interval: 30% locally, 2.5% over 10s
        edges += ev.observe(t, _snap(reg))
        t += 1.0
    assert edges == []


def test_slo_gauge_objective_and_session_loss():
    reg = MetricsRegistry()
    depth = reg.gauge("serve_queue_depth")
    lost = reg.counter("rollout_sessions_lost_total")
    ev = SLOEvaluator([
        SLOObjective("queue", "queue_depth", 8.0,
                     fast_window_s=1.0, slow_window_s=2.0),
        SLOObjective("sessions", "session_loss", 1.0,
                     fast_window_s=1.0, slow_window_s=2.0),
    ])
    edges = ev.observe(0.0, _snap(reg))
    depth.set(20.0)
    # ONE lost session burns exactly 1.0 against the unit threshold —
    # the single-unit event the always-on objective exists to catch
    # must fire (reaching the threshold IS the breach).
    lost.inc(1)
    edges += ev.observe(1.0, _snap(reg))
    states = {(e["objective"], e["state"]) for e in edges}
    assert states == {("queue", "fire"), ("sessions", "fire")}
    depth.set(0.0)
    edges2 = []
    for t in (2.0, 3.0, 4.0):
        edges2 += ev.observe(t, _snap(reg))
    assert {(e["objective"], e["state"]) for e in edges2} == {
        ("queue", "clear"), ("sessions", "clear"),
    }


def test_default_objectives_from_serve_config():
    from gnot_tpu.config import ServeConfig

    sc = ServeConfig(slo_p99_ms=250.0, slo_shed_frac=0.05, queue_limit=100)
    objs = {o.name: o for o in default_objectives(sc)}
    assert objs["latency_p99"].threshold == 250.0
    assert objs["shed_fraction"].threshold == 0.05
    assert objs["queue_saturation"].threshold == 90.0
    assert {"breaker_open", "session_loss"} <= set(objs)
    # No latency objective when the SLO knob is off.
    names = {o.name for o in default_objectives(ServeConfig())}
    assert "latency_p99" not in names
    with pytest.raises(ValueError):
        SLOObjective("x", "not_a_kind", 1.0)
    with pytest.raises(ValueError):
        SLOObjective("x", "shed_frac", 0.1, fast_window_s=10, slow_window_s=5)


# --- serve-tier wiring -----------------------------------------------------


def _stub_server(registry, **kw):
    from gnot_tpu.serve import InferenceEngine, InferenceServer

    fake_forward = lambda params, batch: np.zeros(
        (batch.coords.shape[0], batch.coords.shape[1], 1)
    )
    engine = InferenceEngine(None, None, batch_size=2, forward=fake_forward)
    return InferenceServer(
        engine, max_batch=2, max_wait_ms=5.0, metrics=registry, **kw
    )


def test_server_registry_counters_match_serve_summary(tmp_path):
    samples = datasets.synth_darcy2d(6, seed=0, grid_n=8)
    reg = MetricsRegistry()
    server = _stub_server(reg).start()
    futs = [server.submit(s) for s in samples]
    for f in futs:
        assert f.result(timeout=60).ok
    summary = server.drain()
    # Counters: one increment site each, so the registry and the
    # summary MUST agree exactly.
    assert reg.aggregate_counter("serve_requests_total") == summary["requests"]
    assert reg.aggregate_counter("serve_completed_total") == summary["completed"]
    assert reg.aggregate_counter("serve_dispatches_total") == summary["dispatches"]
    # The summary percentiles come from the SAME histogram the registry
    # holds — equal by construction, and the pool block mirrors them.
    hist = reg.aggregate_histogram("serve_request_latency_ms")
    assert hist.count == summary["completed"]
    assert hist.percentile(0.99) == summary["latency_p99_ms"]
    pool = pool_block(reg.snapshot())
    assert pool["p99_ms"] == summary["latency_p99_ms"]
    assert pool["requests"] == summary["requests"]
    # Per-bucket series exist and sum to the total population.
    bucket = reg.aggregate_histogram("serve_bucket_latency_ms")
    assert bucket.count == summary["completed"]
    # The raw retention is BOUNDED (the reservoir), not the unbounded
    # list it replaced.
    assert len(server.latencies_ms()) <= 2048


def test_server_without_registry_keeps_bounded_retention():
    """metrics=None (every historical caller): no registry series, but
    the retention is still the histogram + reservoir — serve_summary
    percentiles carry the documented estimate semantics either way."""
    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    server = _stub_server(None).start()
    futs = [server.submit(s) for s in samples]
    lats = [f.result(timeout=60).latency_ms for f in futs]
    summary = server.drain()
    assert summary["completed"] == 4
    exact = sorted(lats)
    assert summary["latency_p99_ms"] <= max(exact) * (1 + 1e-9)
    assert summary["latency_p50_ms"] <= summary["latency_p99_ms"]
    assert abs(summary["latency_p99_ms"] - exact[-1]) / exact[-1] <= REL_ERROR


def test_router_pool_merge_equals_sum_of_replicas(tmp_path):
    from gnot_tpu.serve import EngineReplica, InferenceEngine, ReplicaRouter

    fake_forward = lambda params, batch: np.zeros(
        (batch.coords.shape[0], batch.coords.shape[1], 1)
    )
    replicas = [
        EngineReplica(
            i, InferenceEngine(None, None, batch_size=2, forward=fake_forward)
        )
        for i in range(2)
    ]
    reg = MetricsRegistry()
    mp = str(tmp_path / "serve.jsonl")
    with MetricsSink(mp) as sink:
        router = ReplicaRouter(
            replicas, max_batch=2, max_wait_ms=5.0, sink=sink, metrics=reg,
            route_policy="round_robin",
        ).start()
        futs = [router.submit(s) for s in datasets.synth_darcy2d(8, seed=0, grid_n=8)]
        for f in futs:
            assert f.result(timeout=60).ok
        summary = router.drain()
    # Pool merge is the SUM of the per-replica series: counts add
    # exactly and the pool percentile comes from the merged buckets.
    per_counts = [
        reg.histogram("serve_request_latency_ms", replica=i).count
        for i in range(2)
    ]
    assert all(c > 0 for c in per_counts)  # round_robin spread the storm
    agg = reg.aggregate_histogram("serve_request_latency_ms")
    assert agg.count == sum(per_counts) == summary["completed"]
    assert agg.percentile(0.99) == summary["latency_p99_ms"]
    assert agg.percentile(0.50) == summary["latency_p50_ms"]
    # Route counters: one per placement, by reason.
    assert reg.aggregate_counter("router_routes_total") == 8
    # Per-replica summaries agree with their own series.
    for i in range(2):
        s = summary["per_replica"][str(i)]
        assert s["completed"] == per_counts[i]


def test_final_snapshot_agrees_with_serve_summary(tmp_path):
    samples = datasets.synth_darcy2d(6, seed=0, grid_n=8)
    reg = MetricsRegistry()
    pub = MetricsPublisher(
        reg, interval_s=1.0, series_path=str(tmp_path / "s.jsonl")
    )
    server = _stub_server(reg).start()
    futs = [server.submit(s) for s in samples]
    for f in futs:
        assert f.result(timeout=60).ok
    summary = server.drain()
    final = pub.close()
    assert summary_agrees(summary, final) == []
    # A disagreement IS detected (guard against a vacuous check).
    tampered = dict(summary, completed=summary["completed"] + 1)
    assert summary_agrees(tampered, final)


def test_trainer_telemetry_buffer_feeds_registry(tmp_path):
    """The train-loop tap: TelemetryBuffer(metrics=...) lands every
    drained dispatch interval in train_step_time_ms."""
    import jax.numpy as jnp

    from gnot_tpu.obs.telemetry import TelemetryBuffer

    reg = MetricsRegistry()
    # log_every=0: records off, drains only when flushed — the three
    # appends stay one window, so two dispatch intervals are timed.
    buf = TelemetryBuffer(None, log_every=0, metrics=reg)
    for s in range(1, 4):
        buf.append(steps=[s], epoch=0, lrs=[1e-3],
                   loss=jnp.asarray(float(s)),
                   telem={}, batches=[None])
    buf.drain()
    # N appends -> N-1 measurable intervals (the first has no prior
    # timestamp).
    assert reg.aggregate_histogram("train_step_time_ms").count == 2
