"""Native C++ ragged packer vs the numpy fallback."""

import numpy as np

from gnot_tpu import native


def _ragged(rng, n, dim, lo=3, hi=40):
    return [
        rng.standard_normal((int(rng.integers(lo, hi)), dim)).astype(np.float32)
        for _ in range(n)
    ]


def test_native_builds_and_loads():
    # g++ is part of the baked toolchain; the build must succeed here.
    assert native.native_available()


def test_pack_rows_matches_numpy():
    rng = np.random.default_rng(0)
    for n, dim in [(1, 2), (4, 3), (16, 7)]:
        arrs = _ragged(rng, n, dim)
        max_len = max(a.shape[0] for a in arrs) + 5
        out_n, mask_n = native.pack_rows(arrs, max_len)
        out_p, mask_p = native.pack_rows_numpy(arrs, max_len)
        np.testing.assert_array_equal(out_n, out_p)
        np.testing.assert_array_equal(mask_n, mask_p)


def test_pack_rows_large_threaded_path():
    rng = np.random.default_rng(1)
    # > 4 MiB total to cross the threading threshold in ragged_pack.cpp.
    arrs = _ragged(rng, 32, 64, lo=500, hi=1200)
    max_len = max(a.shape[0] for a in arrs)
    out_n, mask_n = native.pack_rows(arrs, max_len)
    out_p, mask_p = native.pack_rows_numpy(arrs, max_len)
    np.testing.assert_array_equal(out_n, out_p)
    np.testing.assert_array_equal(mask_n, mask_p)


def test_collate_uses_packer_consistently():
    """collate output is identical whether or not the native lib loads."""
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate

    samples = datasets.synth_elasticity(6, base_points=64)
    b1 = collate(samples[:4])
    assert b1.coords.dtype == np.float32
    assert b1.node_mask.sum() == sum(s.coords.shape[0] for s in samples[:4])
    # force the numpy fallback and compare
    lib, native._lib, native._load_failed = native._lib, None, True
    try:
        b2 = collate(samples[:4])
    finally:
        native._lib, native._load_failed = lib, False
    np.testing.assert_array_equal(b1.coords, b2.coords)
    np.testing.assert_array_equal(b1.funcs, b2.funcs)
    np.testing.assert_array_equal(b1.func_mask, b2.func_mask)
    np.testing.assert_array_equal(b1.node_mask, b2.node_mask)


def test_pack_rows_fuzz_matches_numpy():
    """Randomized shapes/lengths: the C++ packer and the numpy fallback
    must agree bit-for-bit, including mask placement."""
    from gnot_tpu import native

    if not native.native_available():
        import pytest

        pytest.skip("native packer unavailable")
    rng = np.random.default_rng(123)
    for _ in range(50):
        n = int(rng.integers(1, 9))
        dim = int(rng.integers(1, 17))
        lens = rng.integers(0, 33, size=n)
        max_len = int(max(lens.max(), 1) + rng.integers(0, 8))
        arrs = [
            rng.normal(size=(int(m), dim)).astype(np.float32) for m in lens
        ]
        out_c, mask_c = native.pack_rows(arrs, max_len)
        out_np, mask_np = native.pack_rows_numpy(arrs, max_len)
        np.testing.assert_array_equal(out_c, out_np)
        np.testing.assert_array_equal(mask_c, mask_np)
