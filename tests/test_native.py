"""Native C++ ragged packer vs the numpy fallback.

The dispatch between them is a MEASURED policy
(``native.PACK_NATIVE_MIN_BYTES`` / ``NATIVE_UNPAD_MIN_BYTES``): below
the payload bars numpy's calloc+C-core copies are the fast path, above
them the native sweep is. Parity tests force the native path
(``force_native``) so small fixtures exercise the C++ code instead of
silently comparing numpy against itself."""

import contextlib

import numpy as np

from gnot_tpu import native


@contextlib.contextmanager
def force_native():
    """Drop the payload bars to 0 so every call runs the native path
    (when the .so loaded) regardless of size."""
    saved = dict(native.PACK_NATIVE_MIN_BYTES)
    saved_unpad = native.NATIVE_UNPAD_MIN_BYTES
    native.PACK_NATIVE_MIN_BYTES.update({"float32": 0, "bfloat16": 0})
    native.NATIVE_UNPAD_MIN_BYTES = 0
    try:
        yield
    finally:
        native.PACK_NATIVE_MIN_BYTES.update(saved)
        native.NATIVE_UNPAD_MIN_BYTES = saved_unpad


def _ragged(rng, n, dim, lo=3, hi=40):
    return [
        rng.standard_normal((int(rng.integers(lo, hi)), dim)).astype(np.float32)
        for _ in range(n)
    ]


def test_native_builds_and_loads():
    # g++ is part of the baked toolchain; the build must succeed here.
    assert native.native_available()


def test_pack_rows_matches_numpy():
    rng = np.random.default_rng(0)
    with force_native():
        for n, dim in [(1, 2), (4, 3), (16, 7)]:
            arrs = _ragged(rng, n, dim)
            max_len = max(a.shape[0] for a in arrs) + 5
            out_n, mask_n = native.pack_rows(arrs, max_len)
            out_p, mask_p = native.pack_rows_numpy(arrs, max_len)
            np.testing.assert_array_equal(out_n, out_p)
            np.testing.assert_array_equal(mask_n, mask_p)


def test_pack_rows_large_threaded_path():
    rng = np.random.default_rng(1)
    # ~8 MiB total: exercises the native sweep at real size (threading
    # itself engages at 32 MiB — see ragged_pack.cpp for_samples).
    arrs = _ragged(rng, 32, 64, lo=500, hi=1200)
    max_len = max(a.shape[0] for a in arrs)
    with force_native():
        out_n, mask_n = native.pack_rows(arrs, max_len)
    out_p, mask_p = native.pack_rows_numpy(arrs, max_len)
    np.testing.assert_array_equal(out_n, out_p)
    np.testing.assert_array_equal(mask_n, mask_p)


def test_collate_uses_packer_consistently():
    """collate output is identical whether or not the native lib loads."""
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate

    samples = datasets.synth_elasticity(6, base_points=64)
    b1 = collate(samples[:4])
    assert b1.coords.dtype == np.float32
    assert b1.node_mask.sum() == sum(s.coords.shape[0] for s in samples[:4])
    # force the numpy fallback and compare
    lib, native._lib, native._load_failed = native._lib, None, True
    try:
        b2 = collate(samples[:4])
    finally:
        native._lib, native._load_failed = lib, False
    np.testing.assert_array_equal(b1.coords, b2.coords)
    np.testing.assert_array_equal(b1.funcs, b2.funcs)
    np.testing.assert_array_equal(b1.func_mask, b2.func_mask)
    np.testing.assert_array_equal(b1.node_mask, b2.node_mask)


def test_pack_rows_bf16_bitwise_matches_numpy_fallback():
    """The fused pad-and-cast sweep must be BITWISE the ml_dtypes RNE
    cast the Python fallback does — NaNs, denormals, ties and infs
    included — so which implementation assembled a bf16 dispatch can
    never change a served bit."""
    import ml_dtypes

    if not native.native_available():
        import pytest

        pytest.skip("native packer unavailable")
    rng = np.random.default_rng(7)
    arrs = _ragged(rng, 6, 4)
    # Adversarial block: specials + RNE tie patterns + denormals.
    arrs[0] = np.array(
        [
            [np.nan, -np.nan, np.inf, -np.inf],
            [0.0, -0.0, 1e-40, -1e-40],
            # 1.0 + 2^-9 exactly (an RNE tie) and its neighbors.
            [1.001953125, 1.0019531, 1.0019532, -1.001953125],
            [3.3895314e38, -3.3895314e38, 65504.0, 1.5],
        ],
        np.float32,
    )
    max_len = max(a.shape[0] for a in arrs) + 3
    with force_native():
        out_c, mask_c = native.pack_rows(arrs, max_len, "bfloat16")
    out_p, mask_p = native.pack_rows_numpy(arrs, max_len, "bfloat16")
    assert out_c.dtype == out_p.dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        out_c.view(np.uint16), out_p.view(np.uint16)
    )
    np.testing.assert_array_equal(
        mask_c.view(np.uint16), mask_p.view(np.uint16)
    )
    # And both agree with a straight ml_dtypes cast of the padded f32.
    with force_native():
        out_f, _ = native.pack_rows(arrs, max_len, "float32")
    np.testing.assert_array_equal(
        out_c.view(np.uint16),
        out_f.astype(ml_dtypes.bfloat16).view(np.uint16),
    )


def test_pack_rows_bf16_empty_and_oversize_edges():
    """Edge parity: a zero-length block packs to an all-pad row on both
    paths, and the oversize guard raises identically BEFORE either
    implementation is chosen (the fallback can't accept what the
    native path rejects)."""
    import pytest

    arrs = [np.zeros((0, 3), np.float32), np.ones((2, 3), np.float32)]
    for dtype in ("float32", "bfloat16"):
        out_c, mask_c = native.pack_rows(arrs, 4, dtype)
        out_p, mask_p = native.pack_rows_numpy(arrs, 4, dtype)
        np.testing.assert_array_equal(np.asarray(out_c, np.float32),
                                      np.asarray(out_p, np.float32))
        assert float(np.asarray(mask_c, np.float32)[0].sum()) == 0.0
        assert float(np.asarray(mask_c, np.float32)[1].sum()) == 2.0
    big = [np.ones((9, 3), np.float32)]
    # The oversize guard sits BEFORE the native/fallback choice, so an
    # oversize block fails identically whichever implementation loads
    # (this is the serve oversize-fallback edge: the server routes such
    # requests to a bigger bucket, never into a too-small pack).
    with pytest.raises(ValueError, match="exceeds max_len"):
        native.pack_rows(big, 8, "bfloat16")
    lib, native._lib, native._load_failed = native._lib, None, True
    try:
        with pytest.raises(ValueError, match="exceeds max_len"):
            native.pack_rows(big, 8, "bfloat16")
    finally:
        native._lib, native._load_failed = lib, False
    with pytest.raises(ValueError, match="dtype must be"):
        native.pack_rows(arrs, 4, "float16")


def test_unpad_rows_matches_numpy_exactly():
    """Batched native unpad vs the Python slice loop: exact bytes for
    padded spans (row, 0, n), packed spans (row, offset, n), empty
    spans (n=0), f32 and bf16 element types; results are OWNED arrays,
    not views into the dispatch buffer."""
    import ml_dtypes

    rng = np.random.default_rng(5)
    out = rng.standard_normal((3, 40, 2)).astype(np.float32)
    spans = [(0, 0, 17), (1, 8, 20), (2, 0, 0), (1, 28, 12)]
    for arr in (out, out.astype(ml_dtypes.bfloat16)):
        with force_native():
            got = native.unpad_rows(arr, spans)
        want = native.unpad_rows_numpy(arr, spans)
        assert [g.shape for g in got] == [(17, 2), (20, 2), (0, 2), (12, 2)]
        for g, w in zip(got, want):
            assert g.dtype == arr.dtype
            np.testing.assert_array_equal(
                g.view(np.uint16) if g.dtype != np.float32 else g,
                w.view(np.uint16) if w.dtype != np.float32 else w,
            )
            assert g.base is None  # owned, never a view into `arr`


def test_unpad_rows_bounds_checked():
    import pytest

    out = np.zeros((2, 8, 1), np.float32)
    with pytest.raises(ValueError, match="out of bounds"):
        native.unpad_rows(out, [(0, 4, 5)])
    with pytest.raises(ValueError, match="out of bounds"):
        native.unpad_rows(out, [(2, 0, 1)])
    with pytest.raises(ValueError, match=r"\[R, L, dim\]"):
        native.unpad_rows(np.zeros((4, 4), np.float32), [(0, 0, 1)])


def test_native_status_is_attributable():
    st = native.status()
    assert set(st) == {
        "available", "impl", "so", "error",
        "pack_native_min_bytes", "unpad_native_min_bytes",
    }
    assert st["impl"] in ("native", "python")
    # The record carries the adaptive-dispatch policy: a reader can
    # tell which payload classes actually ran the C sweep.
    assert st["pack_native_min_bytes"] == native.PACK_NATIVE_MIN_BYTES
    assert st["unpad_native_min_bytes"] == native.NATIVE_UNPAD_MIN_BYTES
    if st["available"]:
        assert st["impl"] == "native" and st["so"].endswith(".so")


def test_pack_rows_fuzz_matches_numpy():
    """Randomized shapes/lengths: the C++ packer and the numpy fallback
    must agree bit-for-bit, including mask placement."""
    from gnot_tpu import native

    if not native.native_available():
        import pytest

        pytest.skip("native packer unavailable")
    rng = np.random.default_rng(123)
    with force_native():
        for _ in range(50):
            n = int(rng.integers(1, 9))
            dim = int(rng.integers(1, 17))
            lens = rng.integers(0, 33, size=n)
            max_len = int(max(lens.max(), 1) + rng.integers(0, 8))
            arrs = [
                rng.normal(size=(int(m), dim)).astype(np.float32) for m in lens
            ]
            dt = "bfloat16" if rng.integers(2) else "float32"
            out_c, mask_c = native.pack_rows(arrs, max_len, dt)
            out_np, mask_np = native.pack_rows_numpy(arrs, max_len, dt)
            v = np.uint16 if dt == "bfloat16" else np.float32
            np.testing.assert_array_equal(out_c.view(v), out_np.view(v))
            np.testing.assert_array_equal(mask_c.view(v), mask_np.view(v))


def test_pack_rows_bf16_f64_input_rounds_identically():
    """Non-f32 input must round f64->f32->bf16 on BOTH paths: the
    native sweep reads f32 bits, so a fallback that cast f64->bf16
    directly would diverge on double-rounding edge values."""
    import ml_dtypes

    if not native.native_available():
        import pytest

        pytest.skip("native packer unavailable")
    rng = np.random.default_rng(11)
    # Values engineered near f32 rounding boundaries + random f64s.
    a = np.concatenate([
        rng.standard_normal(64) * np.float64(1.0000000596046448),
        np.nextafter(np.float64(1.001953125), 2.0) * np.ones(8),
        rng.standard_normal(64),
    ]).reshape(-1, 4)
    arrs = [a, rng.standard_normal((5, 4))]  # float64 blocks
    with force_native():
        out_c, _ = native.pack_rows(arrs, 40, "bfloat16")
    out_p, _ = native.pack_rows_numpy(arrs, 40, "bfloat16")
    np.testing.assert_array_equal(
        out_c.view(np.uint16), out_p.view(np.uint16)
    )
    # And both equal the canonical two-step rounding.
    want = arrs[0].astype(np.float32).astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        out_c[0, : a.shape[0]].view(np.uint16), want.view(np.uint16)
    )
