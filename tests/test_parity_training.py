"""End-to-end training parity: JAX path vs the PyTorch reference.

The north-star gate (BASELINE.json): the JAX path must reproduce the
PyTorch reference to <1e-4. test_model.py covers the forward pass; this
file covers a full short TRAINING run — same torch-exported initial
weights, same batches, AdamW at torch defaults on both sides — and
compares per-step losses and final parameters.

Two regimes share one harness (``_assert_training_parity``):

* uniform sample lengths (zero padding) — isolates optimizer + gradient
  parity from the padding-pollution question;
* genuinely ragged batches (elasticity-style lengths) — every batch
  carries nonzero pad rows that pollute attention unmasked on both
  sides (reference main.py:63-82, model.py:77-80).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, OptimConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.train.trainer import TrainState, make_optimizer, make_train_step

pytestmark = pytest.mark.skipif(
    not os.path.exists("/root/reference/model.py"),
    reason="reference checkout not available",
)

MC = ModelConfig(
    input_dim=2,
    theta_dim=1,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=1,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=2,
    n_head=4,
    # Parity mode: the reference's interleaved head merge (and unmasked
    # padding, irrelevant here since batches are pad-free).
    attention_mode="parity",
)
N_STEPS = 6
LR = 1e-3


def _uniform_batches():
    # synth_ns2d: every sample has the same n_points -> zero padding.
    samples = datasets.synth_ns2d(4 * N_STEPS, n_points=64, seed=5)
    return list(Loader(samples, 4, bucket=False, prefetch=0))


def _torch_rel_l2(pred, target, mask):
    num = ((pred - target) ** 2 * mask[..., None]).sum(1)
    den = (target**2 * mask[..., None]).sum(1)
    return ((num / den) ** 0.5).mean()


def _assert_training_parity(mc, batches, torch_seed):
    """Run the same short AdamW training on both backends from identical
    torch-seeded initial weights; assert per-step losses match <1e-4 and
    final parameters stay in parity. The torch loss masks pad rows —
    exactly the reference's unpad-slicing + SumPool (main.py:87-98)."""
    import torch

    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

    # --- torch side -------------------------------------------------------
    torch.manual_seed(torch_seed)
    tmodel = build_reference_model(mc)
    topt = torch.optim.AdamW(tmodel.parameters(), lr=LR)  # wd=0.01 default
    tlosses = []
    for b in batches:
        out = tmodel(
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        )
        loss = _torch_rel_l2(out, torch.from_numpy(b.y), torch.from_numpy(b.node_mask))
        tlosses.append(float(loss.detach()))
        topt.zero_grad()
        loss.backward()
        topt.step()

    # --- jax side, from the SAME initial weights --------------------------
    # tmodel has been updated in place; rebuild the initial weights from
    # the same torch seed.
    torch.manual_seed(torch_seed)
    tmodel0 = build_reference_model(mc)
    params = jax.tree.map(jnp.asarray, state_dict_to_flax(tmodel0.state_dict(), mc))

    model = GNOT(mc)
    tx = make_optimizer(OptimConfig(), LR)
    state = TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )
    step_fn = make_train_step(model, OptimConfig(), "rel_l2")
    jlosses = []
    for b in batches:
        state, loss = step_fn(state, b, jnp.asarray(LR, jnp.float32))
        jlosses.append(float(loss))

    # Per-step training losses match the oracle to the north-star tol.
    np.testing.assert_allclose(jlosses, tlosses, rtol=1e-4, atol=1e-5)

    # Final parameters stay within parity after N_STEPS of AdamW.
    final_torch = state_dict_to_flax(tmodel.state_dict(), mc)
    t_leaves = jax.tree.leaves(final_torch)
    j_leaves = jax.tree.leaves(jax.device_get(state.params))
    assert len(t_leaves) == len(j_leaves)
    for t, j in zip(t_leaves, j_leaves):
        np.testing.assert_allclose(np.asarray(j), np.asarray(t), rtol=2e-3, atol=1e-4)


def test_training_run_parity_vs_torch():
    _assert_training_parity(MC, _uniform_batches(), torch_seed=0)


def test_training_run_parity_vs_torch_ragged():
    """Same gate on genuinely RAGGED batches: nonzero pad rows pollute
    attention unmasked on both sides, while the loss is pad-free on both
    sides (reference unpad-slicing main.py:87-89 == masked segment sums
    here). Closes the round-2 verdict's top gap: padding-pollution
    parity had only ever been tested pad-free."""
    mc = ModelConfig(
        input_dim=2,
        theta_dim=2,
        input_func_dim=3,
        out_dim=2,
        n_input_functions=1,
        n_attn_layers=2,
        n_attn_hidden_dim=32,
        n_mlp_num_layers=2,
        n_mlp_hidden_dim=32,
        n_input_hidden_dim=32,
        n_expert=2,
        n_head=4,
        attention_mode="parity",
    )
    # Elasticity-style ragged lengths; bucket=False reproduces the
    # reference's per-batch-max padding exactly (main.py:63-82).
    samples = datasets.synth_elasticity(4 * N_STEPS, seed=13, base_points=96)
    batches = list(Loader(samples, 4, bucket=False, prefetch=0))
    for b in batches:
        assert float(np.min(b.node_mask)) == 0.0, "batch must carry real padding"
    _assert_training_parity(mc, batches, torch_seed=1)


def test_flax_to_state_dict_roundtrip():
    """flax -> torch -> flax is the identity, and the exported
    state_dict loads into the reference torch model."""
    import torch

    from gnot_tpu.interop.torch_oracle import (
        build_reference_model,
        flax_to_state_dict,
        state_dict_to_flax,
    )

    torch.manual_seed(3)
    tmodel = build_reference_model(MC)
    params = state_dict_to_flax(tmodel.state_dict(), MC)
    sd = flax_to_state_dict(params, MC)
    tmodel.load_state_dict(sd)  # raises on any missing/unexpected key
    back = state_dict_to_flax(tmodel.state_dict(), MC)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
