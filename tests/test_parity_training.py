"""End-to-end training parity: JAX path vs the PyTorch reference.

The north-star gate (BASELINE.json): the JAX path must reproduce the
PyTorch reference to <1e-4. test_model.py covers the forward pass; this
file covers a full short TRAINING run — same torch-exported initial
weights, same batches, AdamW at torch defaults on both sides — and
compares per-step losses and final parameters.

Batches are built with uniform sample lengths (no padding), where
masked and parity numerics coincide, so this isolates optimizer +
gradient parity from the padding-pollution question (which
test_model.py's parity-mode tests cover).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, OptimConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.train.trainer import TrainState, make_optimizer, make_train_step

pytestmark = pytest.mark.skipif(
    not os.path.exists("/root/reference/model.py"),
    reason="reference checkout not available",
)

MC = ModelConfig(
    input_dim=2,
    theta_dim=1,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=1,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=2,
    n_head=4,
    # Parity mode: the reference's interleaved head merge (and unmasked
    # padding, irrelevant here since batches are pad-free).
    attention_mode="parity",
)
N_STEPS = 6
LR = 1e-3


def _uniform_batches():
    # synth_ns2d: every sample has the same n_points -> zero padding.
    samples = datasets.synth_ns2d(4 * N_STEPS, n_points=64, seed=5)
    return list(Loader(samples, 4, bucket=False, prefetch=0))


def _torch_rel_l2(pred, target, mask):
    num = ((pred - target) ** 2 * mask[..., None]).sum(1)
    den = (target**2 * mask[..., None]).sum(1)
    return ((num / den) ** 0.5).mean()


def test_training_run_parity_vs_torch():
    import torch

    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax

    batches = _uniform_batches()

    # --- torch side -------------------------------------------------------
    torch.manual_seed(0)
    tmodel = build_reference_model(MC)
    topt = torch.optim.AdamW(tmodel.parameters(), lr=LR)  # wd=0.01 default
    tlosses = []
    for b in batches:
        out = tmodel(
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        )
        loss = _torch_rel_l2(
            out, torch.from_numpy(b.y), torch.from_numpy(b.node_mask)
        )
        tlosses.append(float(loss))
        topt.zero_grad()
        loss.backward()
        topt.step()

    # --- jax side, from the SAME initial weights --------------------------
    # tmodel has been updated in place; rebuild the initial weights from
    # the same torch seed.
    torch.manual_seed(0)
    tmodel0 = build_reference_model(MC)
    params = jax.tree.map(
        jnp.asarray, state_dict_to_flax(tmodel0.state_dict(), MC)
    )

    model = GNOT(MC)
    tx = make_optimizer(OptimConfig(), LR)
    state = TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )
    step_fn = make_train_step(model, OptimConfig(), "rel_l2")
    jlosses = []
    for b in batches:
        state, loss = step_fn(state, b, jnp.asarray(LR, jnp.float32))
        jlosses.append(float(loss))

    # Per-step training losses match the oracle to the north-star tol.
    np.testing.assert_allclose(jlosses, tlosses, rtol=1e-4, atol=1e-5)

    # Final parameters stay within parity after N_STEPS of AdamW.
    final_torch = state_dict_to_flax(tmodel.state_dict(), MC)
    t_leaves = jax.tree.leaves(final_torch)
    j_leaves = jax.tree.leaves(jax.device_get(state.params))
    assert len(t_leaves) == len(j_leaves)
    for t, j in zip(t_leaves, j_leaves):
        np.testing.assert_allclose(np.asarray(j), np.asarray(t), rtol=2e-3, atol=1e-4)


def test_flax_to_state_dict_roundtrip():
    """flax -> torch -> flax is the identity, and the exported
    state_dict loads into the reference torch model."""
    import torch

    from gnot_tpu.interop.torch_oracle import (
        build_reference_model,
        flax_to_state_dict,
        state_dict_to_flax,
    )

    torch.manual_seed(3)
    tmodel = build_reference_model(MC)
    params = state_dict_to_flax(tmodel.state_dict(), MC)
    sd = flax_to_state_dict(params, MC)
    tmodel.load_state_dict(sd)  # raises on any missing/unexpected key
    back = state_dict_to_flax(tmodel.state_dict(), MC)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
