"""Fused pallas attention kernel vs the einsum formulation.

Runs in pallas interpreter mode on the CPU test platform (the kernel
auto-selects interpret off-TPU); the same code path compiles on TPU.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.ops.pallas_attention import _reference_impl, fused_nla


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "n_funcs,masked,l,lk",
    [
        (1, False, 24, 16),
        (2, True, 24, 16),
        (3, True, 40, 24),
        (1, True, 300, 280),  # > TILE after padding checks the seq tiling
    ],
)
def test_fused_matches_einsum_cross(n_funcs, masked, l, lk):
    b, h, e = 2, 4, 32
    keys = jax.random.split(jax.random.key(0), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], n_funcs, b, lk, e)
    v = _rand(keys[2], n_funcs, b, lk, e)
    if masked:
        mask = (
            jax.random.uniform(keys[3], (n_funcs, b, lk)) > 0.3
        ).astype(jnp.float32)
        mask = mask.at[:, :, 0].set(1.0)  # at least one real row
    else:
        mask = jnp.ones((n_funcs, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_group_softmax_outlier_head_no_nan():
    """One head's logits spiking ~200 above another's must not underflow
    the quiet head's group to 0/0 (the max is per group, not per row)."""
    b, h, l, lk, e = 1, 4, 16, 16, 32
    keys = jax.random.split(jax.random.key(7), 3)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    # Spike head 0's lanes (first e//h lanes) of both q and k.
    q = q.at[..., : e // h].add(200.0)
    k = k.at[..., : e // h].add(200.0)
    mask = jnp.ones((1, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(qs)).all()
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_fused_grads_match_einsum():
    b, h, l, lk, e = 2, 2, 12, 10, 16
    keys = jax.random.split(jax.random.key(1), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    def loss_fused(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_ref(q, k, v):
        out, qs = _reference_impl(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_reference_impl_matches_xla_ops():
    """The merged-layout einsum oracle == the split-head XLA ops path."""
    from gnot_tpu.ops.attention import (
        feature_softmax,
        merge_heads,
        normalized_linear_attention,
        split_heads,
    )

    b, h, l, lk, e = 2, 4, 12, 10, 32
    keys = jax.random.split(jax.random.key(2), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_m, qs_m = _reference_impl(q, k, v, mask, h)
    qh = feature_softmax(split_heads(q, h))
    kh = feature_softmax(split_heads(k[0], h))
    vh = split_heads(v[0], h)
    out_h = normalized_linear_attention(qh, kh, vh, kv_mask=mask[0])
    np.testing.assert_allclose(
        np.asarray(out_m[0]), np.asarray(merge_heads(out_h)), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(qs_m), np.asarray(merge_heads(qh)), rtol=1e-5, atol=1e-6
    )


def test_model_attention_impl_pallas_retired():
    """The model-level pallas attention dispatch was retired in round 4
    (lost the honest A/B at every scale); the config rejects it with a
    pointer to the dead-end analysis. The kernels in
    ops/pallas_attention.py remain tested above."""
    with pytest.raises(ValueError, match="retired"):
        ModelConfig(
            input_dim=2,
            theta_dim=1,
            input_func_dim=3,
            out_dim=1,
            n_input_functions=1,
            n_attn_layers=1,
            n_attn_hidden_dim=16,
            n_mlp_num_layers=1,
            n_mlp_hidden_dim=16,
            n_input_hidden_dim=16,
            n_expert=2,
            n_head=2,
            attention_impl="pallas",
        )


def test_fused_nla_sp_matches_single_device():
    """Sequence-parallel fused attention (reduce -> psum -> apply) over
    an 8-way seq mesh == the single-device op, forward and backward."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(3), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_sp, qs_sp = fused_nla_sp(q, k, v, mask, h, mesh)
    out_1, qs_1 = fused_nla(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_sp), np.asarray(qs_1), rtol=1e-5, atol=1e-6)

    def loss_sp(q, k, v):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_1(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_1 = jax.grad(loss_1, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_sp, g_1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_fused_nla_sp_ring_matches_psum():
    """The ring all-reduce schedule (S-1 ppermute hops) must be
    numerically interchangeable with the one-shot psum, forward and
    backward (the backward replays the ring in reverse)."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(7), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_r, qs_r = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="ring")
    out_p, qs_p = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="psum")
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_p), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_r), np.asarray(qs_p), rtol=1e-5, atol=1e-6)

    def loss(q, k, v, collective):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective=collective)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_r = jax.grad(lambda *a: loss(*a, "ring"), argnums=(0, 1, 2))(q, k, v)
    g_p = jax.grad(lambda *a: loss(*a, "psum"), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_r, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_ring_allreduce_matches_psum_generic():
    """ops/collectives.ring_allreduce == lax.psum for a generic payload."""
    from jax.sharding import Mesh, PartitionSpec as P

    from gnot_tpu.ops.collectives import ring_allreduce

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("s",))
    x = jax.random.normal(jax.random.key(0), (8, 4, 4))

    from gnot_tpu.ops.collectives import shard_map

    ring = shard_map(
        lambda t: ring_allreduce(t, "s", 8),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    ps = shard_map(
        lambda t: jax.lax.psum(t, "s"),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ps), rtol=1e-6, atol=1e-6)


def test_pallas_empty_input_function_is_finite():
    """Op-level twin of test_model.py::test_empty_input_function_is_finite:
    an all-masked function slab reaches nla_apply with ksum == 0; the
    kernel's denominator guard must yield 0, not nan — forward and
    backward."""
    rng = np.random.default_rng(5)
    b, l, e, h, f = 2, 16, 32, 4, 2
    q = rng.normal(size=(b, l, e)).astype(np.float32)
    k = rng.normal(size=(f, b, l, e)).astype(np.float32)
    v = rng.normal(size=(f, b, l, e)).astype(np.float32)
    mask = np.ones((f, b, l), np.float32)
    mask[1, 0, :] = 0.0  # sample 0's second input function is empty

    def loss(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.mean(out**2) + jnp.mean(qs**2)

    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(float(val))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
