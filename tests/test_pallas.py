"""Fused pallas attention kernel vs the einsum formulation.

Runs in pallas interpreter mode on the CPU test platform (the kernel
auto-selects interpret off-TPU); the same code path compiles on TPU.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.ops.pallas_attention import _reference_impl, fused_nla


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "n_funcs,masked,l,lk",
    [
        (1, False, 24, 16),
        (2, True, 24, 16),
        (3, True, 40, 24),
        (1, True, 300, 280),  # > TILE after padding checks the seq tiling
    ],
)
def test_fused_matches_einsum_cross(n_funcs, masked, l, lk):
    b, h, e = 2, 4, 32
    keys = jax.random.split(jax.random.key(0), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], n_funcs, b, lk, e)
    v = _rand(keys[2], n_funcs, b, lk, e)
    if masked:
        mask = (
            jax.random.uniform(keys[3], (n_funcs, b, lk)) > 0.3
        ).astype(jnp.float32)
        mask = mask.at[:, :, 0].set(1.0)  # at least one real row
    else:
        mask = jnp.ones((n_funcs, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_group_softmax_outlier_head_no_nan():
    """One head's logits spiking ~200 above another's must not underflow
    the quiet head's group to 0/0 (the max is per group, not per row)."""
    b, h, l, lk, e = 1, 4, 16, 16, 32
    keys = jax.random.split(jax.random.key(7), 3)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    # Spike head 0's lanes (first e//h lanes) of both q and k.
    q = q.at[..., : e // h].add(200.0)
    k = k.at[..., : e // h].add(200.0)
    mask = jnp.ones((1, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(qs)).all()
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_fused_grads_match_einsum():
    b, h, l, lk, e = 2, 2, 12, 10, 16
    keys = jax.random.split(jax.random.key(1), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    def loss_fused(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_ref(q, k, v):
        out, qs = _reference_impl(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_reference_impl_matches_xla_ops():
    """The merged-layout einsum oracle == the split-head XLA ops path."""
    from gnot_tpu.ops.attention import (
        feature_softmax,
        merge_heads,
        normalized_linear_attention,
        split_heads,
    )

    b, h, l, lk, e = 2, 4, 12, 10, 32
    keys = jax.random.split(jax.random.key(2), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_m, qs_m = _reference_impl(q, k, v, mask, h)
    qh = feature_softmax(split_heads(q, h))
    kh = feature_softmax(split_heads(k[0], h))
    vh = split_heads(v[0], h)
    out_h = normalized_linear_attention(qh, kh, vh, kv_mask=mask[0])
    np.testing.assert_allclose(
        np.asarray(out_m[0]), np.asarray(merge_heads(out_h)), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(qs_m), np.asarray(merge_heads(qh)), rtol=1e-5, atol=1e-6
    )


def test_model_forward_pallas_matches_xla():
    """Full GNOT forward: pallas attention == xla attention."""
    mc = ModelConfig(
        input_dim=2,
        theta_dim=2,
        input_func_dim=3,
        out_dim=2,
        n_input_functions=1,
        n_attn_layers=2,
        n_attn_hidden_dim=32,
        n_mlp_num_layers=2,
        n_mlp_hidden_dim=32,
        n_input_hidden_dim=32,
        n_expert=2,
        n_head=4,
    )
    samples = datasets.synth_elasticity(4, base_points=40)  # ragged -> real masks
    batch = next(iter(Loader(samples, 4)))

    model_xla = GNOT(mc)
    params = model_xla.init(
        jax.random.key(0),
        batch.coords,
        batch.theta,
        batch.funcs,
        node_mask=batch.node_mask,
        func_mask=batch.func_mask,
    )["params"]
    model_pallas = GNOT(dataclasses.replace(mc, attention_impl="pallas"))

    args = (batch.coords, batch.theta, batch.funcs)
    kw = dict(node_mask=batch.node_mask, func_mask=batch.func_mask)
    out_xla = model_xla.apply({"params": params}, *args, **kw)
    out_pallas = model_pallas.apply({"params": params}, *args, **kw)
    np.testing.assert_allclose(
        np.asarray(out_pallas), np.asarray(out_xla), rtol=1e-4, atol=1e-5
    )


def test_fused_nla_sp_matches_single_device():
    """Sequence-parallel fused attention (reduce -> psum -> apply) over
    an 8-way seq mesh == the single-device op, forward and backward."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(3), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_sp, qs_sp = fused_nla_sp(q, k, v, mask, h, mesh)
    out_1, qs_1 = fused_nla(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_sp), np.asarray(qs_1), rtol=1e-5, atol=1e-6)

    def loss_sp(q, k, v):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_1(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_1 = jax.grad(loss_1, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_sp, g_1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_fused_nla_sp_ring_matches_psum():
    """The ring all-reduce schedule (S-1 ppermute hops) must be
    numerically interchangeable with the one-shot psum, forward and
    backward (the backward replays the ring in reverse)."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(7), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_r, qs_r = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="ring")
    out_p, qs_p = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="psum")
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_p), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_r), np.asarray(qs_p), rtol=1e-5, atol=1e-6)

    def loss(q, k, v, collective):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective=collective)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_r = jax.grad(lambda *a: loss(*a, "ring"), argnums=(0, 1, 2))(q, k, v)
    g_p = jax.grad(lambda *a: loss(*a, "psum"), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_r, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_ring_allreduce_matches_psum_generic():
    """ops/collectives.ring_allreduce == lax.psum for a generic payload."""
    from jax.sharding import Mesh, PartitionSpec as P

    from gnot_tpu.ops.collectives import ring_allreduce

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("s",))
    x = jax.random.normal(jax.random.key(0), (8, 4, 4))

    ring = jax.shard_map(
        lambda t: ring_allreduce(t, "s", 8),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    ps = jax.shard_map(
        lambda t: jax.lax.psum(t, "s"),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ps), rtol=1e-6, atol=1e-6)


def test_pallas_rejects_parity():
    mc = ModelConfig(
        input_dim=2,
        theta_dim=1,
        input_func_dim=3,
        out_dim=1,
        n_input_functions=1,
        n_attn_layers=1,
        n_attn_hidden_dim=16,
        n_mlp_num_layers=1,
        n_mlp_hidden_dim=16,
        n_input_hidden_dim=16,
        n_expert=2,
        n_head=2,
        attention_mode="parity",
        attention_impl="pallas",
    )
    samples = datasets.synth_ns2d(2, n_points=16)
    batch = next(iter(Loader(samples, 2, bucket=False)))
    model = GNOT(mc)
    with pytest.raises(ValueError, match="parity"):
        model.init(
            jax.random.key(0), batch.coords, batch.theta, batch.funcs
        )


SMALL_PALLAS = ModelConfig(
    input_dim=2,
    theta_dim=1,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=1,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=3,
    n_head=4,
    attention_impl="pallas",
)


def test_sharded_train_step_with_pallas_matches_single_device():
    """Full sharded train step on a DP x SP x TP mesh with the pallas
    attention dispatched through shard_map == single-device xla step."""
    from gnot_tpu.config import MeshConfig, OptimConfig
    from gnot_tpu.parallel import mesh as mesh_lib
    from gnot_tpu.train.trainer import init_state, make_train_step

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    optim = OptimConfig()
    samples = datasets.synth_ns2d(8, n_points=64)
    batch = next(iter(Loader(samples, 8)))

    ref_model = GNOT(dataclasses.replace(SMALL_PALLAS, attention_impl="xla"))
    state = init_state(ref_model, optim, batch, seed=0)
    single = make_train_step(ref_model, optim, "rel_l2")
    state1, loss1 = single(
        jax.tree.map(jnp.copy, state), batch, jnp.asarray(1e-3, jnp.float32)
    )

    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2, model=2))
    model = GNOT(SMALL_PALLAS, mesh=mesh)
    sharded_state = mesh_lib.shard_state(mesh, state)
    step = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, sharded_state)
    sharded_batch = mesh_lib.shard_batch(mesh, batch)
    state2, loss2 = step(sharded_state, sharded_batch, jnp.asarray(1e-3, jnp.float32))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


def test_sharded_step_pallas_requires_mesh_on_model():
    from gnot_tpu.config import MeshConfig, OptimConfig
    from gnot_tpu.parallel import mesh as mesh_lib
    from gnot_tpu.train.trainer import init_state

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    samples = datasets.synth_ns2d(2, n_points=16)
    batch = next(iter(Loader(samples, 2)))
    model = GNOT(SMALL_PALLAS)  # no mesh attached
    state = init_state(model, OptimConfig(), batch, seed=0)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=1, model=1), jax.devices()[:2])
    with pytest.raises(ValueError, match="mesh"):
        mesh_lib.make_sharded_train_step(model, OptimConfig(), "rel_l2", mesh, state)


def test_pallas_empty_input_function_is_finite():
    """Pallas twin of test_model.py::test_empty_input_function_is_finite:
    an all-masked function slab reaches nla_apply with ksum == 0; the
    kernel's denominator guard must yield 0, not nan."""
    import dataclasses as _dc

    mc = SMALL_PALLAS
    samples = datasets.synth_ns2d(2, n_points=16)
    batch = next(iter(Loader(samples, 2, bucket=False)))
    func_mask = np.array(batch.func_mask)
    func_mask[0, 0, :] = 0.0  # sample 0's only input function is empty

    model = GNOT(mc)
    params = model.init(
        jax.random.key(0), batch.coords, batch.theta, batch.funcs,
        node_mask=batch.node_mask, func_mask=func_mask,
    )["params"]

    def loss(p):
        y = model.apply(
            {"params": p}, batch.coords, batch.theta, batch.funcs,
            node_mask=batch.node_mask, func_mask=func_mask,
        )
        return jnp.mean(y * y)

    val, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    assert all(
        np.isfinite(np.asarray(x)).all() for x in jax.tree_util.tree_leaves(g)
    )
