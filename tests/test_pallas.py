"""Fused pallas attention kernel vs the einsum formulation.

Runs in pallas interpreter mode on the CPU test platform (the kernel
auto-selects interpret off-TPU); the same code path compiles on TPU.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.ops.pallas_attention import _reference_impl, fused_nla


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "n_funcs,masked,l,lk",
    [
        (1, False, 24, 16),
        (2, True, 24, 16),
        (3, True, 40, 24),
        (1, True, 300, 280),  # > TILE after padding checks the seq tiling
    ],
)
def test_fused_matches_einsum_cross(n_funcs, masked, l, lk):
    b, h, e = 2, 4, 32
    keys = jax.random.split(jax.random.key(0), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], n_funcs, b, lk, e)
    v = _rand(keys[2], n_funcs, b, lk, e)
    if masked:
        mask = (
            jax.random.uniform(keys[3], (n_funcs, b, lk)) > 0.3
        ).astype(jnp.float32)
        mask = mask.at[:, :, 0].set(1.0)  # at least one real row
    else:
        mask = jnp.ones((n_funcs, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_group_softmax_outlier_head_no_nan():
    """One head's logits spiking ~200 above another's must not underflow
    the quiet head's group to 0/0 (the max is per group, not per row)."""
    b, h, l, lk, e = 1, 4, 16, 16, 32
    keys = jax.random.split(jax.random.key(7), 3)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    # Spike head 0's lanes (first e//h lanes) of both q and k.
    q = q.at[..., : e // h].add(200.0)
    k = k.at[..., : e // h].add(200.0)
    mask = jnp.ones((1, b, lk), jnp.float32)

    out, qs = fused_nla(q, k, v, mask, h)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(qs)).all()
    out_ref, qs_ref = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6)


def test_fused_grads_match_einsum():
    b, h, l, lk, e = 2, 2, 12, 10, 16
    keys = jax.random.split(jax.random.key(1), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    def loss_fused(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_ref(q, k, v):
        out, qs = _reference_impl(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_reference_impl_matches_xla_ops():
    """The merged-layout einsum oracle == the split-head XLA ops path."""
    from gnot_tpu.ops.attention import (
        feature_softmax,
        merge_heads,
        normalized_linear_attention,
        split_heads,
    )

    b, h, l, lk, e = 2, 4, 12, 10, 32
    keys = jax.random.split(jax.random.key(2), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], 1, b, lk, e)
    v = _rand(keys[2], 1, b, lk, e)
    mask = (jax.random.uniform(keys[3], (1, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_m, qs_m = _reference_impl(q, k, v, mask, h)
    qh = feature_softmax(split_heads(q, h))
    kh = feature_softmax(split_heads(k[0], h))
    vh = split_heads(v[0], h)
    out_h = normalized_linear_attention(qh, kh, vh, kv_mask=mask[0])
    np.testing.assert_allclose(
        np.asarray(out_m[0]), np.asarray(merge_heads(out_h)), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(qs_m), np.asarray(merge_heads(qh)), rtol=1e-5, atol=1e-6
    )


def test_model_attention_impl_pallas_retired():
    """The model-level pallas attention dispatch was retired in round 4
    (lost the honest A/B at every scale); the config rejects it with a
    pointer to the dead-end analysis. The kernels in
    ops/pallas_attention.py remain tested above."""
    with pytest.raises(ValueError, match="retired"):
        ModelConfig(
            input_dim=2,
            theta_dim=1,
            input_func_dim=3,
            out_dim=1,
            n_input_functions=1,
            n_attn_layers=1,
            n_attn_hidden_dim=16,
            n_mlp_num_layers=1,
            n_mlp_hidden_dim=16,
            n_input_hidden_dim=16,
            n_expert=2,
            n_head=2,
            attention_impl="pallas",
        )


def test_fused_nla_sp_matches_single_device():
    """Sequence-parallel fused attention (reduce -> psum -> apply) over
    an 8-way seq mesh == the single-device op, forward and backward."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(3), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_sp, qs_sp = fused_nla_sp(q, k, v, mask, h, mesh)
    out_1, qs_1 = fused_nla(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_sp), np.asarray(qs_1), rtol=1e-5, atol=1e-6)

    def loss_sp(q, k, v):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_1(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_1 = jax.grad(loss_1, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_sp, g_1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_fused_nla_sp_ring_matches_psum():
    """The ring all-reduce schedule (S-1 ppermute hops) must be
    numerically interchangeable with the one-shot psum, forward and
    backward (the backward replays the ring in reverse)."""
    from jax.sharding import Mesh

    from gnot_tpu.ops.pallas_attention import fused_nla_sp

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    b, h, l, lk, e, f = 2, 4, 64, 32, 32, 2
    keys = jax.random.split(jax.random.key(7), 4)
    q = _rand(keys[0], b, l, e)
    k = _rand(keys[1], f, b, lk, e)
    v = _rand(keys[2], f, b, lk, e)
    mask = (jax.random.uniform(keys[3], (f, b, lk)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, :, 0].set(1.0)

    out_r, qs_r = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="ring")
    out_p, qs_p = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective="psum")
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_p), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(qs_r), np.asarray(qs_p), rtol=1e-5, atol=1e-6)

    def loss(q, k, v, collective):
        out, qs = fused_nla_sp(q, k, v, mask, h, mesh, sp_collective=collective)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_r = jax.grad(lambda *a: loss(*a, "ring"), argnums=(0, 1, 2))(q, k, v)
    g_p = jax.grad(lambda *a: loss(*a, "psum"), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_r, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


def test_ring_allreduce_matches_psum_generic():
    """ops/collectives.ring_allreduce == lax.psum for a generic payload."""
    from jax.sharding import Mesh, PartitionSpec as P

    from gnot_tpu.ops.collectives import ring_allreduce

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("s",))
    x = jax.random.normal(jax.random.key(0), (8, 4, 4))

    from gnot_tpu.ops.collectives import shard_map

    ring = shard_map(
        lambda t: ring_allreduce(t, "s", 8),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    ps = shard_map(
        lambda t: jax.lax.psum(t, "s"),
        mesh=mesh, in_specs=P("s"), out_specs=P("s"),
    )(x)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ps), rtol=1e-6, atol=1e-6)


# --- Segment-packed kernels ("pack, don't pad" in the kernel itself) ----


def _packed_case(seed=0, f=2, b=2, e=32, h=4, chunk=8):
    """One adversarial packed layout: row 0 carries segments 0 (2
    chunks) and 1 (3 chunks, ragged tail), row 1 carries segments 2 and
    3; trailing pad chunks carry id n_seg. seg slot 3's span is left
    ragged too."""
    rng = np.random.default_rng(seed)
    n = 6  # chunks per row
    l = n * chunk
    n_seg = 5  # one slot (4) intentionally empty
    q = rng.normal(size=(b, l, e)).astype(np.float32)
    k = rng.normal(size=(f, b, l, e)).astype(np.float32)
    v = rng.normal(size=(f, b, l, e)).astype(np.float32)
    seg = np.array(
        [[0, 0, 1, 1, 1, n_seg], [2, 3, 3, n_seg, n_seg, n_seg]], np.int32
    )
    mask = np.ones((f, b, l), np.float32)
    mask[:, 0, 5 * chunk :] = 0.0  # row 0 pad chunk
    mask[:, 0, 5 * chunk - 3 : 5 * chunk] = 0.0  # seg 1 ragged tail
    mask[:, 1, 3 * chunk :] = 0.0  # row 1 pad chunks
    mask[:, 1, 3 * chunk - 5 : 3 * chunk] = 0.0  # seg 3 ragged tail
    spans = {  # seg id -> (row, token slice, real length)
        0: (0, slice(0, 2 * chunk), 2 * chunk),
        1: (0, slice(2 * chunk, 5 * chunk), 3 * chunk - 3),
        2: (1, slice(0, chunk), chunk),
        3: (1, slice(chunk, 3 * chunk), 2 * chunk - 5),
    }
    return q, k, v, mask, seg, n_seg, spans


def test_packed_matches_reference_seg():
    """Pallas (interpret on CPU; same code path compiles on TPU) vs the
    einsum oracle for the segment-packed stages, forward."""
    from gnot_tpu.ops.pallas_attention import (
        _reference_seg_impl,
        fused_nla_packed,
    )

    q, k, v, mask, seg, n_seg, _ = _packed_case()
    h = 4
    out, qs = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
    out_ref, qs_ref = _reference_seg_impl(q, k, v, mask, seg, seg, n_seg, h)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6
    )


def test_packed_segment_matches_unpacked_solo():
    """Every packed segment's output == the UNPACKED kernel run on that
    segment alone (<= 1e-5 — the ISSUE 6 packed-vs-unpacked numerics
    bar, here at kernel level): packing is a layout change, never a
    semantics change."""
    from gnot_tpu.ops.pallas_attention import fused_nla, fused_nla_packed

    q, k, v, mask, seg, n_seg, spans = _packed_case()
    h = 4
    out, _ = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
    for sid, (row, sl, _n_real) in spans.items():
        out_solo, _ = fused_nla(
            q[row : row + 1, sl],
            k[:, row : row + 1, sl],
            v[:, row : row + 1, sl],
            mask[:, row : row + 1, sl],
            h,
        )
        np.testing.assert_allclose(
            np.asarray(out[:, row, sl]),
            np.asarray(out_solo[:, 0]),
            rtol=1e-5,
            atol=1e-6,
            err_msg=f"segment {sid} diverged from its solo dispatch",
        )


def test_packed_neighbor_independence_bitwise():
    """Segment-boundary adversarial check: a segment packed next to an
    IDENTICAL-PREFIX neighbor must produce BITWISE the same output as
    when packed next to a completely different neighbor. Any cross-
    boundary leak (a neighbor token entering the segment's Gram) shifts
    the fp sums and breaks exact equality."""
    from gnot_tpu.ops.pallas_attention import fused_nla_packed

    rng = np.random.default_rng(3)
    f, b, e, h, chunk = 2, 1, 32, 4, 8
    n, n_seg = 4, 2
    l = n * chunk
    seg = np.array([[0, 0, 1, 1]], np.int32)
    mask = np.ones((f, b, l), np.float32)
    q = rng.normal(size=(b, l, e)).astype(np.float32)
    k = rng.normal(size=(f, b, l, e)).astype(np.float32)
    v = rng.normal(size=(f, b, l, e)).astype(np.float32)
    # Neighbor A: segment 1 is a verbatim copy of segment 0 (identical
    # prefix — the adversarial case: a leak would be invisible to a
    # values-differ check because the leaked rows match).
    qa, ka, va = q.copy(), k.copy(), v.copy()
    half = 2 * chunk
    qa[:, half:], ka[:, :, half:], va[:, :, half:] = (
        q[:, :half], k[:, :, :half], v[:, :, :half],
    )
    # Neighbor B: segment 1 is fresh noise.
    qb, kb, vb = qa.copy(), ka.copy(), va.copy()
    qb[:, half:] = rng.normal(size=(b, half, e)).astype(np.float32)
    kb[:, :, half:] = rng.normal(size=(f, b, half, e)).astype(np.float32)
    vb[:, :, half:] = rng.normal(size=(f, b, half, e)).astype(np.float32)

    out_a, qs_a = fused_nla_packed(qa, ka, va, mask, seg, seg, n_seg, h)
    out_b, qs_b = fused_nla_packed(qb, kb, vb, mask, seg, seg, n_seg, h)
    # Segment 0's tokens are identical in both packings; its outputs
    # must be BITWISE equal — and segment 1's (identical to segment 0
    # in packing A) must bitwise-match segment 0 there.
    assert np.array_equal(
        np.asarray(out_a[:, :, :half]), np.asarray(out_b[:, :, :half])
    ), "segment 0's output depends on its row neighbor — boundary leak"
    assert np.array_equal(
        np.asarray(qs_a[:, :half]), np.asarray(qs_b[:, :half])
    )
    assert np.array_equal(
        np.asarray(out_a[:, :, half:]), np.asarray(out_a[:, :, :half])
    ), "identical segments packed in one row must produce identical outputs"


def test_packed_grads_match_reference_seg():
    """Backward parity: the packed custom-VJP grads == grads of the
    einsum oracle, for every input."""
    from gnot_tpu.ops.pallas_attention import (
        _reference_seg_impl,
        fused_nla_packed,
    )

    q, k, v, mask, seg, n_seg, _ = _packed_case(seed=11)
    h = 4

    def loss_packed(q, k, v):
        out, qs = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    def loss_ref(q, k, v):
        out, qs = _reference_seg_impl(q, k, v, mask, seg, seg, n_seg, h)
        return jnp.sum(out**2) + jnp.sum(qs * 0.5)

    g_p = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_p, g_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6
        )


def test_packed_grad_segment_isolation():
    """A loss over ONE segment's output rows must have exactly-zero
    gradient w.r.t. every OTHER segment's tokens (fwd isolation implies
    bwd isolation; asserted, not assumed)."""
    from gnot_tpu.ops.pallas_attention import fused_nla_packed

    q, k, v, mask, seg, n_seg, spans = _packed_case(seed=5)
    h = 4
    row0, sl0, _ = spans[0]

    def loss_seg0(q, k, v):
        out, _ = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
        return jnp.sum(out[:, row0, sl0] ** 2)

    dq, dk, dv = jax.grad(loss_seg0, argnums=(0, 1, 2))(q, k, v)
    for sid, (row, sl, _n) in spans.items():
        if sid == 0:
            assert np.abs(np.asarray(dq[row, sl])).max() > 0
            continue
        assert np.abs(np.asarray(dq[row, sl])).max() == 0.0, (
            f"segment {sid} query grads leak into segment 0's loss"
        )
        assert np.abs(np.asarray(dk[:, row, sl])).max() == 0.0
        assert np.abs(np.asarray(dv[:, row, sl])).max() == 0.0


def test_packed_pad_chunks_and_empty_slots_zero():
    """Pad chunks (seg id == n_seg) emit exactly 0; the intentionally
    empty segment slot contributes zero Grams; everything stays finite
    forward and backward."""
    from gnot_tpu.ops.pallas_attention import fused_nla_packed, nla_reduce_seg

    q, k, v, mask, seg, n_seg, _ = _packed_case(seed=7)
    h = 4
    out, qs = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(qs)).all()
    # Row 0's 6th chunk and row 1's 4th-6th chunks are padding.
    assert np.abs(np.asarray(out[:, 0, 5 * 8 :])).max() == 0.0
    assert np.abs(np.asarray(out[:, 1, 3 * 8 :])).max() == 0.0
    kv, ksum = nla_reduce_seg(k, v, mask, seg, n_seg, h)
    assert np.abs(np.asarray(kv[:, 4])).max() == 0.0  # empty slot 4
    assert np.abs(np.asarray(ksum[:, 4])).max() == 0.0

    def loss(q, k, v):
        o, s = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
        return jnp.mean(o**2) + jnp.mean(s**2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()


def test_packed_cross_packing_matches_reference():
    """Cross-attention shape: the KEY side uses a DIFFERENT packing
    than the query side (slot-indexed input functions: one row per
    slot, one chunk per row), sharing global segment ids."""
    from gnot_tpu.ops.pallas_attention import (
        _reference_seg_impl,
        fused_nla_packed,
    )

    rng = np.random.default_rng(9)
    f, e, h = 2, 32, 4
    chunk = 8
    n_seg = 3
    # Query side: 1 packed row of 4 chunks: segments [0, 0, 1, pad].
    q_seg = np.array([[0, 0, 1, n_seg], [2, n_seg, n_seg, n_seg]], np.int32)
    bq, lq = q_seg.shape[0], q_seg.shape[1] * chunk
    # Key side: one row per slot, one 16-token chunk each.
    kv_seg = np.array([[0], [1], [2]], np.int32)
    bk, lk = 3, 16
    q = rng.normal(size=(bq, lq, e)).astype(np.float32)
    k = rng.normal(size=(f, bk, lk, e)).astype(np.float32)
    v = rng.normal(size=(f, bk, lk, e)).astype(np.float32)
    mask = np.ones((f, bk, lk), np.float32)
    mask[:, 1, 10:] = 0.0  # slot 1's function is ragged

    out, qs = fused_nla_packed(q, k, v, mask, q_seg, kv_seg, n_seg, h)
    out_ref, qs_ref = _reference_seg_impl(
        q, k, v, mask, q_seg, kv_seg, n_seg, h
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_ref), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(qs), np.asarray(qs_ref), rtol=1e-5, atol=1e-6
    )


def test_packed_alignment_errors():
    """Chunk-misaligned packings are rejected with actionable errors,
    not silently mis-tiled."""
    from gnot_tpu.ops.pallas_attention import fused_nla_packed

    rng = np.random.default_rng(0)
    e, h = 32, 4
    q = rng.normal(size=(1, 20, e)).astype(np.float32)  # 20 % 4 tiles -> 5
    k = rng.normal(size=(1, 1, 20, e)).astype(np.float32)
    v = rng.normal(size=(1, 1, 20, e)).astype(np.float32)
    mask = np.ones((1, 1, 20), np.float32)
    seg = np.zeros((1, 4), np.int32)
    with pytest.raises(ValueError, match="multiple of 8"):
        fused_nla_packed(q, k, v, mask, seg, seg, 1, h)
    seg3 = np.zeros((1, 3), np.int32)
    with pytest.raises(ValueError, match="not divisible"):
        fused_nla_packed(q, k, v, mask, seg3, seg3, 1, h)


def test_pallas_empty_input_function_is_finite():
    """Op-level twin of test_model.py::test_empty_input_function_is_finite:
    an all-masked function slab reaches nla_apply with ksum == 0; the
    kernel's denominator guard must yield 0, not nan — forward and
    backward."""
    rng = np.random.default_rng(5)
    b, l, e, h, f = 2, 16, 32, 4, 2
    q = rng.normal(size=(b, l, e)).astype(np.float32)
    k = rng.normal(size=(f, b, l, e)).astype(np.float32)
    v = rng.normal(size=(f, b, l, e)).astype(np.float32)
    mask = np.ones((f, b, l), np.float32)
    mask[1, 0, :] = 0.0  # sample 0's second input function is empty

    def loss(q, k, v):
        out, qs = fused_nla(q, k, v, mask, h)
        return jnp.mean(out**2) + jnp.mean(qs**2)

    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(float(val))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
