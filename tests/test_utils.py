"""Aux subsystems: checkify guards, profiler hooks, eval-only path."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.utils import profiling
from gnot_tpu.utils.debug import checked


def test_checked_passes_clean_fn():
    fn = checked(lambda x: jnp.sqrt(x) + 1.0)
    np.testing.assert_allclose(np.asarray(fn(jnp.asarray(4.0))), 3.0)


def test_checked_catches_nan():
    from jax.experimental import checkify

    fn = checked(lambda x: jnp.log(x))  # log(-1) -> nan
    with pytest.raises(checkify.JaxRuntimeError):
        fn(jnp.asarray(-1.0))


def test_trace_epoch_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with profiling.trace_epoch(d, epoch=1):
        with profiling.annotate("span"):
            jnp.ones((8, 8)).sum().block_until_ready()
    assert os.path.isdir(d) and os.listdir(d)


def test_trace_epoch_noop_for_other_epochs(tmp_path):
    d = str(tmp_path / "prof2")
    with profiling.trace_epoch(d, epoch=0):
        pass
    assert not os.path.exists(d)
    with profiling.trace_epoch("", epoch=1):
        pass


def test_trace_epoch_custom_trace_at(tmp_path):
    """trace_at selects which epoch fires (Trainer picks the second
    executed epoch to keep compile noise out of the trace)."""
    d = str(tmp_path / "prof3")
    with profiling.trace_epoch(d, epoch=1, trace_at=3):
        pass
    assert not os.path.exists(d)
    with profiling.trace_epoch(d, epoch=3, trace_at=3):
        jnp.zeros((4,)).sum().block_until_ready()
    assert os.path.isdir(d) and os.listdir(d)


def test_annotate_outside_trace_is_harmless():
    """annotate() is a reentrant no-op span when no trace is active —
    the trainer wraps every epoch in it unconditionally."""
    with profiling.annotate("outer"):
        with profiling.annotate("inner"):
            x = float(jnp.ones(()).sum())
    assert x == 1.0


@pytest.fixture
def scratch_cache(tmp_path):
    """Point the persistent compile cache at a fresh dir for one test,
    restoring the session cache afterwards (the suite's warm /tmp
    cache must not absorb or lose entries through these tests)."""
    import jax

    from gnot_tpu.utils.cache import enable_compile_cache

    before = getattr(jax.config, "jax_compilation_cache_dir", None)
    path = str(tmp_path / "cache")
    enable_compile_cache(path)
    try:
        yield path
    finally:
        if before:
            enable_compile_cache(before)


def test_warm_cache_miss_then_hit(scratch_cache):
    """warm_cache: a fresh dir misses (and persists) every program; a
    second pass over FRESH jit objects of the same programs hits the
    on-disk entries — the deploy-time AOT prewarm contract."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.utils.cache import warm_cache

    def thunks():
        # Fresh jit objects each call: the second pass must hit the
        # PERSISTENT cache, not the in-process dispatch cache.
        f = jax.jit(lambda x: jnp.sin(x) @ x.T + 2.0)
        g = jax.jit(lambda x: jnp.cos(x).sum(0) * 3.0)
        x = jnp.ones((32, 32))
        return [
            ("f", lambda: f.lower(x).compile()),
            ("g", lambda: g.lower(x).compile()),
        ]

    cold = warm_cache(thunks())
    assert [p["key"] for p in cold["programs"]] == ["f", "g"]
    assert all(p["seconds"] > 0 for p in cold["programs"])
    assert cold["misses"] == 2 and cold["hits"] == 0
    # min_compile_time was dropped to 0 inside warm_cache, so even
    # these trivial programs persisted...
    assert cold["entries_after"] >= 2
    # ...and the old threshold is restored afterwards.
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.5
    warm = warm_cache(thunks())
    assert warm["hits"] == 2 and warm["misses"] == 0


def test_warm_cache_corrupt_entries_degrade_to_recompile(scratch_cache):
    """Corrupt on-disk cache entries are a MISS (jax warns and
    recompiles), never a crash — a mangled cache dir costs cold-start
    time, not serving correctness."""
    import os as _os
    import warnings

    import jax
    import jax.numpy as jnp

    from gnot_tpu.utils.cache import warm_cache

    def thunks():
        f = jax.jit(lambda x: jnp.tanh(x) @ x + 1.0)
        x = jnp.ones((16, 16))
        return [("f", lambda: f.lower(x).compile())]

    assert warm_cache(thunks())["misses"] == 1
    for de in _os.scandir(scratch_cache):
        with open(de.path, "wb") as fh:
            fh.write(b"not an executable")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # jax's corrupt-entry warning
        again = warm_cache(thunks())
    assert again["misses"] == 1 and again["hits"] == 0


def test_compile_cache_probe_missing_dir():
    """Probe on an unset/absent cache dir: entry counts degrade to
    None, the hit/miss counters still work."""
    import jax

    from gnot_tpu.utils.cache import compile_cache_probe

    before = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        jax.config.update(
            "jax_compilation_cache_dir", "/nonexistent/gnot-cache-dir"
        )
        with compile_cache_probe() as stats:
            pass
        assert stats["entries_before"] is None
        assert stats["entries_after"] is None
        assert stats["requests"] == 0 and stats["misses"] == 0
    finally:
        if before:
            jax.config.update("jax_compilation_cache_dir", before)


def test_cache_dir_manifest(scratch_cache, tmp_path):
    """cache_dir_manifest: occupancy of a real dir; Nones for a
    missing one (a corrupt/absent cache is a cold start, not a crash)."""
    from gnot_tpu.utils.cache import cache_dir_manifest

    (tmp_path / "cache").mkdir(exist_ok=True)
    (tmp_path / "cache" / "entry").write_bytes(b"x" * 64)
    m = cache_dir_manifest(str(tmp_path / "cache"))
    assert m["entries"] == 1 and m["bytes"] == 64
    missing = cache_dir_manifest(str(tmp_path / "nope"))
    assert missing["entries"] is None and missing["bytes"] is None


def test_eval_only_roundtrip(tmp_path):
    """Train 2 epochs with checkpointing, then eval-only from the best
    checkpoint reproduces the best metric."""
    from gnot_tpu import main as cli

    args = [
        "--synthetic", "darcy2d",
        "--n_train", "8", "--n_test", "4",
        "--epochs", "2",
        "--n_attn_layers", "1", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--checkpoint_dir", str(tmp_path / "ckpt"),
    ]
    best = cli.main(args)
    res = cli.main(args + ["--eval_only"])
    np.testing.assert_allclose(res, best, rtol=1e-6)


def test_metrics_sink_numpy_scalars(tmp_path):
    """np.floating values (finite and non-finite) must serialize to
    valid JSON — plain-float isinstance checks miss np.float32."""
    import json

    from gnot_tpu.utils.metrics import MetricsSink

    path = str(tmp_path / "m.jsonl")
    sink = MetricsSink(path)
    sink.log(a=np.float32(1.5), b=np.float32("nan"), c=float("inf"), d=3)
    sink.close()
    with open(path) as f:
        rec = json.loads(f.readline())
    assert rec["a"] == 1.5 and rec["b"] is None and rec["c"] is None and rec["d"] == 3


def test_predict_rejects_oversize_sample():
    """predict() with a mesh longer than the trainer's fixed pad length
    raises a descriptive ValueError, not a numpy broadcast error."""
    from gnot_tpu.config import ModelConfig, make_config
    from gnot_tpu.data import datasets
    from gnot_tpu.train.trainer import Trainer

    train = datasets.synth_ns2d(4, n_points=16, seed=0)
    cfg = make_config(**{
        "data.n_train": 4, "data.n_test": 0, "train.epochs": 1,
        "data.pad_nodes": 16, "data.pad_funcs": 16,
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    trainer = Trainer(cfg, mc, train, [])
    big = datasets.synth_ns2d(1, n_points=64, seed=3)
    with pytest.raises(ValueError, match="fixed pad length"):
        trainer.predict(big)


def test_debug_checks_nan_raises():
    """--debug_checks: a NaN entering the pipeline raises a
    FloatingPointError (with step context) instead of training silently
    on garbage."""
    from gnot_tpu.config import ModelConfig, make_config
    from gnot_tpu.data import datasets
    from gnot_tpu.train.trainer import Trainer

    train = datasets.synth_ns2d(8, n_points=16, seed=0)
    train[2].coords[0, 0] = np.nan  # poison one sample
    test = datasets.synth_ns2d(4, n_points=16, seed=1)
    cfg = make_config(**{
        "data.n_train": 8, "data.n_test": 4, "train.epochs": 1,
        "train.debug_checks": True, "data.shuffle_train": False,
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    trainer = Trainer(cfg, mc, train, test)
    with pytest.raises(FloatingPointError, match="epoch 0"):
        trainer.fit()
