"""Pipeline-parallelism tests on the virtual 8-device CPU mesh.

The shard_map microbatch pipeline (parallel/pipeline.py) must reproduce
the single-device model exactly: same forward, same losses, same
post-update params through the full train step (the backward replays
the ppermute ring in reverse)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import Config, MeshConfig, ModelConfig, OptimConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.parallel import mesh as mesh_lib, pipeline
from gnot_tpu.train.trainer import init_state, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)

SMALL = ModelConfig(
    input_dim=2,
    theta_dim=1,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=1,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=3,
    n_head=4,
)


def make_batch(b=8, n_points=64):
    samples = datasets.synth_ns2d(b, n_points=n_points)
    return next(iter(Loader(samples, b)))


def restack_into(state_pipe, host_params, mesh, n_layers):
    """Overwrite a pipeline state's params with (stacked) host_params so
    single-device and pipelined runs start from identical weights."""
    stacked = pipeline.stack_params(
        jax.tree.map(jnp.asarray, host_params), n_layers
    )
    sh = pipeline.state_shardings(mesh, state_pipe).params
    return dataclasses.replace(
        state_pipe,
        params=jax.tree.map(lambda l, s: jax.device_put(l, s), stacked, sh),
    )


def assert_params_match(single_params, pipe_params, n_layers, **tol):
    un = pipeline.unstack_params(jax.device_get(pipe_params), n_layers)
    key = lambda kv: str(kv[0])
    a_leaves = sorted(
        jax.tree_util.tree_leaves_with_path(jax.device_get(single_params)), key=key
    )
    b_leaves = sorted(jax.tree_util.tree_leaves_with_path(un), key=key)
    assert len(a_leaves) == len(b_leaves)
    for (pa, a), (pb, b) in zip(a_leaves, b_leaves):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)


@pytest.mark.parametrize(
    "mesh_cfg,n_layers,micro",
    [
        (MeshConfig(data=2, pipe=2), 2, 0),  # 1 block/stage, M = S
        (MeshConfig(data=1, pipe=2), 4, 4),  # 2 blocks/stage, M > S
        (MeshConfig(data=4, pipe=2), 2, 2),  # composed with DP
        # composed with DP AND TP: pipe is the manual shard_map axis,
        # model stays a GSPMD auto axis inside the stages
        (MeshConfig(data=2, model=2, pipe=2), 2, 0),
        (MeshConfig(data=1, model=2, pipe=2), 4, 4),
    ],
)
def test_pipelined_step_matches_single_device(mesh_cfg, n_layers, micro):
    from helpers import skip_if_pipe_tp_unsupported

    skip_if_pipe_tp_unsupported(mesh_cfg)
    mc = dataclasses.replace(SMALL, n_attn_layers=n_layers)
    model = GNOT(mc)
    optim = OptimConfig()
    batch = make_batch()
    state = init_state(model, optim, batch, seed=0)
    # Copied BY VALUE (np.array), not the zero-copy device_get view: the
    # donating single-device step below would otherwise write its
    # updated params straight into this "initial" snapshot, so the
    # pipelined arm would start one optimizer step ahead (the round-6/7
    # use-after-donate playbook; docs/parallelism.md parity-debt ledger).
    host_params = jax.tree.map(np.array, jax.device_get(state.params))
    lr = jnp.asarray(1e-3, jnp.float32)

    single = make_train_step(model, optim, "rel_l2")
    s1, loss1 = single(state, batch, lr)

    n_dev = mesh_cfg.data * mesh_cfg.model * mesh_cfg.pipe
    mesh = mesh_lib.make_mesh(mesh_cfg, jax.devices()[:n_dev])
    sp = pipeline.init_pipeline_state(model, optim, batch, 0, mesh)
    sp = restack_into(sp, host_params, mesh, n_layers)
    step = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, sp, micro)
    sp, loss2 = step(sp, mesh_lib.shard_batch(mesh, batch), lr)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    assert_params_match(s1.params, sp.params, n_layers, rtol=2e-4, atol=2e-5)


def test_pipelined_forward_masked_ragged():
    """Ragged elasticity batch (real masks): the pipelined forward must
    equal model.apply exactly — masks travel with their microbatch."""
    samples = datasets.synth_elasticity(4, base_points=48)
    batch = next(iter(Loader(samples, 4)))
    mc = dataclasses.replace(
        SMALL, n_attn_layers=2, **datasets.infer_model_dims(samples)
    )
    model = GNOT(mc)
    state = init_state(model, OptimConfig(), batch, seed=0)
    out_single = np.asarray(
        model.apply(
            {"params": state.params},
            batch.coords,
            batch.theta,
            batch.funcs,
            node_mask=batch.node_mask,
            func_mask=batch.func_mask,
        )
    )

    mesh = mesh_lib.make_mesh(MeshConfig(data=2, pipe=2), jax.devices()[:4])
    stacked = pipeline.stack_params(jax.device_get(state.params), 2)

    @jax.jit
    def fwd(params, b):
        return pipeline.pipelined_forward(mc, mesh, 2, params, b)

    out_pipe = np.asarray(
        jax.device_get(fwd(stacked, mesh_lib.shard_batch(mesh, batch)))
    )
    np.testing.assert_allclose(out_pipe, out_single, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(data=2, pipe=2), MeshConfig(data=2, model=2, pipe=2)],
    ids=["dp-pipe", "dp-tp-pipe"],
)
def test_pipeline_eval_step_matches(mesh_cfg):
    from helpers import skip_if_pipe_tp_unsupported

    skip_if_pipe_tp_unsupported(mesh_cfg)
    model = GNOT(SMALL)
    optim = OptimConfig()
    batch = make_batch()
    state = init_state(model, optim, batch, seed=0)
    host_params = jax.device_get(state.params)
    from gnot_tpu.train.trainer import batch_loss

    loss1 = float(batch_loss(model, state.params, batch, "rel_l2"))

    n_dev = mesh_cfg.data * mesh_cfg.model * mesh_cfg.pipe
    mesh = mesh_lib.make_mesh(mesh_cfg, jax.devices()[:n_dev])
    sp = pipeline.init_pipeline_state(model, optim, batch, 0, mesh)
    sp = restack_into(sp, host_params, mesh, SMALL.n_attn_layers)
    ev = mesh_lib.make_sharded_eval_step(model, "rel_l2", mesh, sp)
    loss2 = float(ev(sp.params, mesh_lib.shard_batch(mesh, batch)))
    np.testing.assert_allclose(loss1, loss2, rtol=1e-5)


def test_stack_unstack_roundtrip():
    model = GNOT(SMALL)
    batch = make_batch()
    params = init_state(model, OptimConfig(), batch, seed=0).params
    rt = pipeline.unstack_params(
        pipeline.stack_params(params, SMALL.n_attn_layers), SMALL.n_attn_layers
    )
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_validation():
    model = GNOT(dataclasses.replace(SMALL, n_attn_layers=3))
    optim = OptimConfig()
    batch = make_batch()
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, pipe=2), jax.devices()[:4])
    sp_model = GNOT(SMALL)
    sp = pipeline.init_pipeline_state(sp_model, optim, batch, 0, mesh)
    # layers not divisible by pipe
    with pytest.raises(ValueError, match="divisible"):
        pipeline.make_pipelined_train_step(model, optim, "rel_l2", mesh, sp)
    # ... and already at state init, before any device_put can fail on
    # uneven sharding
    with pytest.raises(ValueError, match="divisible"):
        pipeline.init_pipeline_state(model, optim, batch, 0, mesh)
    # negative microbatches is a typo, not "auto"
    with pytest.raises(ValueError, match="microbatches"):
        pipeline.resolve_microbatches(mesh, -2)
    # pipe composes with data and model only
    with pytest.raises(ValueError, match="data and model"):
        mesh_lib.make_mesh(MeshConfig(data=1, seq=2, pipe=2), jax.devices()[:4])
    # standard-layout state rejected
    std = init_state(sp_model, optim, batch, seed=0)
    with pytest.raises(ValueError, match="pipeline-layout"):
        pipeline.make_pipelined_train_step(sp_model, optim, "rel_l2", mesh, std)


def test_validate_local_batch_per_host_semantics():
    """batch_size is PER-HOST: with 2 processes sharing a global data
    axis of 4, each host has 2 local data shards — per-host batch 4
    with 2 microbatches is valid (4/2=2, 2%2=0), and the check must not
    divide by the global axis (4//4=1 would wrongly reject it)."""
    mesh = mesh_lib.make_mesh(MeshConfig(data=4, pipe=2))
    pipeline.validate_local_batch(mesh, 4, 2, n_process=2)  # must not raise
    with pytest.raises(ValueError, match="per host"):
        pipeline.validate_local_batch(mesh, 4, 3, n_process=2)
    with pytest.raises(ValueError, match="per host"):
        pipeline.validate_local_batch(mesh, 3, 1, n_process=1)  # 3 % 4


def test_trainer_fit_with_pipeline():
    """End-to-end: Trainer in distributed mode over a data x pipe mesh
    trains and the loss decreases."""
    from gnot_tpu.config import make_config
    from gnot_tpu.train.trainer import Trainer

    samples = datasets.synth_ns2d(16, n_points=64)
    test = datasets.synth_ns2d(8, seed=1, n_points=64)
    cfg = make_config(
        **{
            "data.batch_size": 8,
            "train.epochs": 3,
            "train.distributed": True,
            "mesh.data": 4,
            "mesh.pipe": 2,
        }
    )
    mc = dataclasses.replace(
        SMALL, **datasets.infer_model_dims(samples)
    )
    trainer = Trainer(cfg, mc, samples, test)
    assert trainer.mesh.shape["pipe"] == 2
    best = trainer.fit()
    assert np.isfinite(best)
    # predict unstacks the pipeline layout transparently
    preds = trainer.predict(samples[:3])
    assert len(preds) == 3
    assert preds[0].shape == (samples[0].coords.shape[0], mc.out_dim)


def test_stacked_forward_matches_standard():
    """scan_layers forward (one lax.scan over stacked block params) ==
    the standard inlined-blocks forward, including ragged masks."""
    samples = datasets.synth_elasticity(4, base_points=48)
    batch = next(iter(Loader(samples, 4)))
    mc = dataclasses.replace(
        SMALL, n_attn_layers=3, **datasets.infer_model_dims(samples)
    )
    model = GNOT(mc)
    state = init_state(model, OptimConfig(), batch, seed=0)
    out_std = np.asarray(
        model.apply(
            {"params": state.params},
            batch.coords,
            batch.theta,
            batch.funcs,
            node_mask=batch.node_mask,
            func_mask=batch.func_mask,
        )
    )
    stacked = pipeline.stack_params(jax.device_get(state.params), 3)
    out_scan = np.asarray(
        jax.jit(lambda p, b: pipeline.stacked_forward(mc, p, b))(stacked, batch)
    )
    np.testing.assert_allclose(out_scan, out_std, rtol=2e-5, atol=2e-6)


def test_trainer_fit_scan_layers_matches_standard(capsys):
    """Trainer.fit with scan_layers reproduces the standard run's
    console losses/metrics (same math, stacked layout), and predict
    unstacks transparently."""
    from gnot_tpu.config import make_config
    from gnot_tpu.train.trainer import Trainer

    samples = datasets.synth_ns2d(8, n_points=64)
    test = datasets.synth_ns2d(4, seed=1, n_points=64)

    def run(scan):
        cfg = make_config(**{"data.batch_size": 4, "train.epochs": 2})
        mc = dataclasses.replace(
            SMALL, scan_layers=scan, **datasets.infer_model_dims(samples)
        )
        t = Trainer(cfg, mc, list(samples), list(test))
        best = t.fit()
        preds = t.predict(samples[:2])
        return best, preds, capsys.readouterr().out

    from helpers import assert_epoch_lines_close

    b_std, p_std, out_std = run(False)
    b_scan, p_scan, out_scan = run(True)
    np.testing.assert_allclose(b_std, b_scan, rtol=1e-5)
    assert_epoch_lines_close(out_std, out_scan, rtol=1e-5)
    for a, b in zip(p_std, p_scan):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_scan_layers_on_gspmd_mesh():
    """scan_layers composes with DP x TP: the stacked blocks shard
    their inner axes over `model` (leading layer axis unsharded) and
    the step matches single-device."""
    import jax.numpy as jnp

    from gnot_tpu.train.trainer import (
        make_train_step,
        stacked_loss_fn,
    )

    mc = dataclasses.replace(SMALL, scan_layers=True)
    model = GNOT(mc)
    optim = OptimConfig()
    samples = datasets.synth_ns2d(8, n_points=64)
    batch = next(iter(Loader(samples, 8)))
    state = pipeline.init_stacked_state(model, optim, batch, 0)
    lr = jnp.asarray(1e-3, jnp.float32)
    loss_fn = stacked_loss_fn(mc, "rel_l2")

    single = make_train_step(model, optim, "rel_l2", loss_fn=loss_fn)
    s1, loss1 = single(state, batch, lr)

    mesh = mesh_lib.make_mesh(MeshConfig(data=4, model=2))
    # Same seed -> identical initial params; the re-init also rebuilds
    # the zero opt_state the single-device step donated away.
    s2 = pipeline.init_stacked_state(model, optim, batch, 0)
    s2 = mesh_lib.shard_state(mesh, s2)
    # TP actually sharded the stacked blocks (leading axis unsharded)
    specs = {
        str(s.spec)
        for s in jax.tree.leaves(mesh_lib.state_shardings(mesh, s2))
    }
    assert any("model" in s for s in specs), specs
    step = mesh_lib.make_sharded_train_step(
        model, optim, "rel_l2", mesh, s2, loss_fn=loss_fn
    )
    s2, loss2 = step(s2, mesh_lib.shard_batch(mesh, batch), lr)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


def test_convert_state_layout_roundtrip_resumes_training():
    """A standard-layout TrainState converts to stacked (params AND
    optimizer moments) and back losslessly, and a converted state
    continues training identically: two standard steps == one standard
    step -> convert -> one stacked step -> convert back."""
    from gnot_tpu.train.trainer import (
        make_train_step,
        stacked_loss_fn,
    )

    mc = SMALL
    model = GNOT(mc)
    optim = OptimConfig()
    batch = make_batch()
    lr = jnp.asarray(1e-3, jnp.float32)

    s_ref = init_state(model, optim, batch, seed=0)
    single = make_train_step(model, optim, "rel_l2")
    s_ref, _ = single(s_ref, batch, lr)
    # Post-step state with nonzero moments, copied BY VALUE: the second
    # donating step below would otherwise write the step-2 state into
    # this device_get view (the round-6/7 use-after-donate playbook),
    # and the stacked continuation would start from the wrong state.
    s_mid = jax.tree.map(np.array, jax.device_get(s_ref))
    s_ref, _ = single(s_ref, batch, lr)

    # Round-trip identity on the mid-training state.
    rt = pipeline.convert_state_layout(
        pipeline.convert_state_layout(s_mid, mc.n_attn_layers, "stacked"),
        mc.n_attn_layers,
        "standard",
    )
    for a, b in zip(jax.tree.leaves(s_mid), jax.tree.leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Continue the second step in the STACKED layout; converting back
    # must match the all-standard run (moments carried over correctly).
    mc_scan = dataclasses.replace(mc, scan_layers=True)
    stacked_step = make_train_step(
        GNOT(mc_scan), optim, "rel_l2",
        loss_fn=stacked_loss_fn(mc_scan, "rel_l2"),
    )
    s_stacked = pipeline.convert_state_layout(
        jax.tree.map(jnp.asarray, s_mid), mc.n_attn_layers, "stacked"
    )
    s_stacked, _ = stacked_step(s_stacked, batch, lr)
    back = pipeline.convert_state_layout(
        jax.device_get(s_stacked), mc.n_attn_layers, "standard"
    )
    key = lambda kv: str(kv[0])
    a_l = sorted(
        jax.tree_util.tree_leaves_with_path(jax.device_get(s_ref.params)), key=key
    )
    b_l = sorted(jax.tree_util.tree_leaves_with_path(back.params), key=key)
    for (pa, a), (pb, b) in zip(a_l, b_l):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
