"""Runtime deadlock witness (utils/lockguard.py, ISSUE 19).

The contract, mode by mode:

* off is BYTE-IDENTICAL: ``threading.Lock``/``RLock`` are the original
  factory objects (identity, not equality — the sanitizer's off-mode
  proof pattern), and constructions return raw ``_thread`` primitives.
* witness wraps project-scoped constructions, records the
  happened-before graph, and the first cycle-closing acquisition warns
  ONCE with both stacks (the acquiring stack and the first reverse
  witness).
* strict raises :class:`LockOrderViolation` BEFORE the real acquire —
  the inversion fails fast instead of wedging the suite.

The inversion fixtures are deterministic: two threads run one after
the other (start/join, never concurrent), so the edge order — and
therefore which acquisition closes the cycle — is fixed.
"""

import os
import threading
import warnings

import pytest

from gnot_tpu.utils import lockguard


@pytest.fixture
def guard_mode():
    """Set a GNOT_LOCK_GUARD mode for one test; restore the tier-1
    default (witness, via conftest) and drop the graph afterwards."""
    prev = os.environ.get("GNOT_LOCK_GUARD")

    def set_mode(mode: str) -> None:
        os.environ["GNOT_LOCK_GUARD"] = mode
        lockguard.install()
        lockguard.reset()

    yield set_mode
    if prev is None:
        os.environ.pop("GNOT_LOCK_GUARD", None)
    else:
        os.environ["GNOT_LOCK_GUARD"] = prev
    lockguard.install()
    lockguard.reset()


def test_off_mode_is_byte_identical(guard_mode):
    guard_mode("off")
    # Identity, not wrapper shims: the very objects captured at import.
    assert threading.Lock is lockguard._ORIG_LOCK
    assert threading.RLock is lockguard._ORIG_RLOCK
    lock = threading.Lock()
    assert type(lock).__module__ == "_thread"
    assert lockguard.installed_mode() == "off"


def test_witness_wraps_project_constructions(guard_mode):
    guard_mode("witness")
    lock = threading.Lock()
    assert isinstance(lock, lockguard._LockGuard)
    assert lock.site.startswith("tests/test_lockguard.py:")
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_consistent_order_stays_silent(guard_mode):
    guard_mode("witness")
    a = threading.Lock()
    b = threading.Lock()

    def nested():
        with a:
            with b:
                pass

    for _ in range(3):
        t = threading.Thread(target=nested)
        t.start()
        t.join()
    assert lockguard.inversions() == []
    assert lockguard.edge_count() == 1  # a -> b, recorded once


def test_inversion_warns_once_with_both_stacks(guard_mode):
    guard_mode("witness")
    a = threading.Lock()
    b = threading.Lock()

    def forward():  # witnesses a -> b
        with a:
            with b:
                pass

    def backward():  # closes the cycle: b -> a
        with b:
            with a:
                pass

    t1 = threading.Thread(target=forward)
    t1.start()
    t1.join()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        t2 = threading.Thread(target=backward)
        t2.start()
        t2.join()
        t3 = threading.Thread(target=backward)  # same inversion again
        t3.start()
        t3.join()
    msgs = [str(w.message) for w in caught if "GNOT_LOCK_GUARD" in str(w.message)]
    assert len(msgs) == 1, msgs  # first inversion only, never spam
    msg = msgs[0]
    assert "lock-order inversion" in msg
    # Both stacks, labeled, each pointing into this file's fixtures.
    assert "--- this acquisition ---" in msg
    assert "--- first reverse witness" in msg
    assert msg.count("test_lockguard.py") >= 2
    assert "backward" in msg and "forward" in msg
    (rec,) = lockguard.inversions()
    assert rec["kind"] == "inversion"
    assert len(rec["cycle"]) == 3  # b -> a -> b (both sites + closure)
    assert len(rec["stacks"]) == 2


def test_strict_raises_before_acquire(guard_mode):
    guard_mode("strict")
    a = threading.Lock()
    b = threading.Lock()

    def forward():
        with a:
            with b:
                pass

    t = threading.Thread(target=forward)
    t.start()
    t.join()
    with b:
        with pytest.raises(lockguard.LockOrderViolation):
            with a:
                pass
    # The raise happened BEFORE the real acquire: a is free.
    assert a.acquire(blocking=False)
    a.release()


def test_self_deadlock_reported_not_hung(guard_mode):
    guard_mode("witness")
    lock = threading.Lock()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with lock:
            # Non-blocking, so the test cannot hang even if the guard
            # missed: the report must fire regardless of blocking.
            assert not lock.acquire(blocking=False)  # graftlint: disable=GL008 — deliberate self-deadlock fixture the witness must catch
    msgs = [str(w.message) for w in caught if "GNOT_LOCK_GUARD" in str(w.message)]
    assert len(msgs) == 1
    assert "re-acquired by its holding thread" in msgs[0]


def test_rlock_reentrancy_is_silent(guard_mode):
    guard_mode("witness")
    rlock = threading.RLock()
    with rlock:
        with rlock:  # legal reentrancy: no self-deadlock report
            pass
    assert lockguard.inversions() == []


def test_same_site_siblings_form_no_edge(guard_mode):
    guard_mode("witness")
    # Two instances from ONE construction site (the per-replica-lock
    # shape): nested acquisition must not self-edge into a false
    # positive.
    siblings = [threading.Lock() for _ in range(2)]
    with siblings[0]:
        with siblings[1]:
            pass
    assert lockguard.inversions() == []
    assert lockguard.edge_count() == 0


def test_timeout_and_nonblocking_acquire_pass_through(guard_mode):
    guard_mode("witness")
    lock = threading.Lock()
    assert lock.acquire(timeout=0.5)
    lock.release()
    assert lock.acquire(blocking=False)
    lock.release()


def test_stdlib_constructions_stay_raw(guard_mode):
    guard_mode("witness")
    import queue

    q = queue.Queue()  # queue.py constructs its own lock: out of scope
    assert type(q.mutex).__module__ == "_thread"
