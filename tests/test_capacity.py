"""Program catalog & capacity plane (gnot_tpu/serve/catalog.py,
gnot_tpu/obs/costs.py, docs/observability.md "Program costs &
capacity"): XLA cost extraction and its graceful degradation, catalog
population at compile and AOT-hydrate time, per-program dispatch
attribution under a mixed padded+packed storm, the pad-waste
registry/summary unification, the jit-fallback counter + compile
span, and the capacity model's rate math and report agreement."""

import json
import os
import sys

import jax
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import MeshSample, PackPlan, bucket_length, collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.obs import events
from gnot_tpu.obs.costs import COST_FIELDS, extract_costs, unavailable_costs
from gnot_tpu.obs.metrics import MetricsRegistry
from gnot_tpu.serve import InferenceEngine, InferenceServer, aot
from gnot_tpu.serve.catalog import (
    ProgramCatalog,
    bucket_program_key,
    packed_program_key,
)
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

MAX_BATCH = 2


@pytest.fixture(scope="module")
def setup():
    samples = datasets.synth_darcy2d(8, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:2]), 0)
    return model, params, samples


def fresh_engine(setup):
    model, params, _ = setup
    return InferenceEngine(model, params, batch_size=MAX_BATCH)


def _ragged(setup, sizes, seed=0):
    _, _, samples = setup
    rng = np.random.default_rng(seed)
    f_dim = samples[0].funcs[0].shape[-1]
    return [
        MeshSample(
            coords=rng.uniform(0, 1, size=(m, 2)).astype(np.float32),
            y=np.zeros((m, 1), np.float32),
            theta=samples[0].theta,
            funcs=(
                rng.uniform(0, 1, size=(max(4, m // 4), f_dim)).astype(
                    np.float32
                ),
            ),
        )
        for m in sizes
    ]


def read_events(path):
    return [
        r for r in (json.loads(l) for l in open(path)) if r.get("event")
    ]


# --- cost extraction ------------------------------------------------------


def test_extract_costs_from_real_executable():
    """A genuinely compiled XLA executable yields the full cost dict:
    nonzero flops and bytes from cost_analysis, buffer sizes from
    memory_analysis, no unavailable marker for the numeric fields."""
    compiled = (
        jax.jit(lambda a, b: a @ b)
        .lower(np.ones((16, 16), np.float32), np.ones((16, 16), np.float32))
        .compile()
    )
    costs = extract_costs(compiled)
    assert set(COST_FIELDS) <= set(costs)
    assert costs["flops"] and costs["flops"] > 0
    assert costs["bytes_accessed"] and costs["bytes_accessed"] > 0
    assert costs["argument_bytes"] == 2 * 16 * 16 * 4
    assert costs["output_bytes"] == 16 * 16 * 4
    assert "flops" not in costs.get("unavailable", ())


class _Stub:
    """Duck-typed compiled-executable stub for degradation tests."""

    def __init__(self, ca=None, ma=None, raise_ca=False, raise_ma=False):
        self._ca, self._ma = ca, ma
        self._raise_ca, self._raise_ma = raise_ca, raise_ma

    def cost_analysis(self):
        if self._raise_ca:
            raise RuntimeError("no cost analysis on this backend")
        return self._ca

    def memory_analysis(self):
        if self._raise_ma:
            raise RuntimeError("no memory analysis on this backend")
        return self._ma


class _MemStats:
    argument_size_in_bytes = 128
    output_size_in_bytes = 64
    temp_size_in_bytes = 0
    generated_code_size_in_bytes = 4096


def test_extract_costs_degrades_gracefully():
    """Partial or absent analyses degrade to explicit ``unavailable``
    markers, never zeros and never exceptions — including jaxlib's
    list-of-dicts cost_analysis shape and sentinel values."""
    # Both probes raise: everything unavailable, nothing invented.
    c = extract_costs(_Stub(raise_ca=True, raise_ma=True))
    assert all(c[f] is None for f in COST_FIELDS)
    assert c["unavailable"] == sorted(COST_FIELDS)
    # Partial cost_analysis (flops only, as a list-of-dicts) + memory:
    # the known fields are numbers, the missing ones are named.
    c = extract_costs(_Stub(ca=[{"flops": 123.0}], ma=_MemStats()))
    assert c["flops"] == 123 and c["argument_bytes"] == 128
    assert c["bytes_accessed"] is None
    assert "bytes_accessed" in c["unavailable"]
    assert "transcendentals" in c["unavailable"]
    # Sentinels: -1 and NaN are "would not say", not costs.
    c = extract_costs(
        _Stub(ca={"flops": -1.0, "bytes accessed": float("nan")})
    )
    assert c["flops"] is None and c["bytes_accessed"] is None
    # An object with no probe methods at all.
    c = extract_costs(object())
    assert c["unavailable"] == sorted(COST_FIELDS)


def test_unavailable_costs_marker():
    c = unavailable_costs("snapshot predates costs")
    assert all(c[f] is None for f in COST_FIELDS)
    assert c["unavailable"] == sorted(COST_FIELDS)
    assert c["unavailable_reason"] == "snapshot predates costs"
    json.dumps(c)  # artifact-safe


# --- the catalog ----------------------------------------------------------


def test_catalog_record_upgrade_and_event(tmp_path):
    """First sight wins and emits ONE program_catalog event; a thinner
    re-recording is refused; a strictly fuller one upgrades in place
    without a second event."""
    path = str(tmp_path / "ev.jsonl")
    with MetricsSink(path) as sink:
        cat = ProgramCatalog(sink=sink)
        thin = unavailable_costs("manifest predates costs")
        assert cat.record("bucket:64x64@2@f32", thin, source="manifest")
        assert not cat.record(
            "bucket:64x64@2@f32", thin, source="manifest"
        )
        full = {f: 1 for f in COST_FIELDS}
        assert cat.record("bucket:64x64@2@f32", full, source="compile")
        assert cat.get("bucket:64x64@2@f32")["source"] == "compile"
        # Downgrade refused: the full entry stays.
        assert not cat.record(
            "bucket:64x64@2@f32", thin, source="hydrate"
        )
    recs = [
        e for e in read_events(path) if e["event"] == events.PROGRAM_CATALOG
    ]
    assert len(recs) == 1
    assert events.validate_record(recs[0]) == []


def test_catalog_attach_outputs_replays_backlog(tmp_path):
    """Entries recorded before a sink attaches replay into it — wiring
    order (engines hydrate before the harness opens its sink) cannot
    lose program_catalog events."""
    cat = ProgramCatalog()
    cat.record("bucket:64x64@2@f32", {f: 1 for f in COST_FIELDS},
               source="hydrate")
    path = str(tmp_path / "ev.jsonl")
    with MetricsSink(path) as sink:
        cat.attach_outputs(sink=sink)
    recs = [
        e for e in read_events(path) if e["event"] == events.PROGRAM_CATALOG
    ]
    assert [r["key"] for r in recs] == ["bucket:64x64@2@f32"]


def test_catalog_population_on_compile(setup):
    """An engine with an attached catalog records every program it
    compiles, keyed exactly like the AOT manifest, with live XLA
    costs (source "compile") — and only once per program."""
    _, _, samples = setup
    engine = fresh_engine(setup)
    cat = ProgramCatalog()
    engine.attach_catalog(cat)
    engine.warmup(samples[:1], rows=MAX_BATCH)
    pn, pf = engine.bucket_key(samples[0])
    key = bucket_program_key(pn, pf, MAX_BATCH, engine.dtype)
    entry = cat.get(key)
    assert entry is not None and entry["source"] == "compile"
    assert entry["costs"]["flops"] > 0
    assert entry["costs"]["bytes_accessed"] > 0
    # A second dispatch of the same program records nothing new.
    engine.infer([samples[1]], pad_nodes=pn, pad_funcs=pf, rows=MAX_BATCH)
    assert len(cat.entries()) == 1


def test_aot_manifest_carries_costs_and_hydrate_records(setup, tmp_path):
    """aot_compile stamps each manifest entry with compile-time costs;
    hydrating a fresh twin records them into the twin's catalog BEFORE
    any traffic — and a storm over the hydrated tier then runs with
    zero jit fallbacks and a fully-costed capacity model."""
    _, _, samples = setup
    deploy = fresh_engine(setup)
    specs = aot.enumerate_programs(deploy, samples[:1], rows=MAX_BATCH)
    block = aot.aot_compile(
        deploy, specs, replica_id=0, snapshot_dir=str(tmp_path / "snap")
    )
    for entry in block["programs"]:
        assert entry["costs"]["flops"] > 0, entry
    twin = fresh_engine(setup)
    cat = ProgramCatalog()
    twin.attach_catalog(cat)
    res = aot.hydrate(
        twin, block["programs"], str(tmp_path / "snap"),
        params_sig=block["params_sig"],
    )
    assert res["installed"] == len(specs) and not res["skipped"]
    for spec in specs:
        entry = cat.get(spec.key)
        assert entry is not None, f"hydrate did not record {spec.key}"
        assert entry["source"] in ("hydrate", "manifest")
        assert entry["costs"]["flops"] > 0
    # Storm the hydrated twin: pure AOT dispatches, zero fallbacks,
    # and the standalone server's summary carries the capacity model.
    registry = MetricsRegistry()
    path = str(tmp_path / "serve.jsonl")
    with MetricsSink(path) as sink:
        cat.attach_outputs(metrics=registry, sink=sink)
        server = InferenceServer(
            engine=twin, max_batch=MAX_BATCH, max_wait_ms=5.0,
            sink=sink, metrics=registry, catalog=cat,
        ).start()
        futures = [server.submit(s) for s in samples[:4]]
        assert all(f.result(timeout=60).ok for f in futures)
        summary = server.drain()
    assert summary["jit_fallbacks"] == 0
    assert twin.dispatch_counts["jit"] == 0
    model = summary["capacity_model"]
    for key, prog in model["programs"].items():
        if prog["dispatches"]:
            assert prog["costs"]["flops"] > 0, (key, prog)
    assert model["pool"]["dispatches"] == summary["dispatches"] > 0


def test_mixed_storm_attribution_and_registry_crosscheck(setup, tmp_path):
    """A mixed padded+packed storm attributes every dispatch to its
    dtype-keyed program — packed rides the plan's program, the
    oversize fallback its padded bucket — and the summary's
    pad_waste_by_bucket is read back from the SAME registry counters
    it publishes (the one-accounting unification)."""
    _, _, samples = setup
    engine = fresh_engine(setup)
    cat = ProgramCatalog()
    engine.attach_catalog(cat)
    small = _ragged(setup, [16, 40, 24, 64, 8, 32])
    plan = PackPlan.from_samples(small, chunk=8, batch_size=MAX_BATCH)
    oversize = _ragged(setup, [plan.row_len + 8], seed=5)[0]
    engine.warmup(small + [oversize], rows=MAX_BATCH)
    engine.warmup_packed(small, plan)
    registry = MetricsRegistry()
    path = str(tmp_path / "serve.jsonl")
    with MetricsSink(path) as sink:
        cat.attach_outputs(metrics=registry, sink=sink)
        server = InferenceServer(
            engine=engine, max_batch=MAX_BATCH, max_wait_ms=5.0,
            sink=sink, metrics=registry, pack_plan=plan, catalog=cat,
        ).start()
        futures = [server.submit(s) for s in small + [oversize]]
        assert all(f.result(timeout=60).ok for f in futures)
        summary = server.drain()
    model = summary["capacity_model"]
    pkey = packed_program_key(plan, engine.dtype)
    opn, opf = engine.bucket_key(oversize)
    okey = bucket_program_key(opn, opf, MAX_BATCH, engine.dtype)
    assert model["programs"][pkey]["dispatches"] > 0
    assert model["programs"][okey]["dispatches"] > 0
    assert model["programs"][pkey]["requests"] == len(small)
    assert model["programs"][pkey]["real_tokens"] == sum(
        s.coords.shape[0] for s in small
    )
    # Every dispatched program carries live costs (captured at warmup).
    for key, prog in model["programs"].items():
        if prog["dispatches"]:
            assert prog["costs"]["flops"] > 0, (key, prog)
            assert prog["device_s"] > 0
            assert prog["tokens_per_device_s"] > 0
    assert model["pool"]["dispatches"] == summary["dispatches"]
    # The unification cross-check: summary pad-waste numbers ARE the
    # registry's serve_bucket_* counter values, bucket for bucket.
    snap = registry.snapshot()
    by_bucket: dict = {}
    for row in snap.values():
        name = row["name"]
        if not name.startswith("serve_bucket_") or not name.endswith(
            "_total"
        ):
            continue
        b = row["labels"]["bucket"]
        field = name[len("serve_bucket_"):-len("_total")]
        by_bucket.setdefault(b, {})[field] = row["value"]
    pw = summary["pad_waste_by_bucket"]
    assert set(by_bucket) == set(pw)
    for b, st in pw.items():
        for field in ("dispatches", "real_tokens", "capacity_tokens"):
            assert st[field] == by_bucket[b][field], (b, field)
    # Per-program registry series exist with the program label.
    prog_series = [
        row for row in snap.values()
        if row["name"] == "program_dispatches_total"
    ]
    assert {row["labels"]["program"] for row in prog_series} >= {
        pkey, okey,
    }
    # program_catalog events validated; one per recorded program.
    recs = [
        e for e in read_events(path)
        if e["event"] == events.PROGRAM_CATALOG
    ]
    assert {e["key"] for e in recs} == set(cat.entries())
    for e in recs:
        assert events.validate_record(e) == []
    snap_ev = [
        e for e in read_events(path)
        if e["event"] == events.CAPACITY_SNAPSHOT
    ]
    assert len(snap_ev) == 1
    assert events.validate_record(snap_ev[0]) == []


def test_jit_fallback_counter_and_compile_span(setup, tmp_path):
    """Jit-path dispatches are visible: the per-replica counter and
    summary field count them, and a COLD (fresh-signature) jit
    dispatch gets a dedicated compile span carrying its program key."""
    from gnot_tpu.obs.tracing import Tracer

    engine = fresh_engine(setup)  # deliberately unwarmed: cold jit
    _, _, samples = setup
    registry = MetricsRegistry()
    trace_path = str(tmp_path / "trace.json")
    tracer = Tracer(path=trace_path, sample_rate=1.0)
    path = str(tmp_path / "serve.jsonl")
    with MetricsSink(path) as sink:
        server = InferenceServer(
            engine=engine, max_batch=MAX_BATCH, max_wait_ms=5.0,
            sink=sink, metrics=registry, tracer=tracer,
            default_deadline_ms=60_000,
        ).start()
        futures = [server.submit(s) for s in samples[:2]]
        assert all(f.result(timeout=120).ok for f in futures)
        summary = server.drain()
        tracer.flush()
    assert summary["jit_fallbacks"] == engine.dispatch_counts["jit"] > 0
    counter = [
        row for row in registry.snapshot().values()
        if row["name"] == "serve_jit_fallback_total"
    ]
    assert counter and counter[0]["value"] == summary["jit_fallbacks"]
    with open(trace_path) as f:
        spans = [
            ev for ev in json.load(f)["traceEvents"]
            if ev.get("name") == "compile"
        ]
    assert spans, "cold jit dispatch produced no compile span"
    pn, pf = engine.bucket_key(samples[0])
    want = bucket_program_key(pn, pf, MAX_BATCH, engine.dtype)
    assert any(
        s.get("args", {}).get("program") == want for s in spans
    )


# --- the capacity model and report ----------------------------------------


def test_capacity_model_rates_and_retired_replica_merge():
    """Pure model math: flops/s = flops x dispatches / device_s,
    sustainable pool rates are additive over replicas, and a retired
    replica's traffic stays in the rollup (rows are never deleted)."""
    cat = ProgramCatalog()
    costs = {f: None for f in COST_FIELDS}
    costs["flops"] = 1000
    cat.record("bucket:64x64@2@f32", costs, source="compile")
    cat.note_dispatch(
        "bucket:64x64@2@f32", requests=2, real_tokens=100,
        capacity_tokens=128, device_s=0.5, replica=0,
    )
    cat.note_dispatch(
        "bucket:64x64@2@f32", requests=2, real_tokens=100,
        capacity_tokens=128, device_s=0.25, replica=1,
    )
    model = cat.capacity_model()
    prog = model["programs"]["bucket:64x64@2@f32"]
    assert prog["dispatches"] == 2 and prog["requests"] == 4
    assert prog["flops_per_s"] == pytest.approx(2 * 1000 / 0.75)
    assert prog["useful_token_frac"] == pytest.approx(200 / 256)
    assert set(prog["per_replica"]) == {"0", "1"}
    pool = model["pool"]
    assert pool["replicas"] == 2
    # Additive over replicas: 2/0.5 + 2/0.25 requests per device-sec.
    assert pool["sustainable_requests_per_s"] == pytest.approx(4 + 8)
    assert pool["sustainable_tokens_per_s"] == pytest.approx(
        100 / 0.5 + 100 / 0.25
    )
    # A dispatched-but-never-recorded program surfaces the explicit
    # marker instead of dropping its traffic.
    cat.note_dispatch(
        "bucket:999x64@2@f32", requests=1, real_tokens=10,
        capacity_tokens=64, device_s=None, replica=0,
    )
    model = cat.capacity_model()
    ghost = model["programs"]["bucket:999x64@2@f32"]
    assert ghost["source"] is None
    assert ghost["costs"]["unavailable_reason"] == "never recorded"
    assert ghost["tokens_per_device_s"] is None  # unknown, not infinite


def test_capacity_report_recommendation_and_agreement():
    """tools/capacity_report.py pure parts on a synthetic model: the
    reconstruction preserves exact token totals, the searched plan's
    projection beats the observed padded waste, and the agreement
    check flags drift."""
    import capacity_report

    def prog(dispatches, requests, real, cap):
        return {
            "source": "compile", "costs": {},
            "dispatches": dispatches, "requests": requests,
            "real_tokens": real, "capacity_tokens": cap,
            "device_s": 0.01, "per_replica": {},
            "useful_token_frac": real / cap,
            "tokens_per_device_s": real / 0.01,
            "requests_per_device_s": requests / 0.01,
            "device_us_per_token": 1e4 / real, "flops_per_s": None,
        }

    model = {
        "programs": {
            "bucket:64x64@4@f32": prog(5, 17, 1080, 1280),
            "bucket:192x64@4@f32": prog(2, 5, 810, 1536),
        },
        "pool": {
            "replicas": 1, "programs": 2, "dispatches": 7,
            "requests": 22, "real_tokens": 1890,
            "capacity_tokens": 2816, "device_s": 0.02,
            "sustainable_requests_per_s": 1100.0,
            "sustainable_tokens_per_s": 94500.0,
            "useful_token_frac": 1890 / 2816, "per_replica": {},
        },
    }
    sizes, buckets = capacity_report.reconstruct_sizes(model, 64)
    assert sum(sizes) == 1890 and len(sizes) == 22
    assert {b["bucket"] for b in buckets} == {64, 192}
    rec = capacity_report.pack_recommendation(model, 64, 4, baseline=None)
    assert rec["real_tokens"] == 1890
    assert rec["projected_pad_waste"] < rec["observed_pad_waste"]
    assert rec["plan"]["row_len"] % 64 == 0
    summary = {
        "dispatches": 7,
        "pad_waste_by_bucket": {
            "64x64": {"dispatches": 5, "real_tokens": 1080,
                      "capacity_tokens": 1280},
            "192x64": {"dispatches": 2, "real_tokens": 810,
                       "capacity_tokens": 1536},
        },
    }
    assert capacity_report.agreement(summary, model)["problems"] == []
    summary["dispatches"] = 8  # drift must be flagged, not smoothed
    assert capacity_report.agreement(summary, model)["problems"]


def test_serve_smoke_capacity_flag(tmp_path):
    """The smoke's own --capacity assertions hold end to end (the
    tier-1 twin of the capacity_report storm)."""
    import serve_smoke

    summary = serve_smoke.run([
        "--n", "6", "--mesh_lo", "80", "--mesh_hi", "200",
        "--inject_fault", "none", "--deadline_ms", "10000",
        "--capacity",
        "--metrics_path", str(tmp_path / "smoke.jsonl"),
    ])
    assert summary["failures"] == []
    assert summary["capacity_model"]["pool"]["dispatches"] > 0
