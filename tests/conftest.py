"""Test configuration: force an 8-device CPU JAX platform BEFORE jax
initializes, so sharding/parallelism tests run without TPU hardware
(SURVEY.md §4: the standard way to test multi-chip TPU code)."""

import os
import sys

# Importable from any cwd without an install: the package root is the
# directory above tests/ (an editable `pip install -e .` makes this a
# no-op — pyproject.toml is the installed path).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force CPU: the ambient environment may point JAX_PLATFORMS at a real
# TPU tunnel, whose default bf16 matmuls would break numeric tolerances.
# A sitecustomize may already have imported jax, so the env var alone is
# not enough — update the live config too (backends init lazily).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the suite is dominated by XLA compiles of
# near-identical tiny programs; re-runs hit the cache instead. Shared
# per-user location with the CLI (gnot_tpu/utils/cache.py), so tests
# and CLI runs warm each other. GNOT_COMPILE_CACHE (alias:
# GNOT_TEST_CACHE) overrides the path; "off" or empty gives
# clean-compile runs — honored inside enable_compile_cache, so tests
# that call main() in-process can't silently re-enable the cache.
from gnot_tpu.utils.cache import enable_compile_cache

enable_compile_cache()
