"""Test configuration: force an 8-device CPU JAX platform BEFORE jax
initializes, so sharding/parallelism tests run without TPU hardware
(SURVEY.md §4: the standard way to test multi-chip TPU code)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force CPU: the ambient environment may point JAX_PLATFORMS at a real
# TPU tunnel, whose default bf16 matmuls would break numeric tolerances.
# A sitecustomize may already have imported jax, so the env var alone is
# not enough — update the live config too (backends init lazily).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the suite is dominated by XLA compiles of
# near-identical tiny programs; re-runs hit the cache instead. Per-user
# path: a world-shared /tmp dir would fail for the second user on a
# shared machine and mean executing artifacts another user could write.
import tempfile

_home = os.path.expanduser("~")
if os.path.isabs(_home):
    # User-owned location: nobody else can pre-create or write it.
    _default_cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME") or os.path.join(_home, ".cache"),
        "gnot_jax_cache",
    )
else:  # stripped container env without HOME: uid-scoped tmp fallback
    _default_cache = os.path.join(
        tempfile.gettempdir(), f"gnot_jax_cache_{os.getuid()}"
    )
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("GNOT_TEST_CACHE", _default_cache),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
