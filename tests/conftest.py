"""Test configuration: force an 8-device CPU JAX platform BEFORE jax
initializes, so sharding/parallelism tests run without TPU hardware
(SURVEY.md §4: the standard way to test multi-chip TPU code)."""

import os
import sys

# Importable from any cwd without an install: the package root is the
# directory above tests/ (an editable `pip install -e .` makes this a
# no-op — pyproject.toml is the installed path).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force CPU: the ambient environment may point JAX_PLATFORMS at a real
# TPU tunnel, whose default bf16 matmuls would break numeric tolerances.
# A sitecustomize may already have imported jax, so the env var alone is
# not enough — update the live config too (backends init lazily).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# Session-scoped persistent compile cache for tier-1 (ISSUE 10
# satellite): the suite is dominated by XLA compiles of near-identical
# tiny programs — the compile-bound sharding/pipeline tests pay
# 8-12 s each when cold. The cache lives at a STABLE /tmp path so
# every tier-1 run (and every worker of one) shares the same warm
# entries: populated once, hit thereafter. First use seeds it from the
# per-user CLI cache (gnot_tpu/utils/cache.py default) via hardlinks
# when one exists, so tests and CLI runs keep warming each other.
# GNOT_COMPILE_CACHE (alias: GNOT_TEST_CACHE) still overrides the
# path; "off" or empty gives clean-compile runs — honored inside
# enable_compile_cache, so tests that call main() in-process can't
# silently re-enable the cache.
from gnot_tpu.utils.cache import default_cache_dir, enable_compile_cache


def _tier1_cache_dir() -> str:
    path = os.path.join(
        "/tmp" if os.path.isdir("/tmp") else os.path.expanduser("~"),
        f"gnot_tier1_cache_{os.getuid()}",
    )
    try:
        os.makedirs(path, exist_ok=True)
        st = os.stat(path)
        if st.st_uid != os.getuid() or (st.st_mode & 0o022):
            # A pre-created dir we don't exclusively own would mean
            # deserializing executables another user could write (the
            # utils/cache.py hazard); fall back to the per-user cache.
            return ""
        user_cache = default_cache_dir()
        if not os.listdir(path) and os.path.isdir(user_cache):
            for de in os.scandir(user_cache):
                if de.is_file():
                    try:
                        os.link(de.path, os.path.join(path, de.name))
                    except OSError:
                        pass  # cross-device or racing writer: seed less
    except OSError:
        return ""  # unusable /tmp: fall through to the default resolution
    return path


if not (os.environ.get("GNOT_COMPILE_CACHE") or os.environ.get("GNOT_TEST_CACHE")):
    seeded = _tier1_cache_dir()
    if seeded:
        os.environ["GNOT_COMPILE_CACHE"] = seeded
enable_compile_cache()

# Donation alias guard ON for tier-1 (ISSUE 11): GNOT_ALIAS_GUARD
# defaults to copy mode, so jax.device_get returns BY-VALUE snapshots
# and the nine-times-root-caused test-side use-after-donate (PR 6/7/10
# parity failures — docs/parallelism.md ledger) cannot recur through
# the device_get channel (np.asarray-seeded views remain GL006's
# static territory — docs/robustness.md "The donation sanitizer"). An
# explicit GNOT_ALIAS_GUARD=0 (or =poison, for triage) still wins.
# utils/sanitizer.py; the committed overhead A/B pins the cost.
os.environ.setdefault("GNOT_ALIAS_GUARD", "1")
from gnot_tpu.utils import sanitizer

sanitizer.install()

# Runtime deadlock witness ON for tier-1 (ISSUE 19): GNOT_LOCK_GUARD
# defaults to witness mode, so every project lock constructed by the
# serving/federation/autoscale suites records its acquisition order
# and the first lock-order inversion warns with both stacks — the
# dynamic belt to graftlint GL008's static brace (docs/robustness.md
# "The lock guard"). An explicit GNOT_LOCK_GUARD=0 (or =strict) still
# wins. utils/lockguard.py; measured overhead in static_analysis.md.
os.environ.setdefault("GNOT_LOCK_GUARD", "witness")
from gnot_tpu.utils import lockguard

lockguard.install()
