"""OneCycle schedule vs torch.optim.lr_scheduler.OneCycleLR."""

import numpy as np
import pytest

from gnot_tpu.train.schedule import onecycle_lr


@pytest.mark.parametrize("steps_per_epoch,epochs", [(7, 13), (250, 100), (3, 2)])
def test_onecycle_matches_torch(steps_per_epoch, epochs):
    torch = pytest.importorskip("torch")
    from torch.optim.lr_scheduler import OneCycleLR

    max_lr = 1e-3
    total = steps_per_epoch * epochs
    opt = torch.optim.AdamW([torch.nn.Parameter(torch.zeros(1))], lr=max_lr)
    sched = OneCycleLR(opt, max_lr=max_lr, steps_per_epoch=steps_per_epoch, epochs=epochs)

    got = [onecycle_lr(0, max_lr=max_lr, total_steps=total)]
    want = [opt.param_groups[0]["lr"]]
    for step in range(1, total):
        sched.step()
        want.append(opt.param_groups[0]["lr"])
        got.append(onecycle_lr(step, max_lr=max_lr, total_steps=total))
    np.testing.assert_allclose(got, want, rtol=1e-10)
