"""OneCycle schedule vs torch.optim.lr_scheduler.OneCycleLR."""

import numpy as np
import pytest

from gnot_tpu.train.schedule import onecycle_lr


@pytest.mark.parametrize("steps_per_epoch,epochs", [(7, 13), (250, 100), (3, 2)])
def test_onecycle_matches_torch(steps_per_epoch, epochs):
    torch = pytest.importorskip("torch")
    from torch.optim.lr_scheduler import OneCycleLR

    max_lr = 1e-3
    total = steps_per_epoch * epochs
    opt = torch.optim.AdamW([torch.nn.Parameter(torch.zeros(1))], lr=max_lr)
    sched = OneCycleLR(opt, max_lr=max_lr, steps_per_epoch=steps_per_epoch, epochs=epochs)

    got = [onecycle_lr(0, max_lr=max_lr, total_steps=total)]
    want = [opt.param_groups[0]["lr"]]
    for step in range(1, total):
        sched.step()
        want.append(opt.param_groups[0]["lr"])
        got.append(onecycle_lr(step, max_lr=max_lr, total_steps=total))
    np.testing.assert_allclose(got, want, rtol=1e-10)


@pytest.mark.parametrize("k,steps_per_epoch,epochs", [(3, 9, 4), (3, 10, 3)])
def test_per_step_schedule_with_grad_accum_matches_torch_updates(
    k, steps_per_epoch, epochs
):
    """With grad_accum=k, the per_step schedule must step once per
    OPTIMIZER UPDATE (torch semantics), not once per micro-step:
    MultiSteps applies the LR sampled at each k-th micro-step, so
    lr_fn evaluated there must equal torch OneCycleLR stepped per
    update over the update-count horizon. The second case has
    steps_per_epoch not divisible by k (accumulation windows straddle
    epoch boundaries) — the horizon is the GLOBAL micro-step count / k."""
    torch = pytest.importorskip("torch")
    from torch.optim.lr_scheduler import OneCycleLR

    from gnot_tpu.config import OptimConfig
    from gnot_tpu.train.schedule import make_lr_fn

    cfg = OptimConfig(parity_schedule_bug=False, grad_accum=k)
    lr_fn = make_lr_fn(cfg, steps_per_epoch=steps_per_epoch, epochs=epochs)

    total_updates = (steps_per_epoch * epochs) // k
    opt = torch.optim.AdamW([torch.nn.Parameter(torch.zeros(1))], lr=cfg.lr)
    sched = OneCycleLR(opt, max_lr=cfg.lr, total_steps=total_updates)

    got, want = [], []
    for u in range(total_updates):
        want.append(opt.param_groups[0]["lr"])
        # The micro-step where MultiSteps fires update u is u*k + k - 1;
        # the epoch is wherever that global micro-step falls.
        micro = u * k + k - 1
        got.append(lr_fn(micro, epoch=micro // steps_per_epoch))
        sched.step()
    np.testing.assert_allclose(got, want, rtol=1e-10)
