"""Multi-device sharding tests on the virtual 8-device CPU mesh
(conftest sets --xla_force_host_platform_device_count=8)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import MeshConfig, ModelConfig, OptimConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.parallel import mesh as mesh_lib
from gnot_tpu.train.trainer import init_state, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)

SMALL = ModelConfig(
    input_dim=2,
    theta_dim=1,
    input_func_dim=3,
    out_dim=1,
    n_input_functions=1,
    n_attn_layers=2,
    n_attn_hidden_dim=32,
    n_mlp_num_layers=2,
    n_mlp_hidden_dim=32,
    n_input_hidden_dim=32,
    n_expert=3,
    n_head=4,
)


def make_batch(b=8, n_points=64):
    samples = datasets.synth_ns2d(b, n_points=n_points)
    return next(iter(Loader(samples, b)))


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        # pure DP: the heaviest compile of the grid (8-way data axis) —
        # `slow` to keep tier-1 wall time under its 870s budget; the
        # composed-axes cases below still cover the parity invariant.
        pytest.param(MeshConfig(data=8), marks=pytest.mark.slow),
        MeshConfig(data=2, seq=2, model=2),  # DP x SP x TP
        MeshConfig(data=1, seq=4, model=2),  # SP-heavy (long-context)
    ],
)
def test_sharded_step_matches_single_device(mesh_cfg):
    """One sharded train step == the single-device step, bitwise-ish."""
    model = GNOT(SMALL)
    optim = OptimConfig()
    batch = make_batch()
    state = init_state(model, optim, batch, seed=0)

    single = make_train_step(model, optim, "rel_l2")
    state1, loss1 = single(
        jax.tree.map(jnp.copy, state), batch, jnp.asarray(1e-3, jnp.float32)
    )

    mesh = mesh_lib.make_mesh(mesh_cfg)
    sharded_state = mesh_lib.shard_state(mesh, state)
    step = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, sharded_state)
    sharded_batch = mesh_lib.shard_batch(mesh, batch)
    state2, loss2 = step(sharded_state, sharded_batch, jnp.asarray(1e-3, jnp.float32))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


def test_param_shardings_cover_tree():
    model = GNOT(SMALL)
    batch = make_batch()
    state = init_state(model, OptimConfig(), batch, seed=0)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2, model=2))
    sh = mesh_lib.state_shardings(mesh, state)
    # every leaf got a sharding, and TP actually shards something
    leaves = jax.tree.leaves(sh)
    assert len(leaves) == len(jax.tree.leaves(state))
    specs = {str(s.spec) for s in leaves}
    assert any("model" in s for s in specs), specs


def test_seq_sharding_masked_correctness():
    """SP with ragged masks: padded rows live on specific seq shards;
    the psum'd partial sums must still drop them."""
    model = GNOT(dataclasses.replace(SMALL, attention_mode="masked"))
    samples = datasets.synth_elasticity(4, base_points=48)
    batch = next(iter(Loader(samples, 4)))  # ragged -> real masking
    state = init_state(model, OptimConfig(), batch, seed=0)

    out_single = model.apply(
        {"params": state.params},
        batch.coords,
        batch.theta,
        batch.funcs,
        node_mask=batch.node_mask,
        func_mask=batch.func_mask,
    )

    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2, model=2))
    sb = mesh_lib.shard_batch(mesh, batch)
    ps = mesh_lib.param_shardings(mesh, state.params)
    sp = jax.tree.map(lambda leaf, s: jax.device_put(leaf, s), state.params, ps)

    @jax.jit
    def fwd(params, b):
        return model.apply(
            {"params": params},
            b.coords,
            b.theta,
            b.funcs,
            node_mask=b.node_mask,
            func_mask=b.func_mask,
        )

    out_sharded = fwd(sp, sb)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out_sharded)),
        np.asarray(out_single),
        rtol=2e-4,
        atol=2e-5,
    )


@pytest.mark.slow  # 16k-point compile: tier-1 wall-time headroom (PR 5)
def test_heatsink3d_16k_seq_sharded_step():
    """Heatsink3d at its ACTUAL scale class (>=16k 3D points): a full
    remat+SP train step on the virtual mesh matches the single-device
    step. This is the long-context recipe (docs/performance.md) at the
    scale it exists for, not a 2k-point miniature."""
    samples = datasets.synth_heatsink3d(2, seed=3, base_points=16384)
    assert min(s.coords.shape[0] for s in samples) >= 16384 * 0.9
    batch = next(iter(Loader(samples, 2)))  # bucketed: L divisible by seq
    mc = ModelConfig(
        n_attn_layers=1,
        n_attn_hidden_dim=16,
        n_mlp_num_layers=1,
        n_mlp_hidden_dim=16,
        n_input_hidden_dim=16,
        n_expert=2,
        n_head=2,
        remat=True,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    lr = jnp.asarray(1e-3, jnp.float32)

    single = make_train_step(model, optim, "rel_l2")
    state1, loss1 = single(jax.tree.map(jnp.copy, state), batch, lr)
    assert np.isfinite(float(loss1))

    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=4, model=1))
    s_mesh = mesh_lib.shard_state(mesh, state)
    step = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, s_mesh)
    s_mesh, loss2 = step(s_mesh, mesh_lib.shard_batch(mesh, batch), lr)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state1.params)),
        jax.tree.leaves(jax.device_get(s_mesh.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=2, expert=4),  # DP x EP (every expert on its own shard pair)
        MeshConfig(data=2, model=2, expert=2),  # DP x TP x EP composed
    ],
)
def test_expert_parallel_step_matches_single_device(mesh_cfg):
    """EP over the stacked soft-MoE expert axis: the gated combine's
    contraction over E becomes a psum; the step must still match the
    single-device step."""
    model = GNOT(dataclasses.replace(SMALL, n_expert=4))
    optim = OptimConfig()
    batch = make_batch()
    state = init_state(model, optim, batch, seed=0)

    single = make_train_step(model, optim, "rel_l2")
    state1, loss1 = single(
        jax.tree.map(jnp.copy, state), batch, jnp.asarray(1e-3, jnp.float32)
    )

    mesh = mesh_lib.make_mesh(mesh_cfg)
    sharded_state = mesh_lib.shard_state(mesh, state)
    # EP actually sharded something
    specs = {
        str(s.spec) for s in jax.tree.leaves(mesh_lib.state_shardings(mesh, state))
    }
    assert any("expert" in s for s in specs), specs
    step = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, sharded_state)
    sharded_batch = mesh_lib.shard_batch(mesh, batch)
    state2, loss2 = step(sharded_state, sharded_batch, jnp.asarray(1e-3, jnp.float32))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


def test_expert_axis_divisibility_validated():
    model = GNOT(SMALL)  # n_expert=3
    batch = make_batch()
    state = init_state(model, OptimConfig(), batch, seed=0)
    mesh = mesh_lib.make_mesh(MeshConfig(data=4, expert=2))
    with pytest.raises(ValueError, match="divisible"):
        mesh_lib.make_sharded_train_step(model, OptimConfig(), "rel_l2", mesh, state)
    # The eval-only builder (--eval_only path) must raise the same clear
    # error, not an opaque XLA partitioning failure mid-compile.
    with pytest.raises(ValueError, match="divisible"):
        mesh_lib.make_sharded_eval_step(model, "rel_l2", mesh, state)


def test_mesh_validation():
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(MeshConfig(data=3, seq=2, model=2))


def test_sharded_step_with_grad_accum_matches_single_device():
    """MultiSteps opt-state (nested param-suffixed tree) must shard
    correctly; two sharded micro-steps == two single-device micro-steps."""
    optim = dataclasses.replace(OptimConfig(), grad_accum=2)
    model = GNOT(SMALL)
    batch = make_batch()
    state = init_state(model, optim, batch, seed=0)
    lr = jnp.asarray(1e-3, jnp.float32)

    step_single = make_train_step(model, optim, "rel_l2")
    s_single = state
    for _ in range(2):
        s_single, _ = step_single(s_single, batch, lr)

    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2, model=2), jax.devices()[:8])
    s_mesh = mesh_lib.shard_state(mesh, init_state(model, optim, batch, seed=0))
    step_mesh = mesh_lib.make_sharded_train_step(model, optim, "rel_l2", mesh, s_mesh)
    sharded = mesh_lib.shard_batch(mesh, batch)
    for _ in range(2):
        s_mesh, _ = step_mesh(s_mesh, sharded, lr)

    for a, b in zip(
        jax.tree.leaves(jax.device_get(s_mesh.params)),
        jax.tree.leaves(jax.device_get(s_single.params)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_sharded_multi_step_matches_single_device():
    """K-step scanned dispatch over a DPxTP mesh == K single-device
    steps (GSPMD collectives inside the scan body)."""
    model = GNOT(SMALL)
    optim = OptimConfig()
    samples = datasets.synth_ns2d(16, n_points=64)
    batches = list(Loader(samples, 8))[:2]
    state = init_state(model, optim, batches[0], seed=0)
    # DEEP copy, not a bare device_get: on CPU device_get returns
    # zero-copy views of the live device buffers, and the donated
    # single(...) steps below can write their updated params straight
    # into those buffers (use-after-donate through an aliased host
    # view — the PR 6 playbook; root-caused again here, measured
    # 1.8e-3 of silent drift). The sharded arm must start from the
    # TRUE initial params, so snapshot by value.
    host = jax.tree.map(np.array, jax.device_get(state.params))
    lrs = [1e-3, 8e-4]

    single = make_train_step(model, optim, "rel_l2")
    s1 = state
    for b, lr in zip(batches, lrs):
        s1, _ = single(s1, b, jnp.asarray(lr, jnp.float32))

    from gnot_tpu.train.trainer import TrainState, stack_batches

    mesh = mesh_lib.make_mesh(MeshConfig(data=4, model=2))
    s2 = init_state(model, optim, batches[0], seed=0)
    s2 = dataclasses.replace(s2, params=jax.tree.map(jnp.asarray, host))
    s2 = mesh_lib.shard_state(mesh, s2)
    multi = mesh_lib.make_sharded_multi_train_step(
        model, optim, "rel_l2", mesh, s2
    )
    stacked = mesh_lib.shard_batch(mesh, stack_batches(batches), stacked=True)
    s2, losses = multi(s2, stacked, jnp.asarray(np.asarray(lrs, np.float32)))
    assert np.all(np.isfinite(np.asarray(losses)))
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(data=8), MeshConfig(data=4, seq=2)],
    ids=["pure DP", "DP x SP"],
)
def test_flat_params_sharded_step_matches_single_device(mesh_cfg):
    """The flat [P]-vector layout composes with the data/seq mesh axes:
    params are one replicated buffer (P(None) pspec), batch shards as
    usual, and the step matches the single-device flat step."""
    from gnot_tpu.train.trainer import flat_loss_fn, init_flat_state

    model = GNOT(SMALL)
    optim = OptimConfig(flat_params=True)
    batch = make_batch()
    state, unravel = init_flat_state(model, optim, batch, seed=0)
    loss_fn = flat_loss_fn(model, unravel, "rel_l2")

    single = make_train_step(model, optim, "rel_l2", loss_fn=loss_fn)
    state1, loss1 = single(
        jax.tree.map(jnp.copy, state), batch, jnp.asarray(1e-3, jnp.float32)
    )

    mesh = mesh_lib.make_mesh(mesh_cfg)
    sharded_state = mesh_lib.shard_state(mesh, state)
    step = mesh_lib.make_sharded_train_step(
        model, optim, "rel_l2", mesh, sharded_state, loss_fn=loss_fn
    )
    sharded_batch = mesh_lib.shard_batch(mesh, batch)
    state2, loss2 = step(sharded_state, sharded_batch, jnp.asarray(1e-3, jnp.float32))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(state1.params),
        np.asarray(jax.device_get(state2.params)),
        rtol=2e-4, atol=2e-5,
    )


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        # pure DP packed: second-heaviest compile in this file — `slow`
        # for tier-1 headroom; the composed DP x TP x EP case keeps the
        # packed-sharding invariant in every tier-1 run.
        pytest.param(MeshConfig(data=8), marks=pytest.mark.slow),
        MeshConfig(data=2, model=2, expert=2),
    ],
    ids=["pure DP", "DP x TP x EP"],
)
def test_packed_sharded_step_matches_single_device(mesh_cfg):
    """Packed rows shard over ``data`` (the per-segment Gram scatter
    becomes one GSPMD psum); slot-indexed pieces replicate. The sharded
    packed step matches the single-device packed step."""
    from gnot_tpu.data.batch import PackedLoader
    from gnot_tpu.train.trainer import packed_loss_fn

    model = GNOT(dataclasses.replace(SMALL, n_expert=4))  # EP-divisible
    optim = OptimConfig()
    samples = datasets.synth_elasticity(16, seed=0)
    mesh = mesh_lib.make_mesh(mesh_cfg)
    batch = PackedLoader(
        samples, 16, chunk=64, row_multiple=mesh.shape["data"]
    ).probe_batch()
    state = init_state(model, optim, batch, seed=0)
    loss_fn = packed_loss_fn(model, "rel_l2")

    single = make_train_step(model, optim, "rel_l2", loss_fn=loss_fn)
    state1, loss1 = single(
        jax.tree.map(jnp.copy, state), batch, jnp.asarray(1e-3, jnp.float32)
    )

    sharded_state = mesh_lib.shard_state(mesh, state)
    step = mesh_lib.make_sharded_train_step(
        model, optim, "rel_l2", mesh, sharded_state, loss_fn=loss_fn
    )
    sharded_batch = mesh_lib.shard_batch(mesh, batch)
    state2, loss2 = step(sharded_state, sharded_batch, jnp.asarray(1e-3, jnp.float32))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=2e-4, atol=2e-5
        )


@pytest.mark.slow  # heaviest packed-sharding compile (~20s): tier-1
# wall-time headroom for the ISSUE 6 packed-serve/kernel tests; the
# in-tier DP x TP x EP single-step case keeps the packed-sharding
# invariant covered every run.
def test_packed_sharded_multi_step_matches_single_steps():
    """The docs-claimed packed x mesh x steps_per_dispatch composition:
    K stacked packed dispatches scanned in one sharded program match K
    sharded single steps (static n_seg survives the stacking and the
    stacked pspec prefixing)."""
    from gnot_tpu.data.batch import PackedLoader
    from gnot_tpu.train.trainer import packed_loss_fn, stack_batches

    model = GNOT(SMALL)
    optim = OptimConfig()
    samples = datasets.synth_elasticity(24, seed=0)
    mesh = mesh_lib.make_mesh(MeshConfig(data=8))
    loader = PackedLoader(
        samples, 8, chunk=64, row_multiple=mesh.shape["data"]
    )
    batches = list(loader)[:2]
    assert len(batches) == 2
    loss_fn = packed_loss_fn(model, "rel_l2")
    state = init_state(model, optim, batches[0], seed=0)
    sharded = mesh_lib.shard_state(mesh, state)
    lr = jnp.asarray(1e-3, jnp.float32)

    single = mesh_lib.make_sharded_train_step(
        model, optim, "rel_l2", mesh, sharded, loss_fn=loss_fn
    )
    s1 = jax.tree.map(jnp.copy, sharded)
    losses1 = []
    for b in batches:
        s1, l = single(s1, mesh_lib.shard_batch(mesh, b), lr)
        losses1.append(float(l))

    multi = mesh_lib.make_sharded_multi_train_step(
        model, optim, "rel_l2", mesh, sharded, loss_fn=loss_fn
    )
    stacked = mesh_lib.shard_batch(mesh, stack_batches(batches), stacked=True)
    s2, losses2 = multi(sharded, stacked, jnp.asarray([1e-3, 1e-3], jnp.float32))

    np.testing.assert_allclose(losses1, np.asarray(losses2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            rtol=2e-4, atol=2e-5,
        )
