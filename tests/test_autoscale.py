"""Chaos + unit suite for self-healing elastic serving (ISSUE 15).

Three layers:

* **Controller decision logic on a fake clock** (stub router, no jax
  dispatch): cooldowns, up/down hysteresis, the consecutive-calm-ticks
  requirement, surge scaling, the flap suppressor, min/max bounds, and
  self-healing replacement of dead/wedged replicas — every stability
  guard pinned deterministically.
* **Drain-then-remove on the real replica tier**: ``remove_replica``
  retires a replica without losing requests, resident rollout sessions
  hand over to siblings (zero lost — including when the retiring
  replica is KILLED mid-handover), and the pool rollup keeps the
  retired replica's history (the membership-change history-loss fix).
* **Session resume across restarts** (the PR 13 stretch): a drained
  named session persists its final carry snapshot and a fresh
  server/router resumes it to completion, matching the offline
  trajectory exactly.
"""

import json
import os
import sys
import time

import jax
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig, make_config
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.obs import events as events_registry
from gnot_tpu.resilience.faults import FaultInjector
from gnot_tpu.serve import (
    AutoscaleController,
    InferenceEngine,
    InferenceServer,
    ReplicaRouter,
    SessionStore,
    build_replica,
    offline_rollout,
)
from gnot_tpu.serve.policies import HealthVerdict
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)

MAX_BATCH = 2


def read_events(path):
    return [
        r for r in (json.loads(l) for l in open(path)) if r.get("event")
    ]


@pytest.fixture(scope="module")
def setup():
    """Tiny model + params + Darcy64 traffic (the test_serve shape)."""
    samples = datasets.synth_darcy2d(12, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(samples[:1], rows=MAX_BATCH)
    return model, params, samples, engine


def _make_replicas(setup, n, ids=None, **kw):
    model, params, _, _ = setup
    ids = list(ids) if ids is not None else list(range(n))
    return [
        build_replica(
            model, params, rid, jax.devices()[i : i + 1],
            batch_size=MAX_BATCH, **kw,
        )
        for i, rid in enumerate(ids)
    ]


# --- fake-clock controller units -------------------------------------------


class FakeServer:
    def __init__(self):
        self.depth_v = 0
        self.sessions_v = 0
        self.alive = True
        self.verdict = "ok"

    def depth(self):
        return self.depth_v

    def resident_sessions(self):
        return self.sessions_v

    def worker_alive(self):
        return self.alive


class FakeReplica:
    def __init__(self, rid):
        self.replica_id = rid
        self.server = FakeServer()
        self.retiring = False
        self.warm_stats = {"source": "compile"}


class FakeRouter:
    """The controller-facing surface of ReplicaRouter, minus jax."""

    def __init__(self, n):
        self.replicas = [FakeReplica(i) for i in range(n)]
        self.removed = []
        self.added = []

    def pool(self):
        return list(self.replicas)

    def add_replica(self, replica):
        self.replicas.append(replica)
        self.added.append(replica.replica_id)
        return replica

    def remove_replica(self, rid, *, timeout_s=30.0, reason="scale_in"):
        self.replicas = [r for r in self.replicas if r.replica_id != rid]
        self.removed.append((rid, reason))
        return {"requests": 0, "completed": 0}

    def assess(self, r):
        if not r.server.alive:
            return HealthVerdict(False, "dead")
        if r.server.verdict != "ok":
            return HealthVerdict(False, r.server.verdict)
        return HealthVerdict(True, "ok")

    def set_load(self, per_replica):
        for r in self.replicas:
            r.server.depth_v = per_replica


class ListSink:
    def __init__(self):
        self.records = []

    def log(self, **fields):
        self.records.append(fields)

    def flush(self):
        pass


def _controller(router, clk, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("up_load", 8.0)
    kw.setdefault("down_load", 1.0)
    kw.setdefault("down_ticks", 3)
    kw.setdefault("heal_after_s", 5.0)
    kw.setdefault("sink", ListSink())
    return AutoscaleController(
        router,
        replica_factory=lambda rid, slot: FakeReplica(rid),
        clock=lambda: clk[0],
        **kw,
    )


def test_autoscale_config_validates():
    with pytest.raises(ValueError, match="autoscale_min"):
        make_config(**{"serve.autoscale_min": 5})  # min > max(4)
    with pytest.raises(ValueError, match="hysteresis"):
        make_config(**{"serve.autoscale_down_load": 8.0})
    with pytest.raises(ValueError, match="down_ticks"):
        make_config(**{"serve.autoscale_down_ticks": 0})
    with pytest.raises(ValueError, match="founding pool"):
        make_config(
            **{"serve.autoscale": True, "serve.replicas": 8}
        )
    cfg = make_config(
        **{"serve.autoscale": True, "serve.replicas": 2,
           "serve.autoscale_max": 3}
    )
    assert cfg.serve.autoscale_max == 3
    with pytest.raises(ValueError):
        AutoscaleController(
            FakeRouter(1), replica_factory=lambda r, s: None,
            up_load=1.0, down_load=2.0,
        )


def test_controller_scale_up_cooldown_and_surge():
    clk = [0.0]
    router = FakeRouter(1)
    sink = ListSink()
    c = _controller(router, clk, sink=sink)
    # Idle: nothing happens.
    assert c.tick()["action"] == "none"
    # Load over the up threshold: grow once...
    router.set_load(10)
    d = c.tick()
    assert d["action"] == "scale_up" and d["reason"] == "load"
    assert len(router.pool()) == 2 and router.added == [1]
    # ...but not twice inside the cooldown (pressure still high on
    # every replica, including the newcomer).
    router.set_load(10)
    d = c.tick()
    assert d["action"] == "hold" and d["reason"] == "cooldown_up"
    # Past the cooldown the next step lands.
    clk[0] = 2.5
    assert c.tick()["action"] == "scale_up"
    # SURGE: load >= surge_mult * up_load bypasses the cooldown.
    router.set_load(100)
    d = c.tick()
    assert d["action"] == "scale_up" and d["reason"] == "surge"
    # At the max bound the want is vetoed — as an EDGE event, once.
    d = c.tick()
    assert d["action"] == "hold" and d["reason"] == "at_max"
    c.tick()
    holds = [
        r
        for r in sink.records
        if r["event"] == "autoscale_decision" and r["reason"] == "at_max"
    ]
    assert len(holds) == 1, "steady veto must not spam decision events"
    # Every emitted event validates against the central registry.
    for rec in sink.records:
        assert events_registry.validate_record(rec) == []


def test_controller_hysteresis_down_ticks_and_down_cooldown():
    clk = [0.0]
    router = FakeRouter(3)
    c = _controller(router, clk, flap_suppress_s=0.0)
    # Mid-band load (between down_load and up_load): no action, and it
    # RESETS the calm streak.
    router.set_load(4)
    for _ in range(5):
        assert c.tick()["action"] == "none"
    router.set_load(0)
    assert c.tick()["action"] == "none"  # calm tick 1
    router.set_load(4)
    assert c.tick()["action"] == "none"  # streak broken
    # Three CONSECUTIVE calm ticks are required.
    router.set_load(0)
    assert c.tick()["action"] == "none"
    assert c.tick()["action"] == "none"
    d = c.tick()
    assert d["action"] == "scale_down"
    assert len(router.pool()) == 2
    assert router.removed[0][1] == "scale_in"
    # The down cooldown gates the next shrink even with calm restored.
    clk[0] += 0.5
    for _ in range(3):
        d = c.tick()
    assert d["action"] == "hold" and d["reason"] == "cooldown_down"
    # Past it (the calm streak is long since satisfied), the pool
    # shrinks to the floor.
    clk[0] += 5.0
    d = c.tick()
    assert d["action"] == "scale_down" and len(router.pool()) == 1
    # At the floor, calm no longer wants anything.
    for _ in range(5):
        assert c.tick()["action"] == "none"


def test_controller_flap_suppressor_blocks_down_after_up():
    clk = [0.0]
    router = FakeRouter(1)
    c = _controller(router, clk, cooldown_s=1.0)  # flap window = 3s
    router.set_load(10)
    assert c.tick()["action"] == "scale_up"
    # The burst ends instantly — a reactive shrink now would flap.
    router.set_load(0)
    clk[0] = 1.5  # past the down cooldown, inside the flap window
    for _ in range(4):
        d = c.tick()
    assert d["action"] == "hold" and d["reason"] == "flap_suppressed"
    assert len(router.pool()) == 2
    # Once the suppression window passes, the shrink is allowed.
    clk[0] = 3.5
    actions = [c.tick()["action"] for _ in range(4)]
    assert "scale_down" in actions
    assert len(router.pool()) == 1


def test_controller_replaces_dead_and_wedged_replicas():
    clk = [0.0]
    router = FakeRouter(2)
    sink = ListSink()
    c = _controller(router, clk, sink=sink)
    # Dead: replaced immediately (no dwell), pool size preserved,
    # fresh id on the freed slot.
    router.replicas[0].server.alive = False
    d = c.tick()
    assert d["action"] == "replace" and d["reason"] == "dead"
    assert router.removed == [(0, "heal_dead")]
    assert len(router.pool()) == 2
    assert router.added == [2]  # fresh id, never 0 again
    # Wedged: needs the heal_after_s dwell first.
    clk[0] = 10.0
    router.replicas[0].server.verdict = "wedged"
    assert c.tick()["action"] == "none"  # dwell started, not elapsed
    clk[0] = 12.0
    assert c.tick()["action"] == "none"
    clk[0] = 16.0
    d = c.tick()
    assert d["action"] == "replace" and d["reason"] == "wedged"
    replaces = [
        r for r in sink.records if r["event"] == "replica_replace"
    ]
    assert len(replaces) == 2
    for rec in sink.records:
        assert events_registry.validate_record(rec) == []


def test_controller_replica_seconds_ledger():
    clk = [0.0]
    router = FakeRouter(2)
    c = _controller(router, clk)
    c.tick()
    clk[0] = 10.0
    c.tick()
    assert c.replica_seconds() == pytest.approx(20.0)
    router.set_load(10)
    c.tick()  # -> 3 replicas at t=10
    clk[0] = 20.0
    assert c.replica_seconds() == pytest.approx(20.0 + 30.0)


# --- drain-then-remove on the real tier ------------------------------------


def test_remove_replica_keeps_history_in_pool_rollup(setup, tmp_path):
    """The satellite-1 fix: a replica removed BEFORE drain must keep
    its requests and latency histogram in the final pool summary."""
    model, params, samples, _ = setup
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(samples[:1], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
        ).start()
        first = [router.submit(s) for s in samples[:8]]
        assert all(f.result(timeout=60).ok for f in first)
        removed_summary = router.remove_replica(0, timeout_s=10.0)
        with pytest.raises(ValueError, match="not in the pool"):
            router.remove_replica(0)
        with pytest.raises(ValueError, match="last replica"):
            router.remove_replica(1)
        second = [router.submit(s) for s in samples[8:12]]
        assert all(f.result(timeout=60).ok for f in second)
        summary = router.drain()
    # The removed replica really served something, and nothing was lost.
    assert removed_summary["requests"] > 0
    assert summary["shed"] == {}
    # History retention: pool totals include the retired replica...
    assert summary["requests"] == 12
    assert summary["completed"] == 12
    per = summary["per_replica"]
    assert set(per) == {"0", "1"}
    assert per["0"].get("retired") is True
    assert "retired" not in per["1"]
    # ...and the pool percentiles merge its histogram (population =
    # every request, not just the survivor's).
    assert summary["latency_p50_ms"] is not None
    assert summary["routing"]["removed"] == 1
    events = read_events(str(tmp_path / "serve.jsonl"))
    health = [
        e for e in events
        if e["event"] == "replica_health" and e["reason"] == "retiring"
    ]
    assert health and health[0]["replica"] == 0
    removes = [e for e in events if e["event"] == "replica_remove"]
    assert len(removes) == 1
    assert removes[0]["replica"] == 0
    assert removes[0]["reason"] == "scale_in"
    assert removes[0]["pool"] == 1
    # New ids only: a retired id cannot rejoin (its history is keyed).
    (fresh,) = _make_replicas(setup, 1, ids=[0])
    with pytest.raises(ValueError, match="retired"):
        router.add_replica(fresh)


def test_scale_in_migrates_resident_sessions_zero_lost(setup, tmp_path):
    """Graceful scale-in under a live session storm: every resident
    session hands over to the surviving replica at a step boundary and
    completes — zero lost, trajectories exact."""
    model, params, samples, engine = setup
    steps = 8
    traffic = samples[:6]
    reference = [
        offline_rollout(engine, s, steps, rows=MAX_BATCH)
        for s in traffic
    ]
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(traffic, rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
            session_snapshot_every=2,
        ).start()
        futs = [router.submit_rollout(s, steps) for s in traffic]
        # Let the storm take residence on both replicas, then retire
        # replica 0 while its sessions are mid-rollout.
        time.sleep(0.01)
        router.remove_replica(0, timeout_s=30.0)
        results = [f.result(timeout=120) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.session, r.reason) for r in results if not r.ok
    ]
    sess = summary["sessions"]
    assert sess["completed"] == len(traffic) and sess["lost"] == 0
    worst = 0.0
    for r, ref in zip(results, reference):
        for got, want in zip(r.outputs, ref):
            worst = max(worst, float(np.max(np.abs(got - want))))
    assert worst <= 1e-5
    events = read_events(str(tmp_path / "serve.jsonl"))
    moves = [
        e for e in events
        if e["event"] == "session_migrate" and e["reason"] == "scale_in"
    ]
    # Eviction happened through the planned handover path (how many
    # depends on placement; at least every session resident on 0).
    for e in moves:
        assert e["from_replica"] == 0 and e["to_replica"] == 1
        assert e["replay_from"] == e["at_step"]  # zero-replay handover


def test_scale_in_survives_replica_kill_mid_drain(setup, tmp_path):
    """The chaos bar: the retiring replica is KILLED while still
    handing sessions over — the failure-path migration catches what
    the planned handover had not moved yet. Zero lost sessions."""
    model, params, samples, _ = setup
    steps = 5
    traffic = samples[:6]
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm(traffic, rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            replicas, sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
            session_snapshot_every=2,
            faults={0: FaultInjector.from_spec("replica_kill@4")},
        ).start()
        futs = [router.submit_rollout(s, steps) for s in traffic]
        time.sleep(0.02)
        router.remove_replica(0, timeout_s=30.0)
        results = [f.result(timeout=120) for f in futs]
        summary = router.drain()
    assert all(r.ok for r in results), [
        (r.session, r.reason) for r in results if not r.ok
    ]
    assert summary["sessions"]["lost"] == 0
    assert summary["sessions"]["completed"] == len(traffic)


def test_autoscale_controller_scales_real_pool(setup, tmp_path):
    """End-to-end on the real tier: a burst grows the pool through the
    controller, the burst's tail does NOT flap it back down, and once
    the flap window passes the idle pool shrinks to the floor. The
    controller runs on a FAKE clock (manual ticks — the guard timings
    are deterministic) while the pool serves on the real one. All
    requests complete across the membership changes."""
    model, params, samples, _ = setup
    (r0,) = _make_replicas(setup, 1)
    r0.warm(samples[:2], rows=MAX_BATCH)
    clk = [0.0]
    sink = MetricsSink(str(tmp_path / "serve.jsonl"))
    with sink:
        router = ReplicaRouter(
            [r0], sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
        ).start()

        def factory(rid, slot):
            return build_replica(
                model, params, rid, jax.devices()[slot : slot + 1],
                batch_size=MAX_BATCH,
            )

        c = AutoscaleController(
            router,
            replica_factory=factory,
            min_replicas=1,
            max_replicas=2,
            cooldown_s=0.0,
            flap_suppress_s=1.0,
            up_load=4.0,
            down_load=1.0,
            down_ticks=2,
            warm_samples=samples[:2],
            sink=sink,
            clock=lambda: clk[0],
        )
        futs = [router.submit(s) for s in samples] + [
            router.submit(s) for s in samples
        ]
        d = c.tick()  # burst in flight: depth >> up_load
        assert d["action"] == "scale_up"
        assert len(router.pool()) == 2
        results = [f.result(timeout=60) for f in futs]
        # Burst over (pool idle) but inside the flap window: however
        # long the calm streak grows, the shrink stays vetoed.
        actions = [c.tick()["action"] for _ in range(4)]
        assert set(actions) <= {"none", "hold"}
        assert "scale_down" not in actions
        # Advance past the flap window: the calm streak is already
        # satisfied, the shrink lands.
        clk[0] = 2.0
        d = c.tick()
        assert d["action"] == "scale_down"
        assert len(router.pool()) == 1
        tail = [router.submit(s) for s in samples[:4]]
        results += [f.result(timeout=60) for f in tail]
        summary = router.drain()
    assert all(r.ok for r in results)
    assert summary["shed"] == {}
    assert summary["requests"] == 28 and summary["completed"] == 28
    events = read_events(str(tmp_path / "serve.jsonl"))
    kinds = {e["event"] for e in events}
    assert {"scale_up", "scale_down", "replica_remove",
            "autoscale_decision"} <= kinds
    for e in events:
        assert events_registry.validate_record(e) == []


# --- session resume across restarts ----------------------------------------


def _drain_after_steps(tier, fut, n_steps):
    """Consume ``n_steps`` streamed steps, then drain the tier — the
    session is mid-rollout by construction."""
    it = fut.iter_steps(timeout=60)
    for _ in range(n_steps):
        next(it)
    return tier.drain(10.0)


def test_session_store_roundtrip(setup, tmp_path):
    from gnot_tpu.serve.rollout import RolloutSession

    _, _, samples, _ = setup
    store = SessionStore(str(tmp_path / "sessions"))
    s = RolloutSession("alpha/1", samples[0], 4, snapshot_every=1)
    s.record_step(np.ones_like(samples[0].y))
    s.take_snapshot()
    store.save(s)
    assert store.names() == ["alpha/1"]  # the TRUE sid, from the meta
    state = store.load("alpha/1")
    assert state["cursor"] == 1 and state["steps"] == 4
    restored = RolloutSession.from_state(state)
    assert restored.cursor == 1 and restored.sid == "alpha/1"
    assert restored.named  # resumed sessions re-persist on drain
    np.testing.assert_array_equal(
        restored.sample.coords, s.sample.coords
    )
    # Distinct sids that SANITIZE identically must not share a file.
    twin = RolloutSession("alpha_1", samples[1], 4, snapshot_every=1)
    twin.take_snapshot()
    store.save(twin)
    assert sorted(store.names()) == ["alpha/1", "alpha_1"]
    assert store.load("alpha/1")["cursor"] == 1  # not clobbered
    assert store.load("alpha_1")["cursor"] == 0
    store.delete("alpha/1")
    assert store.load("alpha/1") is None
    assert store.load("alpha_1") is not None


def test_named_session_resumes_across_server_restart(setup, tmp_path):
    """The PR 13 stretch, server tier: drain mid-rollout persists the
    final carry snapshot; a FRESH server resumes the named session
    from its last snapshotted step and the full trajectory matches the
    offline loop exactly (zero re-delivery of the restored prefix)."""
    model, params, samples, engine = setup
    steps = 8
    sample = samples[0]
    reference = offline_rollout(engine, sample, steps, rows=MAX_BATCH)
    store = SessionStore(str(tmp_path / "sessions"))
    server = InferenceServer(
        engine, max_batch=MAX_BATCH, max_wait_ms=2.0,
        session_snapshot_every=1, session_store=store,
    ).start()
    fut = server.submit_rollout(sample, steps, name="cfd-run-7")
    with pytest.raises(ValueError, match="already resident"):
        server.submit_rollout(sample, steps, name="cfd-run-7")
    summary = _drain_after_steps(server, fut, 2)
    first = fut.result(timeout=10)
    assert not first.ok and first.reason == "drained"
    assert first.drained_at_step >= 2
    assert summary["sessions"]["drained"] == 1
    assert "cfd-run-7" in store.names()
    # "Restart": a brand-new server over the same engine + store.
    server2 = InferenceServer(
        engine, max_batch=MAX_BATCH, max_wait_ms=2.0,
        session_snapshot_every=1, session_store=store,
    ).start()
    streamed = []
    fut2 = server2.resume_rollout(
        "cfd-run-7", on_step=lambda sid, k, out: streamed.append(k)
    )
    result = fut2.result(timeout=60)
    server2.drain(10.0)
    assert result.ok and result.steps_completed == steps
    # The restored prefix is NOT re-streamed; only the new steps are.
    assert streamed == list(
        range(first.drained_at_step + 1, steps + 1)
    )
    worst = max(
        float(np.max(np.abs(got - want)))
        for got, want in zip(result.outputs, reference)
    )
    assert worst <= 1e-5
    # Completion cleans the store (a later resume must not replay).
    assert store.load("cfd-run-7") is None
    with pytest.raises(KeyError):
        server2.resume_rollout("cfd-run-7")


def test_named_session_resumes_across_router_restart(setup, tmp_path):
    """Router tier: the persisted snapshot written by one pool's drain
    resumes on a COMPLETELY new pool (fresh replicas), with the
    resume placed like any session (a route event tagged with the
    session name)."""
    model, params, samples, engine = setup
    steps = 6
    sample = samples[1]
    reference = offline_rollout(engine, sample, steps, rows=MAX_BATCH)
    store = SessionStore(str(tmp_path / "sessions"))
    replicas = _make_replicas(setup, 2)
    for r in replicas:
        r.warm([sample], rows=MAX_BATCH)
    router = ReplicaRouter(
        replicas, max_batch=MAX_BATCH, max_wait_ms=2.0,
        session_store=store,
    ).start()
    fut = router.submit_rollout(sample, steps, name="restartable")
    _drain_after_steps(router, fut, 1)
    assert not fut.result(timeout=10).ok
    assert "restartable" in store.names()
    replicas2 = _make_replicas(setup, 2, ids=[10, 11])
    for r in replicas2:
        r.warm([sample], rows=MAX_BATCH)
    sink = MetricsSink(str(tmp_path / "serve2.jsonl"))
    with sink:
        router2 = ReplicaRouter(
            replicas2, sink=sink, max_batch=MAX_BATCH, max_wait_ms=2.0,
            session_store=store,
        ).start()
        with pytest.raises(KeyError):
            router2.resume_rollout("never-existed")
        fut2 = router2.resume_rollout("restartable")
        result = fut2.result(timeout=60)
        router2.drain(10.0)
    assert result.ok and result.steps_completed == steps
    worst = max(
        float(np.max(np.abs(got - want)))
        for got, want in zip(result.outputs, reference)
    )
    assert worst <= 1e-5
    routes = [
        e for e in read_events(str(tmp_path / "serve2.jsonl"))
        if e["event"] == "route"
    ]
    assert any(e.get("session") == "restartable" for e in routes)


# --- the committed A/B tool ------------------------------------------------


@pytest.mark.slow
def test_autoscale_ab_quick_smoke(tmp_path):
    """tools/autoscale_ab.py --quick end-to-end (wiring + the chaos and
    efficiency invariants; the committed artifact's timing bars are
    pinned by test_artifacts — --quick compresses the diurnal ramp
    beyond what any reactive controller tracks)."""
    import autoscale_ab

    out = str(tmp_path / "ab.jsonl")
    summary = autoscale_ab.run(["--quick", "--out", out])
    assert summary["failures"] == []
    assert summary["chaos_lost_sessions"] == 0
    assert summary["chaos_lost_requests"] == 0
    assert summary["scale_ups"] >= 1
    assert (
        summary["replica_seconds_autoscaled"]
        < summary["replica_seconds_static"]
    )
