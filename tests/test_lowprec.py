"""Low-precision serving numerics: the precision policy, pinned.

The policy (``models/precision.py``) says exactly where bf16 is
allowed: block matmuls and activations. Everything normalization- or
metric-critical stays f32 — einsum ACCUMULATION, the attention
normalizer ``1/<q, k_sum>``, and the output head. These tests pin each
clause the way the static-analysis suite pins its rules: a conforming
path must meet the parity bar, and a MUTATED path (the bf16 normalizer
the policy forbids) must violate it — proving the bar actually guards
the clause instead of being slack enough to pass anything.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gnot_tpu.models import precision
from gnot_tpu.ops.attention import (
    feature_softmax,
    normalized_linear_attention,
    packed_normalized_linear_attention,
    segment_one_hot,
)

#: The bf16-vs-f32 relative-error bar for one attention op on bf16
#: inputs under the policy (f32 accumulation + f32 normalizer). The
#: bf16 INPUT quantization alone costs ~2^-9 ~ 2e-3; the policy path
#: must stay at that floor, and the forbidden bf16-normalizer mutant
#: measurably exceeds it (the mutation test below).
ATTN_REL_BAR = 3.5e-3


def _qkv(seed=0, b=2, h=2, l=2048, d=8):
    rng = np.random.default_rng(seed)
    q = feature_softmax(jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32))
    k = feature_softmax(jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32))
    v = jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32)
    mask = jnp.asarray((rng.uniform(size=(b, l)) < 0.8).astype(np.float32))
    return q, k, v, mask


def _rel(a, ref):
    return float(jnp.linalg.norm(a - ref) / jnp.linalg.norm(ref))


# -- the policy object itself ---------------------------------------------


def test_policy_pins_f32_sites():
    pol = precision.policy_for("bfloat16")
    assert pol.compute_dtype == "bfloat16"
    assert pol.weights_dtype == "bfloat16"
    assert pol.accum_dtype == pol.normalizer_dtype == pol.head_dtype == "float32"
    assert pol.tag == "bf16"
    # The RelL2-critical sites are FROZEN policy, not knobs.
    for site in ("accum_dtype", "normalizer_dtype", "head_dtype"):
        with pytest.raises(ValueError, match="must stay float32"):
            dataclasses.replace(pol, **{site: "bfloat16"})
    with pytest.raises(ValueError, match="unknown serve dtype"):
        precision.policy_for("float16")
    # The docs table renders one row per policy site.
    assert len(pol.table()) == 5


def test_cast_params_is_identity_for_f32_and_copy_for_bf16():
    params = {"dense": {"kernel": jnp.ones((4, 4), jnp.float32),
                        "steps": jnp.asarray(3, jnp.int32)}}
    assert precision.cast_params(params, "float32") is params
    cast = precision.cast_params(params, "bfloat16")
    assert cast["dense"]["kernel"].dtype == jnp.bfloat16
    assert cast["dense"]["steps"].dtype == jnp.int32  # non-float untouched
    # The caller's tree is never mutated (params stay f32 at rest).
    assert params["dense"]["kernel"].dtype == jnp.float32


# -- f32 accumulation + normalizer in the attention ops -------------------


def test_bf16_attention_meets_policy_bar():
    q, k, v, mask = _qkv()
    ref = normalized_linear_attention(q, k, v, kv_mask=mask)
    out = normalized_linear_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16), kv_mask=mask,
    )
    # The op hands its compute dtype back; the f32 head casts later.
    assert out.dtype == jnp.bfloat16
    assert _rel(out.astype(jnp.float32), ref) <= ATTN_REL_BAR


def test_f32_attention_is_bitwise_unchanged():
    """The policy branch must not perturb the f32 path at all — same
    einsums, no preferred_element_type, bit-for-bit."""
    q, k, v, mask = _qkv(l=256)

    def legacy(q, k, v, kv_mask):
        k = k * kv_mask[:, None, :, None].astype(k.dtype)
        k_sum = jnp.sum(k, axis=2)
        denom = jnp.einsum("bhld,bhd->bhl", q, k_sum)
        denom = jnp.where(denom == 0.0, 1.0, denom)
        alpha = 1.0 / denom
        kv = jnp.einsum("bhld,bhle->bhde", k, v)
        out = jnp.einsum("bhld,bhde->bhle", q, kv)
        return alpha[..., None] * out

    np.testing.assert_array_equal(
        np.asarray(normalized_linear_attention(q, k, v, kv_mask=mask)),
        np.asarray(legacy(q, k, v, mask)),
    )


def test_mutation_bf16_normalizer_is_caught_by_the_bar():
    """Mutation-style rule pin: recompute the SAME attention with the
    policy-forbidden bf16 normalizer (bf16 k_sum accumulation + bf16
    denominator — the pre-policy math on bf16 inputs). The parity bar
    that the conforming op meets must CATCH the mutant; if this test
    ever fails because the mutant passes the bar, the bar is slack and
    guards nothing."""
    q, k, v, mask = _qkv()
    ref = normalized_linear_attention(q, k, v, kv_mask=mask)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def mutant(q, k, v, kv_mask):
        k = k * kv_mask[:, None, :, None].astype(k.dtype)
        k_sum = jnp.sum(k, axis=2)  # bf16 accumulation — forbidden
        denom = jnp.einsum("bhld,bhd->bhl", q, k_sum)  # bf16 normalizer
        denom = jnp.where(denom == 0.0, 1.0, denom)
        kv = jnp.einsum("bhld,bhle->bhde", k, v)
        out = jnp.einsum("bhld,bhde->bhle", q, kv)
        return out / denom[..., None]

    rel_policy = _rel(
        normalized_linear_attention(qb, kb, vb, kv_mask=mask).astype(
            jnp.float32
        ),
        ref,
    )
    rel_mutant = _rel(mutant(qb, kb, vb, mask).astype(jnp.float32), ref)
    assert rel_policy <= ATTN_REL_BAR
    assert rel_mutant > ATTN_REL_BAR, (
        f"bf16-normalizer mutant ({rel_mutant}) passes the "
        f"{ATTN_REL_BAR} bar — the bar no longer guards the policy"
    )
    assert rel_mutant > 1.3 * rel_policy


def test_bf16_packed_attention_meets_policy_bar():
    rng = np.random.default_rng(3)
    b, h, n, c, d, s = 1, 2, 8, 128, 8, 5
    l = n * c
    q = feature_softmax(jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32))
    k = feature_softmax(jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32))
    v = jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32)
    seg = jnp.asarray(rng.integers(0, s, size=(b, n)), jnp.int32)
    oh = segment_one_hot(seg, s)
    ref = packed_normalized_linear_attention(
        q, k, v, q_seg_oh=oh, kv_seg_oh=oh
    )
    out = packed_normalized_linear_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16), q_seg_oh=oh, kv_seg_oh=oh,
    )
    assert out.dtype == jnp.bfloat16
    assert _rel(out.astype(jnp.float32), ref) <= ATTN_REL_BAR


# -- model-level policy: f32 head, f32-at-rest params ---------------------


def _tiny_model_and_batch():
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples), 0)
    return model, params, samples


def test_serve_model_outputs_f32_head_under_bf16():
    from gnot_tpu.train.trainer import apply_batch
    from gnot_tpu.data.batch import collate

    model, params, samples = _tiny_model_and_batch()
    bf_model = precision.serve_model(model, "bfloat16")
    assert bf_model.config.dtype == "bfloat16"
    assert precision.serve_model(model, "float32") is model
    batch32 = collate(samples)
    batch16 = collate(samples, dtype="bfloat16")
    assert batch16.coords.dtype == precision.np_dtype("bfloat16")
    ref = np.asarray(apply_batch(model, params, batch32))
    out = np.asarray(
        apply_batch(
            bf_model, precision.cast_params(params, "bfloat16"), batch16
        )
    )
    # Output head is f32 by policy — whatever the stack computed in.
    assert out.dtype == np.float32
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 2e-2, f"bf16 forward rel err {rel}"


def test_engine_bf16_publishes_cast_copy_and_keeps_rest_f32():
    from gnot_tpu.serve import InferenceEngine

    model, params, samples = _tiny_model_and_batch()
    eng = InferenceEngine(model, params, batch_size=4, dtype="bfloat16")
    pub = jax.tree.leaves(eng.params)[0].dtype
    assert pub == jnp.bfloat16
    # ... while the tree the caller handed over is untouched f32.
    assert all(
        l.dtype == jnp.float32 for l in jax.tree.leaves(params)
    )
    # Hot reload hands over f32 again; publish casts again.
    eng.swap_params(params)
    assert jax.tree.leaves(eng.params)[0].dtype == jnp.bfloat16
    # Responses are f32 (the policy head) and close to the f32 engine.
    f32 = InferenceEngine(model, params, batch_size=4)
    key = f32.bucket_key(samples[0])
    a = f32.infer([samples[0]], pad_nodes=key[0], pad_funcs=key[1], rows=4)[0]
    b = eng.infer([samples[0]], pad_nodes=key[0], pad_funcs=key[1], rows=4)[0]
    assert b.dtype == np.float32
    assert np.linalg.norm(b - a) / np.linalg.norm(a) < 2e-2


def test_dispatch_signatures_are_dtype_keyed():
    """An f32 and a bf16 program at the SAME shapes are two programs:
    signature_of carries leaf dtypes, so the AOT table and the
    compiled-shapes ledger cannot collide them."""
    from gnot_tpu.data.batch import collate
    from gnot_tpu.serve.engine import InferenceEngine

    _, _, samples = _tiny_model_and_batch()
    s32 = InferenceEngine.signature_of(collate(samples))
    s16 = InferenceEngine.signature_of(collate(samples, dtype="bfloat16"))
    assert [shape for shape, _ in s32] == [shape for shape, _ in s16]
    assert s32 != s16
