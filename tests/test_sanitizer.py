"""Donation sanitizer (utils/sanitizer.py): mode selection, by-value
guarded device_get, poison-mode forensics, and the guard-off
byte-identity contract (ISSUE 11).

Tier-1 itself runs with GNOT_ALIAS_GUARD=1 (tests/conftest.py), so
every test here that flips the mode restores the ambient one.
"""

import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.utils import sanitizer


@pytest.fixture
def set_mode():
    """Flip GNOT_ALIAS_GUARD + reinstall; restore the ambient mode
    (tier-1's copy mode) afterwards."""
    prev = os.environ.get("GNOT_ALIAS_GUARD")

    def _set(value: str) -> str:
        os.environ["GNOT_ALIAS_GUARD"] = value
        return sanitizer.install()

    yield _set
    if prev is None:
        os.environ.pop("GNOT_ALIAS_GUARD", None)
    else:
        os.environ["GNOT_ALIAS_GUARD"] = prev
    sanitizer.install()
    sanitizer.clear_registry()


def _donating_step():
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(x):
        return x + 1.0

    return step


def test_mode_parsing(set_mode):
    assert set_mode("0") == "off"
    assert set_mode("off") == "off"
    assert set_mode("1") == "copy"
    assert set_mode("copy") == "copy"
    assert set_mode("on") == "copy"
    assert set_mode("poison") == "poison"


def test_copy_mode_device_get_is_by_value(set_mode):
    """Guarded device_get returns OWNED arrays: no later donation can
    touch the snapshot — the bug class is gone by construction."""
    assert set_mode("1") == "copy"
    x = jnp.arange(4096, dtype=jnp.float32)
    tree = {"a": x, "b": jnp.ones((8, 8), jnp.float32)}
    host = jax.device_get(tree)
    for leaf in jax.tree.leaves(host):
        assert isinstance(leaf, np.ndarray)
        assert leaf.flags.owndata, "copy mode must return owned memory"
    before = np.array(host["a"])
    step = _donating_step()
    step(x)  # donate x's buffers
    np.testing.assert_array_equal(host["a"], before)


def test_off_mode_is_byte_identical(set_mode):
    """Guard off: jax.device_get is the ORIGINAL function object and
    guard_donating returns the callable itself — zero wrapper frames,
    zero behavior change (the A/B artifact pins the measured side)."""
    assert set_mode("0") == "off"
    assert jax.device_get is sanitizer._orig_device_get
    step = _donating_step()
    assert sanitizer.guard_donating(step) is step
    x = jnp.arange(1024, dtype=jnp.float32)
    host = jax.device_get(x)
    # Off mode preserves today's zero-copy semantics (the view, not a
    # copy) on the CPU backend.
    assert not host.flags.owndata


def test_copy_mode_guard_donating_is_identity(set_mode):
    """Copy mode needs no dispatch wrapper (there are no views to
    poison): the hot path stays the bare jitted callable."""
    assert set_mode("1") == "copy"
    step = _donating_step()
    assert sanitizer.guard_donating(step) is step


def test_poison_mode_stale_view_turns_nan(set_mode):
    """The forensic contract: a zero-copy device_get view that
    survives a donating dispatch is overwritten with the NaN sentinel
    and the warning names the view's creation site."""
    assert set_mode("poison") == "poison"
    sanitizer.clear_registry()
    x = jnp.arange(4096, dtype=jnp.float32) + 1.0
    host = jax.device_get(x)  # zero-copy view, registered
    if host.flags.owndata:  # pragma: no cover — non-zero-copy backend
        pytest.skip("backend returned a copy; nothing to poison")
    assert sanitizer.stale_view_count() == 1
    step = sanitizer.guard_donating(_donating_step())
    with pytest.warns(UserWarning, match="stale host view"):
        step(x)
    # The stale read below IS the poison-mode contract under test.
    assert np.all(np.isnan(host))  # graftlint: disable=GL006 — deliberate use-after-donate fixture
    assert sanitizer.stale_view_count() == 0


def test_poison_mode_copied_snapshot_untouched(set_mode):
    """The committed fix pattern (np.array copies) must sail through
    poison mode: owned memory is never registered, never poisoned."""
    assert set_mode("poison") == "poison"
    sanitizer.clear_registry()
    x = jnp.arange(1024, dtype=jnp.float32)
    snap = np.array(jax.device_get(x))
    before = np.array(snap)
    step = sanitizer.guard_donating(_donating_step())
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any poison warning -> failure
        step(x)
    np.testing.assert_array_equal(snap, before)


def test_poison_mode_rebound_view_is_not_poisoned(set_mode):
    """Views of buffers NOT donated stay intact: donation poisons only
    the donated argument's registered views."""
    assert set_mode("poison") == "poison"
    sanitizer.clear_registry()
    x = jnp.arange(512, dtype=jnp.float32)
    other = jnp.ones(512, jnp.float32) * 7.0
    host_other = jax.device_get(other)
    step = sanitizer.guard_donating(_donating_step())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step(x)
    np.testing.assert_array_equal(
        np.asarray(host_other), np.full(512, 7.0, np.float32)
    )


def test_host_fetch_modes(set_mode):
    x = jnp.ones((16, 16), jnp.float32)
    assert set_mode("0") == "off"
    off = sanitizer.host_fetch(x)
    assert isinstance(off, np.ndarray)
    assert set_mode("1") == "copy"
    copied = sanitizer.host_fetch(x)
    assert copied.flags.owndata
    np.testing.assert_array_equal(copied, np.asarray(off))


def test_poison_wrapper_disarms_with_the_mode(set_mode):
    """A step wrapped under poison must go fully inert when install()
    leaves poison: no memsets, no warnings, registry dropped — the
    off-mode contract holds for already-built objects too."""
    assert set_mode("poison") == "poison"
    sanitizer.clear_registry()
    step = sanitizer.guard_donating(_donating_step())
    x = jnp.arange(1024, dtype=jnp.float32)
    host = jax.device_get(x)  # registered under poison
    assert set_mode("0") == "off"
    assert sanitizer.stale_view_count() == 0  # registry cleared on exit
    before = np.array(host)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        step(x)  # wrapped object, disarmed mode: bare-step behavior
    np.testing.assert_array_equal(np.asarray(host), before)  # graftlint: disable=GL006 — deliberate: asserts the DISARMED guard no longer poisons this stale view


def test_late_poison_install_warns_about_unguarded_builds(set_mode):
    """Arming poison AFTER donating dispatches were built unguarded is
    a silent no-op for those objects — install() must say so."""
    assert set_mode("1") == "copy"
    step = _donating_step()
    assert sanitizer.guard_donating(step) is step  # built unguarded
    with pytest.warns(UserWarning, match="built\\s+unguarded"):
        assert set_mode("poison") == "poison"


def test_guard_donating_forwards_cache_size(set_mode):
    """The recompile monitor keys on _cache_size; the poison wrapper
    must not blind it."""
    assert set_mode("poison") == "poison"
    step = _donating_step()
    wrapped = sanitizer.guard_donating(step)
    assert wrapped is not step
    assert callable(getattr(wrapped, "_cache_size", None)) == callable(
        getattr(step, "_cache_size", None)
    )


def test_trainer_steps_identity_under_copy_mode(set_mode):
    """Trainer.initialize routes its steps through guard_donating: in
    tier-1's copy mode that is the bare jitted step (no wrapper), and
    a fit() epoch trains normally with the guard live."""
    assert set_mode("1") == "copy"
    from tests.test_trainer import small_setup

    cfg, mc, train, test = small_setup(epochs=1)
    from gnot_tpu.train.trainer import Trainer

    t = Trainer(cfg, mc, train, test)
    t.initialize()
    assert callable(getattr(t.train_step, "_cache_size", None)), (
        "copy mode must keep the bare jitted step"
    )
    t.fit()
    assert np.isfinite(t.best_metric)
