"""True multi-process distributed training: 2 processes x 2 CPU devices.

Everything else in the suite runs single-process (8 virtual devices in
one process). This test exercises the real multi-controller path —
``jax.distributed.initialize``, per-host dataset sharding, fixed
dataset-wide pads, ``global_batch`` assembly via
``make_array_from_process_local_data``, and the hybrid DCNxICI mesh —
by launching two actual OS processes and asserting they emit
IDENTICAL, finite epoch losses and eval metrics (SPMD: every process
computes the same global numbers).
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)
from gnot_tpu.main import main
best = main([
    "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
    "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
    "--n_head", "2", "--epochs", "2", "--n_train", "8", "--n_test", "8",
    "--batch_size", "2",  # per-host: global batch 4 over the data axis
    "--synthetic", "ns2d", "--distributed", "--mesh_data", "4",
])
print(f"WORKER_BEST {best}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distributed_training(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = str(_free_port())

    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd="/root/repo",
            env={**os.environ, "PYTHONPATH": "/root/repo"},
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # don't leak workers stuck in a collective
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"

    def lines(out, pat):
        return re.findall(pat, out)

    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
        r"WORKER_BEST ([\d.eE+-]+)",
    ):
        a, b = lines(outs[0], pat), lines(outs[1], pat)
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)
