"""True multi-process distributed training: 2 processes x 2 CPU devices.

Everything else in the suite runs single-process (8 virtual devices in
one process). These tests exercise the real multi-controller path —
``jax.distributed.initialize``, per-host dataset sharding, fixed
dataset-wide pads, ``global_batch`` assembly via
``make_array_from_process_local_data``, the hybrid DCNxICI mesh,
Orbax checkpoint/resume across processes, and distributed predict —
by launching actual OS processes and asserting SPMD invariants
(every process computes the same global numbers).
"""

import os
import pickle
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); n_procs = int(sys.argv[2])
n_devices = int(sys.argv[3]); port = sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
# Worker processes are fresh interpreters: set the device count BEFORE
# jax imports, via XLA_FLAGS, which every jax version honors
# (jax_num_cpu_devices does not exist on 0.4.x builds).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", n_devices)
except AttributeError:
    pass  # XLA_FLAGS above already provisioned the devices
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=n_procs, process_id=proc_id
)
# Establish the gloo CPU-collectives context NOW, while the processes
# are still in lockstep from initialize(): its TCP handshake has a
# hard 30s window, and on a loaded single-core host the compile-time
# skew before the first *training* collective can exceed that. Must be
# a REAL device collective over all devices (sync_global_devices is a
# coordination-service barrier and never touches gloo); this trivial
# all-reduce compiles in ~1s, so the context is built while skew is
# still tiny and every later collective reuses the TCP mesh.
import numpy as _np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
_mesh = Mesh(_np.array(jax.devices()), ("d",))
_x = jax.device_put(
    jnp.ones((len(jax.devices()),), jnp.float32), NamedSharding(_mesh, P("d"))
)
_np.asarray(
    jax.jit(jnp.sum, out_shardings=NamedSharding(_mesh, P()))(_x)
)
from gnot_tpu.main import main
best = main(sys.argv[5:])
print(f"WORKER_BEST {best}")
"""

BASE_ARGS = [
    "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
    "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
    "--n_head", "2", "--n_train", "8", "--n_test", "8",
    "--batch_size", "2",  # per-host: global batch 4 over the data axis
    "--synthetic", "ns2d", "--distributed", "--mesh_data", "4",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_procs(
    tmp_path, cli_args: list[str], n_procs: int = 2, n_devices: int = 2
) -> list[str]:
    """Launch the worker in ``n_procs`` coordinated OS processes with
    ``n_devices`` virtual CPU devices each; return their stdouts
    (asserting all exited 0)."""
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = str(_free_port())
    # Shared on-disk jit cache: repeat launches (preempt/resume runs)
    # skip recompiles, which keeps cross-process compile-time skew
    # under gloo's TCP connect window on a loaded single-core host.
    cache = tmp_path / "jitcache"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(n_procs), str(n_devices),
             port, *cli_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd="/root/repo",
            env={**os.environ, "PYTHONPATH": "/root/repo",
                 "JAX_COMPILATION_CACHE_DIR": str(cache),
                 "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0"},
        )
        for i in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # don't leak workers stuck in a collective
            if p.poll() is None:
                p.kill()
                p.wait()
    if any(p.returncode != 0 for p in procs):
        # The root cause usually lives in ANOTHER process than the one
        # that reports a coordination-barrier failure — show them all.
        detail = "\n".join(
            f"--- process {i} (rc={p.returncode}) ---\n{out[-2000:]}"
            for i, (p, out) in enumerate(zip(procs, outs))
        )
        raise AssertionError(f"worker process(es) failed:\n{detail}")
    return outs


def _run_pair(tmp_path, cli_args: list[str]) -> list[str]:
    return _run_procs(tmp_path, cli_args, n_procs=2)


def test_two_process_distributed_training(tmp_path):
    outs = _run_pair(tmp_path, BASE_ARGS + ["--epochs", "2"])

    def lines(out, pat):
        return re.findall(pat, out)

    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
        r"WORKER_BEST ([\d.eE+-]+)",
    ):
        a, b = lines(outs[0], pat), lines(outs[1], pat)
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)


def test_two_process_expert_parallel(tmp_path):
    """Expert parallelism under ``jax.distributed``: global mesh
    data=2 x expert=2 over 2 hosts x 2 devices (each host's devices
    split the expert stack; the gated-combine psum rides inside the
    host, the gradient psum crosses hosts)."""
    args = [
        "--n_attn_layers", "1", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--n_train", "8", "--n_test", "8", "--batch_size", "2",
        "--synthetic", "ns2d", "--distributed",
        "--mesh_data", "2", "--mesh_expert", "2", "--epochs", "2",
    ]
    outs = _run_pair(tmp_path, args)
    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
    ):
        a, b = re.findall(pat, outs[0]), re.findall(pat, outs[1])
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)


def test_two_process_pipeline_parallel(tmp_path):
    """Pipeline parallelism under ``jax.distributed``: global mesh
    data=2 x pipe=2 over 2 hosts x 2 devices (the pipe axis stays
    inside each host). Covers the pipeline-layout param path end to
    end: SPMD-identical losses, predict and torch export through
    ``gathered_standard_params`` (allgather the stacked block tree,
    THEN unstack — eager indexing into non-fully-addressable arrays
    would raise)."""
    pred, pth = str(tmp_path / "pred.pkl"), str(tmp_path / "model.pth")
    args = [
        "--n_attn_layers", "2", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--n_train", "8", "--n_test", "8", "--batch_size", "2",
        "--synthetic", "ns2d", "--distributed",
        "--mesh_data", "2", "--mesh_pipe", "2",
        "--epochs", "2", "--predict_out", pred, "--export_torch", pth,
    ]
    outs = _run_pair(tmp_path, args)
    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
    ):
        a, b = re.findall(pat, outs[0]), re.findall(pat, outs[1])
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)
    with open(pred, "rb") as f:
        recs = pickle.load(f)
    assert len(recs) == 8
    torch = pytest.importorskip("torch")
    sd = torch.load(pth, weights_only=True)
    # standard reference layout: per-block attention params present
    assert any("attention_layers.1" in k or "block" in k.lower() for k in sd)


def test_two_process_checkpoint_resume_and_predict(tmp_path):
    """Checkpoint/resume and predict under ``jax.distributed``:

    * a 2-epoch run is 'preempted', then resumed to 4 epochs — the
      resumed epochs' losses must equal a continuous 4-epoch run's
      (Orbax save/restore across processes + seeded shuffle replay);
    * both runs write predictions from the best checkpoint (a params
      allgather collective); the files must agree.
    """
    d_cont, d_int = str(tmp_path / "cont"), str(tmp_path / "int")
    p_cont, p_res = str(tmp_path / "pred_cont.pkl"), str(tmp_path / "pred_res.pkl")

    pth = str(tmp_path / "model.pth")
    outs_c = _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_cont, "--checkpoint_every", "1",
           "--predict_out", p_cont, "--export_torch", pth],
    )
    # Same 4-epoch regime (the OneCycle schedule is sized by --epochs),
    # preempted after epoch 1 via fault injection.
    _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_int, "--checkpoint_every", "1",
           "--stop_after_epoch", "2"],
    )
    outs_r = _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_int, "--checkpoint_every", "1",
           "--resume", "--predict_out", p_res],
    )

    pat = r"Epoch (\d+), Loss: ([\d.eE+-]+)"
    cont = dict(re.findall(pat, outs_c[0]))
    res = dict(re.findall(pat, outs_r[0]))
    assert set(res) == {"2", "3"}, f"resume should replay epochs 2-3, got {sorted(res)}"
    for e in ("2", "3"):
        np.testing.assert_allclose(
            float(res[e]), float(cont[e]), rtol=1e-5,
            err_msg=f"resumed epoch {e} loss diverges from continuous run",
        )

    # Predictions: written by process 0 only, identical across runs.
    with open(p_cont, "rb") as f:
        recs_c = pickle.load(f)
    with open(p_res, "rb") as f:
        recs_r = pickle.load(f)
    assert len(recs_c) == len(recs_r) == 8
    for rc, rr in zip(recs_c, recs_r):
        np.testing.assert_allclose(rc[1], rr[1], rtol=1e-5, atol=1e-6)

    # --export_torch under jax.distributed: the gathered state_dict is a
    # loadable torch artifact (written by process 0).
    torch = pytest.importorskip("torch")
    sd = torch.load(pth, weights_only=True)
    assert sd and all(v.ndim in (1, 2) for v in sd.values())


def test_four_process_composed_mesh_checkpoint_resume(tmp_path):
    """The composed data x model x pipe mesh across 4 REAL OS processes
    (4 procs x 4 devices = 16 global devices, mesh data=4 x model=2 x
    pipe=2; the hybrid-mesh rule keeps model/pipe inside each host, so
    the data axis crosses all four hosts) — the config likeliest to
    break on a real pod: process-order global-batch assembly on the
    data axis while the pipe axis shards the layer-stacked params.

    Asserts (a) all four processes print identical global losses and
    metrics, (b) a run preempted after epoch 0 (``--stop_after_epoch
    1`` stops once ``epoch + 1 >= 1``, i.e. with exactly one epoch
    completed) and resumed replays epoch 1 exactly as the continuous
    run (Orbax save/restore of the PIPE-SHARDED TrainState across 4
    processes + seeded shuffle replay)."""
    composed = [
        "--n_attn_layers", "2", "--n_attn_hidden_dim", "8",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "8",
        "--n_input_hidden_dim", "8", "--n_expert", "2", "--n_head", "2",
        "--n_train", "8", "--n_test", "4", "--batch_size", "2",
        "--synthetic", "ns2d", "--distributed",
        "--mesh_data", "4", "--mesh_model", "2", "--mesh_pipe", "2",
    ]
    d_cont, d_int = str(tmp_path / "cont4"), str(tmp_path / "int4")

    outs_c = _run_procs(
        tmp_path,
        composed + ["--epochs", "2", "--checkpoint_dir", d_cont,
                    "--checkpoint_every", "1"],
        n_procs=4, n_devices=4,
    )
    pat_loss = r"Epoch (\d+), Loss: ([\d.eE+-]+)"
    pat_metric = r"Epoch \d+, Test Metric: ([\d.eE+-]+)"
    # (a) SPMD invariant: identical console numbers on all 4 processes.
    for pat in (pat_loss, pat_metric, r"WORKER_BEST ([\d.eE+-]+)"):
        series = [re.findall(pat, o) for o in outs_c]
        assert series[0], f"no matches for {pat}"
        for i, s in enumerate(series[1:], 1):
            assert s == series[0], f"process {i} diverges for {pat}"
    # (b) preempt with one epoch completed, resume, compare the
    # replayed epoch.
    _run_procs(
        tmp_path,
        composed + ["--epochs", "2", "--checkpoint_dir", d_int,
                    "--checkpoint_every", "1", "--stop_after_epoch", "1"],
        n_procs=4, n_devices=4,
    )
    outs_r = _run_procs(
        tmp_path,
        composed + ["--epochs", "2", "--checkpoint_dir", d_int,
                    "--checkpoint_every", "1", "--resume"],
        n_procs=4, n_devices=4,
    )
    cont = dict(re.findall(pat_loss, outs_c[0]))
    res = dict(re.findall(pat_loss, outs_r[0]))
    assert set(res) == {"1"}, f"resume should replay epoch 1 only, got {sorted(res)}"
    np.testing.assert_allclose(
        float(res["1"]), float(cont["1"]), rtol=1e-5,
        err_msg="resumed epoch 1 loss diverges from continuous 4-process run",
    )
