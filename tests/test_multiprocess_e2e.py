"""True multi-process distributed training: 2 processes x 2 CPU devices.

Everything else in the suite runs single-process (8 virtual devices in
one process). These tests exercise the real multi-controller path —
``jax.distributed.initialize``, per-host dataset sharding, fixed
dataset-wide pads, ``global_batch`` assembly via
``make_array_from_process_local_data``, the hybrid DCNxICI mesh,
Orbax checkpoint/resume across processes, and distributed predict —
by launching actual OS processes and asserting SPMD invariants
(every process computes the same global numbers).
"""

import os
import pickle
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)
from gnot_tpu.main import main
best = main(sys.argv[3:])
print(f"WORKER_BEST {best}")
"""

BASE_ARGS = [
    "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
    "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
    "--n_head", "2", "--n_train", "8", "--n_test", "8",
    "--batch_size", "2",  # per-host: global batch 4 over the data axis
    "--synthetic", "ns2d", "--distributed", "--mesh_data", "4",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pair(tmp_path, cli_args: list[str]) -> list[str]:
    """Launch the worker in 2 coordinated OS processes; return their
    stdouts (asserting both exited 0)."""
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port, *cli_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd="/root/repo",
            env={**os.environ, "PYTHONPATH": "/root/repo"},
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # don't leak workers stuck in a collective
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    return outs


def test_two_process_distributed_training(tmp_path):
    outs = _run_pair(tmp_path, BASE_ARGS + ["--epochs", "2"])

    def lines(out, pat):
        return re.findall(pat, out)

    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
        r"WORKER_BEST ([\d.eE+-]+)",
    ):
        a, b = lines(outs[0], pat), lines(outs[1], pat)
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)


def test_two_process_expert_parallel(tmp_path):
    """Expert parallelism under ``jax.distributed``: global mesh
    data=2 x expert=2 over 2 hosts x 2 devices (each host's devices
    split the expert stack; the gated-combine psum rides inside the
    host, the gradient psum crosses hosts)."""
    args = [
        "--n_attn_layers", "1", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--n_train", "8", "--n_test", "8", "--batch_size", "2",
        "--synthetic", "ns2d", "--distributed",
        "--mesh_data", "2", "--mesh_expert", "2", "--epochs", "2",
    ]
    outs = _run_pair(tmp_path, args)
    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
    ):
        a, b = re.findall(pat, outs[0]), re.findall(pat, outs[1])
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)


def test_two_process_pipeline_parallel(tmp_path):
    """Pipeline parallelism under ``jax.distributed``: global mesh
    data=2 x pipe=2 over 2 hosts x 2 devices (the pipe axis stays
    inside each host). Covers the pipeline-layout param path end to
    end: SPMD-identical losses, predict and torch export through
    ``gathered_standard_params`` (allgather the stacked block tree,
    THEN unstack — eager indexing into non-fully-addressable arrays
    would raise)."""
    pred, pth = str(tmp_path / "pred.pkl"), str(tmp_path / "model.pth")
    args = [
        "--n_attn_layers", "2", "--n_attn_hidden_dim", "16",
        "--n_mlp_num_layers", "1", "--n_mlp_hidden_dim", "16",
        "--n_input_hidden_dim", "16", "--n_expert", "2", "--n_head", "2",
        "--n_train", "8", "--n_test", "8", "--batch_size", "2",
        "--synthetic", "ns2d", "--distributed",
        "--mesh_data", "2", "--mesh_pipe", "2",
        "--epochs", "2", "--predict_out", pred, "--export_torch", pth,
    ]
    outs = _run_pair(tmp_path, args)
    for pat in (
        r"Epoch \d+, Loss: ([\d.eE+-]+)",
        r"Epoch \d+, Test Metric: ([\d.eE+-]+)",
    ):
        a, b = re.findall(pat, outs[0]), re.findall(pat, outs[1])
        assert a and a == b, f"process outputs diverge for {pat}: {a} vs {b}"
        assert all(np.isfinite(float(x)) for x in a)
    with open(pred, "rb") as f:
        recs = pickle.load(f)
    assert len(recs) == 8
    torch = pytest.importorskip("torch")
    sd = torch.load(pth, weights_only=True)
    # standard reference layout: per-block attention params present
    assert any("attention_layers.1" in k or "block" in k.lower() for k in sd)


def test_two_process_checkpoint_resume_and_predict(tmp_path):
    """Checkpoint/resume and predict under ``jax.distributed``:

    * a 2-epoch run is 'preempted', then resumed to 4 epochs — the
      resumed epochs' losses must equal a continuous 4-epoch run's
      (Orbax save/restore across processes + seeded shuffle replay);
    * both runs write predictions from the best checkpoint (a params
      allgather collective); the files must agree.
    """
    d_cont, d_int = str(tmp_path / "cont"), str(tmp_path / "int")
    p_cont, p_res = str(tmp_path / "pred_cont.pkl"), str(tmp_path / "pred_res.pkl")

    pth = str(tmp_path / "model.pth")
    outs_c = _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_cont, "--checkpoint_every", "1",
           "--predict_out", p_cont, "--export_torch", pth],
    )
    # Same 4-epoch regime (the OneCycle schedule is sized by --epochs),
    # preempted after epoch 1 via fault injection.
    _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_int, "--checkpoint_every", "1",
           "--stop_after_epoch", "2"],
    )
    outs_r = _run_pair(
        tmp_path,
        BASE_ARGS
        + ["--epochs", "4", "--checkpoint_dir", d_int, "--checkpoint_every", "1",
           "--resume", "--predict_out", p_res],
    )

    pat = r"Epoch (\d+), Loss: ([\d.eE+-]+)"
    cont = dict(re.findall(pat, outs_c[0]))
    res = dict(re.findall(pat, outs_r[0]))
    assert set(res) == {"2", "3"}, f"resume should replay epochs 2-3, got {sorted(res)}"
    for e in ("2", "3"):
        np.testing.assert_allclose(
            float(res[e]), float(cont[e]), rtol=1e-5,
            err_msg=f"resumed epoch {e} loss diverges from continuous run",
        )

    # Predictions: written by process 0 only, identical across runs.
    with open(p_cont, "rb") as f:
        recs_c = pickle.load(f)
    with open(p_res, "rb") as f:
        recs_r = pickle.load(f)
    assert len(recs_c) == len(recs_r) == 8
    for rc, rr in zip(recs_c, recs_r):
        np.testing.assert_allclose(rc[1], rr[1], rtol=1e-5, atol=1e-6)

    # --export_torch under jax.distributed: the gathered state_dict is a
    # loadable torch artifact (written by process 0).
    torch = pytest.importorskip("torch")
    sd = torch.load(pth, weights_only=True)
    assert sd and all(v.ndim in (1, 2) for v in sd.values())
