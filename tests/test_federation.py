"""Federation suite (gnot_tpu/serve/federation.py, docs/distributed.md).

ISSUE 18 acceptance, three layers:

* **Protocol hardening** — the frame codec never wedges on fuzzed /
  truncated / oversize input (garbage degrades to counters and the
  stream resynchronises), version skew refuses loudly at handshake,
  and the ``MESSAGES`` registry stays aligned with its constants.
* **Failure-detector semantics on a fake clock** — the suspect dwell
  (SUSPECT strictly before DEAD), a flapping host that keeps renewing
  its lease never dies, and an ack from ANY state revives (the healed-
  partition path) while reporting the previous state for reconcile.
* **End-to-end federation over loopback** — one-shot + rollout storms
  across hosts with per-step parity against the offline loop, host
  death mid-flight re-migrating sessions from persisted snapshots with
  zero loss, message drop/delay chaos never causing a false death, and
  an idempotent coordinated drain.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import MeshSample, collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.resilience.faults import FAULT_KINDS, FaultInjector
from gnot_tpu.serve.federation import (
    ALIVE,
    DEAD,
    MESSAGES,
    PROTOCOL_VERSION,
    SUSPECT,
    ClusterRouter,
    FailureDetector,
    FrameDecoder,
    HostAgent,
    InProcLink,
    ProtocolError,
    build_local_federation,
    decode_sample,
    encode_frame,
    encode_sample,
    topology_key,
    validate_message,
    wire,
)
from gnot_tpu.serve.rollout import SessionStore, offline_rollout, parity_check
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

MAX_BATCH = 2


# --- wire protocol: framing ------------------------------------------------


def test_frame_roundtrip_any_split():
    msgs = [wire("heartbeat", seq=i) for i in range(5)]
    stream = b"".join(encode_frame(m) for m in msgs)
    # Worst-case TCP: one byte at a time.
    dec = FrameDecoder()
    got = []
    for i in range(len(stream)):
        got.extend(dec.feed(stream[i : i + 1]))
    assert got == msgs
    # And the whole stream in one read.
    dec2 = FrameDecoder()
    assert dec2.feed(stream) == msgs
    assert dec.garbage == dec.oversize == 0


def test_decoder_truncated_frame_buffers_until_complete():
    frame = encode_frame(wire("hello", version=1))
    dec = FrameDecoder()
    assert dec.feed(frame[:7]) == []  # prefix + partial payload: waits
    assert dec.feed(frame[7:]) == [wire("hello", version=1)]


def test_decoder_counts_garbage_and_resyncs():
    dec = FrameDecoder()
    bad_json = b"\x00\x00\x00\x05notjs"
    not_dict = b"\x00\x00\x00\x02[]"
    no_kind = b"\x00\x00\x00\x07{\"a\":1}"
    good = encode_frame(wire("heartbeat", seq=1))
    out = dec.feed(bad_json + not_dict + no_kind + good)
    assert out == [wire("heartbeat", seq=1)]
    assert dec.garbage == 3


def test_decoder_oversize_frame_drained_in_skip_mode():
    dec = FrameDecoder(max_frame_bytes=64)
    claim = (1 << 20).to_bytes(4, "big")  # 1 MiB claim, 64 B ceiling
    dec.feed(claim)
    # Drain the declared payload in chunks: the buffer must stay empty
    # (skip-mode never accumulates a hostile claim).
    for _ in range(16):
        assert dec.feed(b"x" * (1 << 16)) == []
        assert len(dec._buf) == 0
    assert dec.oversize == 1
    # Stream resynchronises on the next well-formed frame.
    assert dec.feed(encode_frame(wire("heartbeat", seq=2))) == [
        wire("heartbeat", seq=2)
    ]


def test_decoder_zero_length_prefix_is_garbage():
    dec = FrameDecoder()
    out = dec.feed(b"\x00\x00\x00\x00" + encode_frame(wire("drain")))
    assert out == [wire("drain")]
    assert dec.garbage == 1


def test_encode_frame_rejects_oversize_payload():
    big = {"kind": "submit", "blob": "x" * (9 * 1024 * 1024)}
    with pytest.raises(ProtocolError):
        encode_frame(big)


# --- wire protocol: schema registry ---------------------------------------


def test_wire_builds_registry_valid_messages():
    m = wire("heartbeat", seq=3)
    validate_message(m)  # no raise
    m2 = wire("heartbeat", seq=3, extra="fine")
    validate_message(m2)  # extras ride the same contract as events


def test_validate_message_refuses_unknown_and_missing():
    with pytest.raises(ProtocolError):
        validate_message({"kind": "no_such_kind"})
    with pytest.raises(ProtocolError):
        validate_message({"kind": "heartbeat"})  # missing seq
    with pytest.raises(ProtocolError):
        validate_message({"no": "kind"})


def test_wire_refuses_unregistered_kind():
    with pytest.raises(ProtocolError):
        wire("definitely_not_registered")  # graftlint: disable=GL005 — deliberate unregistered kind: asserts wire() refuses it


def test_messages_registry_shape():
    assert len(MESSAGES) == 22
    for kind, spec in MESSAGES.items():
        assert spec.doc, f"{kind} has no doc line"
        assert isinstance(spec.fields, tuple)
    # The error reply's offending-kind field must NOT collide with the
    # envelope's own 'kind'.
    assert "kind" not in MESSAGES["error"].fields
    assert "kind" not in MESSAGES["error"].optional


def test_sample_codec_roundtrip():
    rng = np.random.default_rng(0)
    s = MeshSample(
        coords=rng.uniform(size=(17, 2)).astype(np.float32),
        y=rng.uniform(size=(17, 1)).astype(np.float32),
        theta=rng.uniform(size=(3,)).astype(np.float32),
        funcs=(rng.uniform(size=(5, 3)).astype(np.float32),),
    )
    enc = encode_sample(s)
    json.dumps(enc)  # must be wire-serializable as-is
    back = decode_sample(enc)
    np.testing.assert_array_equal(back.coords, s.coords)
    np.testing.assert_array_equal(back.y, s.y)
    np.testing.assert_array_equal(back.theta, s.theta)
    assert len(back.funcs) == 1
    np.testing.assert_array_equal(back.funcs[0], s.funcs[0])


def test_topology_key():
    assert topology_key(2, 3) == "h2r3"


def test_federation_fault_kinds_registered():
    for kind in ("host_kill", "net_partition", "msg_drop", "msg_delay"):
        assert kind in FAULT_KINDS
    fi = FaultInjector.from_spec("host_kill@2,msg_delay@50")
    assert not fi.maybe_host_kill(1)
    assert fi.maybe_host_kill(2)
    assert not fi.maybe_host_kill(2)  # single-fire
    assert fi.maybe_msg_delay() == 50
    assert fi.maybe_msg_delay() == 0  # single-fire


# --- failure detector: fake clock ------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_detector_suspect_dwell_before_death():
    clk = _Clock()
    det = FailureDetector(suspect_after_s=2.0, dead_after_s=6.0, clock=clk)
    det.register("h0")
    assert det.state("h0") == ALIVE
    clk.t += 1.9
    assert det.sweep() == []
    clk.t += 0.2  # 2.1 s silent: SUSPECT, not DEAD
    assert det.sweep() == [("h0", ALIVE, SUSPECT)]
    clk.t += 3.0  # 5.1 s: still dwelling
    assert det.sweep() == []
    assert det.state("h0") == SUSPECT
    clk.t += 1.0  # 6.1 s: dead
    assert det.sweep() == [("h0", SUSPECT, DEAD)]
    # DEAD is sticky under silence.
    clk.t += 10.0
    assert det.sweep() == []
    assert det.state("h0") == DEAD


def test_detector_flapping_host_never_dies():
    clk = _Clock()
    det = FailureDetector(suspect_after_s=1.0, dead_after_s=3.0, clock=clk)
    det.register("h0")
    # Repeatedly silent just past the suspicion bound, then acks: the
    # lease keeps renewing, so the dwell restarts and DEAD is never
    # reached no matter how often it flaps.
    for _ in range(10):
        clk.t += 1.5
        det.sweep()
        assert det.state("h0") == SUSPECT
        assert det.ack("h0") == SUSPECT
        assert det.state("h0") == ALIVE
    assert det.sweep() == []


def test_detector_ack_revives_from_dead_and_reports_old_state():
    clk = _Clock()
    det = FailureDetector(suspect_after_s=1.0, dead_after_s=2.0, clock=clk)
    det.register("h0")
    clk.t += 5.0
    det.sweep()
    assert det.state("h0") == DEAD
    # A healed partition: the ack revives AND reports DEAD so the
    # caller reconciles (re-drives in-flight work).
    assert det.ack("h0") == DEAD
    assert det.state("h0") == ALIVE
    assert det.silent_s("h0") == 0.0


def test_detector_probe_anchors_silence_after_idle_gap():
    # Registration → long controller idle (replica warm-up, a GC
    # pause) → first probe: that gap is the CONTROLLER's, not the
    # host's. Silence must anchor at the first unanswered probe, or
    # the first sweep after the gap declares instant death without a
    # single real probe going unanswered.
    clk = _Clock()
    det = FailureDetector(suspect_after_s=1.0, dead_after_s=3.0, clock=clk)
    det.register("h0")
    clk.t += 10.0  # controller busy: no probes sent yet
    det.probe("h0")
    assert det.silent_s("h0") == 0.0
    assert det.sweep() == []  # no instant death off the idle gap
    # A host silent across REAL probes still dies on the normal
    # dwell, measured from the FIRST unanswered probe (later probes
    # keep the original anchor).
    clk.t += 1.5
    det.probe("h0")
    assert det.sweep() == [("h0", ALIVE, SUSPECT)]
    clk.t += 2.0  # 3.5 s past the first unanswered probe
    assert det.sweep() == [("h0", SUSPECT, DEAD)]
    # The eventual ack answers the probe and revives.
    assert det.ack("h0") == DEAD
    assert det.silent_s("h0") == 0.0


def test_detector_requires_dwell_ordering():
    with pytest.raises(ValueError):
        FailureDetector(suspect_after_s=3.0, dead_after_s=3.0)
    with pytest.raises(ValueError):
        FailureDetector(suspect_after_s=0.0, dead_after_s=1.0)


# --- agent hardening (stub router, no jax) ---------------------------------


class _StubRouter:
    def pool(self):
        return []

    def drain(self, timeout_s=30.0):
        return {"requests": 0}

    def prewarm_from(self, manifest):
        return {}


def _collect():
    out = []
    return out, out.append


def test_agent_answers_error_and_keeps_serving():
    agent = HostAgent("h0", _StubRouter())
    got, send = _collect()
    agent.handle({"kind": "no_such_kind"}, send)
    agent.handle({"kind": "heartbeat"}, send)  # missing required seq
    agent.handle({"kind": "result", "id": "x", "ok": True}, send)  # wrong way
    assert [m["kind"] for m in got] == ["error", "error", "error"]
    assert got[0]["bad_kind"] == "no_such_kind"
    assert agent.errors == 3
    # The stream continues: a well-formed hello still handshakes.
    agent.handle(wire("hello", version=PROTOCOL_VERSION), send)
    assert got[-1]["kind"] == "hello_ok"


def test_agent_internal_exception_becomes_error_reply():
    agent = HostAgent("h0", _StubRouter())
    got, send = _collect()
    # Schema-valid submit whose sample payload is garbage: the decode
    # blows up INSIDE the handler — the agent must answer ERROR, not die.
    agent.handle(
        {"kind": "submit", "id": "r1", "sample": {"bogus": True}}, send
    )
    assert got and got[0]["kind"] == "error"
    assert got[0]["reason"] == "internal"
    agent.handle(wire("hello", version=PROTOCOL_VERSION), send)
    assert got[-1]["kind"] == "hello_ok"


def test_killed_agent_goes_silent():
    agent = HostAgent("h0", _StubRouter())
    got, send = _collect()
    agent.kill()
    agent.handle(wire("hello", version=PROTOCOL_VERSION), send)
    assert got == []  # no replies, no errors — pure silence


def test_version_skew_refused_loudly():
    skewed = HostAgent("h0", _StubRouter(), version=PROTOCOL_VERSION + 1)
    cluster = ClusterRouter()
    with pytest.raises(ProtocolError, match="version skew"):
        cluster.add_host("h0", InProcLink(skewed))
    assert cluster.hosts() == []


def test_tcp_fuzzed_connection_never_wedges_agent():
    agent = HostAgent("h0", _StubRouter())
    port = agent.listen()
    try:
        # Connection 1: raw garbage (misread as a bogus length prefix).
        fuzz = socket.create_connection(("127.0.0.1", port), timeout=5)
        fuzz.sendall(b"\xff\xfe\x00garbage not a frame at all\x00\x01")
        fuzz.close()
        # Connection 2 (fresh decoder): the agent still handshakes.
        dec = FrameDecoder()
        conn = socket.create_connection(("127.0.0.1", port), timeout=5)
        conn.sendall(encode_frame(wire("hello", version=PROTOCOL_VERSION)))
        conn.settimeout(5)
        got = []
        while not got:
            got = dec.feed(conn.recv(65536))
        conn.close()
        assert got[0]["kind"] == "hello_ok"
        assert got[0]["host"] == "h0"
    finally:
        agent.stop()


def test_tcp_oversize_claim_skipped_then_serves():
    agent = HostAgent("h0", _StubRouter())
    port = agent.listen()
    try:
        conn = socket.create_connection(("127.0.0.1", port), timeout=5)
        # An 16 MiB length claim with only a sliver of payload, then a
        # valid frame once the skip window is satisfied: the per-conn
        # decoder must drain the claim and answer the real frame. To
        # keep the test fast, satisfy the claim fully.
        claim = 1024
        conn.sendall(
            (16 * 1024 * 1024).to_bytes(4, "big") + b"z" * claim
        )
        conn.sendall(b"z" * (16 * 1024 * 1024 - claim))
        conn.sendall(encode_frame(wire("hello", version=PROTOCOL_VERSION)))
        conn.settimeout(10)
        dec = FrameDecoder()
        got = []
        while not got:
            got = dec.feed(conn.recv(65536))
        conn.close()
        assert got[0]["kind"] == "hello_ok"
    finally:
        agent.stop()


# --- end-to-end federation over loopback (jax) -----------------------------


@pytest.fixture(scope="module")
def setup():
    samples = datasets.synth_darcy2d(8, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    from gnot_tpu.serve import InferenceEngine

    engine = InferenceEngine(model, params, batch_size=MAX_BATCH)
    engine.warmup(samples[:MAX_BATCH], rows=MAX_BATCH)
    return model, params, samples, engine


def _federation(setup, tmp_path, hosts=2, *, store=True, warm=True, **kw):
    import jax

    from gnot_tpu.serve import build_replica

    model, params, samples, _ = setup
    devs = jax.devices()
    groups = [
        [
            build_replica(
                model, params, 0, [devs[h % len(devs)]],
                batch_size=MAX_BATCH,
            )
        ]
        for h in range(hosts)
    ]
    sink = MetricsSink(str(tmp_path / "fed.jsonl"))
    session_store = (
        SessionStore(str(tmp_path / "sessions")) if store else None
    )
    kw.setdefault("router_kwargs", dict(max_batch=MAX_BATCH, max_wait_ms=2.0))
    cluster, agents = build_local_federation(
        groups, sink=sink, session_store=session_store, **kw
    )
    for a in agents.values():
        a.router.start()
    if warm:
        for g in groups:
            for r in g:
                r.warm(samples[:MAX_BATCH], rows=MAX_BATCH)
    return cluster, agents, sink, str(tmp_path / "fed.jsonl")


def _tick_until(cluster, pred, timeout_s=30.0, dt=0.02):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        cluster.tick()
        if pred():
            return True
        time.sleep(dt)
    return False


def test_federated_one_shot_and_rollout_parity(setup, tmp_path):
    model, params, samples, engine = setup
    cluster, agents, sink, _path = _federation(setup, tmp_path, hosts=2)
    with sink:
        futs = [cluster.submit(s) for s in samples[:4]]
        results = [f.result(timeout=60) for f in futs]
        assert all(r.ok for r in results), [r.reason for r in results]
        # One-shot outputs match a direct engine dispatch (same params,
        # deterministic batcher; the wire codec is float32-exact).
        pn, pf = engine.bucket_key(samples[0])
        solo = engine.infer(
            [samples[0]], pad_nodes=pn, pad_funcs=pf, rows=MAX_BATCH
        )[0]
        np.testing.assert_allclose(results[0].output, solo, atol=1e-5)
        # A rollout session through the cluster matches the offline
        # K-step loop per step.
        fut = cluster.submit_rollout(samples[0], 4, name="sess-a")
        res = fut.result(timeout=120)
        assert res.ok and len(res.outputs) == 4
        ref = offline_rollout(engine, samples[0], 4, rows=MAX_BATCH)
        assert parity_check(res.outputs, ref) <= 1e-5
        summary = cluster.drain()
    assert summary["requests"] == 4
    # 'completed' is the whole-ledger counter: 4 one-shots + 1 session.
    assert summary["completed"] == 5
    assert summary["sessions"] == 1
    assert summary["lost"] == 0
    assert summary["protocol_errors"] == 0
    for a in agents.values():
        a.stop()


def test_host_kill_remigrates_sessions_zero_loss(setup, tmp_path):
    model, params, samples, engine = setup
    steps = 12
    cluster, agents, sink, path = _federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.2, dead_after_s=0.5,
    )
    with sink:
        futs = [
            cluster.submit_rollout(s, steps, name=f"s{i}")
            for i, s in enumerate(samples[:2])
        ]
        # Let a session make real progress, then kill its owner
        # between frames — no goodbye, only silence.
        assert _tick_until(
            cluster,
            lambda: any(
                2 <= s.streamed < steps - 2
                for s in cluster._sessions.values()
            ),
        ), "no session reached the kill window"
        victim = next(
            s.owner
            for s in cluster._sessions.values()
            if 2 <= s.streamed < steps - 2
        )
        agents[victim].kill()
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=180) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert all(r.ok for r in results), [
        (r.session, r.reason, r.detail) for r in results
    ]
    assert summary["remigrated"] >= 1
    assert summary["lost"] == 0
    assert summary["hosts_dead"] == 1
    # Per-step parity against the offline loop survives the migration.
    refs = [
        offline_rollout(engine, s, steps, rows=MAX_BATCH)
        for s in samples[:2]
    ]
    worst = max(
        parity_check(r.outputs, ref) for r, ref in zip(results, refs)
    )
    assert worst <= 1e-5
    events = [json.loads(l) for l in open(path)]
    assert any(e.get("event") == "host_dead" for e in events)
    remigs = [e for e in events if e.get("event") == "session_remigrate"]
    assert remigs and all(e["from_host"] == victim for e in remigs)


def test_msg_drop_and_delay_cause_no_false_death(setup, tmp_path):
    # One heartbeat delayed 50 ms, one dropped outright (the ticker
    # runs alone first so the single-fire faults land on heartbeats,
    # not submits — submit loss is the hedge tests' job). Lease
    # renewal must absorb both without a false death.
    fi = FaultInjector.from_spec("msg_drop@3,msg_delay@50")
    cluster, agents, sink, _path = _federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.3, dead_after_s=5.0,
        link_faults={"host0": fi, "host1": fi},
    )
    model, params, samples, _engine = setup
    with sink:
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        time.sleep(0.3)  # several beats: both faults fire on heartbeats
        futs = [cluster.submit(s) for s in samples[:4]]
        results = [f.result(timeout=120) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    # A dropped frame and a delayed frame are noise, not death: every
    # future resolves and nobody gets declared dead.
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["hosts_dead"] == 0
    assert summary["lost"] == 0
    for a in agents.values():
        a.stop()


def test_dropped_submit_on_healthy_host_is_redriven(setup, tmp_path):
    # msg_drop eats the SUBMIT frame itself while the lease stays
    # green: heartbeats keep flowing, so no detector edge (reconcile/
    # hedge/death) ever re-drives it — only the age-based re-delivery
    # sweep can save the future. A dropped heartbeat is absorbed by
    # the next one; a dropped submit has no next one.
    cluster, agents, sink, _path = _federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.2, dead_after_s=30.0,
    )
    model, params, samples, _engine = setup
    with sink:
        # Arm AFTER the handshake so each link's next outbound frame —
        # the submit itself — is the chaos victim. Frame ordinals are
        # absolute per link and the hello was frame 1, so the submit
        # is frame 2 (msg_drop@1 would never fire post-handshake).
        for host_id in ("host0", "host1"):
            cluster._hosts[host_id].link.arm(
                FaultInjector.from_spec("msg_drop@2")
            )
        futs = [cluster.submit(s) for s in samples[:4]]
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=60) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["hosts_dead"] == 0  # the lease never flickered
    assert summary["lost"] == 0
    for a in agents.values():
        a.stop()


def test_dropped_session_submit_is_redriven_with_sample(setup, tmp_path):
    # Same gap for sessions: the dropped SUBMIT_ROLLOUT is replayed
    # verbatim (fresh placement → the sample rides the re-send), the
    # unacked-placement flag gates it, and the trajectory still
    # matches the offline loop exactly.
    cluster, agents, sink, _path = _federation(
        setup, tmp_path, hosts=1,
        suspect_after_s=0.2, dead_after_s=30.0,
    )
    model, params, samples, engine = setup
    steps = 3
    with sink:
        # Frame 1 was the handshake hello; the rollout submit is #2.
        cluster._hosts["host0"].link.arm(
            FaultInjector.from_spec("msg_drop@2")
        )
        fut = cluster.submit_rollout(samples[0], steps, name="redrive")
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        res = fut.result(timeout=60)
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert res.ok, res.reason
    assert res.steps_completed == steps
    assert summary["hosts_dead"] == 0
    assert summary["lost"] == 0
    reference = offline_rollout(engine, samples[0], steps, rows=MAX_BATCH)
    assert parity_check(list(res.outputs), reference) <= 1e-5
    for a in agents.values():
        a.stop()


def test_net_partition_heals_and_reconciles(setup, tmp_path):
    # Partition host0's link at its 3rd outbound frame (mid-storm),
    # heal it before the dead bound, and require every future to
    # resolve: the revival reconcile (outbox replay + resume) repairs
    # whatever the partition ate.
    fi = FaultInjector.from_spec("net_partition@3")
    cluster, agents, sink, _path = _federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.2, dead_after_s=30.0,
        link_faults={"host0": fi},
    )
    model, params, samples, _engine = setup
    link = cluster._hosts["host0"].link
    with sink:
        futs = [cluster.submit(s) for s in samples[:4]]
        assert _tick_until(
            cluster, lambda: link.partitioned, timeout_s=10
        ), "partition never armed"
        # Dwell in SUSPECT (hedges cover the one-shots), then heal.
        assert _tick_until(
            cluster,
            lambda: cluster.host_state("host0") == SUSPECT,
            timeout_s=10,
        )
        link.heal_partition()
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=120) for f in futs]
        stop.set()
        t.join(timeout=5)
        # The healed link's next ack renews the lease (DEAD was never
        # reached; reconcile re-drove anything the partition ate).
        assert _tick_until(
            cluster,
            lambda: cluster.host_state("host0") == ALIVE,
            timeout_s=10,
        )
        summary = cluster.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["hosts_dead"] == 0
    assert summary["lost"] == 0
    for a in agents.values():
        a.stop()


def test_cluster_drain_is_idempotent_and_resolves_all(setup, tmp_path):
    model, params, samples, _engine = setup
    cluster, agents, sink, path = _federation(setup, tmp_path, hosts=2)
    with sink:
        futs = [cluster.submit(s) for s in samples[:4]]
        summary = cluster.drain()
        again = cluster.drain()
    # Every future resolved by the drain (completed or honestly shed).
    for f in futs:
        assert f.done()
        f.result(timeout=0)
    assert summary["completed"] + summary["shed"] == summary["requests"] == 4
    # Idempotent: the second drain returns the same ledger without
    # re-draining (per_host detail may be elided on the cached path).
    for key in ("requests", "completed", "shed", "sessions", "lost"):
        assert again[key] == summary[key]
    events = [json.loads(l) for l in open(path)]
    assert sum(e.get("event") == "cluster_summary" for e in events) == 1
    for a in agents.values():
        a.stop()
