"""Cluster-scoped distributed tracing suite (obs/dtrace.py, ISSUE 20).

Four layers, mirroring the module's four pieces:

* **Propagation** — ``TraceContext`` wire roundtrip and tolerant
  decode; ``Tracer.adopt`` honoring a remote head-sampling decision
  without consulting local counters (shadow ids when a flight recorder
  is attached, coverage ledger deduped per trace).
* **Clock alignment** — ``ClockSync`` midpoint arithmetic, the
  min-RTT window rule, and honest half-RTT error bars.
* **Stitching** — ``merge_traces`` rebasing remote spans into the
  controller frame, host-prefixing span/parent ids, and recording
  per-source offset/coverage metadata.
* **Flight recorder** — ring retention by window and cap, atomic
  trigger dumps, the ``FlightRecorderSink`` trigger predicates
  (``slo_alert`` fires only on its FIRE edge), and the lockguard hook.

Plus the federated chaos layer (ISSUE 20 satellite): a dropped submit
re-delivered as a LINKED placement of the same trace (never a second
chain), a SUSPECT-dwell hedge as a span link, and a mid-rollout host
kill whose re-migrated steps join the ORIGINAL trace while the
controller's black box dumps on the ``host_dead`` edge.
"""

import json
import os
import threading
import time

import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import collate
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.obs.dtrace import (
    ClockSync,
    FlightRecorder,
    FlightRecorderSink,
    TraceContext,
    merge_traces,
)
from gnot_tpu.obs.tracing import Tracer
from gnot_tpu.resilience.faults import FaultInjector
from gnot_tpu.serve.federation import SUSPECT, build_local_federation
from gnot_tpu.serve.rollout import SessionStore
from gnot_tpu.train.trainer import init_params
from gnot_tpu.utils.metrics import MetricsSink

MAX_BATCH = 2
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    """Deterministic monotonic clock: reads are stable, ticks explicit."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


# --- trace-context propagation ---------------------------------------------


def test_trace_context_wire_roundtrip():
    full = TraceContext(
        trace_id="t000007", span_id="s000003", sampled=True, tenant="acme"
    )
    assert TraceContext.from_wire(full.to_wire()) == full
    minimal = TraceContext(trace_id="t000001")
    wire_min = minimal.to_wire()
    # Optional fields are OMITTED from the wire form, not sent as null.
    assert set(wire_min) == {"trace_id", "sampled"}
    assert TraceContext.from_wire(wire_min) == minimal


def test_trace_context_tolerant_decode():
    # A missing/malformed trace_ctx means "run untraced", never an error.
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire("junk") is None
    assert TraceContext.from_wire({}) is None
    assert TraceContext.from_wire({"trace_id": ""}) is None
    got = TraceContext.from_wire(
        {"trace_id": 42, "span_id": 7, "sampled": 0, "tenant": 1}
    )
    assert got == TraceContext(
        trace_id="42", span_id="7", sampled=False, tenant="1"
    )


def test_adopt_honors_remote_decision_not_local_counters():
    # rate 0: the local counter would sample everything OUT — but a
    # propagated sampled=True decision wins, and the span exports.
    tr = Tracer(sample_rate=0.0)
    assert tr.start_trace() is None  # the local decision, for contrast
    ctx = TraceContext(trace_id="t000009", span_id="s000001", tenant="a")
    tid = tr.adopt(ctx)
    assert tid == "t000009"
    with tr.span("admission", trace=tid):
        pass
    spans = tr.export()["traceEvents"]
    assert [s["args"]["trace_id"] for s in spans] == ["t000009"]
    cov = tr.coverage()
    assert cov["adopted"] == 1 and cov["kept"] == 1
    # An unsampled decision with no recorder is a no-op.
    assert tr.adopt(TraceContext(trace_id="t000010", sampled=False)) is None
    cov = tr.coverage()
    assert cov["adopted"] == 2 and cov["kept"] == 1


def test_adopt_dedupes_repeated_context():
    # A session's steps adopt the SAME ctx once per step: one trace,
    # one coverage unit.
    tr = Tracer()
    ctx = TraceContext(trace_id="r000001")
    for _ in range(5):
        assert tr.adopt(ctx) == "r000001"
    cov = tr.coverage()
    assert cov["adopted"] == 1 and cov["kept"] == 1 and cov["seen"] == 1


def test_adopt_shadow_with_recorder(tmp_path):
    rec = FlightRecorder(str(tmp_path), window_s=30.0, host="h0")
    tr = Tracer(recorder=rec)
    # Unsampled ctx -> shadow id; shadow-prefixed ctx keeps its prefix.
    sid = tr.adopt(TraceContext(trace_id="t000004", sampled=False))
    assert sid == "!t000004"
    assert tr.adopt(TraceContext(trace_id="!t000005")) == "!t000005"
    with tr.span("admission", trace=sid):
        pass
    # Shadow spans exist ONLY in the recorder's ring, never the export.
    assert tr.export()["traceEvents"] == []
    ring = rec.snapshot()["entries"]
    assert [e["trace_id"] for e in ring] == ["!t000004"]
    cov = tr.coverage()
    assert cov["adopted"] == 2 and cov["kept"] == 0


def test_start_trace_shadow_ids_unique_at_rate_zero(tmp_path):
    rec = FlightRecorder(str(tmp_path), window_s=30.0)
    tr = Tracer(sample_rate=0.0, recorder=rec)
    a, b = tr.start_trace(), tr.start_trace()
    assert a == "!t000001" and b == "!t000002"
    cov = tr.coverage()
    assert cov["seen"] == 2 and cov["kept"] == 0


# --- clock alignment --------------------------------------------------------


def test_clock_sync_midpoint_and_error_bar():
    cs = ClockSync()
    cs.observe("h0", t_send=10.0, t_recv=10.2, remote_t=15.1)
    off, err = cs.offset("h0")
    assert off == pytest.approx(15.1 - 10.1)  # midpoint method
    assert err == pytest.approx(0.1)  # rtt / 2
    assert cs.rtt_ms("h0") == pytest.approx(200.0)
    assert cs.offset("unknown") is None and cs.rtt_ms("unknown") is None


def test_clock_sync_trusts_min_rtt_and_discards_retrograde():
    cs = ClockSync()
    cs.observe("h0", 10.0, 10.2, 15.1)  # tight exchange: offset 5.0
    cs.observe("h0", 20.0, 21.0, 26.0)  # noisy exchange: offset 5.5
    off, err = cs.offset("h0")
    assert off == pytest.approx(5.0) and err == pytest.approx(0.1)
    assert cs.rtt_ms("h0") == pytest.approx(1000.0)  # newest, not min
    # Negative RTT (mixed clocks) is discarded, not folded in.
    cs.observe("h0", 5.0, 4.0, 100.0)
    assert cs.snapshot()["h0"]["samples"] == 2


def test_clock_sync_sliding_window_evicts_oldest():
    cs = ClockSync(window=2)
    cs.observe("h0", 0.0, 0.01, 5.0)  # the tightest exchange...
    cs.observe("h0", 1.0, 1.5, 9.0)
    cs.observe("h0", 2.0, 2.4, 9.2)  # ...falls out of the window here
    off, err = cs.offset("h0")
    assert off == pytest.approx(9.2 - 2.2) and err == pytest.approx(0.2)
    with pytest.raises(ValueError):
        ClockSync(window=0)


# --- cross-host stitching ---------------------------------------------------


def _ev(name, ts_us, dur_us, span_id, parent_id=None, **args):
    a = {"trace_id": "t000001", "span_id": span_id, **args}
    if parent_id is not None:
        a["parent_id"] = parent_id
    return {
        "name": name, "cat": "host", "ph": "X", "ts": ts_us, "dur": dur_us,
        "pid": 1, "tid": 7, "args": a,
    }


def _export(spans, t0, **counters):
    return {
        "traceEvents": spans,
        "otherData": {"clock_t0_s": t0, **counters},
    }


def test_merge_traces_rebases_prefixes_and_reports():
    # Host clock = controller clock + 5 s; its span at local abs 205 s
    # lands at controller abs 200 s — 100 s after the controller span.
    merged = merge_traces(
        {
            "controller": _export(
                [_ev("cluster_request", 0.0, 50_000.0, "s000001")],
                t0=100.0, traces_seen=1, traces_kept=1,
            ),
            "host0": _export(
                [_ev("device", 0.0, 10_000.0, "s000001",
                     parent_id="s000009")],
                t0=205.0, traces_seen=0, traces_kept=0,
            ),
        },
        offsets={"host0": (5.0, 0.01)},
    )
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert [(m["pid"], m["args"]["name"]) for m in meta] == [
        (1, "controller"), (2, "host0"),
    ]
    spans = {
        e["name"]: e for e in merged["traceEvents"] if e.get("ph") == "X"
    }
    ctrl, dev = spans["cluster_request"], spans["device"]
    assert ctrl["ts"] == pytest.approx(0.0) and ctrl["pid"] == 1
    assert dev["ts"] == pytest.approx(100e6) and dev["pid"] == 2
    # Per-host s%06d counters cannot collide after prefixing; remote
    # spans gain the per-host breakdown key.
    assert ctrl["args"]["span_id"] == "controller:s000001"
    assert dev["args"]["span_id"] == "host0:s000001"
    assert dev["args"]["parent_id"] == "host0:s000009"
    assert "host" not in ctrl["args"] and dev["args"]["host"] == "host0"
    hosts = merged["otherData"]["hosts"]
    assert hosts["controller"]["clock_offset_s"] == 0.0
    assert hosts["host0"]["clock_offset_s"] == 5.0
    assert hosts["host0"]["clock_err_s"] == 0.01
    assert hosts["controller"]["traces_kept"] == 1
    assert hosts["host0"]["spans"] == 1


def test_merge_traces_without_offset_keeps_own_frame():
    merged = merge_traces(
        {
            "controller": _export(
                [_ev("cluster_request", 0.0, 1_000.0, "s000001")], t0=100.0
            ),
            "host1": _export(
                [_ev("device", 0.0, 1_000.0, "s000001")], t0=205.0
            ),
        },
        offsets={},
    )
    hosts = merged["otherData"]["hosts"]
    # No estimate -> recorded honestly, times left in the host frame.
    assert hosts["host1"]["clock_offset_s"] is None
    assert hosts["host1"]["clock_err_s"] is None
    dev = next(
        e for e in merged["traceEvents"] if e.get("name") == "device"
    )
    assert dev["ts"] == pytest.approx(105e6)


# --- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_eviction_by_cap_and_window(tmp_path):
    clk = FakeClock()
    rec = FlightRecorder(
        str(tmp_path), window_s=10.0, max_items=3, clock=clk
    )
    for i in range(4):
        rec.record_event({"event": f"e{i}"})
        clk.tick(1.0)
    snap = rec.snapshot()
    assert [e["record"]["event"] for e in snap["entries"]] == [
        "e1", "e2", "e3",
    ]
    assert snap["evicted"] == 1  # the max_items cap
    clk.t = 20.0
    rec.record_event({"event": "late"})
    snap = rec.snapshot()
    assert [e["record"]["event"] for e in snap["entries"]] == ["late"]
    assert snap["evicted"] == 4  # the trailing window
    with pytest.raises(ValueError):
        FlightRecorder(str(tmp_path), window_s=0.0)
    with pytest.raises(ValueError):
        FlightRecorder(str(tmp_path), max_items=0)


def test_flight_recorder_trigger_writes_one_file_per_edge(tmp_path):
    rec = FlightRecorder(str(tmp_path), window_s=30.0, host="controller")
    tr = Tracer(recorder=rec)
    t = tr.start_trace()
    with tr.span("admission", trace=t):
        pass
    p1 = rec.trigger("breaker_open", host="host0")
    p2 = rec.trigger("host_dead", host="host1")
    assert os.path.basename(p1) == "flight_001_breaker_open.json"
    assert os.path.basename(p2) == "flight_002_host_dead.json"
    assert rec.dumps == [p1, p2]
    dump = json.load(open(p1))
    assert dump["trigger"]["kind"] == "breaker_open"
    assert dump["trigger"]["host"] == "host0"
    assert dump["host"] == "controller"
    spans = [e for e in dump["entries"] if e["type"] == "span"]
    assert [s["name"] for s in spans] == ["admission"]
    # The second dump is a separate file — a second fault must never
    # overwrite the first one's evidence.
    assert json.load(open(p2))["trigger"]["kind"] == "host_dead"


class _ListSink:
    def __init__(self):
        self.records = []

    def log(self, **fields):
        self.records.append(fields)


def test_flight_recorder_sink_trigger_predicates(tmp_path):
    rec = FlightRecorder(str(tmp_path), window_s=30.0)
    inner = _ListSink()
    sink = FlightRecorderSink(inner, rec)
    sink.log(event="host_heartbeat", host="host0")  # not a trigger
    sink.log(event="slo_alert", state="clear")  # good news: no dump
    assert rec.dumps == []
    sink.log(event="slo_alert", state="fire", objective="p99")
    sink.log(event="non_finite_loss", step=3)
    assert [os.path.basename(p) for p in rec.dumps] == [
        "flight_001_slo_alert.json", "flight_002_non_finite_loss.json",
    ]
    dump = json.load(open(rec.dumps[0]))
    assert dump["trigger"]["objective"] == "p99"
    # Transparent wrapper: the inner sink saw the identical stream, and
    # every record also landed in the ring.
    assert [r["event"] for r in inner.records] == [
        "host_heartbeat", "slo_alert", "slo_alert", "non_finite_loss",
    ]
    assert len(rec.snapshot()["entries"]) == 4
    sink.flush()  # inner without flush(): a no-op, not an error
    # A sink-less wrapper (recorder-only plumbing) still triggers.
    bare = FlightRecorderSink(None, rec)
    bare.log(event="breaker_open", host="host1")
    assert len(rec.dumps) == 3
    bare.flush()


def test_watch_lockguard_registers_trigger_hook(tmp_path):
    from gnot_tpu.utils import lockguard

    rec = FlightRecorder(str(tmp_path), window_s=30.0, host="controller")
    rec.watch_lockguard()
    try:
        assert lockguard.on_report is not None
        lockguard.on_report({"kind": "inversion", "message": "A -> B"})
        (path,) = rec.dumps
        assert os.path.basename(path) == "flight_001_lockguard_warning.json"
        dump = json.load(open(path))
        assert dump["trigger"]["kind"] == "lockguard_warning"
        assert dump["trigger"]["message"] == "A -> B"
    finally:
        lockguard.on_report = None


# --- federated chaos: propagation + stitching end to end --------------------


@pytest.fixture(scope="module")
def setup():
    samples = datasets.synth_darcy2d(8, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    return model, params, samples


def _traced_federation(setup, tmp_path, hosts=2, *, recorders=None, **kw):
    import jax

    from gnot_tpu.serve import build_replica

    model, params, samples = setup
    devs = jax.devices()
    groups = [
        [
            build_replica(
                model, params, 0, [devs[h % len(devs)]],
                batch_size=MAX_BATCH,
            )
        ]
        for h in range(hosts)
    ]
    sink = MetricsSink(str(tmp_path / "fed.jsonl"))
    trace_path = str(tmp_path / "cluster_trace.json")
    kw.setdefault("router_kwargs", dict(max_batch=MAX_BATCH, max_wait_ms=2.0))
    cluster, agents = build_local_federation(
        groups,
        sink=sink,
        session_store=SessionStore(str(tmp_path / "sessions")),
        cluster_tracer=Tracer(
            sample_rate=1.0, recorder=(recorders or {}).get("controller")
        ),
        tracer_factory=lambda h: Tracer(
            recorder=(recorders or {}).get(h)
        ),
        trace_path=trace_path,
        recorders=recorders,
        **kw,
    )
    for a in agents.values():
        a.router.start()
    for g in groups:
        for r in g:
            r.warm(samples[:MAX_BATCH], rows=MAX_BATCH)
    return cluster, agents, sink, trace_path


def _tick_until(cluster, pred, timeout_s=30.0, dt=0.02):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        cluster.tick()
        if pred():
            return True
        time.sleep(dt)
    return False


def _spans(merged):
    return [e for e in merged["traceEvents"] if e.get("ph") == "X"]


def _placements(spans, tid):
    return [
        s for s in spans
        if s["name"] == "placement" and s["args"]["trace_id"] == tid
    ]


def _assert_one_chain(spans, tid):
    """One trace = ONE chain: exactly one root placement, every later
    placement (hedge/redeliver/remigrate/...) a link back to it."""
    plc = _placements(spans, tid)
    roots = [p for p in plc if "link_to" not in p["args"]]
    assert len(roots) == 1, [p["args"] for p in plc]
    anchor = roots[0]["args"]["span_id"].split(":")[-1]
    for p in plc:
        if p is not roots[0]:
            assert p["args"]["link_to"] == anchor, p["args"]
    return plc


def _tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"gnot_tool_{name}", os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_federated_stitch_coverage_and_breakdowns(setup, tmp_path):
    model, params, samples = setup
    cluster, agents, sink, trace_path = _traced_federation(
        setup, tmp_path, hosts=2
    )
    with sink:
        futs = [cluster.submit(s, tenant="acme") for s in samples[:4]]
        fut = cluster.submit_rollout(samples[0], 4, name="sess-t")
        results = [f.result(timeout=60) for f in futs]
        res = fut.result(timeout=120)
        # A few heartbeat rounds so every host has a clock estimate.
        for _ in range(3):
            cluster.tick()
            time.sleep(0.02)
        summary = cluster.drain()
    assert all(r.ok for r in results) and res.ok
    for a in agents.values():
        a.stop()
    # Per-host coverage rolls up into cluster_summary: the controller
    # decided (and kept) all 5 traces; every trace was adopted — not
    # re-decided — by at least one host; clock estimates ride along.
    cov = summary["trace_coverage"]
    assert set(cov) == {"controller", "host0", "host1"}
    assert cov["controller"]["seen"] == 5
    assert cov["controller"]["kept"] == 5
    assert cov["controller"]["dropped"] == 0
    assert sum(cov[h]["adopted"] for h in ("host0", "host1")) >= 5
    for h in ("host0", "host1"):
        assert abs(cov[h]["clock_offset_s"]) < 0.1  # same process clock
        assert cov[h]["clock_err_s"] >= 0.0
    merged = json.load(open(trace_path))
    assert cluster.merged_trace is not None
    assert set(merged["otherData"]["hosts"]) == {
        "controller", "host0", "host1",
    }
    spans = _spans(merged)
    # Stitching leaves no dangling chains: every parent_id resolves to
    # a span in the merged file (prefixing is consistent per source).
    ids = {s["args"]["span_id"] for s in spans}
    for s in spans:
        parent = s["args"].get("parent_id")
        assert parent is None or parent in ids, s["args"]
    # One terminal span per request/session, on the controller track.
    reqs = [s for s in spans if s["name"] == "cluster_request"]
    assert len(reqs) == 4
    assert len({s["args"]["trace_id"] for s in reqs}) == 4
    (roll,) = [s for s in spans if s["name"] == "cluster_rollout"]
    # Host-side phase spans ADOPTED the cluster's ids and carry the
    # propagated tenant tag.
    host_spans = [
        s for s in spans
        if s["args"].get("host") in ("host0", "host1")
        and s["name"] != "placement"
    ]
    cluster_tids = {s["args"]["trace_id"] for s in reqs}
    cluster_tids.add(roll["args"]["trace_id"])
    assert {s["args"]["trace_id"] for s in host_spans} <= cluster_tids
    assert any(s["args"].get("tenant") == "acme" for s in host_spans)
    # The merged file feeds trace_report's federated breakdowns
    # (tenant and per-host views agree with the drain rollup's keys).
    rep = _tool("trace_report").report(trace_path)
    assert "acme" in rep["tenants"]
    assert rep["tenants"]["acme"]["requests"] >= 1
    assert set(rep["hosts"]) >= {"host0", "host1"}
    assert sum(h["placements"] for h in rep["hosts"].values()) >= 5


def test_redelivered_submit_is_linked_span_same_trace(setup, tmp_path):
    # msg_drop eats the SUBMIT frame on a healthy host: the age-based
    # re-delivery must show up in the trace as a LINKED placement of
    # the SAME trace — never a dangling chain or a second trace.
    model, params, samples = setup
    cluster, agents, sink, trace_path = _traced_federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.2, dead_after_s=30.0,
    )
    with sink:
        # Frame ordinals are absolute per link: the handshake hello was
        # frame 1, so the next outbound frame — the submit — is #2.
        for host_id in ("host0", "host1"):
            cluster._hosts[host_id].link.arm(
                FaultInjector.from_spec("msg_drop@2")
            )
        futs = [cluster.submit(s) for s in samples[:4]]
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=60) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["hosts_dead"] == 0 and summary["lost"] == 0
    for a in agents.values():
        a.stop()
    spans = _spans(json.load(open(trace_path)))
    reqs = [s for s in spans if s["name"] == "cluster_request"]
    assert len(reqs) == 4  # one terminal per request, duplicates never
    redriven = 0
    for r in reqs:
        plc = _assert_one_chain(spans, r["args"]["trace_id"])
        redriven += sum(
            1 for p in plc if p["args"]["kind"] == "redeliver"
        )
    assert redriven >= 1  # the dropped submits WERE re-driven


def test_hedge_is_linked_span_not_second_chain(setup, tmp_path):
    # Partition host0 mid-storm and dwell in SUSPECT: the hedges that
    # cover its stranded one-shots are span LINKS on the original
    # traces — the suppressed duplicate never mints a second chain.
    model, params, samples = setup
    fi = FaultInjector.from_spec("net_partition@3")
    cluster, agents, sink, trace_path = _traced_federation(
        setup, tmp_path, hosts=2,
        suspect_after_s=0.2, dead_after_s=30.0,
        link_faults={"host0": fi},
    )
    link = cluster._hosts["host0"].link
    with sink:
        futs = [cluster.submit(s) for s in samples[:4]]
        assert _tick_until(
            cluster, lambda: link.partitioned, timeout_s=10
        ), "partition never armed"
        assert _tick_until(
            cluster,
            lambda: cluster.host_state("host0") == SUSPECT,
            timeout_s=10,
        )
        link.heal_partition()
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=120) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert all(r.ok for r in results), [r.reason for r in results]
    assert summary["hosts_dead"] == 0
    for a in agents.values():
        a.stop()
    spans = _spans(json.load(open(trace_path)))
    reqs = [s for s in spans if s["name"] == "cluster_request"]
    assert len(reqs) == 4
    assert len({s["args"]["trace_id"] for s in reqs}) == 4
    hedges = [
        s for s in spans
        if s["name"] == "placement" and s["args"]["kind"] == "hedge"
    ]
    assert hedges, "SUSPECT dwell produced no hedge placement"
    for h in hedges:
        _assert_one_chain(spans, h["args"]["trace_id"])


def test_host_kill_migration_joins_original_trace(setup, tmp_path):
    # The acceptance scenario: kill a session's owner mid-trajectory.
    # The re-migration appears as a linked 'remigrate' placement on the
    # ORIGINAL trace, the survivor's resumed step spans carry the SAME
    # trace id, and the controller's flight recorder dumps its black
    # box on the host_dead trigger edge.
    model, params, samples = setup
    steps = 12
    recorders = {
        "controller": FlightRecorder(
            str(tmp_path / "flight"), window_s=30.0, host="controller"
        )
    }
    cluster, agents, sink, trace_path = _traced_federation(
        setup, tmp_path, hosts=2, recorders=recorders,
        suspect_after_s=0.2, dead_after_s=0.5,
    )
    with sink:
        fut = cluster.submit_rollout(samples[0], steps, name="sess-kill")
        assert _tick_until(
            cluster,
            lambda: any(
                2 <= s.streamed < steps - 2
                for s in cluster._sessions.values()
            ),
        ), "session never reached the kill window"
        victim = next(
            s.owner
            for s in cluster._sessions.values()
            if 2 <= s.streamed < steps - 2
        )
        agents[victim].kill()
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        res = fut.result(timeout=180)
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    assert res.ok and len(res.outputs) == steps
    assert summary["remigrated"] >= 1 and summary["lost"] == 0
    for a in agents.values():
        a.stop()
    # The black box fired on the death edge, tagged with the victim.
    dumps = [
        p for p in recorders["controller"].dumps
        if os.path.basename(p).endswith("_host_dead.json")
    ]
    assert dumps, recorders["controller"].dumps
    dump = json.load(open(dumps[0]))
    assert dump["trigger"]["kind"] == "host_dead"
    assert dump["trigger"]["host"] == victim
    kinds = {e["record"].get("event") for e in dump["entries"]
             if e["type"] == "event"}
    assert "host_dead" in kinds  # the trigger record itself is retained
    assert any(e["type"] == "span" for e in dump["entries"])
    # Stitched trace: the resumed steps joined the ORIGINAL trace.
    spans = _spans(json.load(open(trace_path)))
    (roll,) = [s for s in spans if s["name"] == "cluster_rollout"]
    tid = roll["args"]["trace_id"]
    plc = _assert_one_chain(spans, tid)
    assert "remigrate" in {p["args"]["kind"] for p in plc}
    survivor = next(h for h in ("host0", "host1") if h != victim)
    resumed = [
        s for s in spans
        if s["args"].get("host") == survivor
        and s["args"].get("trace_id") == tid
        and s["name"] != "placement"
    ]
    assert resumed, f"no {survivor} spans joined trace {tid}"
