"""End-to-end training tests: loss decreases, eval is deterministic,
checkpoint round-trips, CLI runs."""

import os

import numpy as np
import pytest

from gnot_tpu import make_config
from gnot_tpu.data import datasets
from gnot_tpu.main import build_parser, config_from_args, model_config
from gnot_tpu.train.trainer import Trainer


def small_setup(tmp_path=None, epochs=3, **flag_overrides):
    argv = [
        "--n_attn_layers", "2", "--n_attn_hidden_dim", "32", "--n_mlp_num_layers", "2",
        "--n_mlp_hidden_dim", "32", "--n_input_hidden_dim", "32", "--n_expert", "2",
        "--n_head", "4", "--epochs", str(epochs), "--n_train", "16", "--n_test", "8",
        "--synthetic", "darcy2d",
    ]
    for k, v in flag_overrides.items():
        # value None -> bare store_true flag
        argv += [f"--{k}"] if v is None else [f"--{k}", str(v)]
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    train, test = datasets.load(cfg.data)
    mc = model_config(cfg, args, train)
    return cfg, mc, train, test


def test_training_reduces_loss(capsys):
    cfg, mc, train, test = small_setup(epochs=5)
    trainer = Trainer(cfg, mc, train, test)
    best = trainer.fit()
    out = capsys.readouterr().out
    # Reference-format console lines (main.py:105,147-148,153).
    assert "Epoch 0, Loss: " in out
    assert "Epoch 0, Test Metric: " in out
    assert "Best Test Metric: " in out
    first = float(out.split("Epoch 0, Loss: ")[1].splitlines()[0])
    last = float(out.split(f"Epoch {cfg.train.epochs - 1}, Loss: ")[1].splitlines()[0])
    assert last < first, f"training did not reduce loss: {first} -> {last}"
    assert best < first


def test_eval_deterministic():
    cfg, mc, train, test = small_setup(epochs=1)
    trainer = Trainer(cfg, mc, train, test)
    trainer.initialize()
    assert trainer.evaluate() == trainer.evaluate()


def test_checkpoint_resume(tmp_path):
    from gnot_tpu.train.checkpoint import Checkpointer

    cfg, mc, train, test = small_setup(
        epochs=2, checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=1
    )
    t1 = Trainer(cfg, mc, train, test, checkpointer=Checkpointer(cfg.train.checkpoint_dir))
    t1.fit()

    import dataclasses

    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume=True, epochs=2)
    )
    t2 = Trainer(cfg2, mc, train, test, checkpointer=Checkpointer(cfg.train.checkpoint_dir))
    t2.initialize()
    assert t2.start_epoch == 2  # resumes past both epochs
    np.testing.assert_array_equal(
        np.asarray(t2.state.step), np.asarray(t1.state.step)
    )
    leaves1 = [np.asarray(x) for x in __import__("jax").tree.leaves(t1.state.params)]
    leaves2 = [np.asarray(x) for x in __import__("jax").tree.leaves(t2.state.params)]
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_crash_window_keeps_old_state(tmp_path):
    """A save whose sidecar never got published (crash between commit
    and the next wait) must leave the previous checkpoint restorable."""
    from gnot_tpu.train.checkpoint import Checkpointer

    cfg, mc, train, test = small_setup(epochs=1)
    t = Trainer(cfg, mc, train, test)
    t.initialize()

    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save_latest(t.state, epoch=1, best_metric=0.5)
    ck.wait()  # epoch-1 committed + sidecar published
    # Second save commits but its sidecar is never published ("crash"
    # before the next wait): a fresh Checkpointer must restore epoch 1.
    ck.save_latest(t.state, epoch=2, best_metric=0.4)
    ck._ckptr.wait_until_finished()  # data committed, sidecar NOT flushed

    ck2 = Checkpointer(str(tmp_path / "ckpt"))
    restored = ck2.restore_latest(t.state)
    assert restored is not None
    _, epoch, best = restored
    assert (epoch, best) == (1, 0.5)

    # After a proper wait the new save becomes the restore target and the
    # superseded directory is pruned.
    ck.wait()
    restored = Checkpointer(str(tmp_path / "ckpt")).restore_latest(t.state)
    assert restored is not None and restored[1:] == (2, 0.4)
    dirs = sorted(
        d for d in os.listdir(tmp_path / "ckpt")
        if (tmp_path / "ckpt" / d).is_dir()
    )
    assert dirs == ["latest.2"]


def test_cli_smoke(capsys):
    from gnot_tpu.main import main

    best = main(
        [
            "--n_attn_layers", "1", "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
            "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16", "--n_expert", "2",
            "--n_head", "2", "--epochs", "1", "--n_train", "8", "--n_test", "4",
            "--synthetic", "ns2d",
        ]
    )
    assert np.isfinite(best)


def test_parity_schedule_bug_lr_stays_on_warmup():
    """With the per-epoch stepping bug, LR after `epochs` scheduler steps
    is still deep in the warm-up ramp (SURVEY.md §2 row 8)."""
    from gnot_tpu.config import OptimConfig
    from gnot_tpu.train.schedule import make_lr_fn

    cfg = OptimConfig(parity_schedule_bug=True)
    lr_fn = make_lr_fn(cfg, steps_per_epoch=250, epochs=100)
    lr_final = lr_fn(0, 99)  # epoch counter after 99 steps
    # 100 steps into a 25000-step cycle: still < 1/6 of the ramp.
    assert lr_final < cfg.lr / 2
    correct = OptimConfig(parity_schedule_bug=False)
    lr_fn2 = make_lr_fn(correct, steps_per_epoch=250, epochs=100)
    assert lr_fn2(24999, 0) < 1e-6  # per-step schedule reaches the floor


def test_checkpoint_resume_replay_same_epoch_keeps_committed_dir(tmp_path):
    """Saving the same (name, epoch) the published sidecar names must not
    delete that committed directory at kickoff — resume-replay hits this
    when a re-run epoch improves the metric again."""
    from gnot_tpu.train.checkpoint import Checkpointer

    cfg, mc, train, test = small_setup(epochs=1)
    t = Trainer(cfg, mc, train, test)
    t.initialize()

    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save_best(t.state, epoch=7, best_metric=0.5)
    ck.wait()  # best.7 committed, sidecar published

    # Replay epoch 7 (e.g. after resume from latest.6); the new save
    # must land in a fresh dir while best.7 stays restorable.
    ck.save_best(t.state, epoch=7, best_metric=0.4)
    assert (tmp_path / "ckpt" / "best.7").is_dir()  # old one intact
    restored = Checkpointer(str(tmp_path / "ckpt")).restore_best(t.state)
    assert restored is not None and restored[1:] == (7, 0.5)

    ck.wait()
    restored = Checkpointer(str(tmp_path / "ckpt")).restore_best(t.state)
    assert restored is not None and restored[1:] == (7, 0.4)
    dirs = sorted(
        d for d in os.listdir(tmp_path / "ckpt") if (tmp_path / "ckpt" / d).is_dir()
    )
    assert dirs == ["best.7r1"]


def test_grad_accum_two_micro_equals_one_full_batch():
    """MultiSteps(k=2): two micro-batches of B/2 produce the same update
    as one step on the combined batch (equal micro sizes -> averaged
    micro-grads == grad of the combined per-batch-mean loss)."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import (
        TrainState,
        init_state,
        make_optimizer,
        make_train_step,
    )

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=1, n_attn_hidden_dim=16,
        n_mlp_num_layers=1, n_mlp_hidden_dim=16, n_input_hidden_dim=16,
        n_expert=2, n_head=2,
    )
    samples = datasets.synth_ns2d(4, n_points=32, seed=3)
    full = collate(samples, bucket=False)
    micro1 = collate(samples[:2], bucket=False)
    micro2 = collate(samples[2:], bucket=False)
    model = GNOT(mc)
    lr = jnp.asarray(1e-3, jnp.float32)

    base = OptimConfig()
    params0 = init_state(model, base, full, seed=0).params
    state_full = init_state(model, base, full, seed=0)
    step_full = make_train_step(model, base, "rel_l2")
    out_full, _ = step_full(state_full, full, lr)

    accum = OptimConfig(grad_accum=2)
    tx = make_optimizer(accum, lr)
    state_acc = TrainState(
        params=jax.tree.map(jnp.copy, params0),
        opt_state=tx.init(params0),
        step=jnp.zeros((), jnp.int32),
    )
    step_acc = make_train_step(model, accum, "rel_l2")
    state_acc, _ = step_acc(state_acc, micro1, lr)
    # After the first micro-batch no real update has happened.
    for a, b in zip(jax.tree.leaves(state_acc.params), jax.tree.leaves(params0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state_acc, _ = step_acc(state_acc, micro2, lr)

    for a, b in zip(
        jax.tree.leaves(state_acc.params), jax.tree.leaves(out_full.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_torch_backend_cli_smoke(capsys):
    """--backend torch drives the reference model through this
    framework's data pipeline (the oracle path)."""
    pytest.importorskip("torch")
    if not os.path.exists("/root/reference/model.py"):
        pytest.skip("reference checkout not available")
    from gnot_tpu.main import main

    best = main(
        [
            "--backend", "torch", "--synthetic", "darcy2d", "--epochs", "1",
            "--n_train", "8", "--n_test", "4", "--n_attn_layers", "1",
            "--n_attn_hidden_dim", "16", "--n_mlp_num_layers", "1",
            "--n_mlp_hidden_dim", "16", "--n_input_hidden_dim", "16",
            "--n_expert", "2", "--n_head", "2",
        ]
    )
    out = capsys.readouterr().out
    assert np.isfinite(best)
    assert "Epoch 0, Loss: " in out  # reference console format


def test_bf16_training_reduces_loss(capsys):
    """bfloat16 compute path trains (loss decreases, stays finite)."""
    cfg, mc, train, test = small_setup(epochs=4)
    import dataclasses

    mc = dataclasses.replace(mc, dtype="bfloat16")
    trainer = Trainer(cfg, mc, train, test)
    best = trainer.fit()
    out = capsys.readouterr().out
    first = float(out.split("Epoch 0, Loss: ")[1].splitlines()[0])
    last = float(out.split(f"Epoch {cfg.train.epochs - 1}, Loss: ")[1].splitlines()[0])
    assert np.isfinite(best)
    assert last < first, f"bf16 training did not reduce loss: {first} -> {last}"


def test_multi_step_dispatch_matches_single_steps():
    """steps_per_dispatch=K scans K steps into one program; the result
    must be numerically identical to K single-step dispatches (same
    final params, same per-step losses)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import (
        init_state,
        make_multi_train_step,
        make_train_step,
        stack_batches,
    )

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=1, n_attn_hidden_dim=16,
        n_mlp_num_layers=1, n_mlp_hidden_dim=16, n_input_hidden_dim=16,
        n_expert=2, n_head=2,
    )
    samples = datasets.synth_ns2d(8, n_points=32)
    batches = list(Loader(samples, 2))[:4]
    model = GNOT(mc)
    optim = OptimConfig()
    lrs = [1e-3, 9e-4, 8e-4, 7e-4]

    s1 = init_state(model, optim, batches[0], seed=0)
    # DEEP copies, not jax.device_get: on the CPU backend device_get
    # returns zero-copy views of the device buffers, and the donated
    # (donate_argnums=(0,)) train_step below may write its updated
    # params INto those very buffers — whether it actually does depends
    # on the executable's buffer assignment, which differs between a
    # fresh XLA compile and a persistent-compile-cache load. That made
    # this test fail only with a warm compile cache (losses2 came out
    # as steps 5-8 of a continued run: s2 silently started from s1's
    # FINAL params). Root cause of the long-standing tier-1 failure —
    # use-after-donate through an aliased host view, not numerics.
    host = jax.tree.map(lambda x: np.array(x, copy=True), s1.params)
    single = make_train_step(model, optim, "rel_l2")
    losses1 = []
    for b, lr in zip(batches, lrs):
        s1, loss = single(s1, b, jnp.asarray(lr, jnp.float32))
        losses1.append(float(loss))

    s2 = init_state(model, optim, batches[0], seed=0)
    s2 = dataclasses.replace(s2, params=jax.tree.map(jnp.asarray, host))
    multi = make_multi_train_step(model, optim, "rel_l2")
    s2, losses2 = multi(
        s2, stack_batches(batches), jnp.asarray(np.asarray(lrs, np.float32))
    )
    np.testing.assert_allclose(losses1, np.asarray(losses2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_trainer_fit_steps_per_dispatch_matches_single(capsys):
    """Trainer.fit with steps_per_dispatch=2 reproduces the k=1 run's
    per-epoch losses and metrics exactly; with 3 steps/epoch the odd
    batch flushes through the single-step path."""

    def run(k):
        # 6 train samples at batch 2 -> 3 steps/epoch: one full group
        # of 2 plus a remainder single step per epoch.
        cfg, mc, train, test = small_setup(
            epochs=2, n_train=6, n_test=4, batch_size=2,
            steps_per_dispatch=k,
        )
        best = Trainer(cfg, mc, train, test).fit()
        return best, capsys.readouterr().out

    from helpers import assert_epoch_lines_close

    b1, out1 = run(1)
    b2, out2 = run(2)
    np.testing.assert_allclose(b1, b2, rtol=1e-5)
    assert_epoch_lines_close(out1, out2, rtol=1e-6)


def test_same_seed_reproduces_run(capsys):
    """Two Trainer.fit runs with identical config and seed produce
    identical console losses/metrics (init, shuffle order, and the
    whole compiled path are deterministic)."""

    def run():
        cfg, mc, train, test = small_setup(epochs=2, n_train=8, n_test=4)
        best = Trainer(cfg, mc, train, test).fit()
        return best, capsys.readouterr().out

    b1, out1 = run()
    b2, out2 = run()
    assert b1 == b2
    l1 = [l for l in out1.splitlines() if l.startswith("Epoch")]
    l2 = [l for l in out2.splitlines() if l.startswith("Epoch")]
    assert l1 and l1 == l2


def test_scan_layers_with_steps_per_dispatch(capsys):
    """The two compile/dispatch levers compose: scan_layers' stacked
    loss_fn threads through the multi-step scanned builders, matching
    the plain run's console output."""

    from helpers import assert_epoch_lines_close

    def run(extra):
        cfg, mc, train, test = small_setup(
            epochs=2, n_train=8, n_test=4, batch_size=2, **extra
        )
        best = Trainer(cfg, mc, train, test).fit()
        return best, capsys.readouterr().out

    b_plain, out_plain = run({})
    b_both, out_both = run({"scan_layers": None, "steps_per_dispatch": 2})
    np.testing.assert_allclose(b_plain, b_both, rtol=1e-5)
    assert_epoch_lines_close(out_plain, out_both, rtol=1e-5)


def test_flat_params_step_matches_standard():
    """The flat [P]-vector layout is the SAME math: N training steps
    from the same init produce (near-)identical losses and params —
    ravel/unravel is exact and AdamW is elementwise, so only XLA
    fusion differences remain."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.train.trainer import (
        flat_loss_fn,
        init_flat_state,
        init_state,
        make_train_step,
    )

    cfg, mc, train, _ = small_setup(epochs=1)
    model = GNOT(mc)
    batch = next(iter(Loader(train, cfg.data.batch_size)))
    s_std = init_state(model, cfg.optim, batch, seed=0)
    s_flat, unravel = init_flat_state(model, cfg.optim, batch, seed=0)
    step_std = make_train_step(model, cfg.optim, cfg.train.loss)
    step_flat = make_train_step(
        model, cfg.optim, cfg.train.loss,
        loss_fn=flat_loss_fn(model, unravel, cfg.train.loss),
    )
    lr = jnp.asarray(1e-3, jnp.float32)
    for _ in range(3):
        s_std, loss_std = step_std(s_std, batch, lr)
        s_flat, loss_flat = step_flat(s_flat, batch, lr)
        np.testing.assert_allclose(
            float(loss_std), float(loss_flat), rtol=1e-6
        )
    import jax as _jax

    _jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s_std.params,
        unravel(s_flat.params),
    )


def test_flat_params_fit_matches_standard(capsys):
    """Trainer end-to-end with --flat_params: same console losses,
    same final params (via standard_params), same predictions."""
    from helpers import assert_epoch_lines_close

    def run(extra):
        cfg, mc, train, test = small_setup(epochs=3, **extra)
        t = Trainer(cfg, mc, train, test)
        best = t.fit()
        return t, test, best, capsys.readouterr().out

    t_std, test_s, b_std, out_std = run({})
    t_flat, _, b_flat, out_flat = run({"flat_params": None})
    np.testing.assert_allclose(b_std, b_flat, rtol=1e-5)
    assert_epoch_lines_close(out_std, out_flat, rtol=1e-5)
    import jax

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        t_std.standard_params(),
        t_flat.standard_params(),
    )
    for a, b in zip(t_std.predict(test_s[:3]), t_flat.predict(test_s[:3])):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_flat_params_steps_per_dispatch_matches(capsys):
    """flat_params threads through the K-step scanned dispatch path."""
    from helpers import assert_epoch_lines_close

    def run(extra):
        cfg, mc, train, test = small_setup(
            epochs=2, n_train=8, n_test=4, batch_size=2, **extra
        )
        best = Trainer(cfg, mc, train, test).fit()
        return best, capsys.readouterr().out

    b_plain, out_plain = run({})
    b_flat, out_flat = run({"flat_params": None, "steps_per_dispatch": 2})
    np.testing.assert_allclose(b_plain, b_flat, rtol=1e-5)
    assert_epoch_lines_close(out_plain, out_flat, rtol=1e-5)


def test_flat_params_checkpoint_resume(tmp_path):
    """Flat-layout TrainStates round-trip through Orbax save/resume."""
    from gnot_tpu.train.checkpoint import Checkpointer

    cfg, mc, train, test = small_setup(
        epochs=2, checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=1,
        flat_params=None,
    )
    t1 = Trainer(cfg, mc, train, test, checkpointer=Checkpointer(cfg.train.checkpoint_dir))
    t1.fit()

    import dataclasses

    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume=True, epochs=2)
    )
    t2 = Trainer(cfg2, mc, train, test, checkpointer=Checkpointer(cfg.train.checkpoint_dir))
    t2.initialize()
    assert t2.start_epoch == 2
    np.testing.assert_array_equal(
        np.asarray(t2.state.params), np.asarray(t1.state.params)
    )


def test_flat_params_rejects_incompatible_layouts():
    """flat_params needs the tree layout's absence: scan_layers and
    param-sharding mesh axes raise at construction with named flags."""
    cfg, mc, train, test = small_setup(epochs=1, flat_params=None, scan_layers=None)
    with pytest.raises(ValueError, match="flat_params"):
        Trainer(cfg, mc, train, test)

    cfg, mc, train, test = small_setup(
        epochs=1, flat_params=None, distributed=None, mesh_model="2", mesh_data="4",
    )
    with pytest.raises(ValueError, match="flat_params"):
        Trainer(cfg, mc, train, test)


def test_flat_params_checkpoint_layout_warning(tmp_path, capsys):
    """Restoring a flat-layout checkpoint into a tree-layout run warns
    with the flag to flip BEFORE orbax's structure error surfaces."""
    from gnot_tpu.train.checkpoint import Checkpointer

    cfg, mc, train, test = small_setup(epochs=1, flat_params=None)
    t = Trainer(cfg, mc, train, test)
    t.initialize()
    ck = Checkpointer(str(tmp_path / "ckpt"), extra_meta={"flat_params": True})
    ck.save_latest(t.state, 1, 0.5)
    ck.wait()

    cfg2, mc2, train2, test2 = small_setup(epochs=1)
    t2 = Trainer(cfg2, mc2, train2, test2)
    t2.initialize()
    ck2 = Checkpointer(str(tmp_path / "ckpt"), extra_meta={"flat_params": False})
    with pytest.raises(Exception):
        ck2.restore_latest(t2.state)
    out = capsys.readouterr().out
    assert "--flat_params" in out and "layout" in out


def test_flat_params_grad_accum_matches_tree():
    """The claimed flat_params x grad_accum composition: MultiSteps over
    the single flat leaf produces the same trajectory as over the tree."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import (
        flat_loss_fn,
        init_flat_state,
        init_state,
        make_train_step,
    )

    mc = ModelConfig(
        input_dim=2, theta_dim=1, input_func_dim=3, out_dim=1,
        n_input_functions=1, n_attn_layers=1, n_attn_hidden_dim=16,
        n_mlp_num_layers=1, n_mlp_hidden_dim=16, n_input_hidden_dim=16,
        n_expert=2, n_head=2,
    )
    samples = datasets.synth_ns2d(4, n_points=32, seed=3)
    micros = [collate(samples[:2], bucket=False), collate(samples[2:], bucket=False)]
    model = GNOT(mc)
    optim = OptimConfig(grad_accum=2)
    lr = jnp.asarray(1e-3, jnp.float32)

    s_tree = init_state(model, optim, micros[0], seed=0)
    step_tree = make_train_step(model, optim, "rel_l2")
    s_flat, unravel = init_flat_state(model, optim, micros[0], seed=0)
    step_flat = make_train_step(
        model, optim, "rel_l2", loss_fn=flat_loss_fn(model, unravel, "rel_l2")
    )
    for b in micros * 2:  # two full accumulation windows
        s_tree, loss_t = step_tree(s_tree, b, lr)
        s_flat, loss_f = step_flat(s_flat, b, lr)
        np.testing.assert_allclose(float(loss_t), float(loss_f), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s_tree.params,
        unravel(s_flat.params),
    )


def test_convert_flat_state_roundtrip_continues_training():
    """convert_flat_state moves a mid-training TrainState (params AND
    AdamW moments) between layouts: flat steps -> convert -> tree steps
    matches an all-tree run, and the roundtrip is exact."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.train.trainer import (
        convert_flat_state,
        flat_loss_fn,
        init_flat_state,
        init_params,
        init_state,
        make_train_step,
    )

    cfg, mc, train, _ = small_setup(epochs=1)
    model = GNOT(mc)
    batch = next(iter(Loader(train, cfg.data.batch_size)))
    lr = jnp.asarray(1e-3, jnp.float32)
    template = init_params(model, batch, seed=0)

    s_tree = init_state(model, cfg.optim, batch, seed=0)
    step_tree = make_train_step(model, cfg.optim, cfg.train.loss)
    s_flat, unravel = init_flat_state(model, cfg.optim, batch, seed=0)
    step_flat = make_train_step(
        model, cfg.optim, cfg.train.loss,
        loss_fn=flat_loss_fn(model, unravel, cfg.train.loss),
    )
    for _ in range(2):
        s_tree, _ = step_tree(s_tree, batch, lr)
        s_flat, _ = step_flat(s_flat, batch, lr)

    # Roundtrip exactness.
    rt = convert_flat_state(
        convert_flat_state(s_flat, template, "tree"), template, "flat"
    )
    np.testing.assert_array_equal(
        np.asarray(rt.params), np.asarray(s_flat.params)
    )

    # Converted state continues training in the OTHER layout: one more
    # tree step from the converted flat state == three all-tree steps.
    s_conv = convert_flat_state(s_flat, template, "tree")
    s_conv, loss_c = step_tree(s_conv, batch, lr)
    s_tree, loss_t = step_tree(s_tree, batch, lr)
    np.testing.assert_allclose(float(loss_c), float(loss_t), rtol=1e-6)
    import jax as _jax

    _jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s_tree.params,
        s_conv.params,
    )


def test_convert_flat_state_with_grad_accum_state():
    """The conversion docstring's MultiSteps claim: acc_grads (a
    param-shaped tree nested inside MultiStepsState) crosses layouts
    too, mid-accumulation-window."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.train.trainer import (
        convert_flat_state,
        flat_loss_fn,
        init_flat_state,
        init_params,
        make_train_step,
    )

    cfg, mc, train, _ = small_setup(epochs=1)
    import dataclasses

    optim = dataclasses.replace(cfg.optim, grad_accum=2, grad_clip_norm=1.0)
    model = GNOT(mc)
    batch = next(iter(Loader(train, cfg.data.batch_size)))
    template = init_params(model, batch, seed=0)
    s_flat, unravel = init_flat_state(model, optim, batch, seed=0)
    step_flat = make_train_step(
        model, optim, cfg.train.loss,
        loss_fn=flat_loss_fn(model, unravel, cfg.train.loss),
    )
    # ONE step: mid-window, acc_grads holds a nonzero accumulator —
    # assert it, or a window-accounting change could silently turn this
    # into an all-zeros conversion that tests nothing.
    s_flat, _ = step_flat(s_flat, batch, jnp.asarray(1e-3, jnp.float32))
    size = np.asarray(s_flat.params).size
    mid_window = [
        leaf
        for leaf in jax.tree.leaves(s_flat.opt_state)
        if np.ndim(leaf) == 1 and np.size(leaf) == size and np.any(leaf)
    ]
    assert mid_window, "expected a nonzero param-shaped accumulator mid-window"

    tree = convert_flat_state(s_flat, template, "tree")
    # Every param-shaped piece (params + moments + accumulators) is now
    # tree-structured: no 1-D size-P leaf survives anywhere.
    for leaf in jax.tree.leaves(tree):
        assert not (np.ndim(leaf) == 1 and np.size(leaf) == size)
    rt = convert_flat_state(tree, template, "flat")
    assert jax.tree_util.tree_structure(rt) == jax.tree_util.tree_structure(
        s_flat
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        rt,
        s_flat,
    )


def test_packed_training_reduces_loss(capsys):
    """--packed end-to-end on a ragged config: trains, converges, and
    the packed loader covers every sample each epoch."""
    cfg, mc, train, test = small_setup(
        epochs=5, synthetic="elasticity", packed=None
    )
    trainer = Trainer(cfg, mc, train, test)
    best = trainer.fit()
    out = capsys.readouterr().out
    assert "Epoch 0, Loss: " in out and "Best Test Metric: " in out
    first = float(out.split("Epoch 0, Loss: ")[1].splitlines()[0])
    last = float(out.split(f"Epoch {cfg.train.epochs - 1}, Loss: ")[1].splitlines()[0])
    assert last < first
    assert np.isfinite(best)
    # predict still runs through the standard unpacked path.
    preds = trainer.predict(test[:2])
    assert len(preds) == 2
    assert preds[0].shape == test[0].y.shape


def test_packed_eval_close_to_unpacked_eval():
    """The packed eval metric ~= the unpacked masked eval on the same
    params (both are means of per-sample rel-L2, grouped differently)."""
    cfg, mc, train, test = small_setup(epochs=1, synthetic="elasticity")
    t_std = Trainer(cfg, mc, train, test)
    t_std.initialize()
    m_std = t_std.evaluate()

    cfg_p, mc_p, train_p, test_p = small_setup(
        epochs=1, synthetic="elasticity", packed=None
    )
    t_p = Trainer(cfg_p, mc_p, train_p, test_p)
    t_p.initialize()
    m_p = t_p.evaluate()
    # Same init (same seed) and the same per-sample metric; only the
    # grouping of the mean differs (per-batch vs per-dispatch).
    np.testing.assert_allclose(m_std, m_p, rtol=0.05)


def test_packed_rejects_incompatible_modes():
    for extra, match in (
        ({"packed": None, "attention_mode": "parity", "no_bucket": None}, "masked"),
        ({"packed": None, "scan_layers": None}, "scan_layers"),
        ({"packed": None, "flat_params": None}, "flat_params"),
        ({"packed": None, "distributed": None, "mesh_seq": "2"}, "seq"),
    ):
        cfg, mc, train, test = small_setup(epochs=1, **extra)
        with pytest.raises(ValueError, match=match):
            Trainer(cfg, mc, train, test)


def test_packed_distributed_fit():
    """--packed --distributed (single-process mesh): rows shard over
    the data axis, training runs and converges."""
    cfg, mc, train, test = small_setup(
        epochs=3, synthetic="elasticity", packed=None, distributed=None,
        mesh_data="4", mesh_model="2",
    )
    trainer = Trainer(cfg, mc, train, test)
    assert trainer.train_loader.n_rows % 4 == 0
    best = trainer.fit()
    assert np.isfinite(best)
