"""Chaos suite: every injected fault class either completes training or
exits resume-ready, and resumed runs land within tolerance of an
uninterrupted run (ISSUE 2 acceptance criteria; docs/robustness.md).

Fast faults run unmarked in tier-1; long multi-fault scenarios carry
``-m slow``. Fault specs drive everything (``train.inject_fault``) so
the tests exercise the same mechanism operators use.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from gnot_tpu import make_config
from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.resilience.faults import (
    FaultInjector,
    FaultSpec,
    corrupt_checkpoint,
    dangle_sidecar,
    parse_fault_spec,
)
from gnot_tpu.resilience.retry import RetryPolicy, retry_io
from gnot_tpu.train.checkpoint import Checkpointer
from gnot_tpu.train.trainer import Trainer
from gnot_tpu.utils.metrics import MetricsSink


def tiny_setup(epochs=3, n_train=16, n_test=8, **over):
    cfg = make_config(**{
        "data.n_train": n_train, "data.n_test": n_test,
        "data.synthetic": "darcy2d", "train.epochs": epochs, **over,
    })
    train, test = datasets.load(cfg.data)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    return cfg, mc, train, test


def read_events(path):
    recs = [json.loads(l) for l in open(path)]
    return [r for r in recs if r.get("event")]


@pytest.fixture(scope="module")
def clean_best():
    """Best metric of the uninterrupted 3-epoch reference run — the
    tolerance anchor every fault scenario compares against."""
    cfg, mc, train, test = tiny_setup()
    return Trainer(cfg, mc, train, test).fit()


# --- spec parsing / plumbing ----------------------------------------------


def test_parse_fault_spec():
    assert parse_fault_spec("") == []
    assert parse_fault_spec("nan_grad@3, ckpt_io@2") == [
        FaultSpec("nan_grad", 3), FaultSpec("ckpt_io", 2),
    ]
    for bad in ("nan_grad", "nan_grad@x", "typo@3", "nan_grad@0"):
        with pytest.raises(ValueError, match="fault spec"):
            parse_fault_spec(bad)


def test_stop_after_epoch_is_injector_alias():
    """--stop_after_epoch and stop_epoch@N are ONE mechanism: the
    legacy flag maps into the injection plan."""
    cfg = make_config(**{"train.stop_after_epoch": 2})
    inj = FaultInjector.from_config(cfg.train)
    assert inj is not None
    assert inj.stop_after_epoch(1) and not inj.stop_after_epoch(0)
    # The spec form behaves identically.
    inj2 = FaultInjector.from_config(
        make_config(**{"train.inject_fault": "stop_epoch@2"}).train
    )
    assert inj2.stop_after_epoch(1) and not inj2.stop_after_epoch(0)


def test_bad_fault_spec_fails_at_construction():
    cfg, mc, train, test = tiny_setup(**{"train.inject_fault": "nope@1"})
    with pytest.raises(ValueError, match="fault spec"):
        Trainer(cfg, mc, train, test)


def test_retry_io_backoff_and_final_raise():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert (
        retry_io(flaky, policy=RetryPolicy(attempts=4, base_delay_s=0.0),
                 sleep=lambda s: None)
        == "ok"
    )
    assert len(calls) == 3

    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry_io(always, policy=RetryPolicy(attempts=2, base_delay_s=0.0),
                 sleep=lambda s: None)
    # Non-transient errors pass straight through (no retry).
    def corrupt():
        calls.append("c")
        raise ValueError("bad bytes")

    calls.clear()
    with pytest.raises(ValueError):
        retry_io(corrupt, policy=RetryPolicy(attempts=4, base_delay_s=0.0),
                 sleep=lambda s: None)
    assert calls == ["c"]
    # Permanent filesystem answers (missing path, permission denied)
    # are OSErrors but NOT transient: no retry, immediate raise.
    def missing():
        calls.append("m")
        raise FileNotFoundError("no such file")

    calls.clear()
    with pytest.raises(FileNotFoundError):
        retry_io(missing, policy=RetryPolicy(attempts=4, base_delay_s=0.0),
                 sleep=lambda s: None)
    assert calls == ["m"]


def test_retry_io_deadline_clamps_sleeps_and_stops():
    """ISSUE 3 satellite: with a `deadline`, backoff sleeps are clamped
    to the remaining budget and a retry never starts past it — the
    retry loop cannot outlive its caller (a serving request, a hot
    reload)."""
    clk = [0.0]
    slept = []

    def sleep(s):
        slept.append(s)
        clk[0] += s

    def always():
        clk[0] += 0.05  # each attempt costs wall time too
        raise OSError("down")

    # Budget 0.4 s against 10 s base delays: every sleep is clamped to
    # the remaining budget and the loop gives up at the deadline — with
    # attempts=6 and no deadline this would sleep minutes.
    with pytest.raises(OSError, match="down"):
        retry_io(
            always,
            policy=RetryPolicy(attempts=6, base_delay_s=10.0, max_delay_s=10.0),
            sleep=sleep, deadline=0.4, clock=lambda: clk[0],
        )
    assert slept, "should have retried at least once before the deadline"
    assert all(s <= 0.4 for s in slept)
    assert clk[0] <= 0.4 + 0.05 + 1e-9  # overshoot bounded by one attempt

    # An already-expired deadline: the first failure is final (no sleep).
    slept.clear()
    with pytest.raises(OSError):
        retry_io(
            always,
            policy=RetryPolicy(attempts=6, base_delay_s=10.0),
            sleep=sleep, deadline=0.0, clock=lambda: clk[0],
        )
    assert slept == []


def test_checkpoint_restore_honors_deadline(tmp_path):
    """The checkpoint-restore call sites pass the caller's deadline
    through to the retry loop: a restore against injected flaky I/O
    with 30 s backoff completes within the (sub-second) budget instead
    of sitting through the schedule."""
    import time as _time

    from gnot_tpu.resilience.faults import FaultInjector

    cfg, mc, train, test = tiny_setup(epochs=1)
    ck = Checkpointer(str(tmp_path / "ck"))
    t = Trainer(cfg, mc, train, test, checkpointer=ck)
    t.initialize()
    ck.save_latest(t.state, 1, 0.5)
    ck.wait()
    flaky = Checkpointer(
        str(tmp_path / "ck"),
        fault_injector=FaultInjector.from_spec("ckpt_io@1"),
        retry_policy=RetryPolicy(attempts=4, base_delay_s=30.0),
    )
    t0 = _time.monotonic()
    out = flaky.restore_latest(t.state, deadline=_time.monotonic() + 0.3)
    elapsed = _time.monotonic() - t0
    assert out is not None  # restored once the injected budget drained
    assert elapsed < 10.0  # NOT the 30-60 s the un-clamped backoff takes


# --- NaN / bad-sample recovery --------------------------------------------


@pytest.mark.parametrize("fault", ["nan_grad@5", "bad_sample@5"])
def test_nonfinite_fault_recovers_within_tolerance(
    tmp_path, clean_best, fault
):
    """One poisoned step mid-run: the supervisor rolls back to the
    last-good snapshot, quarantines the dispatch, and training
    completes with a finite best metric within tolerance of the clean
    run (one skipped batch of trajectory drift)."""
    mp = str(tmp_path / "m.jsonl")
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": fault, "train.recovery": True,
        "train.snapshot_every": 2, "train.metrics_path": mp,
    })
    with MetricsSink(mp) as sink:
        best = Trainer(cfg, mc, train, test, metrics_sink=sink).fit()
    kinds = [e["event"] for e in read_events(mp)]
    assert "rollback" in kinds and "batch_quarantined" in kinds
    assert np.isfinite(best)
    np.testing.assert_allclose(best, clean_best, rtol=0.1)


def test_rollback_replay_pins_shuffle_order(tmp_path):
    """Content-poisoned sample + shuffle ON: recovery must replay the
    SAME epoch order (the loader's epoch counter advances per
    iteration, so the replay has to re-pin it). With the correct
    replay, quarantining the bad batch's ordinal actually skips the
    bad sample and each epoch costs ONE rollback; a wrong-order replay
    re-dispatches the bad sample, burns the budget, and aborts."""
    train = datasets.synth_darcy2d(16, seed=0)
    train[5].y[:] = np.nan  # a genuinely bad record, found by content
    test = datasets.synth_darcy2d(4, seed=1)
    cfg = make_config(**{
        "data.n_train": 16, "data.n_test": 4, "train.epochs": 2,
        "train.recovery": True, "train.snapshot_every": 1,
        "train.max_rollbacks": 2,  # one per epoch, none to waste
    })
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(train),
    )
    assert cfg.data.shuffle_train  # the property under test needs shuffle
    best = Trainer(cfg, mc, train, test).fit()
    assert np.isfinite(best)


def test_recovery_off_keeps_hard_abort(tmp_path):
    """Without --recovery the first NaN still kills the run (the
    fail-fast default is unchanged)."""
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": "nan_grad@2", "train.debug_checks": True,
    })
    with pytest.raises(FloatingPointError, match="non-finite"):
        Trainer(cfg, mc, train, test).fit()


def test_recovery_escalates_to_checkpoint_restore(tmp_path):
    """Rollback budget 0: the ladder's second rung restores the latest
    checkpoint and continues (the injected fault fires once, so the
    replay is clean)."""
    mp = str(tmp_path / "m.jsonl")
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": "nan_grad@6", "train.recovery": True,
        "train.max_rollbacks": 0, "train.snapshot_every": 2,
        "train.checkpoint_dir": ck, "train.checkpoint_every": 1,
        "train.metrics_path": mp,
    })
    with MetricsSink(mp) as sink:
        best = Trainer(
            cfg, mc, train, test, metrics_sink=sink,
            checkpointer=Checkpointer(ck),
        ).fit()
    kinds = [e["event"] for e in read_events(mp)]
    assert "recovery_restore" in kinds
    assert np.isfinite(best)


def test_recovery_exhausted_aborts_with_report(tmp_path):
    """No rollback budget, no checkpointer: the ladder bottoms out in
    the original hard abort (FloatingPointError + non_finite_loss
    event)."""
    mp = str(tmp_path / "m.jsonl")
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": "nan_grad@2", "train.recovery": True,
        "train.max_rollbacks": 0, "train.snapshot_every": 1,
        "train.metrics_path": mp,
    })
    with MetricsSink(mp) as sink:
        with pytest.raises(FloatingPointError, match="non-finite"):
            Trainer(cfg, mc, train, test, metrics_sink=sink).fit()
    assert any(e["event"] == "non_finite_loss" for e in read_events(mp))


# --- graceful preemption --------------------------------------------------


def test_sigterm_midepoch_saves_and_resumes(tmp_path, clean_best):
    """A real SIGTERM mid-epoch stops at the step boundary, saves
    ``latest``, logs preempt_save, and the --resume run reaches a best
    metric within tolerance of the uninterrupted run."""
    mp = str(tmp_path / "m.jsonl")
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": "sigterm@6", "train.checkpoint_dir": ck,
        "train.metrics_path": mp,
    })
    with MetricsSink(mp) as sink:
        Trainer(
            cfg, mc, train, test, metrics_sink=sink,
            checkpointer=Checkpointer(ck),
        ).fit()
    events = read_events(mp)
    assert any(
        e["event"] == "preempt_save" and e["resumable"] for e in events
    )
    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume=True, inject_fault="")
    )
    t2 = Trainer(cfg2, mc, train, test, checkpointer=Checkpointer(ck))
    best = t2.fit()
    assert np.isfinite(best)
    np.testing.assert_allclose(best, clean_best, rtol=0.1)


def test_preemption_handler_flag_and_restore():
    """The handler context installs/restores handlers and the stop flag
    reaches should_stop (single-process path, no collective)."""
    import signal

    from gnot_tpu.resilience.preemption import PreemptionHandler

    before = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.should_stop()
        h.request_stop()
        assert h.should_stop()
    assert signal.getsignal(signal.SIGTERM) is before


# --- checkpoint corruption / I/O ------------------------------------------


def _fitted_checkpoint(tmp_path, epochs=2):
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(
        epochs=epochs, n_train=8, n_test=4,
        **{"train.checkpoint_dir": ck, "train.checkpoint_every": 1},
    )
    t = Trainer(cfg, mc, train, test, checkpointer=Checkpointer(ck))
    t.fit()
    return ck, cfg, mc, train, test, t


def _resumed(ck, cfg, mc, train, test, **ck_kwargs):
    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume=True)
    )
    c = Checkpointer(ck, **ck_kwargs)
    t = Trainer(cfg2, mc, train, test, checkpointer=c)
    t.initialize()
    return c, t


def test_truncated_latest_dir_falls_back_to_best(tmp_path):
    ck, cfg, mc, train, test, _ = _fitted_checkpoint(tmp_path)
    meta = json.load(open(os.path.join(ck, "latest.json")))
    corrupt_checkpoint(os.path.join(ck, meta["dir"]), mode="truncate")
    c, t = _resumed(
        ck, cfg, mc, train, test,
        retry_policy=RetryPolicy(attempts=2, base_delay_s=0.0),
    )
    assert c.last_restore is not None and c.last_restore["fallback"]
    assert c.last_restore["name"] == "best"
    assert t.start_epoch == c.last_restore["epoch"]  # resumed from best


def test_dangling_sidecar_falls_back(tmp_path):
    """Sidecar names a directory that no longer exists — the walk must
    skip it, not crash or silently restart from scratch."""
    ck, cfg, mc, train, test, _ = _fitted_checkpoint(tmp_path)
    dangle_sidecar(ck, "latest")
    c, t = _resumed(ck, cfg, mc, train, test)
    assert c.last_restore is not None and c.last_restore["fallback"]
    assert t.start_epoch == c.last_restore["epoch"]


def test_missing_sidecar_scans_dirs(tmp_path):
    """Both sidecars deleted (crash before first publish): the on-disk
    directory scan still restores the newest committed checkpoint,
    with best_metric degraded to +inf (re-established by eval)."""
    ck, cfg, mc, train, test, _ = _fitted_checkpoint(tmp_path)
    os.remove(os.path.join(ck, "latest.json"))
    os.remove(os.path.join(ck, "best.json"))
    c, t = _resumed(ck, cfg, mc, train, test)
    assert c.last_restore is not None
    assert c.last_restore["dir"].startswith("latest.")
    assert c.last_restore["best_metric"] == float("inf")
    assert t.start_epoch >= 1


def test_everything_corrupt_restores_nothing(tmp_path):
    """All candidates unrestorable → restore_latest returns None (the
    trainer then starts fresh) — never an unhandled exception."""
    ck, cfg, mc, train, test, _ = _fitted_checkpoint(tmp_path)
    for d in os.listdir(ck):
        full = os.path.join(ck, d)
        if os.path.isdir(full):
            corrupt_checkpoint(full, mode="remove")
    c, t = _resumed(
        ck, cfg, mc, train, test,
        retry_policy=RetryPolicy(attempts=2, base_delay_s=0.0),
    )
    assert c.last_restore is None
    assert t.start_epoch == 0


def test_transient_ckpt_io_errors_retried(tmp_path):
    """ckpt_io@2: two injected transient failures during saves are
    retried with backoff; the run completes and the checkpoint is
    restorable; io_retry events land in the sink."""
    mp = str(tmp_path / "m.jsonl")
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(
        epochs=2, n_train=8, n_test=4,
        **{
            "train.inject_fault": "ckpt_io@2", "train.checkpoint_dir": ck,
            "train.checkpoint_every": 1, "train.metrics_path": mp,
        },
    )
    with MetricsSink(mp) as sink:
        t = Trainer(
            cfg, mc, train, test, metrics_sink=sink,
            checkpointer=Checkpointer(
                ck, retry_policy=RetryPolicy(attempts=4, base_delay_s=0.0)
            ),
        )
        best = t.fit()
    assert np.isfinite(best)
    assert sum(e["event"] == "io_retry" for e in read_events(mp)) == 2
    assert Checkpointer(ck).restore_latest(t.state) is not None


def test_corrupt_ckpt_injection_then_resume_falls_back(tmp_path):
    """corrupt_ckpt@2 truncates the committed epoch-2 'latest' after
    publish; the --resume run walks to a restorable candidate and
    still resumes (restore_fallback event)."""
    mp = str(tmp_path / "m.jsonl")
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(
        epochs=2, n_train=8, n_test=4,
        **{
            "train.inject_fault": "corrupt_ckpt@2",
            "train.checkpoint_dir": ck, "train.checkpoint_every": 1,
        },
    )
    t = Trainer(cfg, mc, train, test, checkpointer=Checkpointer(ck))
    t.fit()
    with MetricsSink(mp) as sink:
        cfg2 = dataclasses.replace(
            cfg,
            train=dataclasses.replace(
                cfg.train, resume=True, inject_fault="", metrics_path=mp
            ),
        )
        c = Checkpointer(
            ck, retry_policy=RetryPolicy(attempts=2, base_delay_s=0.0)
        )
        t2 = Trainer(
            cfg2, mc, train, test, metrics_sink=sink, checkpointer=c
        )
        t2.initialize()
    assert c.last_restore is not None and c.last_restore["fallback"]
    assert any(e["event"] == "restore_fallback" for e in read_events(mp))


def test_async_save_not_corrupted_by_donated_buffers(tmp_path):
    """Regression: the async orbax write used to read zero-copy views
    of state buffers the NEXT train step donates, so any checkpoint
    overlapped by further training held garbage (silently — or a heap
    abort). The save must snapshot: a 'latest' written mid-run and then
    overlapped by training restores the state AS OF THE SAVE."""
    import jax
    import jax.numpy as jnp

    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(epochs=1, n_train=8, n_test=4)
    t = Trainer(cfg, mc, train, test)
    t.initialize()
    batch = next(iter(t.train_loader))
    lr = jnp.asarray(1e-3, jnp.float32)
    t.state, _ = t.train_step(t.state, batch, lr)
    # True host copies (np.array copies; device_get could alias).
    ref = [np.array(x) for x in jax.tree.leaves(jax.device_get(t.state.params))]
    c = Checkpointer(ck)
    c.save_latest(t.state, 1, 0.5)  # async kickoff
    for _ in range(3):  # overlap the write with donating steps
        t.state, _ = t.train_step(t.state, batch, lr)
    c.wait()
    restored = Checkpointer(ck).restore_latest(t.state)
    assert restored is not None
    for a, b in zip(ref, jax.tree.leaves(restored[0].params)):
        np.testing.assert_array_equal(a, np.asarray(b))


# --- stop_epoch alias end to end ------------------------------------------


def test_stop_epoch_fault_then_resume_matches_continuous(capsys):
    """The injection-framework form of the preemption fault: a run
    stopped by stop_epoch@1 and resumed replays the continuous run's
    remaining epochs exactly (seeded shuffle + checkpointed state)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "ckpt")
        cont_cfg, mc, train, test = tiny_setup(
            epochs=2, n_train=8, n_test=4,
            **{"train.checkpoint_dir": os.path.join(d, "cont"),
               "train.checkpoint_every": 1},
        )
        Trainer(
            cont_cfg, mc, train, test,
            checkpointer=Checkpointer(cont_cfg.train.checkpoint_dir),
        ).fit()
        cont_out = capsys.readouterr().out

        int_cfg = dataclasses.replace(
            cont_cfg,
            train=dataclasses.replace(
                cont_cfg.train, checkpoint_dir=ck, inject_fault="stop_epoch@1"
            ),
        )
        Trainer(int_cfg, mc, train, test, checkpointer=Checkpointer(ck)).fit()
        capsys.readouterr()
        res_cfg = dataclasses.replace(
            int_cfg,
            train=dataclasses.replace(
                int_cfg.train, resume=True, inject_fault=""
            ),
        )
        Trainer(res_cfg, mc, train, test, checkpointer=Checkpointer(ck)).fit()
        res_out = capsys.readouterr().out

    cont = dict(
        l.split(", Loss: ")
        for l in cont_out.splitlines()
        if ", Loss: " in l
    )
    res = dict(
        l.split(", Loss: ")
        for l in res_out.splitlines()
        if ", Loss: " in l
    )
    assert set(res) == {"Epoch 1"}
    np.testing.assert_allclose(
        float(res["Epoch 1"]), float(cont["Epoch 1"]), rtol=1e-5
    )


# --- long scenarios (tier-2) ----------------------------------------------


@pytest.mark.slow
def test_multi_fault_chaos_run(tmp_path, clean_best):
    """Everything at once: a bad sample, a NaN step, two flaky
    checkpoint writes and a SIGTERM — the run survives the first three,
    exits resume-ready on the SIGTERM, and the resumed run lands within
    tolerance of the clean run."""
    mp = str(tmp_path / "m.jsonl")
    ck = str(tmp_path / "ckpt")
    cfg, mc, train, test = tiny_setup(**{
        "train.inject_fault": (
            "bad_sample@2,nan_grad@6,ckpt_io@2,sigterm@10"
        ),
        "train.recovery": True, "train.snapshot_every": 2,
        "train.checkpoint_dir": ck, "train.checkpoint_every": 1,
        "train.metrics_path": mp,
    })
    with MetricsSink(mp) as sink:
        Trainer(
            cfg, mc, train, test, metrics_sink=sink,
            checkpointer=Checkpointer(
                ck, retry_policy=RetryPolicy(attempts=4, base_delay_s=0.0)
            ),
        ).fit()
    kinds = [e["event"] for e in read_events(mp)]
    assert "rollback" in kinds and "preempt_save" in kinds
    cfg2 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume=True, inject_fault="")
    )
    best = Trainer(cfg2, mc, train, test, checkpointer=Checkpointer(ck)).fit()
    assert np.isfinite(best)
    np.testing.assert_allclose(best, clean_best, rtol=0.15)
