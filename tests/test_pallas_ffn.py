"""Fused gated expert FFN kernel vs the einsum formulation."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import ModelConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.ops.pallas_ffn import _reference_impl, fits_vmem, fused_gated_ffn


def _setup(key, b=2, l=20, din=16, hid=24, dout=16, e=3, n_layers=2):
    keys = jax.random.split(key, 2 * (n_layers + 1) + 2)
    dims = [din] + [hid] * n_layers + [dout]
    kernels = [
        jax.random.normal(keys[i], (e, dims[i], dims[i + 1]), jnp.float32) * 0.3
        for i in range(n_layers + 1)
    ]
    biases = [
        jax.random.normal(keys[n_layers + 1 + i], (e, dims[i + 1]), jnp.float32)
        for i in range(n_layers + 1)
    ]
    x = jax.random.normal(keys[-2], (b, l, din), jnp.float32)
    scores = jax.nn.softmax(jax.random.normal(keys[-1], (b, l, e)), axis=-1)
    return x, scores, kernels, biases


@pytest.mark.parametrize("l", [20, 300])  # 300 exercises the seq tiling
def test_fused_ffn_matches_einsum(l):
    x, scores, kernels, biases = _setup(jax.random.key(0), l=l)
    out = fused_gated_ffn(x, scores, kernels, biases)
    ref = _reference_impl(x, scores, kernels, biases)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_fused_ffn_grads_match_einsum():
    x, scores, kernels, biases = _setup(jax.random.key(1), l=12)

    def loss_fused(x, s, k, b):
        return jnp.sum(fused_gated_ffn(x, s, k, b) ** 2)

    def loss_ref(x, s, k, b):
        return jnp.sum(_reference_impl(x, s, k, b) ** 2)

    g1 = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, scores, kernels, biases)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, scores, kernels, biases)
    # The cotangent comes from the kernel's forward, whose tile-wise
    # accumulation order differs from the einsum's — a few-ulp wiggle
    # on O(100) sum-of-squares gradients.
    for a, b_ in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=5e-6)


def test_ffn_reference_matches_xla_module_math():
    """The kernel's einsum oracle == GatedExpertFfn's batched-GEMM math."""
    import flax.linen as nn

    from gnot_tpu.models.layers import GatedExpertFfn

    x, scores, kernels, biases = _setup(jax.random.key(2), l=16, din=16, dout=16)
    mod = GatedExpertFfn(n_expert=3, num_layers=2, hidden_dim=24, output_dim=16)
    params = {
        "experts": {
            f"dense_{i}": {"kernel": kernels[i], "bias": biases[i]}
            for i in range(3)
        }
    }
    out_mod = mod.apply({"params": params}, x, scores)
    out_ref = _reference_impl(x, scores, kernels, biases)
    np.testing.assert_allclose(
        np.asarray(out_ref), np.asarray(out_mod), rtol=1e-5, atol=1e-6
    )


def test_model_forward_ffn_pallas_matches_xla():
    mc = ModelConfig(
        input_dim=2,
        theta_dim=2,
        input_func_dim=3,
        out_dim=2,
        n_input_functions=1,
        n_attn_layers=2,
        n_attn_hidden_dim=32,
        n_mlp_num_layers=2,
        n_mlp_hidden_dim=32,
        n_input_hidden_dim=32,
        n_expert=2,
        n_head=4,
    )
    samples = datasets.synth_elasticity(4, base_points=40)
    batch = next(iter(Loader(samples, 4)))

    model_xla = GNOT(mc)
    params = model_xla.init(
        jax.random.key(0),
        batch.coords,
        batch.theta,
        batch.funcs,
        node_mask=batch.node_mask,
        func_mask=batch.func_mask,
    )["params"]
    model_pallas = GNOT(dataclasses.replace(mc, ffn_impl="pallas"))

    args = (batch.coords, batch.theta, batch.funcs)
    kw = dict(node_mask=batch.node_mask, func_mask=batch.func_mask)
    out_xla = model_xla.apply({"params": params}, *args, **kw)
    out_pallas = model_pallas.apply({"params": params}, *args, **kw)
    np.testing.assert_allclose(
        np.asarray(out_pallas), np.asarray(out_xla), rtol=1e-4, atol=1e-5
    )


def test_fits_vmem_gate():
    big = [jnp.zeros((4, 2048, 2048))]  # 64 MB > budget
    small = [jnp.zeros((3, 256, 256))]
    assert not fits_vmem(big)
    assert fits_vmem(small)


def test_sharded_step_rejects_ffn_pallas():
    from gnot_tpu.config import MeshConfig, OptimConfig
    from gnot_tpu.parallel import mesh as mesh_lib
    from gnot_tpu.train.trainer import init_state

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    mc = ModelConfig(
        input_dim=2,
        theta_dim=1,
        input_func_dim=3,
        out_dim=1,
        n_input_functions=1,
        n_attn_layers=1,
        n_attn_hidden_dim=16,
        n_mlp_num_layers=1,
        n_mlp_hidden_dim=16,
        n_input_hidden_dim=16,
        n_expert=2,
        n_head=2,
        ffn_impl="pallas",
    )
    samples = datasets.synth_ns2d(2, n_points=16)
    batch = next(iter(Loader(samples, 2)))
    model = GNOT(mc)
    state = init_state(model, OptimConfig(), batch, seed=0)
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=1, model=1), jax.devices()[:2])
    with pytest.raises(ValueError, match="ffn_impl"):
        mesh_lib.make_sharded_train_step(model, OptimConfig(), "rel_l2", mesh, state)
