"""Multi-host helpers on a single process (the logic that can be tested
without a pod: sharding math, degenerate meshes, global-batch assembly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gnot_tpu.config import MeshConfig
from gnot_tpu.data import datasets
from gnot_tpu.data.batch import Loader
from gnot_tpu.parallel import mesh as mesh_lib
from gnot_tpu.parallel import multihost


def test_initialize_noop_single_process():
    multihost.initialize()  # must not raise or try to connect


def test_shard_samples_partition():
    samples = list(range(10))
    shards = [
        multihost.shard_samples(samples, process_index=i, process_count=3)
        for i in range(3)
    ]
    assert sorted(sum(shards, [])) == samples
    assert shards[0] == [0, 3, 6, 9]


def test_hybrid_mesh_degenerates_single_process():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    cfg = MeshConfig(data=2, seq=2, model=2)
    mesh = multihost.make_hybrid_mesh(cfg)
    assert mesh.shape == {
        "data": 2, "seq": 2, "model": 2, "expert": 1, "pipe": 1,
    }


def test_global_batch_matches_shard_batch():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = mesh_lib.make_mesh(MeshConfig(data=2, seq=2, model=2))
    samples = datasets.synth_ns2d(8, n_points=64)
    batch = next(iter(Loader(samples, 8)))

    g = multihost.global_batch(mesh, batch)
    s = mesh_lib.shard_batch(mesh, batch)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(s)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
        assert a.sharding == b.sharding


def test_distributed_trainer_matches_single_device():
    """Trainer with train.distributed=True over the 2x2x2 CPU mesh:
    same eval metric and same first-epoch loss as the single-device
    trainer from the same seed."""
    from gnot_tpu import config as config_lib
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.train.trainer import Trainer

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mc = ModelConfig(
        input_dim=2,
        theta_dim=1,
        input_func_dim=3,
        out_dim=1,
        n_input_functions=1,
        n_attn_layers=1,
        n_attn_hidden_dim=32,
        n_mlp_num_layers=1,
        n_mlp_hidden_dim=32,
        n_input_hidden_dim=32,
        n_expert=2,
        n_head=4,
    )
    train = datasets.synth_ns2d(16, n_points=64, seed=2)
    test = datasets.synth_ns2d(8, n_points=64, seed=3)

    def build(distributed):
        cfg = config_lib.make_config(
            **{
                "data.batch_size": 8,
                "train.epochs": 1,
                "train.distributed": distributed,
                "mesh.data": 2,
                "mesh.seq": 2,
                "mesh.model": 2,
            }
        )
        t = Trainer(cfg, mc, train, test)
        t.initialize()
        return t

    single, dist = build(False), build(True)
    np.testing.assert_allclose(single.evaluate(), dist.evaluate(), rtol=1e-5)
    np.testing.assert_allclose(single.fit(), dist.fit(), rtol=1e-4)


@pytest.mark.parametrize(
    "overrides",
    [
        {"mesh.data": 4, "mesh.model": 2},
        {"mesh.data": 2, "mesh.seq": 2, "mesh.model": 2},
        # pipe microbatches need batch/data >= 2 per shard
        {"mesh.data": 4, "mesh.pipe": 2, "data.batch_size": 8},
        {"mesh.data": 4, "mesh.model": 2, "model.scan_layers": True},
    ],
    ids=["dp-tp", "dp-sp-tp", "dp-pipe", "dp-tp-scan"],
)
def test_distributed_eval_ragged_test_set(overrides):
    """n_test=10 with batch_size=4: distributed eval pads the tail batch
    with repeats and drops them from the metric (VERDICT r3 #6) — the
    metric must equal the single-device trainer's, which evaluates the
    ragged tail batch natively like the reference (main.py:113-132)."""
    from gnot_tpu import config as config_lib
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.train.trainer import Trainer

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mc = ModelConfig(
        input_dim=2,
        theta_dim=1,
        input_func_dim=3,
        out_dim=1,
        n_input_functions=1,
        n_attn_layers=2,
        n_attn_hidden_dim=32,
        n_mlp_num_layers=1,
        n_mlp_hidden_dim=32,
        n_input_hidden_dim=32,
        n_expert=2,
        n_head=4,
        scan_layers=bool(overrides.pop("model.scan_layers", False)),
    )
    train = datasets.synth_ns2d(8, n_points=64, seed=2)
    test = datasets.synth_ns2d(10, n_points=64, seed=3)
    bs = overrides.pop("data.batch_size", 4)  # same bs both builds: the
    # metric is a mean of batch means, so batching must match.

    def build(distributed, mc_=mc):
        cfg = config_lib.make_config(
            **{
                "data.batch_size": bs,
                "train.epochs": 1,
                "train.distributed": distributed,
                **(overrides if distributed else {}),
            }
        )
        t = Trainer(cfg, mc_, train, test)
        t.initialize()
        return t

    import dataclasses as _dc

    single = build(False, _dc.replace(mc, scan_layers=False))
    dist = build(True)
    np.testing.assert_allclose(single.evaluate(), dist.evaluate(), rtol=1e-5)
