"""Guard the committed artifact corpus: every docs/artifacts/*.jsonl
must parse as valid JSONL (the quality-gate tests pin against these
files; a hand-edit or a writer regression that emits bare NaN tokens
would otherwise surface as an obscure gate failure much later)."""

import glob
import json
import os

import pytest

from gnot_tpu.obs import tracing

ARTIFACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "artifacts",
)


def _jsonl_files():
    return sorted(glob.glob(os.path.join(ARTIFACT_DIR, "*.jsonl")))


def test_artifact_corpus_present():
    assert _jsonl_files(), f"no JSONL artifacts under {ARTIFACT_DIR}"


@pytest.mark.parametrize(
    "path", _jsonl_files(), ids=[os.path.basename(p) for p in _jsonl_files()]
)
def test_artifact_parses_as_jsonl(path):
    with open(path) as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    assert lines, f"{path} is empty"
    for i, line in enumerate(lines, 1):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            raise AssertionError(
                f"{os.path.basename(path)}:{i} is not valid JSON: {exc}"
            ) from exc
        assert isinstance(rec, dict), (
            f"{os.path.basename(path)}:{i} is not a JSON object"
        )


@pytest.mark.parametrize(
    "path",
    sorted(glob.glob(os.path.join(ARTIFACT_DIR, "*.json"))),
    ids=lambda p: os.path.basename(p),
)
def test_json_artifact_parses(path):
    with open(path) as f:
        json.load(f)


def test_tracing_ab_artifact_schema():
    """The committed tracing A/B (tools/tracing_ab.py): two timed arms
    plus a summary whose overhead_frac meets the <=2% acceptance bar at
    the default sample rate (the ISSUE 5 criterion)."""
    path = os.path.join(ARTIFACT_DIR, "tracing_overhead_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"tracing_off", "tracing_on"}
    for r in arms.values():
        assert r["ms_per_step"] > 0 and r["sample_rate"] == 1.0
    (summary,) = [r for r in recs if r.get("summary") == "tracing_overhead"]
    assert isinstance(summary["overhead_frac"], float)
    assert summary["overhead_frac"] <= 0.02
    assert summary["ms_per_step_on"] == arms["tracing_on"]["ms_per_step"]


def test_dtrace_ab_artifact_schema():
    """The committed distributed-tracing A/B (tools/dtrace_ab.py):
    federated per-request latency with the cluster tracing plane +
    flight recorders off vs on, plus a summary whose overhead_frac
    meets the <=2% acceptance bar (the ISSUE 20 criterion)."""
    path = os.path.join(ARTIFACT_DIR, "dtrace_overhead_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"dtrace_off", "dtrace_on"}
    for r in arms.values():
        assert r["ms_per_request"] > 0 and r["hosts"] == 2
        assert r["sample_rate"] == 1.0  # every request traced on the ON arm
        assert r["flight_recorder_s"] > 0  # recorder armed, not a no-op arm
    (summary,) = [r for r in recs if r.get("summary") == "dtrace_overhead"]
    assert isinstance(summary["overhead_frac"], float)
    assert summary["overhead_frac"] <= 0.02
    assert summary["ms_per_request_on"] == arms["dtrace_on"]["ms_per_request"]


def test_federated_trace_example_schema():
    """The committed stitched cluster trace (docs/observability.md
    "Distributed tracing"): spans from >=2 host sources plus the
    controller, a host-kill remigration recorded as a LINKED placement
    span on the ORIGINAL rollout trace, and per-source clock metadata."""
    path = os.path.join(ARTIFACT_DIR, "federated_trace_example.json")
    with open(path) as f:
        m = json.load(f)
    hosts = m["otherData"]["hosts"]
    assert "controller" in hosts and len(hosts) >= 3
    for meta in hosts.values():
        assert "clock_offset_s" in meta and "clock_err_s" in meta
    spans = [e for e in m["traceEvents"] if e.get("ph") == "X"]
    (roll,) = [s for s in spans if s["name"] == "cluster_rollout"]
    tid = roll["args"]["trace_id"]
    placements = [
        s for s in spans
        if s["name"] == "placement" and s["args"]["trace_id"] == tid
    ]
    kinds = {p["args"]["kind"] for p in placements}
    assert "remigrate" in kinds
    remig = next(p for p in placements if p["args"]["kind"] == "remigrate")
    assert remig["args"]["link_to"]  # linked span, not a second chain
    served = {
        s["args"].get("host") for s in spans
        if s["args"].get("trace_id") == tid and s["args"].get("host")
    }
    assert len(served) >= 2  # the SAME trace crossed hosts


def test_metrics_ab_artifact_schema():
    """The committed metrics-plane overhead A/B (tools/metrics_ab.py):
    interleaved serve-storm arms with the registry + publisher +
    evaluator off vs on, plus a summary whose overhead_frac meets the
    <=2% acceptance bar (the ISSUE 14 criterion) with the publisher
    demonstrably running (snapshots published mid-storm)."""
    path = os.path.join(ARTIFACT_DIR, "metrics_overhead_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"metrics_off", "metrics_on"}
    for r in arms.values():
        assert r["requests_per_s"] > 0 and r["requests"] >= 1000
        assert r["repeats"] >= 3  # interleaved best-of, not one sample
    assert arms["metrics_on"]["snapshots"] >= 1  # the publisher RAN
    (summary,) = [r for r in recs if r.get("summary") == "metrics_overhead"]
    assert isinstance(summary["overhead_frac"], float)
    assert summary["overhead_frac"] <= 0.02
    assert summary["requests_per_s_on"] == arms["metrics_on"]["requests_per_s"]
    assert summary["overhead_frac"] == pytest.approx(
        1.0 - summary["requests_per_s_on"] / summary["requests_per_s_off"],
        abs=1e-3,
    )


def test_sanitizer_ab_artifact_schema():
    """The committed donation-sanitizer A/B (tools/sanitizer_ab.py):
    three timed arms plus a summary meeting both ISSUE 11 bars —
    guard-off within an honest noise window of a never-installed
    baseline (|frac| <= 10%; the off arm runs the SAME machine code,
    byte-identity is unit-proven by test_off_mode_is_byte_identical,
    so a regenerated artifact must not flake on timing-noise sign) and
    copy mode bounded (<=10% at snapshot_every=10)."""
    path = os.path.join(ARTIFACT_DIR, "sanitizer_overhead_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"baseline", "guard_off", "guard_copy"}
    for r in arms.values():
        assert r["ms_per_step"] > 0 and r["snapshot_every"] == 10
    (summary,) = [r for r in recs if r.get("summary") == "sanitizer_overhead"]
    assert isinstance(summary["off_vs_baseline_frac"], float)
    assert abs(summary["off_vs_baseline_frac"]) <= 0.10
    assert isinstance(summary["copy_overhead_frac"], float)
    assert summary["copy_overhead_frac"] <= 0.10
    assert (
        summary["ms_per_step_copy"] == arms["guard_copy"]["ms_per_step"]
    )


def test_pack_ab_artifact_schema():
    """The committed packing A/B (tools/pack_ab.py): four measured arms
    plus a summary meeting the ISSUE 6 acceptance bar — pad waste DOWN
    and throughput UP on BOTH hot paths, packed-vs-unpacked outputs
    within 1e-5 per request."""
    path = os.path.join(ARTIFACT_DIR, "pack_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {
        "train_padded", "train_packed", "serve_unpacked", "serve_packed",
    }
    for r in arms.values():
        assert 0.0 <= r["pad_waste_frac"] < 1.0
    assert arms["train_padded"]["real_tokens"] > 0
    assert arms["serve_packed"]["pack_chunk"] % 8 == 0
    (summary,) = [r for r in recs if r.get("summary") == "pack_ab"]
    # Pad waste reduced on both paths.
    assert (
        summary["train_pad_waste_packed"] < summary["train_pad_waste_padded"]
    )
    assert (
        summary["serve_pad_waste_packed"] < summary["serve_pad_waste_unpacked"]
    )
    # Throughput improved on both paths (tokens/s train, requests/s serve).
    assert summary["train_speedup"] > 1.0
    assert summary["serve_speedup"] > 1.0
    assert summary["train_speedup"] == pytest.approx(
        summary["train_tokens_per_s_packed"]
        / summary["train_tokens_per_s_padded"],
        rel=1e-2,
    )
    # Numerics bar: packed output == solo padded output per request.
    assert summary["max_abs_diff"] <= 1e-5


def test_serve_bench_artifact_schema():
    """The committed replicated-serving load bench
    (tools/serve_bench.py): open-loop runs for the 1- and N-replica
    arms over a shared offered-load ladder, plus a summary meeting the
    ISSUE 9 acceptance bar — N=4 replicas sustain >= 2.5x the
    requests/s of N=1 at equal p99 (both arms held to the same p99
    SLO), with per-request replicated-vs-solo outputs <= 1e-5."""
    path = os.path.join(ARTIFACT_DIR, "serve_bench.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    runs = [r for r in recs if "arm" in r]
    arms = {r["arm"] for r in runs}
    (summary,) = [r for r in recs if r.get("summary") == "serve_bench"]
    n = summary["replicas_n"]
    assert arms == {"replicas_1", f"replicas_{n}"} and n >= 4
    # Both arms measured over the SAME offered-load ladder.
    ladder = {r["load_mult"] for r in runs if r["replicas"] == 1}
    assert ladder == {r["load_mult"] for r in runs if r["replicas"] == n}
    assert len(ladder) >= 3
    for r in runs:
        assert r["submitted"] > 0 and r["offered_rps"] > 0
        assert r["completed"] + sum(r["shed"].values()) == r["submitted"]
        if r["completed"]:
            assert r["p50_ms"] <= r["p99_ms"]
        # Open-loop honesty: achieved never exceeds offered by more
        # than Poisson jitter.
        assert r["achieved_rps"] <= r["offered_rps"] * 1.25
    # The acceptance bar (not quick mode), at equal p99: both
    # sustained points meet the same SLO.
    assert summary["quick"] is False
    slo = summary["slo_p99_ms"]
    assert summary["p99_at_sustained_1"] <= slo
    assert summary["p99_at_sustained_n"] <= slo
    assert summary["speedup"] == pytest.approx(
        summary["sustained_rps_n"] / summary["sustained_rps_1"], rel=1e-2
    )
    assert summary["speedup"] >= summary["bar_speedup"] == 2.5
    assert summary["max_abs_diff"] <= summary["bar_numeric"] == 1e-5


def test_coldstart_ab_artifact_schema():
    """The committed cold-start A/B (tools/coldstart_ab.py): scale-out
    1->N under open-loop overload, cold compiles vs deploy-time AOT
    prewarm — the ISSUE 10 acceptance bar: prewarmed replica
    time-to-first-served >= 5x faster than cold, ZERO requests shed
    during the prewarmed scale-out (the cold arm sheds for the whole
    compile window), every scale-out probe served ok."""
    path = os.path.join(ARTIFACT_DIR, "coldstart_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    (summary,) = [r for r in recs if r.get("summary") == "coldstart_ab"]
    assert summary["quick"] is False
    assert summary["replicas_from"] == 1 and summary["replicas_to"] >= 4
    (deploy,) = [r for r in recs if r.get("arm") == "deploy"]
    assert deploy["programs"] > 0 and deploy["snapshot_bytes"] > 0
    per = [r for r in recs if "replica" in r and "ttfs_s" in r]
    by_arm: dict = {}
    for r in per:
        assert r["probe_ok"] is True
        assert r["ttfs_s"] > 0
        by_arm.setdefault(r["arm"], []).append(r)
    n_new = summary["replicas_to"] - 1
    assert len(by_arm["cold"]) == len(by_arm["prewarmed"]) == n_new
    assert all(r["warm_source"] == "compile" for r in by_arm["cold"])
    assert all(r["warm_source"] == "snapshot" for r in by_arm["prewarmed"])
    arms = {r["arm"]: r for r in recs if r.get("arm") in ("cold", "prewarmed")
            and "submitted" in r}
    assert set(arms) == {"cold", "prewarmed"}
    for r in arms.values():
        # Both arms ran the SAME calibrated offered load, and every
        # submitted request resolved one way or the other.
        assert r["offered_rps"] == summary["offered_rps"] > 0
        assert r["completed"] + r["shed_total"] == r["submitted"]
    # The acceptance bars.
    assert summary["speedup"] == pytest.approx(
        summary["ttfs_cold_s"] / summary["ttfs_prewarmed_s"], rel=1e-2
    )
    assert summary["speedup"] >= summary["bar_speedup"] == 5.0
    assert summary["shed_prewarmed"] == 0
    # The cold arm's compile window genuinely overloaded the pool.
    assert summary["shed_cold"] > 0


def test_rollout_ab_artifact_schema():
    """The committed rollout chaos A/B (tools/rollout_ab.py): a storm
    of concurrent K-step rollout sessions with a replica KILLED
    mid-storm, run twice — the ISSUE 13 acceptance bars: the migration
    arm loses ZERO sessions (vs measured losses on the no-migration
    twin, so the kill was not vacuous), and every served rollout
    matches the offline engine-only K-step loop to <= 1e-5 per step at
    original tolerances (no loosening)."""
    path = os.path.join(ARTIFACT_DIR, "rollout_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"migration", "no_migration"}
    for r in arms.values():
        # Identical storm + identical fault across the arms.
        assert r["sessions"] > 0 and r["steps"] > 1
        assert r["killed_replica"] == 0 and r["kill_at_step"] >= 1
        assert r["snapshot_every"] >= 2  # migration exercises a replay
        assert r["completed"] + r["lost"] + r["drained"] + r["shed"] == (
            r["sessions"]
        )
    # The acceptance bars.
    assert arms["migration"]["lost"] == 0
    assert arms["migration"]["migrated"] >= 1
    assert arms["migration"]["completed"] == arms["migration"]["sessions"]
    assert arms["no_migration"]["lost"] >= 1
    assert arms["no_migration"]["lost_reasons"] == ["error_replica_dead"]
    (parity,) = [r for r in recs if r.get("probe") == "parity"]
    assert parity["sessions_checked"] == arms["migration"]["sessions"]
    assert parity["max_abs_diff"] <= parity["bar"] == 1e-5
    (summary,) = [r for r in recs if r.get("summary") == "rollout_ab"]
    assert summary["quick"] is False
    assert summary["lost_migration"] == 0 == summary["bar_lost_migration"]
    assert summary["lost_no_migration"] == arms["no_migration"]["lost"] >= 1
    assert summary["migrated"] == arms["migration"]["migrated"]
    assert summary["max_abs_diff"] <= summary["bar_numeric"] == 1e-5


def test_autoscale_ab_artifact_schema():
    """The committed autoscaling A/B (tools/autoscale_ab.py): one
    seeded diurnal+bursty open-loop trace through a static max-size
    pool vs the controller-scaled pool — the ISSUE 15 acceptance bars:
    p99 within the stated noise factor of the static pool, STRICTLY
    fewer replica-seconds, zero shed on the up-ramp; and the chaos arm
    (scale-in with the retiring replica killed mid-handover) loses
    zero sessions and zero requests with exact trajectory parity."""
    path = os.path.join(ARTIFACT_DIR, "autoscale_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"static", "autoscaled"}
    for r in arms.values():
        # Every submitted request resolved, one way or the other.
        assert r["submitted"] > 0
        assert r["completed"] + r["shed_total"] == r["submitted"]
        assert r["p50_ms"] <= r["p99_ms"]
        assert r["replica_seconds"] > 0
    static, auto = arms["static"], arms["autoscaled"]
    assert static["autoscale"] is False and static["removed"] == 0
    assert static["replicas_founding"] == static["replicas_max"]
    assert auto["autoscale"] is True
    assert auto["replicas_founding"] < auto["replicas_max"]
    # The controller genuinely acted (both directions).
    assert auto["autoscale_stats"]["scale_ups"] >= 1
    assert auto["autoscale_stats"]["scale_downs"] >= 1
    assert auto["removed"] == auto["autoscale_stats"]["scale_downs"]
    # The chaos arm: drain-then-remove under a kill loses nothing.
    (chaos,) = [r for r in recs if r.get("probe") == "chaos_scale_in"]
    assert chaos["lost_sessions"] == 0 == chaos["bar_lost"]
    assert chaos["lost_requests"] == 0
    assert chaos["completed"] == chaos["sessions"]
    assert chaos["migrated"] >= 1
    assert chaos["kill_at_step"] >= 1
    assert chaos["max_abs_diff"] <= chaos["bar_numeric"] == 1e-5
    (summary,) = [r for r in recs if r.get("summary") == "autoscale_ab"]
    assert summary["quick"] is False
    assert summary["trace"] == "diurnal_bursty"
    # The acceptance bars.
    assert summary["p99_ratio"] == pytest.approx(
        summary["p99_autoscaled_ms"] / summary["p99_static_ms"], rel=1e-2
    )
    assert summary["p99_ratio"] <= summary["bar_p99_ratio"] == 1.5
    assert (
        summary["replica_seconds_autoscaled"]
        < summary["replica_seconds_static"]
    )
    assert summary["replica_seconds_saved_frac"] > 0
    assert summary["shed_up_ramp"] == 0 == summary["bar_shed_up_ramp"]
    assert summary["chaos_lost_sessions"] == 0
    assert summary["chaos_lost_requests"] == 0


def test_serve_trace_example_is_complete_chrome_trace():
    """The committed example trace (docs/observability.md "Reading a
    trace"): a real serve-smoke run whose completed requests each carry
    the full admission->resolve chain under one trace_id."""
    path = os.path.join(ARTIFACT_DIR, "serve_trace_example.json")
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert events and doc["displayTimeUnit"] == "ms"
    by_trace = {}
    for e in events:
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
        by_trace.setdefault(e["args"]["trace_id"], set()).add(e["name"])
    chain = set(tracing.SERVE_SPANS)
    complete = [t for t, names in by_trace.items() if chain <= names]
    assert len(complete) >= 1
    # Every trace at least entered admission (shed chains stop early).
    assert all("admission" in names for names in by_trace.values())


def test_lowprec_ab_artifact_schema():
    """The committed low-precision serving A/B (tools/lowprec_ab.py):
    per-dataset bf16-vs-f32 RelL2 parity under the stated bar, both
    serve arms measured over one shared offered-load ladder through
    the real replica tier (sustained req/s + tokens/s + p99 at the
    same SLO), the native-vs-python host-phase trace breakdown showing
    a measured reduction, and the device microbench that makes the
    req/s ratio attributable to this backend's bf16 lowering. The
    quality bar is the hard one (no tolerance loosening anywhere); the
    throughput record pins no-regression-beyond-the-measured-device-
    slowdown on this CPU proxy, with the 1.3x MXU design target
    recorded beside the evidence (docs/performance.md round 12)."""
    path = os.path.join(ARTIFACT_DIR, "lowprec_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    # Attribution: the artifact names the packer path that produced it.
    (packer,) = [r for r in recs if r.get("probe") == "native_packer"]
    assert packer["impl"] in ("native", "python")
    # Quality parity, per dataset, under the stated bar.
    parity = {r["dataset"]: r for r in recs if r.get("probe") == "parity"}
    assert set(parity) == {"darcy64", "elasticity", "ns2d", "heatsink3d"}
    for r in parity.values():
        assert 0 < r["rel_l2_f32"] and 0 < r["rel_l2_bf16"]
        assert abs(r["delta"]) <= r["bar"] == 0.01, (
            f"{r['dataset']}: bf16 RelL2 delta {r['delta']} over the bar"
        )
    # The device microbench (the honest-hardware evidence line).
    (micro,) = [r for r in recs if r.get("probe") == "device_microbench"]
    assert micro["dispatch_ms_f32"] > 0 and micro["dispatch_ms_bf16"] > 0
    assert micro["bf16_dispatch_slowdown"] == pytest.approx(
        micro["dispatch_ms_bf16"] / micro["dispatch_ms_f32"], rel=1e-2
    )
    # Both serve arms over the SAME ladder; every request accounted.
    runs = [r for r in recs if str(r.get("arm", "")).startswith("serve_")]
    ladder32 = {r["load_mult"] for r in runs if r["arm"] == "serve_f32"}
    ladder16 = {r["load_mult"] for r in runs if r["arm"] == "serve_bf16"}
    assert ladder32 == ladder16 and len(ladder32) >= 3
    for r in runs:
        assert r["completed"] + sum(r["shed"].values()) == r["submitted"]
        assert r["achieved_rps"] <= r["offered_rps"] * 1.25
        assert r["tokens_per_s"] is None or r["tokens_per_s"] >= 0
        assert r["dtype"] == (
            "bfloat16" if r["arm"] == "serve_bf16" else "float32"
        )
    # Host-phase before/after (trace_report breakdown): a measured
    # reduction under the adaptive native path.
    arms = {r["arm"]: r for r in recs if str(r.get("arm", "")).startswith("host_")}
    assert set(arms) == {"host_python", "host_native"}
    for r in arms.values():
        assert r["batch_assembly_total_ms"] > 0
        assert r["batch_assembly_trimmed_ms"] > 0
    (summary,) = [r for r in recs if r.get("summary") == "lowprec_ab"]
    assert summary["quick"] is False
    assert summary["parity_max_delta"] <= summary["parity_bar"] == 0.01
    assert summary["host_reduction_frac"] > 0
    # Throughput: both arms sustained a point under the ONE shared SLO
    # ("equal p99" = held to the same number), with the ratio pinned
    # against the measured device-side slowdown — the host-path work
    # must not ADD a regression on top of what the backend's bf16
    # lowering costs (the microbench beside it is the evidence; the
    # MXU design target stays recorded as bar_req_s_ratio_target).
    slo = summary["slo_p99_ms"]
    assert summary["p99_at_sustained_f32"] <= slo
    assert summary["p99_at_sustained_bf16"] <= slo
    assert summary["req_s_ratio"] == pytest.approx(
        summary["sustained_rps_bf16"] / summary["sustained_rps_f32"],
        rel=1e-2,
    )
    assert summary["bar_req_s_ratio_target"] == 1.3
    assert summary["bf16_dispatch_slowdown_cpu"] > 0
    floor = min(1.0, 1.0 / summary["bf16_dispatch_slowdown_cpu"]) * 0.8
    assert summary["req_s_ratio"] >= floor, (
        "bf16 serving regressed beyond the measured device slowdown — "
        "the host path added a loss of its own"
    )


def test_capacity_snapshot_artifact_schema():
    """The committed capacity snapshot (tools/capacity_report.py): the
    cost x traffic join from a real storm — every dispatched program
    carries a catalog entry (nonzero XLA costs or the explicit
    ``unavailable`` marker), the capacity model agrees with
    serve_summary number-for-number, and the PackPlan recommendation's
    projected pad waste beats the committed pack_ab packed arm on the
    same traffic (the ISSUE 16 acceptance bar)."""
    path = os.path.join(ARTIFACT_DIR, "capacity_snapshot.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    progs = [r for r in recs if r.get("record") == "program"]
    assert progs, "no per-program cost x traffic rows"
    for r in progs:
        assert r["program"].startswith(("bucket:", "packed:"))
        costs = r["costs"]
        assert costs["flops"] or costs.get("unavailable"), r
        if r["dispatches"]:
            assert r["source"] is not None  # dispatched => catalogued
            assert r["real_tokens"] <= r["capacity_tokens"]
    (cap,) = [r for r in recs if r.get("record") == "capacity"]
    assert cap["agreement"]["problems"] == []
    assert cap["dispatches"] == sum(r["dispatches"] for r in progs)
    assert cap["sustainable_tokens_per_s"] > 0
    assert cap["headroom_tokens"] is not None
    (rec,) = [r for r in recs if r.get("record") == "pack_recommendation"]
    assert rec["plan"]["row_len"] % rec["plan"]["chunk"] == 0
    assert rec["candidates_searched"] >= 1
    (summary,) = [r for r in recs if r.get("summary") == "capacity_report"]
    assert summary["agreement_problems"] == []
    assert summary["projected_pad_waste"] == rec["projected_pad_waste"]
    # The bar: the recommendation beats the committed packed arm's pad
    # waste (docs/artifacts/pack_ab.jsonl) on the same traffic shape.
    pack_path = os.path.join(ARTIFACT_DIR, "pack_ab.jsonl")
    with open(pack_path) as f:
        pack = [json.loads(l) for l in f if l.strip()]
    (pack_summary,) = [r for r in pack if r.get("summary") == "pack_ab"]
    baseline = pack_summary["serve_pad_waste_packed"]
    assert summary["baseline_packed_pad_waste"] == baseline
    assert summary["projected_pad_waste"] <= baseline
    assert summary["beats_baseline"] is True


def test_capacity_ab_artifact_schema():
    """The committed catalog-attribution overhead A/B
    (tools/capacity_ab.py): interleaved serve-storm arms with the
    program catalog + per-dispatch attribution off vs on — both over
    the full live metrics plane — plus a summary whose overhead_frac
    meets the <=2% bar with attribution demonstrably live."""
    path = os.path.join(ARTIFACT_DIR, "capacity_overhead_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"capacity_off", "capacity_on"}
    for r in arms.values():
        assert r["requests_per_s"] > 0 and r["requests"] >= 1000
        assert r["repeats"] >= 3  # interleaved best-of, not one sample
    on = arms["capacity_on"]
    assert on["snapshots"] >= 1  # the publisher RAN in the timed arm
    assert on["attributed_dispatches"] > 0  # the catalog saw the storm
    assert on["programs"] >= 1
    (summary,) = [
        r for r in recs if r.get("summary") == "capacity_overhead"
    ]
    assert isinstance(summary["overhead_frac"], float)
    assert summary["overhead_frac"] <= 0.02
    assert summary["attributed_dispatches"] == on["attributed_dispatches"]
    assert summary["overhead_frac"] == pytest.approx(
        1.0 - summary["requests_per_s_on"] / summary["requests_per_s_off"],
        abs=1e-3,
    )


def test_tenant_ab_artifact_schema():
    """The committed noisy-neighbor A/B (tools/tenant_ab.py): one
    shared two-stream storm (a batch flood >= 3x its fair share beside
    a well-behaved interactive stream) through the tenant-isolation
    plane vs the untagged open pool — the ISSUE 17 acceptance bars:
    the isolated arm's interactive stream sheds NOTHING and holds its
    p99 SLO while the flooding tenant eats quota fast-fails; the open
    arm demonstrably breaches (the flood was not vacuous); and the
    open arm's own event stream proves the default-path pin (zero
    tenant footprint when nothing is tagged)."""
    path = os.path.join(ARTIFACT_DIR, "tenant_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"isolated", "open"}
    # Both arms replayed the SAME storm (shared multi_stream trace).
    assert arms["isolated"]["submitted"] == arms["open"]["submitted"] > 0
    for r in arms.values():
        assert r["completed"] + sum(r["shed"].values()) == r["submitted"]
        per = r["tenants"]
        assert set(per) == {"interactive", "batch"}
        for t in per.values():
            assert t["completed"] + t["shed_total"] == t["submitted"]
    iso, opn = arms["isolated"], arms["open"]
    assert iso["tagged"] is True and opn["tagged"] is False
    assert opn["policy"] is None  # the open arm ran the DEFAULT path
    # The default-path pin probe reads the open arm's own artifacts.
    (pin,) = [r for r in recs if r.get("probe") == "default_pin"]
    assert pin["events_scanned"] > 0
    assert pin["tenant_named_events"] == pin["tenant_fields"] == 0
    assert pin["summary_has_tenants"] is False and pin["bar"] == 0
    (summary,) = [r for r in recs if r.get("summary") == "tenant_ab"]
    assert summary["quick"] is False
    assert summary["trace"].startswith("multi_stream:")
    assert summary["arrivals"] == iso["submitted"]
    # The flood was real: batch offered >= 3x its fair quarter-share.
    assert summary["flood_factor"] >= summary["bar_flood_factor"] == 3.0
    # Isolation bars: the well-behaved tenant rode through untouched.
    assert summary["isolated_interactive_shed"] == 0
    assert (
        summary["isolated_interactive_p99_ms"] <= summary["slo_p99_ms"]
    )
    assert summary["isolated_batch_quota_sheds"] >= 1
    assert summary["isolated_batch_quota_sheds"] == (
        iso["tenants"]["batch"]["shed"]["shed_tenant_quota"]
    )
    # The open pool breached under the SAME storm — the contrast that
    # makes the isolation bars meaningful.
    assert summary["open_breached"] is True
    assert (
        summary["open_interactive_p99_ms"] > summary["slo_p99_ms"]
        or summary["open_interactive_shed"] > 0
    )
    assert summary["pin_tenant_footprint"] == 0


def test_federation_ab_artifact_schema():
    """The committed federation chaos A/B (tools/federation_ab.py):
    a 2-host loopback federation with the owner host of a mid-flight
    rollout session KILLED — the ISSUE 18 acceptance bars: the chaos
    arm loses ZERO sessions (re-migrated cross-host from persisted
    snapshots) with <= 1e-5 per-step parity against the offline loop,
    the no-failover twin measurably loses sessions (the kill was not
    vacuous), and the federation-off single-host path stays
    byte-identical at the batcher and serve_summary levels."""
    path = os.path.join(ARTIFACT_DIR, "federation_ab.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    arms = {r["arm"]: r for r in recs if "arm" in r}
    assert set(arms) == {"chaos", "no_failover"}
    for r in arms.values():
        # Identical storm + identical kill across the arms.
        assert r["hosts"] >= 2 and r["sessions"] > 0 and r["steps"] > 1
        assert r["snapshot_every"] >= 2  # re-migration replays for real
        assert r["killed_host"].startswith("host")
        assert r["hosts_dead"] == 1
        assert r["protocol_errors"] == 0
        assert r["completed"] + r["lost"] == r["sessions"]
    # The acceptance bars.
    chaos, nofail = arms["chaos"], arms["no_failover"]
    assert chaos["failover"] is True and nofail["failover"] is False
    assert chaos["lost"] == 0
    assert chaos["remigrated"] >= 1
    assert chaos["completed"] == chaos["sessions"]
    assert nofail["lost"] >= 1
    assert nofail["lost_reasons"] == ["host_dead"]
    assert nofail["remigrated"] == 0
    (parity,) = [r for r in recs if r.get("probe") == "parity"]
    assert parity["sessions_checked"] == chaos["sessions"]
    assert parity["max_abs_diff"] <= parity["bar"] == 1e-5
    (pin,) = [r for r in recs if r.get("probe") == "single_host_pin"]
    assert pin["byte_identical"] is True
    assert pin["summary_match"] is True
    assert pin["ledger"]["requests"] == pin["requests"] > 0
    assert pin["ledger"]["completed"] == pin["requests"]
    (summary,) = [r for r in recs if r.get("summary") == "federation_ab"]
    assert summary["quick"] is False
    assert summary["lost_chaos"] == 0 == summary["bar_lost_chaos"]
    assert summary["lost_no_failover"] == nofail["lost"] >= 1
    assert summary["remigrated"] == chaos["remigrated"]
    assert summary["max_abs_diff"] <= summary["bar_numeric"] == 1e-5
    assert summary["single_host_byte_identical"] is True


def test_lockmap_artifact_schema():
    """The committed lock map (tools/lockmap_report.py): every lock
    identity as a node record, every acquires-while-holding edge with
    its file:line witness chain, and a summary pinned to the shippable
    state — zero cycles over a census of at least 20 locks (the
    serving/obs/federation planes). A locking change regenerates the
    artifact; this test keeps a stale or cyclic map out of the tree."""
    path = os.path.join(ARTIFACT_DIR, "lockmap.jsonl")
    with open(path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["record"], []).append(r)
    assert set(by_kind) == {"node", "edge", "summary"}
    nodes = {r["lock"] for r in by_kind["node"]}
    for r in by_kind["node"]:
        assert r["kind"] in ("Lock", "RLock", "Condition")
        assert r["file"].endswith(".py") and r["line"] >= 1
        assert r["module"]
    for r in by_kind["edge"]:
        assert r["held"] != r["acquired"]  # a self-loop IS a cycle
        assert r["held"] in nodes or r["acquired"] in nodes
        assert len(r["witness"]) >= 2  # outer hop + inner acquisition
        assert all(":" in hop for hop in r["witness"])
    (summary,) = by_kind["summary"]
    assert summary["schema"] == 1
    assert summary["cycles"] == []  # THE bar: the graph is acyclic
    assert summary["locks"] == len(by_kind["node"]) >= 20
    assert summary["edges"] == len(by_kind["edge"])
    assert sum(summary["census"].values()) == summary["locks"]
    # The live tree regenerates to the SAME graph shape (nodes/edges/
    # cycles) — a committed map that drifted from source is stale.
    import importlib.util

    repo_root = os.path.normpath(os.path.join(ARTIFACT_DIR, "..", ".."))
    spec = importlib.util.spec_from_file_location(
        "gnot_lockmap_cli",
        os.path.join(repo_root, "tools", "lockmap_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    live_lines, n_cycles = mod.lockmap_lines(repo_root)
    assert n_cycles == 0
    live = [json.loads(l) for l in live_lines]
    assert [r for r in live if r["record"] == "summary"] == [summary]
    assert sorted(
        (r["held"], r["acquired"]) for r in live if r["record"] == "edge"
    ) == sorted((r["held"], r["acquired"]) for r in by_kind["edge"])
