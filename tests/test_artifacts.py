"""Guard the committed artifact corpus: every docs/artifacts/*.jsonl
must parse as valid JSONL (the quality-gate tests pin against these
files; a hand-edit or a writer regression that emits bare NaN tokens
would otherwise surface as an obscure gate failure much later)."""

import glob
import json
import os

import pytest

ARTIFACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "artifacts",
)


def _jsonl_files():
    return sorted(glob.glob(os.path.join(ARTIFACT_DIR, "*.jsonl")))


def test_artifact_corpus_present():
    assert _jsonl_files(), f"no JSONL artifacts under {ARTIFACT_DIR}"


@pytest.mark.parametrize(
    "path", _jsonl_files(), ids=[os.path.basename(p) for p in _jsonl_files()]
)
def test_artifact_parses_as_jsonl(path):
    with open(path) as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    assert lines, f"{path} is empty"
    for i, line in enumerate(lines, 1):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            raise AssertionError(
                f"{os.path.basename(path)}:{i} is not valid JSON: {exc}"
            ) from exc
        assert isinstance(rec, dict), (
            f"{os.path.basename(path)}:{i} is not a JSON object"
        )


@pytest.mark.parametrize(
    "path",
    sorted(glob.glob(os.path.join(ARTIFACT_DIR, "*.json"))),
    ids=lambda p: os.path.basename(p),
)
def test_json_artifact_parses(path):
    with open(path) as f:
        json.load(f)
