"""Autoscaling A/B: a static pool vs the self-scaling pool, plus a
scale-in chaos arm.

The ISSUE 15 acceptance artifact. One seeded diurnal+bursty open-loop
trace (tools/loadgen.py — the rate ramps from base to a mid-window
peak with square bursts riding it) is driven through two pools built
from the SAME engines:

* ``static`` — a fixed ``--max_replicas``-wide pool (the conservative
  deployment: provisioned for the peak, idle at the edges).
* ``autoscaled`` — a pool founded at ``--min_replicas`` with the
  ``AutoscaleController`` closing the loop from the live metrics
  registry + SLO evaluator to capacity: prewarm-snapshotted
  scale-out under pressure, drain-then-remove scale-in after calm.

Bars (pinned by tests/test_artifacts.py::
test_autoscale_ab_artifact_schema):

* **equal p99** — the autoscaled arm's p99 within the noise factor of
  the static arm's (``bar_p99_ratio``);
* **strictly fewer replica-seconds** — the controller's pool-size
  integral under the static arm's ``max * duration``;
* **zero shed on the up-ramp** — the first half of the diurnal window
  (where the pool must GROW before it sheds) completes every request.

The **chaos arm** re-runs the scale-in path under fire: a storm of
K-step rollout sessions over 3 replicas, ``remove_replica`` of a
session-holding replica mid-storm, with the retiring replica KILLED
(``replica_kill``) while it is still handing sessions over — the bars:
zero lost sessions, zero lost requests, every session completes.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/autoscale_ab.py --out docs/artifacts/autoscale_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BAR_P99_RATIO = 1.5  # "equal p99 within noise" on a CPU-proxy timeline


def _ensure_xla_flags(n: int) -> None:
    import sys as _sys

    if "jax" in _sys.modules:
        print("autoscale_ab: note — jax already imported; flags unchanged")
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={max(8, n)}"
    if "xla_cpu_multi_thread_eigen" not in flags:
        flags += (
            " --xla_cpu_multi_thread_eigen=false"
            " intra_op_parallelism_threads=1"
        )
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_model(max_batch: int):
    """A mid-size GNOT on the Darcy operator schema (the serve_bench
    sizing): dispatches are COMPUTE-heavy — tens of ms inside XLA with
    the GIL released — so replica workers genuinely run concurrently on
    CPU and the capacity estimate means what it says."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(max_batch, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=96, n_mlp_num_layers=2,
        n_mlp_hidden_dim=96, n_input_hidden_dim=96, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    return model, init_params(model, collate(samples), 0)


def _build_pool(model, params, n, *, max_batch, manifest, devices):
    """n manifest-hydrated single-device replicas (prewarm-before-join
    applied to the founding pool too — arm setup pays snapshot loads,
    not compiles)."""
    from gnot_tpu.serve import build_replica

    replicas = [
        build_replica(
            model, params, i, devices[i : i + 1], batch_size=max_batch
        )
        for i in range(n)
    ]
    for r in replicas:
        stats = r.prewarm_from(manifest)
        assert stats["source"] == "snapshot", (
            f"replica {r.replica_id} failed to hydrate: {stats}"
        )
    return replicas


def _arm(
    name,
    model,
    params,
    traffic,
    times,
    *,
    manifest,
    n_replicas,
    max_batch,
    autoscale,
    max_replicas,
    duration_s,
    cooldown_s,
    up_load,
    down_load,
):
    """One open-loop run of the shared trace through a fresh pool.
    Returns the arm record (+ the controller's ledger when elastic)."""
    import jax
    import loadgen

    from gnot_tpu.obs.metrics import (
        MetricsPublisher,
        MetricsRegistry,
        SLOEvaluator,
        SLOObjective,
    )
    from gnot_tpu.serve import AutoscaleController, ReplicaRouter, build_replica

    devices = list(jax.devices())
    registry = MetricsRegistry()
    replicas = _build_pool(
        model, params, n_replicas, max_batch=max_batch,
        manifest=manifest, devices=devices,
    )
    router = ReplicaRouter(
        replicas,
        max_batch=max_batch,
        max_wait_ms=4.0,
        queue_limit=512,
        metrics=registry,
    ).start()
    controller = None
    publisher = None
    if autoscale:
        evaluator = SLOEvaluator(
            [
                SLOObjective(
                    "queue_saturation", "queue_depth", 64.0,
                    fast_window_s=0.5, slow_window_s=1.5,
                ),
            ]
        )
        publisher = MetricsPublisher(
            registry, interval_s=0.25, evaluator=evaluator
        ).start()

        def factory(rid, slot):
            return build_replica(
                model, params, rid,
                devices[slot : slot + 1], batch_size=max_batch,
            )

        controller = AutoscaleController(
            router,
            replica_factory=factory,
            min_replicas=n_replicas,
            max_replicas=max_replicas,
            interval_s=0.1,
            cooldown_s=cooldown_s,
            up_load=up_load,
            down_load=down_load,
            down_ticks=15,
            registry=registry,
            evaluator=evaluator,
            # Prewarm-before-join: a scale-out replica hydrates its
            # slot's AOT snapshot (0.x s) instead of paying cold XLA
            # compiles mid-ramp.
            prewarm_manifest=manifest,
        ).start()
    t0 = time.perf_counter()
    submit_at: list[float] = []

    def submit(i):
        submit_at.append(time.perf_counter() - t0)
        return router.submit(traffic[i % len(traffic)])

    futures = loadgen.replay(submit, times)
    results = [f.result(timeout=300) for f in futures]
    elapsed = time.perf_counter() - t0
    if controller is not None:
        controller.close()
    if publisher is not None:
        publisher.close()
    summary = router.drain()
    ramp_n = loadgen.ramp_split(times, duration_s)
    shed_up_ramp = sum(1 for r in results[:ramp_n] if not r.ok)
    completed = sum(r.ok for r in results)
    rs = (
        controller.replica_seconds()
        if controller is not None
        else n_replicas * elapsed
    )
    rec = {
        "arm": name,
        "replicas_founding": n_replicas,
        "replicas_max": max_replicas,
        "autoscale": autoscale,
        "submitted": len(futures),
        "completed": completed,
        "shed": summary["shed"],
        "shed_total": len(futures) - completed,
        "shed_up_ramp": shed_up_ramp,
        "ramp_requests": ramp_n,
        "p50_ms": summary["latency_p50_ms"],
        "p99_ms": summary["latency_p99_ms"],
        "achieved_rps": round(completed / elapsed, 2),
        "replica_seconds": round(rs, 2),
        "duration_s": round(elapsed, 2),
        "removed": summary["routing"]["removed"],
    }
    if controller is not None:
        rec["autoscale_stats"] = controller.stats()
    return rec


def _chaos_scale_in(
    engine, manifest, *, max_batch, sessions, steps, traffic, quick
):
    """Scale-in under fire: rollout sessions resident on the retiring
    replica, which is KILLED while still handing them over. Bars: zero
    lost sessions, zero lost requests, every session completes and
    matches the offline trajectory."""
    import jax

    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.serve import ReplicaRouter, rollout
    from gnot_tpu.serve.rollout import offline_rollout

    devices = list(jax.devices())
    traffic = traffic[:sessions]
    reference = [
        offline_rollout(engine, s, steps, rows=max_batch) for s in traffic
    ]
    replicas = _build_pool(
        engine.model, engine.params, 3, max_batch=max_batch,
        manifest=manifest, devices=devices,
    )
    # The kill lands on replica 0 AFTER the removal starts: armed by
    # rollout-step ordinal, sized so eviction is mid-flight.
    kill_at = max(4, sessions // 2)
    router = ReplicaRouter(
        replicas,
        max_batch=max_batch,
        max_wait_ms=2.0,
        session_snapshot_every=2,
        faults={0: FaultInjector.from_spec(f"replica_kill@{kill_at}")},
    ).start()
    futures = [router.submit_rollout(s, steps) for s in traffic]
    # Let the storm take residence everywhere, then retire replica 0
    # while it still holds sessions — the kill fires during the drain.
    time.sleep(0.05)
    t0 = time.perf_counter()
    router.remove_replica(0, timeout_s=60.0, reason="scale_in")
    remove_s = time.perf_counter() - t0
    results = [f.result(timeout=300) for f in futures]
    summary = router.drain()
    lost_sessions = sum(1 for r in results if not r.ok)
    worst = 0.0
    for r, ref in zip(results, reference):
        if r.ok:
            worst = max(worst, rollout.parity_check(r.outputs, ref))
    sess = summary.get("sessions") or {}
    return {
        "probe": "chaos_scale_in",
        "quick": quick,
        "sessions": sessions,
        "steps": steps,
        "removed_replica": 0,
        "kill_at_step": kill_at,
        "remove_s": round(remove_s, 3),
        "completed": sum(1 for r in results if r.ok),
        "lost_sessions": lost_sessions,
        "lost_requests": sum(
            n
            for reason, n in summary["shed"].items()
            if reason not in ("error_replica_dead",)
        ),
        "dead_request_failures_replayed": summary["shed"].get(
            "error_replica_dead", 0
        ),
        "migrated": sess.get("migrated", 0),
        "max_abs_diff": worst,
        "bar_lost": 0,
        "bar_numeric": 1e-5,
    }


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--min_replicas", type=int, default=2)
    p.add_argument("--max_replicas", type=int, default=4)
    p.add_argument("--duration_s", type=float, default=32.0)
    p.add_argument("--base_mult", type=float, default=0.5,
                   help="base offered load as a multiple of one "
                        "replica's measured capacity")
    p.add_argument("--peak_mult", type=float, default=5.0,
                   help="diurnal peak rate as a multiple of base")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--sessions", type=int, default=10,
                   help="chaos arm: concurrent rollout sessions")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="short window + small storm (CI smoke, not the "
                        "committed artifact)")
    args = p.parse_args(argv)
    if args.quick:
        args.duration_s = min(args.duration_s, 8.0)
        args.sessions, args.steps = 6, 4

    _ensure_xla_flags(args.max_replicas)

    import tempfile

    import jax
    import loadgen
    import serve_smoke

    from gnot_tpu.serve import InferenceEngine, aot, build_replica
    from gnot_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()
    model, params = _build_model(args.max_batch)
    traffic = serve_smoke.mixed_traffic(
        16, seed=args.seed, mesh_lo=600, mesh_hi=1000
    )
    engine = InferenceEngine(model, params, batch_size=args.max_batch)
    engine.warmup(traffic, rows=args.max_batch)

    # Deploy-time AOT pass for the MAX topology: every arm (and every
    # scale-out) hydrates warm snapshots instead of compiling — the
    # prewarm-before-join contract the controller enforces.
    devices = list(jax.devices())
    deploy = [
        build_replica(
            model, params, i, devices[i : i + 1],
            batch_size=args.max_batch,
        )
        for i in range(args.max_replicas)
    ]
    t0 = time.perf_counter()
    manifest = aot.prewarm_deployment(
        [(r.replica_id, r.engine) for r in deploy],
        traffic,
        rows=args.max_batch,
        snapshot_dir=tempfile.mkdtemp(prefix="autoscale_ab_snap_"),
    )
    print(
        f"autoscale_ab: deploy AOT pass for {args.max_replicas} slots "
        f"in {time.perf_counter() - t0:.1f}s"
    )

    # Capacity probe: one replica's dispatch rate sets the trace scale
    # (the diurnal peak must genuinely overload a min-size pool).
    keys = [engine.bucket_key(s) for s in traffic]
    t0 = time.perf_counter()
    for s, k in zip(traffic[:8], keys[:8]):
        engine.infer([s], pad_nodes=k[0], pad_funcs=k[1],
                     rows=args.max_batch)
    dispatch_s = (time.perf_counter() - t0) / 8
    cap1 = args.max_batch / dispatch_s
    base_rps = args.base_mult * cap1
    print(
        f"autoscale_ab: dispatch {dispatch_s * 1e3:.1f} ms -> 1-replica "
        f"capacity ~{cap1:.0f}/s; trace base {base_rps:.0f}/s, peak "
        f"~{base_rps * args.peak_mult:.0f}/s over {args.duration_s}s"
    )
    times = loadgen.trace_times(
        "diurnal_bursty",
        base_rps=base_rps,
        duration_s=args.duration_s,
        seed=args.seed,
        peak_mult=args.peak_mult,
        bursts=2,
        burst_mult=2.0,
        burst_frac=0.06,
    )
    print(f"autoscale_ab: {len(times)} arrivals on the shared trace")

    # Controller thresholds in per-replica in-system requests: grow
    # well before the queue saturates, shrink near-idle.
    up_load = 1.0 * args.max_batch
    down_load = 0.5 * args.max_batch
    common = dict(
        max_batch=args.max_batch,
        max_replicas=args.max_replicas,
        duration_s=args.duration_s,
        cooldown_s=0.5,
        up_load=up_load,
        down_load=down_load,
    )
    records: list[dict] = []
    failures: list[str] = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    static = _arm(
        "static", model, params, traffic, times, manifest=manifest,
        n_replicas=args.max_replicas, autoscale=False, **common,
    )
    records.append(static)
    print(
        f"  static     p99={static['p99_ms']:.1f}ms shed="
        f"{static['shed_total']} replica_s={static['replica_seconds']}"
    )
    auto = _arm(
        "autoscaled", model, params, traffic, times, manifest=manifest,
        n_replicas=args.min_replicas, autoscale=True, **common,
    )
    records.append(auto)
    print(
        f"  autoscaled p99={auto['p99_ms']:.1f}ms shed="
        f"{auto['shed_total']} shed_up_ramp={auto['shed_up_ramp']} "
        f"replica_s={auto['replica_seconds']} "
        f"(ups={auto['autoscale_stats']['scale_ups']} "
        f"downs={auto['autoscale_stats']['scale_downs']})"
    )

    chaos = _chaos_scale_in(
        engine, manifest, max_batch=args.max_batch,
        sessions=args.sessions, steps=args.steps, traffic=traffic,
        quick=args.quick,
    )
    records.append(chaos)
    print(
        f"  chaos      lost_sessions={chaos['lost_sessions']} "
        f"lost_requests={chaos['lost_requests']} "
        f"migrated={chaos['migrated']} parity={chaos['max_abs_diff']:.2e}"
    )

    p99_ratio = (
        auto["p99_ms"] / static["p99_ms"] if static["p99_ms"] else None
    )
    summary = {
        "summary": "autoscale_ab",
        "quick": bool(args.quick),
        "trace": "diurnal_bursty",
        "duration_s": args.duration_s,
        "base_rps": round(base_rps, 1),
        "peak_mult": args.peak_mult,
        "arrivals": len(times),
        "min_replicas": args.min_replicas,
        "max_replicas": args.max_replicas,
        "up_load": up_load,
        "down_load": down_load,
        "p99_static_ms": static["p99_ms"],
        "p99_autoscaled_ms": auto["p99_ms"],
        "p99_ratio": round(p99_ratio, 3) if p99_ratio else None,
        "bar_p99_ratio": BAR_P99_RATIO,
        "replica_seconds_static": static["replica_seconds"],
        "replica_seconds_autoscaled": auto["replica_seconds"],
        "replica_seconds_saved_frac": round(
            1.0 - auto["replica_seconds"] / static["replica_seconds"], 3
        ),
        "shed_up_ramp": auto["shed_up_ramp"],
        "bar_shed_up_ramp": 0,
        "scale_ups": auto["autoscale_stats"]["scale_ups"],
        "scale_downs": auto["autoscale_stats"]["scale_downs"],
        "chaos_lost_sessions": chaos["lost_sessions"],
        "chaos_lost_requests": chaos["lost_requests"],
        "chaos_migrated": chaos["migrated"],
        "chaos_max_abs_diff": chaos["max_abs_diff"],
    }
    records.append(summary)

    if not args.quick:
        # The timing bars hold on the committed (full-window) trace;
        # --quick compresses the diurnal ramp faster than any reactive
        # controller can track, so the CI smoke checks wiring + the
        # chaos/efficiency invariants only.
        check(
            p99_ratio is not None and p99_ratio <= BAR_P99_RATIO,
            f"autoscaled p99 {auto['p99_ms']} vs static "
            f"{static['p99_ms']} (ratio {p99_ratio}) beyond the "
            f"{BAR_P99_RATIO} noise bar",
        )
        check(
            auto["shed_up_ramp"] == 0,
            f"autoscaled arm shed {auto['shed_up_ramp']} requests on "
            "the up-ramp (must grow before it sheds)",
        )
    check(
        auto["replica_seconds"] < static["replica_seconds"],
        "autoscaled pool did not save replica-seconds "
        f"({auto['replica_seconds']} vs {static['replica_seconds']})",
    )
    check(
        auto["autoscale_stats"]["scale_ups"] >= 1,
        "controller never scaled out — the trace was vacuous",
    )
    check(
        chaos["lost_sessions"] == 0,
        f"chaos arm lost {chaos['lost_sessions']} sessions",
    )
    check(
        chaos["lost_requests"] == 0,
        f"chaos arm lost {chaos['lost_requests']} requests",
    )
    check(
        chaos["max_abs_diff"] <= chaos["bar_numeric"],
        f"chaos-arm parity {chaos['max_abs_diff']} over the bar",
    )

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(
        f"autoscale_ab: p99 {auto['p99_ms']:.1f} vs {static['p99_ms']:.1f}"
        f"ms (ratio {p99_ratio:.2f}), replica-seconds "
        f"{auto['replica_seconds']:.0f} vs {static['replica_seconds']:.0f}"
        f" (saved {summary['replica_seconds_saved_frac']:.0%}), "
        f"up-ramp shed {auto['shed_up_ramp']}; wrote {args.out}"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary = dict(summary)
    summary["failures"] = failures
    return summary


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
