"""Validate the COMPILED Mosaic kernels on real TPU hardware.

The pytest suite pins itself to 8 virtual CPU devices (tests/conftest.py),
so it exercises the pallas kernels only in interpret mode. This script
runs the compiled kernels on the default accelerator and checks them
against their einsum oracles — run it on a TPU VM after touching
``gnot_tpu/ops/pallas_*.py``:

    python tools/validate_tpu_kernels.py

Expected deviations on TPU f32 (MXU accumulation order + transcendental
approximation): attention out ~1e-4 abs, softmaxed q ~1e-6, grads ~1e-4;
FFN ~1e-5. Exits nonzero on violation.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# A site hook may have initialized the real-chip backend already; honor
# JAX_PLATFORMS anyway (backends re-initialize lazily after the update).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np


def validate_attention() -> None:
    from gnot_tpu.ops.pallas_attention import _reference_impl, fused_nla

    rng = np.random.default_rng(1)
    f, b, l, lk, e, h = 2, 2, 300, 200, 64, 4
    q = jnp.asarray(rng.normal(size=(b, l, e)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(f, b, lk, e)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(f, b, lk, e)).astype(np.float32))
    mask = jnp.asarray((rng.uniform(size=(f, b, lk)) > 0.3).astype(np.float32))

    out, qs = fused_nla(q, k, v, mask, h)
    ref_out, ref_qs = _reference_impl(q, k, v, mask, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=1e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(qs), np.asarray(ref_qs), rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda q_: jnp.sum(fused_nla(q_, k, v, mask, h)[0] ** 2))(q)
    g2 = jax.grad(lambda q_: jnp.sum(_reference_impl(q_, k, v, mask, h)[0] ** 2))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3, atol=5e-4)
    print(
        f"attention ok  (max out diff {float(jnp.max(jnp.abs(out - ref_out))):.2e}, "
        f"max grad diff {float(jnp.max(jnp.abs(g1 - g2))):.2e})"
    )


def validate_attention_seg() -> None:
    """Segment-packed stages (nla_reduce_seg / nla_apply_seg): Mosaic
    compiles of the scalar-prefetch scatter/gather path vs the einsum
    oracle, fwd + grad, on a two-row multi-segment packing with ragged
    tails, pad chunks and an empty slot."""
    from gnot_tpu.ops.pallas_attention import (
        _reference_seg_impl,
        fused_nla_packed,
    )

    rng = np.random.default_rng(2)
    f, b, e, h, chunk = 2, 2, 64, 4, 128
    n, n_seg = 6, 5  # slot 4 left empty
    l = n * chunk
    q = jnp.asarray(rng.normal(size=(b, l, e)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(f, b, l, e)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(f, b, l, e)).astype(np.float32))
    seg = jnp.asarray(
        np.array([[0, 0, 1, 1, 1, n_seg], [2, 3, 3, n_seg, n_seg, n_seg]],
                 np.int32)
    )
    mask = np.ones((f, b, l), np.float32)
    mask[:, 0, 5 * chunk - 17 :] = 0.0  # seg 1 ragged tail + pad chunk
    mask[:, 1, 3 * chunk - 40 :] = 0.0  # seg 3 ragged tail + pad chunks
    mask = jnp.asarray(mask)

    out, qs = fused_nla_packed(q, k, v, mask, seg, seg, n_seg, h)
    ref_out, ref_qs = _reference_seg_impl(q, k, v, mask, seg, seg, n_seg, h)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_out), rtol=1e-3, atol=5e-4
    )
    np.testing.assert_allclose(
        np.asarray(qs), np.asarray(ref_qs), rtol=1e-5, atol=1e-5
    )

    g1 = jax.grad(
        lambda q_: jnp.sum(
            fused_nla_packed(q_, k, v, mask, seg, seg, n_seg, h)[0] ** 2
        )
    )(q)
    g2 = jax.grad(
        lambda q_: jnp.sum(
            _reference_seg_impl(q_, k, v, mask, seg, seg, n_seg, h)[0] ** 2
        )
    )(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3, atol=5e-4)
    print(
        f"attention seg ok (max out diff "
        f"{float(jnp.max(jnp.abs(out - ref_out))):.2e}, "
        f"max grad diff {float(jnp.max(jnp.abs(g1 - g2))):.2e})"
    )


def validate_ffn() -> None:
    from gnot_tpu.ops.pallas_ffn import _reference_impl, fused_gated_ffn

    rng = np.random.default_rng(0)
    e_, b, l, d, hid = 3, 2, 300, 32, 64
    x = jnp.asarray(rng.normal(size=(b, l, d)).astype(np.float32))
    s = jax.nn.softmax(jnp.asarray(rng.normal(size=(b, l, e_)).astype(np.float32)), -1)
    ks = [
        jnp.asarray(rng.normal(size=(e_, d, hid)).astype(np.float32) * 0.1),
        jnp.asarray(rng.normal(size=(e_, hid, hid)).astype(np.float32) * 0.1),
        jnp.asarray(rng.normal(size=(e_, hid, d)).astype(np.float32) * 0.1),
    ]
    bs = [jnp.asarray(rng.normal(size=(e_, k.shape[-1])).astype(np.float32) * 0.1) for k in ks]

    out = fused_gated_ffn(x, s, ks, bs)
    ref = _reference_impl(x, s, ks, bs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)

    g1 = jax.grad(lambda x_: jnp.sum(fused_gated_ffn(x_, s, ks, bs) ** 2))(x)
    g2 = jax.grad(lambda x_: jnp.sum(_reference_impl(x_, s, ks, bs) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3, atol=1e-4)
    print(f"ffn ok        (max out diff {float(jnp.max(jnp.abs(out - ref))):.2e})")


def main() -> int:
    backend = jax.default_backend()
    print(f"backend: {backend}")
    validate_attention()
    validate_attention_seg()
    validate_ffn()
    if backend != "tpu":
        # Interpret-mode results must not masquerade as hardware
        # validation for a CI job or a skimming operator.
        print(
            "NOT on TPU: kernels ran in interpret mode — this only "
            "re-checked what the pytest suite covers; compiled-kernel "
            "validation did NOT happen"
        )
        return 2
    print("all compiled-kernel checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
