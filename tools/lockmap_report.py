#!/usr/bin/env python
"""Emit the project lock map: every lock, every ordering edge, zero cycles.

The GL008 pass (``gnot_tpu/analysis/lockorder.py``) already builds the
project-wide acquires-while-holding graph to *gate* on cycles; this
tool publishes the same graph as a committed artifact,
``docs/artifacts/lockmap.jsonl`` — the concurrency plane's census,
alongside the capacity/overhead artifacts. A reviewer reading a
locking change diffs the lockmap instead of re-deriving the ordering
discipline from source; ``tests/test_artifacts.py`` pins the schema
and asserts ``cycles == 0`` and the lock census floor, so the
committed map can never drift stale or cyclic.

Record shapes (one JSON object per line, ``record`` discriminates):

* ``{"record": "node", "lock", "kind", "file", "line", "module",
  "class"}`` — one per lock identity (``Class.attr`` /
  ``module.name`` / ``module.fn.name``).
* ``{"record": "edge", "held", "acquired", "witness": [...]}`` — one
  per ordering edge; ``witness`` is the ``file:line`` hop chain from
  the outer acquisition to the inner one (call-mediated hops carry
  ``(inside callee)`` markers).
* ``{"record": "summary", "schema": 1, "locks", "edges", "cycles",
  "census": {module: lock count}}`` — last line; ``cycles`` is a
  LIST (shippable state: ``[]``), so a regression is visible in the
  artifact itself, not only in the exit status.

Usage::

    python tools/lockmap_report.py                     # stdout
    python tools/lockmap_report.py --out docs/artifacts/lockmap.jsonl

Exit status: 0 when cycle-free, 1 when any cycle exists.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Same import shim as tools/lint.py: the analysis package is
# stdlib-only; skip gnot_tpu/__init__.py's jax import.
if "gnot_tpu" not in sys.modules:
    import types

    _stub = types.ModuleType("gnot_tpu")
    _stub.__path__ = [os.path.join(_REPO_ROOT, "gnot_tpu")]
    sys.modules["gnot_tpu"] = _stub

from gnot_tpu.analysis.core import (  # noqa: E402
    FileContext,
    iter_python_files,
    load_config,
)
from gnot_tpu.analysis.lockorder import build_lock_graph  # noqa: E402


def lockmap_lines(root: str) -> tuple[list[str], int]:
    """The artifact's lines (no trailing newline each) and the cycle
    count — separated from main() so tests can call it in-process."""
    cfg = load_config(root)
    contexts = []
    for rel in iter_python_files(cfg.paths, root, cfg):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        contexts.append(FileContext(root, rel, source, cfg))
    nodes, edges, cycles = build_lock_graph(contexts)

    lines: list[str] = []
    for lock in sorted(nodes):
        lines.append(json.dumps({"record": "node", "lock": lock, **nodes[lock]}))
    for held, acquired in sorted(edges):
        lines.append(
            json.dumps(
                {
                    "record": "edge",
                    "held": held,
                    "acquired": acquired,
                    "witness": edges[(held, acquired)],
                }
            )
        )
    census: dict[str, int] = {}
    for meta in nodes.values():
        census[meta["module"]] = census.get(meta["module"], 0) + 1
    lines.append(
        json.dumps(
            {
                "record": "summary",
                "schema": 1,
                "locks": len(nodes),
                "edges": len(edges),
                "cycles": cycles,
                "census": dict(sorted(census.items())),
            }
        )
    )
    return lines, len(cycles)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_REPO_ROOT)
    ap.add_argument(
        "--out", default="", help="write here instead of stdout"
    )
    args = ap.parse_args(argv)

    lines, n_cycles = lockmap_lines(args.root)
    text = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    if n_cycles:
        print(f"lockmap: {n_cycles} cycle(s) — NOT shippable", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
