"""Shared open-loop load-trace generation for the serving benches.

Every serving A/B so far re-implemented its own Poisson arrival loop
(serve_bench, coldstart_ab, lowprec_ab each had one). This module is
THE one generator: it produces the full arrival schedule up front
(seeded, replayable — both arms of an A/B submit at identical instants)
and replays it open-loop (submissions happen on schedule whether or not
the pool keeps up; the backlog is the measurement, never the throttle).

Rate shapes (``trace_times``):

* ``steady`` — homogeneous Poisson at ``base_rps`` (the classic
  serve_bench arrival process).
* ``diurnal`` — one sinusoidal "day" over the window: the rate ramps
  from ``base_rps`` up to ``peak_mult * base_rps`` at mid-window and
  back. The canonical autoscaling workload: a fixed pool either sheds
  at the peak or idles at the edges.
* ``bursty`` — ``bursts`` evenly-spaced square bursts of
  ``burst_mult * base_rps``, each ``burst_frac`` of the window wide.
* ``diurnal_bursty`` — the product of the two: bursts riding the
  diurnal ramp (the autoscale A/B's trace).

Inhomogeneous arrivals are drawn by thinning (Lewis & Shedler): a
homogeneous Poisson stream at the peak rate, each point kept with
probability ``rate(t) / rate_max`` — exact for any bounded rate
function, and deterministic under the seed.

Multi-tenant traces (``multi_stream_times``): N independent seeded
streams — one per tenant, each its own pattern/rate — merged into one
interleaved ``[(offset, tenant)]`` schedule. Each stream derives its
seed from the master seed and its position, so the composite is
deterministic and one tenant's shape change never perturbs a
sibling's arrivals (``tools/tenant_ab.py``, ``serve_smoke --tenants``).
"""

from __future__ import annotations

import math
import time
from typing import Callable

import numpy as np

PATTERNS = ("steady", "diurnal", "bursty", "diurnal_bursty")


def rate_fn(
    pattern: str,
    *,
    base_rps: float,
    duration_s: float,
    peak_mult: float = 3.0,
    bursts: int = 2,
    burst_mult: float = 3.0,
    burst_frac: float = 0.08,
) -> tuple[Callable[[float], float], float]:
    """``(rate(t), rate_max)`` for one named pattern over the window.
    ``rate_max`` is the exact least upper bound the thinning loop
    samples at."""
    if pattern not in PATTERNS:
        raise ValueError(f"unknown pattern {pattern!r}; one of {PATTERNS}")
    if base_rps <= 0 or duration_s <= 0:
        raise ValueError("base_rps and duration_s must be > 0")
    if peak_mult < 1.0 or burst_mult < 1.0:
        raise ValueError("peak_mult and burst_mult must be >= 1")
    if not 0 < burst_frac < 1:
        raise ValueError(f"burst_frac must be in (0, 1), got {burst_frac}")

    def diurnal(t: float) -> float:
        # sin^2 ramp: base at the edges, base*peak_mult at mid-window.
        s = math.sin(math.pi * t / duration_s)
        return 1.0 + (peak_mult - 1.0) * s * s

    def burst(t: float) -> float:
        # `bursts` square windows centered at (k + 0.5) / bursts.
        if bursts < 1:
            return 1.0
        width = burst_frac * duration_s
        for k in range(bursts):
            center = (k + 0.5) / bursts * duration_s
            if abs(t - center) <= width / 2:
                return burst_mult
        return 1.0

    if pattern == "steady":
        return (lambda t: base_rps), base_rps
    if pattern == "diurnal":
        return (lambda t: base_rps * diurnal(t)), base_rps * peak_mult
    if pattern == "bursty":
        return (lambda t: base_rps * burst(t)), base_rps * burst_mult
    return (
        lambda t: base_rps * diurnal(t) * burst(t)
    ), base_rps * peak_mult * burst_mult


def trace_times(
    pattern: str,
    *,
    base_rps: float,
    duration_s: float,
    seed: int = 0,
    **shape,
) -> list[float]:
    """The full arrival schedule: sorted offsets (seconds from t0) of
    one seeded open-loop trace. Same pattern + seed => identical trace,
    so A/B arms submit at the same instants."""
    rate, rate_max = rate_fn(
        pattern, base_rps=base_rps, duration_s=duration_s, **shape
    )
    rng = np.random.default_rng(seed)
    times: list[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / rate_max))
        if t >= duration_s:
            return times
        # Thinning: always consume one uniform per candidate so the
        # kept-point stream is a deterministic function of the seed.
        if float(rng.uniform()) * rate_max <= rate(t):
            times.append(t)


def multi_stream_times(
    streams: dict[str, dict],
    *,
    duration_s: float,
    seed: int = 0,
) -> list[tuple[float, str]]:
    """Compose N independent per-tenant traces into ONE interleaved
    open-loop schedule: ``[(offset_s, tenant), ...]`` sorted by offset.

    ``streams`` maps tenant name -> that tenant's ``trace_times``
    kwargs (``pattern``, ``base_rps``, plus any shape kwargs; an
    optional per-stream ``seed`` overrides the derived one). Each
    stream is seeded independently and deterministically —
    ``seed + stream index in insertion order`` — so one tenant's shape
    change never perturbs a sibling's arrivals, and the same
    (streams, duration, seed) always yields the identical interleaved
    schedule (the tenant A/B's shared-trace requirement: both arms
    replay the same storm). Ties break by (offset, tenant) — stable
    and replayable.
    """
    if not streams:
        raise ValueError("multi_stream_times needs at least one stream")
    merged: list[tuple[float, str]] = []
    for i, (tenant, spec) in enumerate(streams.items()):
        kw = dict(spec)
        stream_seed = kw.pop("seed", seed + i)
        pattern = kw.pop("pattern")
        times = trace_times(
            pattern, duration_s=duration_s, seed=stream_seed, **kw
        )
        merged.extend((t, tenant) for t in times)
    merged.sort(key=lambda e: (e[0], e[1]))
    return merged


def replay(
    submit: Callable[[int], object],
    times: list[float],
    *,
    clock: Callable[[], float] = time.perf_counter,
    sleep: Callable[[float], None] = time.sleep,
) -> list[object]:
    """Open-loop replay: call ``submit(i)`` at each scheduled offset.
    Behind schedule? Submit immediately — the generator never waits for
    the pool (queueing collapse must be observable, not hidden).
    Returns the submit results in arrival order."""
    out: list[object] = []
    t0 = clock()
    for i, at in enumerate(times):
        lag = at - (clock() - t0)
        if lag > 0:
            sleep(lag)
        out.append(submit(i))
    return out


def ramp_split(times: list[float], duration_s: float) -> int:
    """Index of the first arrival past mid-window — everything before
    it rode the diurnal UP-ramp (the autoscale A/B's zero-shed bar is
    scoped to this prefix)."""
    half = duration_s / 2.0
    for i, t in enumerate(times):
        if t > half:
            return i
    return len(times)
