"""Tracing-overhead A/B: train step time with span tracing OFF vs ON.

The acceptance bar for the tracing subsystem (docs/observability.md
"Tracing") is <=2% step-time regression at the default sample rate
(1.0 — every step traced) on the ns2d CPU micro-bench. The ON arm runs
the REAL per-step span sites the trainer uses — ``Tracer.timed_iter``
wrapping the batch iterator (one ``data_iter`` span per pull) and a
``step`` span wrapping ``host_to_device`` + ``step_dispatch`` children
per step (``host_to_device`` times the single-device identity
placement, exactly what ``Trainer._device_batch`` is with no mesh),
one trace for the whole window — against a live ``Tracer`` with a real
output path, and the final flush (the Chrome-JSON write) is INSIDE the
timed window, so the measured cost is everything tracing adds end to
end. Timed windows are best-of-N and interleaved off/on like
tools/telemetry_ab.py, so ambient machine-load drift hits both arms
alike.

Usage::

    JAX_PLATFORMS=cpu python tools/tracing_ab.py \
        --steps 60 --repeats 3 --out docs/artifacts/tracing_overhead_ab.jsonl

Emits one JSONL record per arm plus a summary record with
``overhead_frac``; committed as docs/artifacts/tracing_overhead_ab.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(n_points: int, batch_size: int):
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_state, make_train_step

    samples = datasets.synth_ns2d(batch_size, n_points=n_points, seed=0)
    batch = next(iter(Loader(samples, batch_size)))
    # Same micro-bench architecture as tools/telemetry_ab.py: reference
    # shape at half width/depth — CPU-fast, realistic relative cost.
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=128, n_mlp_num_layers=2,
        n_mlp_hidden_dim=128, n_input_hidden_dim=128, n_expert=3, n_head=4,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    step = make_train_step(model, optim, "rel_l2")
    return step, state, batch


def _window(step, state0, batch, traced: bool, steps: int, sample_rate: float,
            copy_tree, lr) -> float:
    """One timed window of ``steps`` steps; the ON arm runs the real
    trainer span sites plus the end-of-window flush. Warm-up step
    outside the window."""
    from gnot_tpu.obs.tracing import Tracer

    state = copy_tree(state0)
    tracer = trace = None
    if traced:
        tracer = Tracer(
            path=os.path.join(tempfile.mkdtemp(), "tracing_ab_trace.json"),
            sample_rate=sample_rate,
        )
        trace = tracer.start_trace()

    import contextlib

    def tspan(name, **args):
        if trace is None:
            return contextlib.nullcontext()
        return tracer.span(name, trace=trace, args=args or None)

    def one(state, i, b):
        with tspan("step", step=i):
            with tspan("host_to_device"):
                db = b  # single-device _device_batch is the identity
            with tspan("step_dispatch"):
                state, loss = step(state, db, lr)
        return state, loss

    def batch_iter(n):
        # The trainer wraps its loader in Tracer.timed_iter — same
        # data_iter span site here, over the same repeated batch.
        it = iter([batch] * n)
        if trace is not None:
            return tracer.timed_iter(it, "data_iter", trace=trace)
        return it

    state, loss = one(state, 0, batch)
    np.asarray(loss)
    t0 = time.perf_counter()
    for i, b in enumerate(batch_iter(steps), start=1):
        state, loss = one(state, i, b)
    if tracer is not None:
        tracer.flush()
    np.asarray(loss)  # hard fetch: the window ends when the device does
    return (time.perf_counter() - t0) / steps


def time_ab(n_points: int, batch_size: int, steps: int, sample_rate: float,
            repeats: int) -> tuple[float, float]:
    """Best-of-``repeats`` seconds/step for (off, on), timed windows
    interleaved off/on so ambient load drift cancels (the
    tools/telemetry_ab.py methodology)."""
    step, state, batch = build(n_points, batch_size)
    lr = jnp.asarray(1e-3, jnp.float32)
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    best_off = best_on = float("inf")
    for _ in range(max(1, repeats)):
        best_off = min(
            best_off,
            _window(step, state, batch, False, steps, sample_rate,
                    copy_tree, lr),
        )
        best_on = min(
            best_on,
            _window(step, state, batch, True, steps, sample_rate,
                    copy_tree, lr),
        )
    return best_off, best_on


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n_points", type=int, default=512)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--sample_rate", type=float, default=1.0)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    platform = jax.devices()[0].platform
    sec_off, sec_on = time_ab(
        args.n_points, args.batch_size, args.steps, args.sample_rate,
        args.repeats,
    )
    records = []
    for arm, sec in (("tracing_off", sec_off), ("tracing_on", sec_on)):
        records.append({
            "arm": arm, "ms_per_step": round(sec * 1e3, 4),
            "platform": platform, "n_points": args.n_points,
            "batch_size": args.batch_size, "steps": args.steps,
            "sample_rate": args.sample_rate, "repeats": args.repeats,
        })
    off, on = records[0]["ms_per_step"], records[1]["ms_per_step"]
    records.append({
        "summary": "tracing_overhead", "config": "ns2d_micro",
        "ms_per_step_off": off, "ms_per_step_on": on,
        "overhead_frac": round(on / off - 1.0, 4),
        "bar": "overhead_frac < 0.02 at the default sample_rate=1.0",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
