"""Capacity report: program costs x live traffic, headroom, and the
pack-plan recommendation the observed traffic supports.

The sensor loop closed (docs/observability.md "Program costs &
capacity"): run a real ``serve_smoke`` storm with the program catalog
attached (``--capacity``), then fold the resulting
``serve_summary.capacity_model`` into the operator-facing tables:

* **program rows** — one per dispatched program: XLA cost entry
  (flops / bytes / memory breakdown, or the explicit ``unavailable``
  marker), attributed traffic (dispatches, requests, real vs capacity
  tokens, device seconds) and the derived rates (device-us per token,
  achieved FLOPs/s, useful-token fraction).
* **capacity row** — the pool model vs the observed offered load:
  sustainable requests/s and tokens/s per replica (the 100%-device-duty
  bound) against what the storm actually offered, as headroom ratios —
  plus an ``agreement`` block asserting the model's traffic totals
  match the serve_summary's own counters number-for-number (empty
  ``problems`` list required; a drifting join is a bug, not a report).
* **pack_recommendation row** — the adaptive-packing hook: derive a
  ``PackPlan`` from the traffic the catalog OBSERVED (per-bucket
  request counts and mean sizes reconstructed from the padded
  programs' token tallies), simulate the server's own first-fit FIFO
  prefix packing over the reconstructed arrival mix, and report the
  projected pad waste next to the measured padded waste and the
  committed packed-arm baseline (docs/artifacts/pack_ab.jsonl). The
  reconstruction is exact for the pack simulation whenever each bucket
  lies within one chunk band (true for the default chunk=64 small-mesh
  workload): every size in a bucket then packs to the same aligned
  segment, so per-bucket means lose nothing.

Usage::

    JAX_PLATFORMS=cpu python tools/capacity_report.py \
        --out docs/artifacts/capacity_snapshot.jsonl

Defaults reproduce the pack_ab serve arm's storm (same traffic
generator, same knobs), so the recommendation row is directly
comparable to the committed packed-arm number. Committed as
docs/artifacts/capacity_snapshot.jsonl and schema-checked by
tests/test_artifacts.py::test_capacity_snapshot_artifact_schema.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _round(x, nd=4):
    return None if x is None else round(x, nd)


def program_rows(model: dict) -> list[dict]:
    """One record per program: the cost x traffic join, verbatim."""
    rows = []
    for key, prog in model["programs"].items():
        rows.append({
            "record": "program",
            "program": key,
            "source": prog["source"],
            "costs": prog["costs"],
            "dispatches": prog["dispatches"],
            "requests": prog["requests"],
            "real_tokens": prog["real_tokens"],
            "capacity_tokens": prog["capacity_tokens"],
            "device_s": _round(prog["device_s"], 6),
            "useful_token_frac": _round(prog["useful_token_frac"]),
            "device_us_per_token": _round(prog["device_us_per_token"], 3),
            "tokens_per_device_s": _round(prog["tokens_per_device_s"], 1),
            "flops_per_s": _round(prog["flops_per_s"], 1),
        })
    return rows


def agreement(summary: dict, model: dict) -> dict:
    """The model's traffic totals vs serve_summary's own counters —
    the two views are one accounting; any drift is a bug."""
    pool = model["pool"]
    pw = summary.get("pad_waste_by_bucket") or {}
    checks = {
        "dispatches": (pool["dispatches"], summary.get("dispatches")),
        "real_tokens": (
            pool["real_tokens"],
            sum(st["real_tokens"] for st in pw.values()),
        ),
        "capacity_tokens": (
            pool["capacity_tokens"],
            sum(st["capacity_tokens"] for st in pw.values()),
        ),
    }
    problems = [
        f"{name}: model {a} != summary {b}"
        for name, (a, b) in checks.items()
        if a != b
    ]
    return {
        **{name: a for name, (a, _) in checks.items()},
        "problems": problems,
    }


def capacity_row(summary: dict, model: dict) -> dict:
    """Pool capacity vs observed offered load, as headroom ratios."""
    pool = model["pool"]
    wall = summary.get("wall_s") or 0.0
    offered_rps = summary.get("requests_per_s")
    offered_tps = pool["real_tokens"] / wall if wall else None
    sus_rps = pool["sustainable_requests_per_s"]
    sus_tps = pool["sustainable_tokens_per_s"]
    return {
        "record": "capacity",
        "replicas": pool["replicas"],
        "programs": pool["programs"],
        "dispatches": pool["dispatches"],
        "requests": pool["requests"],
        "real_tokens": pool["real_tokens"],
        "capacity_tokens": pool["capacity_tokens"],
        "useful_token_frac": _round(pool["useful_token_frac"]),
        "device_s": _round(pool["device_s"], 6),
        "sustainable_requests_per_s": _round(sus_rps, 1),
        "sustainable_tokens_per_s": _round(sus_tps, 1),
        "offered_requests_per_s": _round(offered_rps, 1),
        "offered_tokens_per_s": _round(offered_tps, 1),
        # Headroom > 1: the pool could absorb that factor more load at
        # 100% device duty. The autoscaler's capacity-side signal.
        "headroom_requests": _round(
            sus_rps / offered_rps if sus_rps and offered_rps else None, 2
        ),
        "headroom_tokens": _round(
            sus_tps / offered_tps if sus_tps and offered_tps else None, 2
        ),
        "agreement": agreement(summary, model),
    }


def reconstruct_sizes(model: dict, chunk: int) -> tuple[list[int], list[dict]]:
    """Per-request mesh sizes reconstructed from the padded programs'
    observed traffic (requests + real tokens per bucket). Arrival
    order is modeled as a STRIDE interleave — each bucket's requests
    spread evenly over the sequence, so a numerous bucket (the Darcy64
    queries of the mixed workload) appears proportionally often
    between the rarer large meshes, like the storm that produced the
    histogram. The reconstruction is exact for the pack simulation
    when each bucket's sizes share one chunk-aligned segment length
    (true whenever the bucket spans at most one chunk band)."""
    slots: list[tuple[float, int, int]] = []
    buckets = []
    for bi, (key, prog) in enumerate(sorted(model["programs"].items())):
        if not key.startswith("bucket:") or not prog["requests"]:
            continue
        pn = int(key.split(":")[1].split("x")[0])
        reqs, real = prog["requests"], prog["real_tokens"]
        mean = real // reqs
        rem = real - mean * reqs
        sizes = [min(pn, mean + 1)] * rem + [max(1, mean)] * (reqs - rem)
        for i, n in enumerate(sizes):
            slots.append(((i + 0.5) / reqs, bi, n))
        buckets.append({
            "bucket": pn,
            "requests": reqs,
            "mean_size": _round(real / reqs, 1),
        })
    slots.sort(key=lambda t: (t[0], t[1]))
    return [n for _, _, n in slots], buckets


def _simulate(sizes: list[int], plan, max_batch: int) -> tuple[int, int, int]:
    """(packed_dispatches, fallback_dispatches, capacity_tokens) of
    running ``sizes`` through the server's own first-fit FIFO prefix
    packing under ``plan``; oversize requests take the padded
    per-bucket fallback path at their bucket's capacity."""
    from gnot_tpu.data.batch import bucket_length, pack_prefix

    packable = [n for n in sizes if plan.aligned(n) <= plan.row_len]
    oversize = [n for n in sizes if plan.aligned(n) > plan.row_len]
    rest, packed_dispatches = packable, 0
    while rest:
        placements = pack_prefix(rest, plan)
        k = max(1, len(placements))
        packed_dispatches += 1
        rest = rest[k:]
    capacity = packed_dispatches * plan.capacity_tokens
    fallback_dispatches = 0
    by_bucket: dict[int, int] = {}
    for n in oversize:
        by_bucket[bucket_length(n)] = by_bucket.get(bucket_length(n), 0) + 1
    for pn, cnt in by_bucket.items():
        d = -(-cnt // max_batch)
        fallback_dispatches += d
        capacity += d * max_batch * pn
    return packed_dispatches, fallback_dispatches, capacity


def pack_recommendation(
    model: dict, chunk: int, max_batch: int, baseline: float | None
) -> dict:
    """The adaptive-packing recommendation: search the plan grid
    (chunk-aligned row lengths x row counts) over the reconstructed
    observed traffic, simulating each candidate with the server's own
    packing, and report the lowest-projected-waste plan. A search, not
    a single heuristic derivation: the observed histogram says which
    grid its mix actually fills."""
    from gnot_tpu.data.batch import PackPlan

    sizes, buckets = reconstruct_sizes(model, chunk)
    if not sizes:
        return {"record": "pack_recommendation", "plan": None,
                "reason": "no padded traffic observed"}
    pad_funcs = max(
        (
            int(k.split(":")[1].split("x")[1].split("@")[0])
            for k in model["programs"]
            if k.startswith("bucket:")
        ),
        default=0,
    )
    real = sum(sizes)
    max_aligned = max(-(-n // chunk) * chunk for n in sizes)
    best = None
    candidates = 0
    for row_len in range(max_aligned, 4 * max_aligned + 1, chunk):
        for n_rows in range(1, 2 * max_batch + 1):
            plan = PackPlan(
                row_len=row_len, chunk=chunk, n_rows=n_rows,
                n_slots=n_rows * (row_len // chunk), pad_funcs=pad_funcs,
            )
            candidates += 1
            packed_d, fallback_d, capacity = _simulate(
                sizes, plan, max_batch
            )
            waste = 1.0 - real / capacity if capacity else None
            # Lowest projected waste wins; ties break toward the
            # smaller dispatch capacity (cheapest program).
            if best is None or (waste, plan.capacity_tokens) < (
                best[0], best[1].capacity_tokens,
            ):
                best = (waste, plan, packed_d, fallback_d, capacity)
    projected, plan, packed_dispatches, fallback_dispatches, capacity = best
    observed = (
        1.0 - model["pool"]["real_tokens"] / model["pool"]["capacity_tokens"]
        if model["pool"]["capacity_tokens"]
        else None
    )
    return {
        "record": "pack_recommendation",
        "plan": dataclasses.asdict(plan),
        "candidates_searched": candidates,
        "observed_buckets": buckets,
        "requests": len(sizes),
        "packed_dispatches": packed_dispatches,
        "fallback_dispatches": fallback_dispatches,
        "real_tokens": real,
        "capacity_tokens": capacity,
        "observed_pad_waste": _round(observed),
        "projected_pad_waste": _round(projected),
        "baseline_packed_pad_waste": baseline,
        "beats_baseline": (
            None
            if baseline is None or projected is None
            else bool(projected <= baseline)
        ),
    }


def load_baseline(path: str) -> float | None:
    """The committed pack_ab packed-arm pad waste (the bar the
    recommendation must reproduce or beat on the same traffic)."""
    try:
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("summary") == "pack_ab":
                    return rec.get("serve_pad_waste_packed")
    except OSError:
        pass
    return None


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=32, help="storm size "
                   "(default: the pack_ab serve arm's)")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--mesh_lo", type=int, default=40)
    p.add_argument("--mesh_hi", type=int, default=200)
    p.add_argument("--chunk", type=int, default=64,
                   help="recommendation plan's segment alignment")
    p.add_argument("--pack_ab", type=str,
                   default=os.path.join(
                       os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))),
                       "docs", "artifacts", "pack_ab.jsonl"),
                   help="committed pack_ab artifact to read the "
                        "packed-arm baseline from")
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    import jax

    import serve_smoke

    t0 = time.perf_counter()
    summary = serve_smoke.run([
        "--n", str(args.n), "--max_batch", str(args.max_batch),
        "--inject_fault", "none", "--deadline_ms", "10000",
        "--mesh_lo", str(args.mesh_lo), "--mesh_hi", str(args.mesh_hi),
        "--capacity",
    ])
    if summary["failures"]:
        print(f"FAIL: storm failed its own assertions: "
              f"{summary['failures']}")
        return 1
    model = summary["capacity_model"]
    records = program_rows(model)
    cap = capacity_row(summary, model)
    records.append(cap)
    rec = pack_recommendation(
        model, args.chunk, args.max_batch, load_baseline(args.pack_ab)
    )
    records.append(rec)
    records.append({
        "summary": "capacity_report",
        "platform": jax.devices()[0].platform,
        "n_requests": args.n,
        "max_batch": args.max_batch,
        "mesh_lo": args.mesh_lo,
        "mesh_hi": args.mesh_hi,
        "chunk": args.chunk,
        "programs": model["pool"]["programs"],
        "agreement_problems": cap["agreement"]["problems"],
        "projected_pad_waste": rec.get("projected_pad_waste"),
        "baseline_packed_pad_waste": rec.get("baseline_packed_pad_waste"),
        "beats_baseline": rec.get("beats_baseline"),
        "elapsed_s": round(time.perf_counter() - t0, 1),
        "bar": "agreement_problems empty; projected_pad_waste <= the "
               "committed pack_ab packed-arm pad waste on the same "
               "traffic",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out)
    ok = not cap["agreement"]["problems"] and rec.get("beats_baseline") in (
        True, None,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
