"""Reference-scale --train_data demonstration (VERDICT r4 missing #2 / next #5).

The reference's design point is real pickles of ~1100 samples with
~10k-point meshes and ~800-point input functions (the dataset the
hardcoded paths name, ``/root/reference/main.py:28-29``; the inline
shape comment ``/root/reference/model.py:110-116`` checks
``q [4,10044,256]`` / input function ``[4,805,256]``).  This tool
closes the gap between "schema-compatible" and "demonstrated at
reference scale": it writes synthetic pickles AT that scale in the
reference record schema ``[X, Y, theta, (f,)]`` and drives the real
``--train_data`` CLI path on the chip, recording throughput and the
convergence curve.

  python tools/reference_scale_demo.py --generate   # ~220 MB under /tmp
  python tools/reference_scale_demo.py --train --epochs 5 \
      --out docs/artifacts/reference_scale_demo.jsonl

The committed artifact is the JSONL of per-epoch losses + the
points/sec summary line; docs/performance.md carries the table row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN_PKL = "/tmp/ref_scale_train.pkl"
TEST_PKL = "/tmp/ref_scale_test.pkl"


def generate(n_train: int, n_test: int, seed: int = 0) -> None:
    from gnot_tpu.data.batch import MeshSample
    from gnot_tpu.data.datasets import _smooth_target, save_pickle

    rng = np.random.default_rng(seed)

    def make(n_samples):
        out = []
        for _ in range(n_samples):
            # The reference shape-of-record: ~10044-point meshes,
            # ~805-point input functions (model.py:110-116 comment).
            n = int(rng.integers(9500, 10500))
            m = int(rng.integers(760, 850))
            coords = rng.uniform(0, 1, size=(n, 2)).astype(np.float32)
            theta = rng.uniform(0, 1, size=(1,)).astype(np.float32)
            fc = rng.uniform(0, 1, size=(m, 2)).astype(np.float32)
            w0 = np.sin(2 * np.pi * fc @ rng.uniform(1, 2, size=(2, 1))).astype(
                np.float32
            )
            f = np.concatenate([fc, w0], axis=1)
            y = _smooth_target(coords, theta, (f,))
            out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=(f,)))
        return out

    for path, n in ((TRAIN_PKL, n_train), (TEST_PKL, n_test)):
        t0 = time.time()
        save_pickle(make(n), path)
        # Sidecar count: lets train() stamp the true scale in its
        # summary without re-unpickling the ~150 MB file.
        with open(path + ".count", "w") as f:
            f.write(str(n))
        print(
            f"{path}: {n} samples, {os.path.getsize(path)/1e6:.0f} MB "
            f"({time.time()-t0:.0f}s)"
        )


def train(args) -> None:
    from gnot_tpu.main import main as cli_main

    # The ACTUAL scale trained on (not the --n_train the generate step
    # may or may not have used) — the artifact test pins this field.
    # Prefer the generate() sidecar; fall back to counting the pickle.
    try:
        with open(TRAIN_PKL + ".count") as f:
            n_train_actual = int(f.read())
    except (OSError, ValueError):
        from gnot_tpu.data.datasets import load_pickle

        n_train_actual = len(load_pickle(TRAIN_PKL))
    out = args.out
    metrics = "/tmp/ref_scale_metrics.jsonl"
    if os.path.exists(metrics):
        os.remove(metrics)
    t0 = time.time()
    best = cli_main(
        [
            "--train_data", TRAIN_PKL, "--test_data", TEST_PKL,
            "--epochs", str(args.epochs),
            "--dtype", "bfloat16",
            "--steps_per_dispatch", str(args.steps_per_dispatch),
            "--metrics_path", metrics,
        ]
    )
    wall = time.time() - t0
    with open(metrics) as f:
        records = [json.loads(line) for line in f]
    epochs = [r for r in records if "train_loss" in r and "epoch" in r]
    # Whole-run average throughput from REAL (unpadded) points — the
    # trainer's per-epoch meter times the full host+dispatch loop, so
    # this is the end-to-end number, deliberately more conservative
    # than the bench.py device-marginal.
    total_points = sum(
        r["points_per_sec"] * r["epoch_seconds"]
        for r in epochs
        if r.get("points_per_sec") and r.get("epoch_seconds")
    )
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write(
            json.dumps(
                {
                    "kind": "summary",
                    "n_train": n_train_actual,
                    "epochs": args.epochs,
                    "best_metric": best,
                    "wall_seconds": round(wall, 1),
                    "train_points_per_sec_end_to_end": (
                        round(total_points / wall, 1) if total_points else None
                    ),
                }
            )
            + "\n"
        )
    print(f"best={best} wall={wall:.0f}s -> {out}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--generate", action="store_true")
    p.add_argument("--train", action="store_true")
    p.add_argument("--n_train", type=int, default=1100)
    p.add_argument("--n_test", type=int, default=110)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--steps_per_dispatch", type=int, default=5)
    p.add_argument("--out", default="docs/artifacts/reference_scale_demo.jsonl")
    args = p.parse_args()
    if args.generate:
        generate(args.n_train, args.n_test)
    if args.train:
        train(args)


if __name__ == "__main__":
    main()
