"""Program-catalog overhead A/B: serve-storm throughput with the
catalog + per-dispatch attribution OFF vs ON.

The acceptance bar for the capacity plane (docs/observability.md
"Program costs & capacity", mirroring the tracing/metrics subsystems)
is <=2% throughput cost. BOTH arms run the full live metrics plane —
registry, publisher, SLO evaluator, event sink — so the A/B isolates
exactly what the catalog ADDS on its real hot path: the per-dispatch
program-key stamp in the engine, the ``note_dispatch`` attribution
(traffic ledger + per-program counters/histograms/gauges in the
registry), and the dispatch-provenance check feeding the jit-fallback
counter. Cost CAPTURE is deliberately outside the timed windows: it
runs once per program at warmup in any real deployment (and is
pre-recorded here the same way), so timing it inside a storm window
would measure a startup cost as a steady-state one.

Timed windows are interleaved off/on like tools/metrics_ab.py, so
ambient machine-load drift hits both arms alike; each arm reports the
interquartile mean of its windows (see the estimator note in main —
GC is also held off inside every timed window, both arms).

Usage::

    JAX_PLATFORMS=cpu python tools/capacity_ab.py \
        --n 400 --repeats 3 --out docs/artifacts/capacity_overhead_ab.jsonl

Emits one JSONL record per arm plus a summary record with
``overhead_frac``; committed as docs/artifacts/capacity_overhead_ab.jsonl
and schema-pinned by tests/test_artifacts.py.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _window(
    engine, traffic, warm_catalog, *, on: bool, interval_s: float,
    max_batch: int
) -> tuple[float, dict]:
    """One timed storm window: submit -> all resolved, on a fresh
    server over the shared warm engine. Returns (seconds, info)."""
    from gnot_tpu.obs.metrics import (
        MetricsPublisher,
        MetricsRegistry,
        SLOEvaluator,
        SLOObjective,
    )
    from gnot_tpu.serve import InferenceServer
    from gnot_tpu.serve.catalog import ProgramCatalog
    from gnot_tpu.utils.metrics import MetricsSink

    tmp = tempfile.mkdtemp(prefix="capacity_ab_")
    info: dict = {}
    sink = MetricsSink(os.path.join(tmp, "events.jsonl"))
    registry = MetricsRegistry()
    publisher = MetricsPublisher(
        registry,
        interval_s=interval_s,
        sink=sink,
        series_path=os.path.join(tmp, "series.jsonl"),
        exposition_path=os.path.join(tmp, "expo.prom"),
        evaluator=SLOEvaluator([
            SLOObjective("shed_fraction", "shed_frac", 0.05,
                         fast_window_s=0.5, slow_window_s=2.0),
            SLOObjective("breaker_open", "breaker_open", 1.0,
                         fast_window_s=0.5, slow_window_s=2.0),
        ]),
    )
    catalog = None
    if on:
        # Fresh per-window catalog bound to this window's registry and
        # sink, PRE-POPULATED with the warmup capture's cost entries —
        # exactly a prewarmed deployment's steady state, so the window
        # times attribution, never a capture compile.
        catalog = ProgramCatalog(metrics=registry, sink=sink)
        for key, entry in warm_catalog.entries().items():
            catalog.record(key, entry["costs"], source=entry["source"])
    engine.attach_catalog(catalog)
    try:
        server = InferenceServer(
            engine, max_batch=max_batch, max_wait_ms=2.0,
            queue_limit=4 * len(traffic), metrics=registry, sink=sink,
            catalog=catalog,
        ).start()
        publisher.start()
        # GC parity: a collection pause landing inside one arm's window
        # (the interpreter's gen2 walks jax's whole object graph, ~10ms
        # a pop) is the dominant noise term at these window lengths —
        # collect up front and hold GC off for the timed region of BOTH
        # arms so neither wins or loses the pause lottery.
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            futures = [server.submit(s) for s in traffic]
            for f in futures:
                r = f.result(timeout=120)
                assert r.ok, r.reason
            seconds = time.perf_counter() - t0
        finally:
            gc.enable()
        summary = server.drain()
        info["snapshots"] = publisher.close()["seq"]
        if on:
            model = summary.get("capacity_model") or {}
            pool = model.get("pool") or {}
            assert pool.get("dispatches", 0) > 0, (
                "ON arm attributed no dispatches — the A/B measured "
                "nothing"
            )
            info["attributed_dispatches"] = pool["dispatches"]
        sink.close()
    finally:
        engine.attach_catalog(None)
    return seconds, info


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=400, help="requests per window")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--interval_s", type=float, default=0.25,
                   help="publisher cadence (both arms)")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    import jax

    from serve_smoke import build_engine
    from gnot_tpu.data import datasets
    from gnot_tpu.serve.catalog import ProgramCatalog

    platform = jax.devices()[0].platform
    engine = build_engine(max_batch=args.max_batch)
    # Uniform darcy64 traffic: ONE bucket, warmed up front, so the
    # windows time dispatch + attribution — never a compile (the cost
    # capture rides the warmup, like a real deployment's startup).
    traffic = datasets.synth_darcy2d(args.n, seed=0, grid_n=8)
    warm_catalog = ProgramCatalog()
    engine.attach_catalog(warm_catalog)
    engine.warmup(traffic[: args.max_batch], rows=args.max_batch)
    engine.attach_catalog(None)
    assert warm_catalog.entries(), "warmup captured no catalog entries"

    times: dict[str, list[float]] = {"off": [], "on": []}
    snapshots = attributed = 0
    for _ in range(max(1, args.repeats)):
        # Interleaved off/on (the telemetry/tracing A/B methodology):
        # ambient load drift cancels across arms.
        sec_off, _ = _window(
            engine, traffic, warm_catalog, on=False,
            interval_s=args.interval_s, max_batch=args.max_batch,
        )
        sec_on, info = _window(
            engine, traffic, warm_catalog, on=True,
            interval_s=args.interval_s, max_batch=args.max_batch,
        )
        times["off"].append(sec_off)
        times["on"].append(sec_on)
        snapshots = max(snapshots, info.get("snapshots", 0))
        attributed = max(attributed, info.get("attributed_dispatches", 0))

    # Interquartile mean per arm, NOT best-of: this host's window
    # times are burst-noisy with EQUAL means but unequal spread across
    # arms, and a min estimator systematically flatters whichever arm's
    # distribution has the fatter fast tail. Trimming the top and
    # bottom quarter and averaging the middle is robust to both the
    # bursts and the tail asymmetry.
    def iq_mean(xs: list[float]) -> float:
        xs = sorted(xs)
        k = len(xs) // 4
        mid = xs[k : len(xs) - k] or xs
        return sum(mid) / len(mid)

    records = []
    for arm in ("off", "on"):
        sec = iq_mean(times[arm])
        records.append({
            "arm": f"capacity_{arm}",
            "requests": args.n,
            "seconds": round(sec, 4),
            "seconds_min": round(min(times[arm]), 4),
            "windows": len(times[arm]),
            "requests_per_s": round(args.n / sec, 2),
            "platform": platform,
            "max_batch": args.max_batch,
            "interval_s": args.interval_s,
            "repeats": args.repeats,
            **(
                {
                    "snapshots": snapshots,
                    "attributed_dispatches": attributed,
                    "programs": len(warm_catalog.entries()),
                }
                if arm == "on"
                else {}
            ),
        })
    rps_off = records[0]["requests_per_s"]
    rps_on = records[1]["requests_per_s"]
    records.append({
        "summary": "capacity_overhead",
        "config": "darcy64_storm",
        "requests_per_s_off": rps_off,
        "requests_per_s_on": rps_on,
        "snapshots_on": snapshots,
        "attributed_dispatches": attributed,
        "overhead_frac": round(1.0 - rps_on / rps_off, 4),
        "bar": "overhead_frac <= 0.02 with catalog attribution live",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
