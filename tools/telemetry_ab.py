"""Telemetry-overhead A/B: step time with obs telemetry OFF vs ON.

The acceptance bar for the telemetry subsystem (docs/observability.md)
is <2% step-time regression at ``log_every=10`` on the ns2d CPU
micro-bench. This tool measures it honestly: both arms run the REAL hot
path — the ON arm uses the instrumented train step plus a live
``TelemetryBuffer`` draining into a real ``MetricsSink`` file every
``log_every`` steps, so the measured cost includes the extra compiled
reductions, the buffered device-array bookkeeping, the batched
``device_get`` and the JSONL writes. Timed windows are best-of-N with a
hard fetch at the end (the bench.py methodology; stalls only ever add
time).

Usage::

    JAX_PLATFORMS=cpu python tools/telemetry_ab.py \
        --steps 60 --repeats 3 --out docs/artifacts/telemetry_overhead_ab.jsonl

Emits one JSONL record per arm plus a summary record with
``overhead_frac``; committed as docs/artifacts/telemetry_overhead_ab.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(telemetry: bool, n_points: int, batch_size: int):
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.obs import telemetry as obs_telemetry
    from gnot_tpu.train.trainer import init_state, make_train_step

    samples = datasets.synth_ns2d(batch_size, n_points=n_points, seed=0)
    batch = next(iter(Loader(samples, batch_size)))
    # Micro-bench architecture: reference shape at half width/depth so a
    # CPU arm finishes in seconds while norms/gate stats keep realistic
    # relative cost.
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=128, n_mlp_num_layers=2,
        n_mlp_hidden_dim=128, n_input_hidden_dim=128, n_expert=3, n_head=4,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    if telemetry:
        step = obs_telemetry.make_train_step(model, optim, "rel_l2")
    else:
        step = make_train_step(model, optim, "rel_l2")
    return step, state, batch


def _window(step, state0, batch, telemetry: bool, steps: int, log_every: int,
            copy_tree, lr) -> float:
    """One timed window of ``steps`` steps; the ON arm runs the full
    buffer+sink hot path. Warm-up step outside the window."""
    from gnot_tpu.obs.telemetry import TelemetryBuffer
    from gnot_tpu.utils.metrics import MetricsSink

    state = copy_tree(state0)
    sink = buf = None
    if telemetry:
        sink = MetricsSink(os.path.join(tempfile.mkdtemp(), "telemetry_ab.jsonl"))
        buf = TelemetryBuffer(sink, log_every)

    def one(state, i):
        if telemetry:
            state, (loss, telem) = step(state, batch, lr)
            buf.append(steps=[i], epoch=0, lrs=[1e-3], loss=loss,
                       telem=telem, batches=[batch])
        else:
            state, loss = step(state, batch, lr)
        return state, loss

    state, loss = one(state, 0)
    np.asarray(loss)
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        state, loss = one(state, i)
    if buf is not None:
        buf.drain()
    np.asarray(loss)  # hard fetch: the window ends when the device does
    sec = (time.perf_counter() - t0) / steps
    if sink is not None:
        sink.close()
    return sec


def time_ab(n_points: int, batch_size: int, steps: int, log_every: int,
            repeats: int) -> tuple[float, float]:
    """Best-of-``repeats`` seconds/step for (off, on), with the arms'
    timed windows INTERLEAVED off/on/off/on: ambient machine-load drift
    over the minutes the A/B takes hits both arms alike instead of
    whichever ran second (observed mis-attributing ~5% to the second
    arm on a shared host)."""
    step_off, state_off, batch = build(False, n_points, batch_size)
    step_on, state_on, _ = build(True, n_points, batch_size)
    lr = jnp.asarray(1e-3, jnp.float32)
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    best_off = best_on = float("inf")
    for _ in range(max(1, repeats)):
        best_off = min(
            best_off,
            _window(step_off, state_off, batch, False, steps, log_every,
                    copy_tree, lr),
        )
        best_on = min(
            best_on,
            _window(step_on, state_on, batch, True, steps, log_every,
                    copy_tree, lr),
        )
    return best_off, best_on


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n_points", type=int, default=512)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--log_every", type=int, default=10)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    platform = jax.devices()[0].platform
    sec_off, sec_on = time_ab(
        args.n_points, args.batch_size, args.steps, args.log_every,
        args.repeats,
    )
    records = []
    for arm, sec in (("telemetry_off", sec_off), ("telemetry_on", sec_on)):
        records.append({
            "arm": arm, "ms_per_step": round(sec * 1e3, 4),
            "platform": platform, "n_points": args.n_points,
            "batch_size": args.batch_size, "steps": args.steps,
            "log_every": args.log_every, "repeats": args.repeats,
        })
    off, on = records[0]["ms_per_step"], records[1]["ms_per_step"]
    records.append({
        "summary": "telemetry_overhead", "config": "ns2d_micro",
        "ms_per_step_off": off, "ms_per_step_on": on,
        "overhead_frac": round(on / off - 1.0, 4),
        "bar": "overhead_frac < 0.02 at log_every=10",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
