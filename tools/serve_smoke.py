"""Serving smoke test: engine on synthetic Darcy64, mixed-bucket
traffic, one injected fault, asserted counters.

The minimal end-to-end proof that the serving subsystem
(``gnot_tpu/serve/``, docs/serving.md) holds its contract under
realistic conditions: ragged mixed-bucket traffic (64-point Darcy64
queries interleaved with elasticity-sized ~300-700-point clouds in the
same operator schema), dynamic per-bucket batching, a deterministic
injected fault (default: ``slow_request@3`` against a per-request
deadline → one deadline shed), graceful drain, and a ``serve_summary``
whose counters are ASSERTED, not just printed:

* every submitted request resolved (completed + shed == submitted);
* the injected fault produced >= 1 deadline shed;
* latency percentiles exist and p50 <= p99;
* no dispatch mixed two buckets and the compiled-program count is
  bounded by the distinct-bucket count (O(log L_max), never O(traffic)).

Usage::

    JAX_PLATFORMS=cpu python tools/serve_smoke.py \
        --n 24 --inject_fault slow_request@3 --deadline_ms 200

Exit code 0 iff every assertion holds. The fast version runs in tier-1
(tests/test_serve.py::test_serve_smoke_tool); longer storms via --n.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_engine(seed: int = 0, max_batch: int = 4, dtype: str = "float32"):
    """Tiny GNOT + fresh params on the Darcy64 schema (64-point grid,
    one input function) — weights untrained; serving correctness is
    about plumbing, not accuracy. ``dtype`` is the serving compute
    dtype (models/precision.py); params stay f32 at rest."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.serve import InferenceEngine
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(max_batch, seed=seed, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=1, n_attn_hidden_dim=16, n_mlp_num_layers=1,
        n_mlp_hidden_dim=16, n_input_hidden_dim=16, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples), seed)
    return InferenceEngine(model, params, batch_size=max_batch, dtype=dtype)


def mixed_traffic(n: int, seed: int = 0, mesh_lo: int = 300, mesh_hi: int = 700):
    """Darcy64 queries (64 points) interleaved with ragged clouds
    (``mesh_lo``..``mesh_hi`` points, default elasticity-sized 300-700)
    in the SAME operator schema — the adversarial mix that makes naive
    padding pathological (ISSUE 3) and exercises multiple buckets.
    Small ``mesh_hi`` (e.g. 200) makes the mixed SMALL-mesh workload the
    packing A/B (tools/pack_ab.py) measures."""
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import MeshSample

    rng = np.random.default_rng(seed)
    darcy = datasets.synth_darcy2d(n, seed=seed, grid_n=8)
    out = []
    for i in range(n):
        if i % 2 == 0:
            out.append(darcy[i])
            continue
        m = int(rng.integers(mesh_lo, mesh_hi))
        coords = rng.uniform(0, 1, size=(m, 2)).astype(np.float32)
        f = rng.uniform(0, 1, size=(m // 4, 3)).astype(np.float32)
        out.append(
            MeshSample(
                coords=coords,
                y=np.zeros((m, 1), np.float32),
                theta=darcy[i].theta,
                funcs=(f,),
            )
        )
    return out


def _run_federated(args) -> dict:
    """``--hosts N``: the federated storm (serve/federation.py,
    docs/distributed.md). The pool splits across N loopback hosts —
    one ``ReplicaRouter`` + ``HostAgent`` each, a ``ClusterRouter``
    placing the storm over in-proc links that run the real frame codec
    — and the smoke asserts the federation contract: zero lost futures,
    registry-valid events, per-host compile bounds, a coherent
    ``cluster_summary`` ledger."""
    import threading
    import time as _time

    import jax

    from gnot_tpu.data.batch import bucket_length
    from gnot_tpu.obs import events as events_registry
    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.serve import build_replica
    from gnot_tpu.serve.federation import build_local_federation
    from gnot_tpu.serve.rollout import SessionStore
    from gnot_tpu.utils.metrics import MetricsSink

    metrics_path = args.metrics_path or os.path.join(
        tempfile.mkdtemp(prefix="serve_smoke_"), "serve.jsonl"
    )
    engine = build_engine(max_batch=args.max_batch)
    traffic = mixed_traffic(args.n, mesh_lo=args.mesh_lo, mesh_hi=args.mesh_hi)
    per = max(1, args.replicas // args.hosts)
    devs = jax.devices()
    # Device slices wrap modulo the visible set: a 1-device CPU run
    # still federates (hosts share the device; the protocol plane —
    # what this mode tests — is host-level, not device-level).
    groups = [
        [
            build_replica(
                engine.model, engine.params, r,
                [devs[(h * per + r) % len(devs)]],
                batch_size=args.max_batch,
            )
            for r in range(per)
        ]
        for h in range(args.hosts)
    ]
    store = SessionStore(tempfile.mkdtemp(prefix="serve_smoke_sess_"))
    fi = FaultInjector.from_spec(args.inject_fault)
    chaos = (
        {f"host{h}": fi for h in range(args.hosts)}
        if fi is not None
        else None
    )
    failures = []

    def check(ok: bool, msg: str) -> None:
        if not ok:
            failures.append(msg)

    with MetricsSink(metrics_path) as sink:
        cluster, agents = build_local_federation(
            groups,
            sink=sink,
            session_store=store,
            suspect_after_s=0.5,
            dead_after_s=1.5,
            link_faults=chaos,
            host_faults=chaos,
            router_kwargs=dict(
                max_batch=args.max_batch,
                max_wait_ms=args.max_wait_ms,
                queue_limit=args.queue_limit,
                default_deadline_ms=args.deadline_ms,
                session_snapshot_every=args.session_snapshot_every,
            ),
        )
        for a in agents.values():
            a.router.start()
        # Serving-startup discipline, per host: every bucket compiles
        # on every replica before traffic.
        for g in groups:
            for r in g:
                r.warm(traffic, rows=args.max_batch)
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.05)

        ticker = threading.Thread(target=_ticker, daemon=True)
        ticker.start()
        try:
            t_submit = _time.perf_counter()
            futures = [
                cluster.submit_rollout(s, args.rollout)
                if args.rollout
                else cluster.submit(s)
                for s in traffic
            ]
            results = [f.result(timeout=120) for f in futures]
            wall_s = _time.perf_counter() - t_submit
        finally:
            # Stop the control loop BEFORE the sink context closes —
            # a ticker outliving a failed storm would write heartbeat
            # events into a closed file.
            stop.set()
            ticker.join(timeout=5)
        summary = cluster.drain()
        for a in agents.values():
            a.stop()
    summary["wall_s"] = wall_s
    summary["requests_per_s"] = args.n / wall_s if wall_s > 0 else None

    # -- assertions (the federation contract) -------------------------------
    # Zero lost futures: every submission resolved — a partition, a
    # dead host, or a drain may shed work honestly, but a future that
    # never resolves (or a session the ledger wrote off) fails.
    check(
        len(results) == args.n,
        f"{len(results)} resolved futures != {args.n} submitted",
    )
    check(
        summary["lost"] == 0,
        f"cluster lost sessions: lost={summary['lost']}",
    )
    n_ok = sum(r.ok for r in results)
    if args.rollout:
        check(
            summary["sessions"] == args.n,
            f"sessions ledger {summary['sessions']} != {args.n} submitted",
        )
    else:
        check(
            summary["requests"] == args.n
            and summary["completed"] + summary["shed"] == args.n,
            f"one-shot ledger incoherent: {summary['completed']}+"
            f"{summary['shed']} != {summary['requests']} != {args.n}",
        )
    if not args.inject_fault:
        check(
            n_ok == args.n,
            f"clean federated storm failed futures: {n_ok}/{args.n} ok",
        )
        check(
            summary["hosts_dead"] == 0 and summary["remigrated"] == 0,
            f"clean storm declared deaths/migrations: {summary}",
        )
        check(
            summary["protocol_errors"] == 0,
            f"clean storm counted protocol errors: "
            f"{summary['protocol_errors']}",
        )
    # Every record in the merged stream validates against the central
    # registry — per-host tagging (host=...) rides the extras contract.
    events = [json.loads(l) for l in open(metrics_path)]
    bad = [
        (e.get("event"), events_registry.validate_record(e))
        for e in events
        if events_registry.validate_record(e)
    ]
    check(
        not bad,
        f"{len(bad)} events fail registry validation; first: {bad[:3]}",
    )
    check(
        sum(e.get("event") == "cluster_summary" for e in events) == 1,
        "expected exactly one cluster_summary event",
    )
    hb_hosts = {
        e["host"] for e in events if e.get("event") == "host_heartbeat"
    }
    check(
        hb_hosts == set(agents),
        f"heartbeats observed from {sorted(hb_hosts)} != hosts "
        f"{sorted(agents)}",
    )
    # Single compile per bucket per host: each host's replicas warmed
    # every traffic bucket exactly once — the compiled-program count is
    # bounded by the distinct-bucket count, never O(traffic).
    expected = {
        (
            bucket_length(s.coords.shape[0]),
            bucket_length(max(f.shape[0] for f in s.funcs)),
        )
        for s in traffic
    }
    for h, g in enumerate(groups):
        for r in g:
            check(
                r.engine.compiled_shapes <= len(expected),
                f"host{h} replica {r.replica_id} compiled "
                f"{r.engine.compiled_shapes} shapes > "
                f"{len(expected)} traffic buckets",
            )
    print(
        f"serve_smoke: federated {args.hosts} hosts x {per} replicas, "
        f"{n_ok}/{args.n} ok, lost={summary['lost']}, "
        f"remigrated={summary['remigrated']}, "
        f"hosts_dead={summary['hosts_dead']}, "
        f"protocol_errors={summary['protocol_errors']}, "
        f"{summary['requests_per_s']:.1f} req/s"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary["failures"] = failures
    return summary


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=16, help="requests to fire")
    p.add_argument(
        "--inject_fault", type=str, default="",
        help="serve-side kind@N spec; default: slow_request@<n> (stall "
             "the LAST request's dispatch past its deadline — earlier "
             "batches complete, the victim's batch sheds, so the storm "
             "demonstrates both outcomes)"
    )
    p.add_argument("--deadline_ms", type=float, default=200.0)
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--max_wait_ms", type=float, default=5.0)
    p.add_argument("--queue_limit", type=int, default=256)
    p.add_argument(
        "--metrics_path", type=str, default="",
        help="JSONL event sink (default: a temp file, validated then "
             "discarded)"
    )
    p.add_argument(
        "--trace_path", type=str, default="",
        help="also run the span tracer (obs/tracing.py) and write the "
             "Chrome trace-event JSON here; the smoke then ALSO asserts "
             "every completed request has a complete admission->resolve "
             "span chain sharing one trace_id (the ISSUE 5 acceptance "
             "criterion)"
    )
    p.add_argument("--trace_sample_rate", type=float, default=1.0)
    p.add_argument(
        "--packed", action="store_true",
        help="packed dispatch mode ('pack, don't pad', docs/performance"
             ".md): derive a PackPlan from the traffic, pack plan-"
             "fitting requests as chunk-aligned segments into ONE "
             "fixed-shape program per dispatch; oversize requests fall "
             "back to the padded per-bucket path. The smoke then ALSO "
             "asserts packed-dispatch bucket discipline and the "
             "serve_summary pad-waste rollup"
    )
    p.add_argument("--pack_chunk", type=int, default=64,
                   help="packed-mode segment alignment (tokens)")
    p.add_argument(
        "--mesh_lo", type=int, default=300,
        help="ragged-cloud size range lower bound (with --mesh_hi; "
             "small values make the mixed small-mesh packing workload)"
    )
    p.add_argument("--mesh_hi", type=int, default=700)
    p.add_argument(
        "--replicas", type=int, default=1,
        help="run the storm through the compile-affinity ReplicaRouter "
             "over N mesh-sliced engine replicas (serve/router.py) "
             "instead of one InferenceServer; the smoke then ALSO "
             "asserts per-replica compiled-program bounds, one route "
             "event per request, and the pool-level serve_summary "
             "per-replica rollup"
    )
    p.add_argument(
        "--route_policy", type=str, default="affinity",
        choices=["affinity", "least_loaded", "round_robin"],
    )
    p.add_argument(
        "--rollout", type=int, default=0, metavar="K",
        help="autoregressive rollout mode (serve/rollout.py, "
             "docs/serving.md 'Rollout serving'): drive each traffic "
             "sample as ONE K-step stateful session instead of a "
             "one-shot request. The smoke then asserts the SESSION "
             "contract: every session future resolves, zero lost "
             "sessions, exactly one rollout_step event per committed "
             "step (1..K in order), session affinity honored (an "
             "unmigrated session's steps all ran on one replica), and "
             "the serve_summary sessions rollup is coherent. Default "
             "fault in this mode: none (arm one explicitly to chaos-"
             "test)"
    )
    p.add_argument(
        "--session_snapshot_every", type=int, default=1,
        help="rollout mode: host-side carry-snapshot cadence (steps)"
    )
    p.add_argument(
        "--metrics_interval_s", type=float, default=0.0, metavar="S",
        help="live metrics plane (obs/metrics.py): attach a "
             "MetricsRegistry to the tier and publish snapshots every "
             "S seconds (plus one guaranteed MID-STORM tick and the "
             "final post-drain tick). The smoke then ALSO asserts the "
             "ISSUE 14 contract: a mid-storm snapshot reports a "
             "NONZERO live pool p99 before drain, the final snapshot "
             "agrees with serve_summary number-for-number (counters "
             "exact, percentiles within the documented histogram "
             "bound), metrics_snapshot/slo_alert records validate "
             "against the event registry, and the alert stream is "
             "edge-disciplined (fire/clear alternation, no spam)"
    )
    p.add_argument(
        "--slo_shed_frac", type=float, default=0.05,
        help="metrics mode: tolerated windowed shed fraction before "
             "the shed_fraction objective fires"
    )
    p.add_argument(
        "--slo_fast_window_s", type=float, default=0.5,
        help="metrics mode: fast burn-rate window (smoke timescale)"
    )
    p.add_argument(
        "--slo_slow_window_s", type=float, default=2.0,
        help="metrics mode: slow burn-rate window (smoke timescale)"
    )
    p.add_argument(
        "--pace_s", type=float, default=0.0,
        help="sleep between submissions: shapes the storm over wall "
             "time (an open-loop trickle instead of one burst), so "
             "cadence-driven metrics snapshots land genuinely "
             "mid-storm"
    )
    p.add_argument(
        "--prewarm", action="store_true",
        help="deploy-time AOT prewarm (serve/aot.py): compile + "
             "snapshot the whole program family for the target "
             "topology first, then serve the storm from FRESH "
             "hydrated engines; the smoke then ALSO asserts the "
             "prewarmed tier compiled NOTHING — zero compile-cache "
             "requests and zero jit-fallback dispatches per replica "
             "during hydration + storm"
    )
    p.add_argument(
        "--tenants", action="store_true",
        help="multi-tenant isolation mode (serve/policies.py "
             "TenantPolicy, docs/serving.md 'Multi-tenant isolation'): "
             "run the storm as a TWO-TENANT burst — 'interactive' "
             "(weight 3, no quota) interleaved with a flooding 'batch' "
             "(weight 1, small admission quota) over ONE bucket so the "
             "WFQ + priority drain is the only arbiter. The smoke then "
             "ALSO asserts the isolation contract: batch sheds on its "
             "quota (tenant-tagged tenant_quota_shed events, registry-"
             "validated) while the interactive sibling never does, the "
             "priority drain keeps interactive p50 <= batch p50 under "
             "the shared backlog, and the serve_summary per-tenant "
             "rollup matches the observed per-future outcomes "
             "number-for-number. One-shot only (no --rollout)"
    )
    p.add_argument(
        "--tenant_weights", type=str, default="interactive:3,batch:1",
        help="tenants mode: WFQ weight spec (config.parse_tenant_spec "
             "grammar)"
    )
    p.add_argument(
        "--tenant_quotas", type=str, default="",
        help="tenants mode: admission quota spec; default "
             "batch:max(2, n//4) — small enough that the batch flood "
             "fast-fails on quota while interactive stays unthrottled"
    )
    p.add_argument(
        "--hosts", type=int, default=1,
        help="federated storm mode (serve/federation.py, docs/"
             "distributed.md): split the pool across N loopback hosts "
             "— each behind a HostAgent speaking the versioned wire "
             "protocol, a ClusterRouter placing the storm over lease-"
             "checked links. The smoke then asserts the FEDERATION "
             "contract instead: zero lost futures (every submission "
             "resolves, cluster_summary.lost == 0), every event record "
             "validates against the obs/events.py registry, per-host "
             "single-compile-per-bucket bounds, heartbeats observed "
             "from every host, and a coherent cluster_summary ledger. "
             "Composes with --rollout (K-step sessions through the "
             "cluster) and --inject_fault (federation kinds: host_kill@"
             "N, net_partition@N, msg_drop@N, msg_delay@MS)"
    )
    p.add_argument(
        "--capacity", action="store_true",
        help="program catalog & capacity plane (serve/catalog.py, "
             "docs/observability.md 'Program costs & capacity'): share "
             "ONE ProgramCatalog across the tier — XLA cost/memory "
             "analysis recorded per compiled program, every dispatch "
             "attributed to its program key — and assert the capacity "
             "contract: every dispatched program has a catalog entry "
             "(nonzero costs or an explicit unavailable marker), "
             "serve_summary carries the capacity model, and the model's "
             "traffic totals agree with the summary's own counters"
    )
    args = p.parse_args(argv)
    if args.tenants and args.rollout:
        p.error("--tenants is a one-shot storm mode (no --rollout)")
    if args.hosts > 1:
        if args.tenants or args.packed or args.prewarm or args.capacity:
            p.error(
                "--hosts composes with --rollout/--inject_fault only "
                "(the single-host modes assert single-host invariants)"
            )
        if args.inject_fault == "none":
            args.inject_fault = ""
        return _run_federated(args)
    if args.inject_fault == "none":
        args.inject_fault = ""
    elif not args.inject_fault:
        # Rollout mode defaults to a clean storm (its assertions pin
        # zero lost sessions); tenants mode too (the quota fast-fail IS
        # the demonstrated shed path, and the isolation assertions pin
        # the interactive sibling clean); the one-shot smoke keeps its
        # classic straggler-sheds-the-last-request scenario.
        args.inject_fault = (
            "" if (args.rollout or args.tenants) else f"slow_request@{args.n}"
        )

    from gnot_tpu.data.batch import bucket_length
    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.serve import InferenceServer
    from gnot_tpu.utils.metrics import MetricsSink

    metrics_path = args.metrics_path or os.path.join(
        tempfile.mkdtemp(prefix="serve_smoke_"), "serve.jsonl"
    )
    tracer = None
    if args.trace_path:
        from gnot_tpu.obs.tracing import Tracer

        tracer = Tracer(
            path=args.trace_path, sample_rate=args.trace_sample_rate
        )
    engine = build_engine(max_batch=args.max_batch)
    traffic = mixed_traffic(args.n, mesh_lo=args.mesh_lo, mesh_hi=args.mesh_hi)
    tenant_policy = tenant_of = None
    if args.tenants:
        from gnot_tpu.data import datasets
        from gnot_tpu.serve import TenantPolicy

        # Single-bucket traffic on purpose: with every request in ONE
        # bucket the WFQ + priority drain is the only arbiter of
        # dispatch order, so the fairness assertion (interactive p50 <=
        # batch p50) measures the policy, not bucket readiness.
        traffic = datasets.synth_darcy2d(args.n, seed=0, grid_n=8)
        quotas = args.tenant_quotas or f"batch:{max(2, args.n // 4)}"
        tenant_policy = TenantPolicy.from_specs(
            weights=args.tenant_weights, quotas=quotas
        )
        # (i//2) % 2: pairs alternate — interactive, interactive,
        # batch, batch, ... Equal split, interleaved, so both tenants
        # share the burst's backlog from the first flush on.
        tenant_of = lambda i: ("interactive", "batch")[(i // 2) % 2]  # noqa: E731
    pack_plan = None
    if args.packed:
        import jax as _jax

        from gnot_tpu.data.batch import PackPlan

        pack_plan = PackPlan.for_slices(
            traffic,
            chunk=args.pack_chunk,
            batch_size=args.max_batch,
            per_devices=(
                len(_jax.devices()) // args.replicas
                if args.replicas > 1
                else 1
            ),
        )
    # Precompile every bucket the storm will hit (serving-startup
    # discipline — docs/serving.md): an XLA compile landing under a
    # 200 ms deadline would shed everything queued behind it. Replicas
    # each warm their own executables (placement differs per slice).
    # Under --prewarm the compiles happen in a DEPLOY pass instead
    # (AOT compile + snapshot), and the serving engines below are
    # fresh twins that hydrate executables without compiling anything.
    manifest = None
    if args.prewarm:
        from gnot_tpu.serve import aot, build_replicas

        snap_dir = tempfile.mkdtemp(prefix="serve_smoke_snap_")
        if args.replicas > 1:
            deploy = build_replicas(
                engine.model, engine.params, args.replicas,
                batch_size=args.max_batch,
            )
            engines = [(r.replica_id, r.engine) for r in deploy]
        else:
            engines = [(0, engine)]
        manifest = aot.prewarm_deployment(
            engines, traffic, rows=args.max_batch, pack_plan=pack_plan,
            snapshot_dir=snap_dir,
        )
        if args.replicas <= 1:
            # The single-server arm reuses `engine` for the deploy
            # compile; serve from a fresh twin so the storm proves the
            # snapshots (not the deploy engine's in-process jit cache).
            engine = build_engine(max_batch=args.max_batch)
    import contextlib
    import time as _time

    from gnot_tpu.utils.cache import compile_cache_probe

    # One catalog shared by every engine/server/router of the tier —
    # attached BEFORE warmup/hydration so program entries are captured
    # at startup (warmup compiles, snapshot hydration) and never on the
    # storm's hot path. Registry and sink late-bind below.
    catalog = None
    if args.capacity:
        from gnot_tpu.serve.catalog import ProgramCatalog

        catalog = ProgramCatalog()
        engine.attach_catalog(catalog)

    # Under --prewarm the probe spans replica build + hydration + the
    # whole storm: the assertion below is "the serving tier compiled
    # NOTHING", not just "warmup was warm".
    with contextlib.ExitStack() as serve_stack:
        serve_cache = serve_stack.enter_context(compile_cache_probe())
        replicas = None
        if args.replicas > 1:
            from gnot_tpu.serve import build_replicas

            replicas = build_replicas(
                engine.model, engine.params, args.replicas,
                batch_size=args.max_batch,
            )
            if catalog is not None:
                for r in replicas:
                    r.engine.attach_catalog(catalog)
            if manifest is None:
                for r in replicas:
                    r.warm(traffic, rows=args.max_batch, pack_plan=pack_plan)
        elif manifest is not None:
            from gnot_tpu.serve import aot

            aot.hydrate_block(engine, manifest, 0)
        else:
            engine.warmup(traffic, rows=args.max_batch)
            if pack_plan is not None:
                engine.warmup_packed(traffic, pack_plan)

        registry = publisher = mid_snap = final_snap = None
        if args.metrics_interval_s > 0:
            from gnot_tpu.obs.metrics import (
                MetricsPublisher,
                MetricsRegistry,
                SLOEvaluator,
                SLOObjective,
            )

            registry = MetricsRegistry()
        with MetricsSink(metrics_path) as sink:
            if catalog is not None:
                # Entries recorded before this point (warmup captures,
                # snapshot hydration) replay their program_catalog
                # events into the now-open sink.
                catalog.attach_outputs(metrics=registry, sink=sink)
            common = dict(
                max_batch=args.max_batch,
                max_wait_ms=args.max_wait_ms,
                queue_limit=args.queue_limit,
                default_deadline_ms=args.deadline_ms,
                sink=sink,
                faults=FaultInjector.from_spec(args.inject_fault),
                tracer=tracer,
                pack_plan=pack_plan,
                session_snapshot_every=args.session_snapshot_every,
                metrics=registry,
                catalog=catalog,
                tenants=tenant_policy,
            )
            if registry is not None:
                w = dict(
                    fast_window_s=args.slo_fast_window_s,
                    slow_window_s=args.slo_slow_window_s,
                )
                stem = os.path.splitext(metrics_path)[0]
                publisher = MetricsPublisher(
                    registry,
                    interval_s=args.metrics_interval_s,
                    sink=sink,
                    series_path=f"{stem}.series.jsonl",
                    exposition_path=f"{stem}.prom",
                    evaluator=SLOEvaluator([
                        SLOObjective(
                            "shed_fraction", "shed_frac",
                            args.slo_shed_frac, **w,
                        ),
                        SLOObjective("breaker_open", "breaker_open", 1.0, **w),
                        SLOObjective("session_loss", "session_loss", 1.0, **w),
                    ]),
                )
            if replicas is not None:
                from gnot_tpu.serve import ReplicaRouter

                server = ReplicaRouter(
                    replicas, route_policy=args.route_policy, **common
                )
                if manifest is not None:
                    # Warm-replica hydration through the router so each
                    # replica's replica_warm event (source "snapshot")
                    # lands in the sink.
                    server.prewarm_from(manifest)
                server.start()
            else:
                server = InferenceServer(engine, **common).start()
            if publisher is not None:
                publisher.start()
            t_submit = _time.perf_counter()
            futures = []
            for i, s in enumerate(traffic):
                tkw = {"tenant": tenant_of(i)} if tenant_of else {}
                if args.rollout:
                    futures.append(
                        server.submit_rollout(s, args.rollout, **tkw)
                    )
                else:
                    futures.append(server.submit(s, **tkw))
                if args.pace_s:
                    _time.sleep(args.pace_s)
            results = []
            for i, f in enumerate(futures):
                results.append(f.result(timeout=120))
                if (
                    publisher is not None
                    and mid_snap is None
                    and i + 1 >= max(1, args.n // 2)
                ):
                    # The guaranteed MID-STORM snapshot (the cadence
                    # thread publishes too; this tick pins one while
                    # requests are demonstrably still in flight) —
                    # live p99 must be nonzero BEFORE drain.
                    mid_snap = publisher.tick()
            wall_s = _time.perf_counter() - t_submit
            summary = server.drain()
            if publisher is not None:
                if args.inject_fault:
                    # Observe the fault's breach right at drain (the
                    # fire edge, if cadence didn't catch it), then let
                    # it leave the FAST window and observe once more so
                    # the alert CLEARS before the final snapshot (the
                    # fire->clear edge-pair acceptance criterion — a
                    # drained tier with a still-open alert would read
                    # as a live incident).
                    publisher.tick()
                    _time.sleep(args.slo_fast_window_s * 1.25)
                    publisher.tick()
                final_snap = publisher.close()
            if tracer is not None:
                tracer.flush(sink=sink)
    # Storm throughput (submit -> last resolve; the pack_ab serve
    # metric). Not part of the serve_summary event schema — stamped on
    # the RETURNED dict only, after the sink closed.
    summary["wall_s"] = wall_s
    summary["requests_per_s"] = args.n / wall_s if wall_s > 0 else None

    # -- assertions (the point of a smoke test) ----------------------------
    failures = []

    def check(ok: bool, msg: str) -> None:
        if not ok:
            failures.append(msg)

    n_ok = sum(r.ok for r in results)
    n_shed = sum(not r.ok for r in results)
    check(
        n_ok + n_shed == args.n,
        f"every request must resolve: {n_ok}+{n_shed} != {args.n}",
    )
    if args.rollout:
        # results are RolloutResults: completed counts ok STEP
        # dispatches (>= the committed steps — a migration replays the
        # post-snapshot tail, at-least-once).
        check(
            summary["completed"]
            >= sum(r.steps_completed for r in results),
            "summary.completed < committed rollout steps",
        )
    else:
        check(
            summary["completed"] == n_ok, "summary.completed != observed oks"
        )
    check(n_ok >= 1, "storm completed zero requests")
    if "slow_request" in args.inject_fault and args.deadline_ms:
        check(
            summary["shed"].get("shed_deadline", 0) >= 1,
            f"injected straggler must shed >= 1 deadline: {summary['shed']}",
        )
    check(
        summary["latency_p50_ms"] is not None
        and summary["latency_p50_ms"] <= summary["latency_p99_ms"],
        f"latency percentiles malformed: {summary}",
    )
    # Bucket discipline from the event stream: every PADDED dispatch
    # names ONE bucket, every PACKED dispatch carries the plan's fixed
    # shape, and the engine compiled at most one program per bucket
    # (+1 for the pack plan).
    events = [json.loads(l) for l in open(metrics_path)]
    dispatches = [e for e in events if e.get("event") == "queue_depth"]
    padded_d = [e for e in dispatches if not e.get("packed")]
    packed_d = [e for e in dispatches if e.get("packed")]
    buckets = {(e["bucket_nodes"], e["bucket_funcs"]) for e in padded_d}
    lengths = {s.coords.shape[0] for s in traffic}
    expected = {
        (bucket_length(n), bucket_length(max(f.shape[0] for f in s.funcs)))
        for s in traffic
        for n in [s.coords.shape[0]]
    }
    check(
        buckets <= expected,
        f"dispatch buckets {buckets} outside the traffic's bucket set "
        f"{expected} — a batch mixed buckets",
    )
    l_max = bucket_length(max(lengths))
    bound = 2 * (int(math.log2(l_max / 64)) + 1)  # ~2 per octave, 2 axes
    per_bound = max(len(expected), bound) + (1 if pack_plan is not None else 0)
    if replicas is not None:
        # Bounded PER-REPLICA compile counts under the mixed-bucket
        # storm: each replica compiles at most one program per bucket
        # it warmed/was assigned — never O(traffic) — and the pool
        # total is bounded by replicas x the single-server bound.
        for r in replicas:
            check(
                r.engine.compiled_shapes <= per_bound,
                f"replica {r.replica_id} compiled "
                f"{r.engine.compiled_shapes} shapes > per-replica bound "
                f"{per_bound}",
            )
        check(
            summary["compiled_shapes"] <= per_bound * args.replicas,
            f"pool compiled {summary['compiled_shapes']} shapes exceeds "
            f"{per_bound} x {args.replicas} replicas",
        )
        routes = [e for e in events if e.get("event") == "route"]
        check(
            len(routes) == args.n,
            f"{len(routes)} route events != {args.n} submitted requests",
        )
        check(
            set(summary.get("per_replica", {}))
            == {str(r.replica_id) for r in replicas},
            f"serve_summary.per_replica rollup malformed: "
            f"{sorted(summary.get('per_replica', {}))}",
        )
    else:
        check(
            summary["compiled_shapes"] <= per_bound,
            f"{summary['compiled_shapes']} compiled shapes exceeds the "
            f"O(log L) bound ({bound}) / bucket count ({len(expected)})",
        )
    check(
        all(
            0 < e["real_tokens"] <= e["capacity_tokens"] for e in dispatches
        ),
        "a dispatch reported incoherent real/capacity token counts",
    )
    if pack_plan is not None:
        check(
            bool(packed_d),
            "packed mode on but no dispatch rode the pack plan",
        )
        check(
            all(
                (e["bucket_nodes"], e["bucket_funcs"])
                == (pack_plan.row_len, pack_plan.pad_funcs)
                for e in packed_d
            ),
            "a packed dispatch escaped the plan's fixed shape",
        )
        pw = summary.get("pad_waste_by_bucket") or {}
        pk = f"packed:{pack_plan.n_rows}x{pack_plan.row_len}"
        check(
            pk in pw and pw[pk]["fill_frac"] is not None,
            f"serve_summary.pad_waste_by_bucket missing the packed "
            f"bucket {pk}: {sorted(pw)}",
        )
    check(
        any(e.get("event") == "serve_summary" for e in events),
        "no serve_summary event in the sink",
    )
    if args.capacity:
        # The capacity contract (docs/observability.md "Program costs
        # & capacity"): the catalog saw every dispatched program, and
        # the cost x traffic join agrees with the summary's own
        # counters number-for-number.
        model = summary.get("capacity_model")
        check(
            bool(model),
            "capacity mode on but serve_summary carries no capacity_model",
        )
        if model:
            progs = model["programs"]
            check(bool(progs), "capacity model recorded no programs")
            missing = [
                k for k, pr in progs.items() if pr["source"] is None
            ]
            check(
                not missing,
                f"dispatched programs missing catalog entries: {missing}",
            )
            for key, pr in progs.items():
                c = pr["costs"]
                check(
                    any(c.get(f) for f in ("flops", "bytes_accessed"))
                    or bool(c.get("unavailable")),
                    f"program {key}: neither nonzero costs nor an "
                    f"explicit unavailable marker: {c}",
                )
            check(
                model["pool"]["dispatches"] == len(dispatches),
                f"capacity model counted {model['pool']['dispatches']} "
                f"dispatches != {len(dispatches)} dispatch events",
            )
            pw = summary.get("pad_waste_by_bucket") or {}
            check(
                model["pool"]["real_tokens"]
                == sum(st["real_tokens"] for st in pw.values())
                and model["pool"]["capacity_tokens"]
                == sum(st["capacity_tokens"] for st in pw.values()),
                "capacity model token totals disagree with "
                "pad_waste_by_bucket",
            )
            cat_events = {
                e["key"]
                for e in events
                if e.get("event") == "program_catalog"
            }
            check(
                set(progs) <= cat_events,
                f"programs without a program_catalog event: "
                f"{sorted(set(progs) - cat_events)}",
            )
            snap_events = [
                e for e in events if e.get("event") == "capacity_snapshot"
            ]
            check(
                len(snap_events) == 1,
                f"{len(snap_events)} capacity_snapshot events != 1",
            )
            print(
                f"serve_smoke: capacity model {len(progs)} programs, "
                f"pool sustainable "
                f"{model['pool']['sustainable_tokens_per_s'] and round(model['pool']['sustainable_tokens_per_s'])} tok/s, "
                f"useful_token_frac="
                f"{model['pool']['useful_token_frac'] and round(model['pool']['useful_token_frac'], 4)}"
            )
    if args.rollout:
        # The session contract (docs/serving.md "Rollout serving").
        migrated = {
            e["session"]
            for e in events
            if e.get("event") == "session_migrate"
        }
        rsteps = [e for e in events if e.get("event") == "rollout_step"]
        by_session: dict = {}
        for e in rsteps:
            by_session.setdefault(e["session"], []).append(e)
        for r in results:
            if not r.ok:
                continue
            got = sorted(e["step"] for e in by_session.get(r.session, []))
            # Exactly one rollout_step event per committed step, 1..K
            # (a migrated session may log replayed duplicates of the
            # post-snapshot tail — committed coverage must still be
            # exactly 1..K).
            want = list(range(1, args.rollout + 1))
            ok_steps = (
                got == want
                if r.session not in migrated
                else sorted(set(got)) == want
            )
            check(
                ok_steps,
                f"session {r.session}: rollout_step events {got} != "
                f"1..{args.rollout}",
            )
            # Session affinity: an unmigrated session's steps all ran
            # on ONE replica (steps 2..K never re-route).
            if replicas is not None and r.session not in migrated:
                owners = {
                    e.get("replica") for e in by_session.get(r.session, [])
                }
                check(
                    len(owners) == 1,
                    f"session {r.session} steps spread over replicas "
                    f"{owners} without a migration",
                )
        # "Lost" matches the router rollup's definition: a migration
        # give-up, i.e. a terminal BACKEND failure — not a deadline/
        # queue shed (those count under `shed`) and not a drain
        # (drained_at_step is set, possibly 0).
        from gnot_tpu.serve.server import MIGRATABLE_REASONS

        lost = [
            r
            for r in results
            if not r.ok
            and r.drained_at_step is None
            and r.reason in MIGRATABLE_REASONS
        ]
        if not args.inject_fault:
            check(
                not lost,
                f"clean rollout storm lost sessions: "
                f"{[(r.session, r.reason) for r in lost]}",
            )
        sess = summary.get("sessions") or {}
        check(
            sess.get("started", 0) >= args.n,
            f"sessions rollup malformed: {sess}",
        )
        if replicas is not None:
            check(
                sess.get("lost", 0) == len(lost),
                f"sessions rollup lost={sess.get('lost')} != observed "
                f"{len(lost)}",
            )
        snaps = [e for e in events if e.get("event") == "session_snapshot"]
        check(bool(snaps), "rollout storm took no session snapshots")
    if args.tenants:
        # The multi-tenant isolation contract (docs/serving.md
        # "Multi-tenant isolation"): quota fast-fail is tenant-scoped
        # and tenant-tagged, the sibling stays clean, the priority/WFQ
        # drain favors interactive under the shared backlog, and the
        # serve_summary per-tenant rollup agrees with the observed
        # per-future outcomes number-for-number.
        from gnot_tpu.obs import events as ev_registry

        observed: dict = {}
        for i, r in enumerate(results):
            st = observed.setdefault(
                tenant_of(i), {"requests": 0, "completed": 0, "shed": {}}
            )
            st["requests"] += 1
            if r.ok:
                st["completed"] += 1
            else:
                st["shed"][r.reason] = st["shed"].get(r.reason, 0) + 1
        roll = summary.get("tenants") or {}
        check(
            set(roll) == set(observed),
            f"serve_summary tenants {sorted(roll)} != submitted tenants "
            f"{sorted(observed)}",
        )
        for t, obs in sorted(observed.items()):
            got = roll.get(t) or {}
            check(
                got.get("requests") == obs["requests"]
                and got.get("completed") == obs["completed"]
                and (got.get("shed") or {}) == obs["shed"],
                f"tenant {t} rollup {got} != observed {obs}",
            )
        # Quota fast-fail: the flooding batch tenant shed on its quota;
        # the unthrottled interactive sibling NEVER did (isolation) —
        # and in the default clean storm interactive shed NOTHING.
        batch_obs = observed.get("batch") or {"shed": {}, "completed": 0}
        inter_obs = observed.get("interactive") or {"shed": {}}
        n_quota = batch_obs["shed"].get("shed_tenant_quota", 0)
        check(
            n_quota >= 1,
            f"batch flood never hit its admission quota: {batch_obs}",
        )
        check(
            inter_obs["shed"].get("shed_tenant_quota", 0) == 0,
            f"quota sheds leaked onto the interactive sibling: "
            f"{inter_obs}",
        )
        if not args.inject_fault:
            check(
                inter_obs["shed"] == {},
                f"clean tenants storm shed interactive requests: "
                f"{inter_obs['shed']}",
            )
        check(
            batch_obs["completed"] >= 1,
            "batch tenant completed nothing — quota too tight to "
            "measure the drain",
        )
        # Tenant-tagged quota shed events, one per observed quota shed,
        # all naming the offender, all registry-valid.
        qevents = [
            e for e in events if e.get("event") == "tenant_quota_shed"
        ]
        check(
            len(qevents)
            == sum(
                st["shed"].get("shed_tenant_quota", 0)
                for st in observed.values()
            ),
            f"{len(qevents)} tenant_quota_shed events != observed quota "
            f"sheds",
        )
        check(
            all(e.get("tenant") == "batch" for e in qevents),
            f"a quota shed event named the wrong tenant: "
            f"{sorted({e.get('tenant') for e in qevents})}",
        )
        for rec in qevents:
            check(
                ev_registry.validate_record(rec) == [],
                f"tenant_quota_shed fails registry validation: {rec}",
            )
        # WFQ/priority drain fairness: both tenants queued into ONE
        # bucket in one interleaved burst; the interactive class (3x
        # weight, higher priority tier) must clear no slower than the
        # deprioritized batch flood.
        ip50 = (roll.get("interactive") or {}).get("latency_p50_ms")
        bp50 = (roll.get("batch") or {}).get("latency_p50_ms")
        check(
            ip50 is not None and bp50 is not None and ip50 <= bp50,
            f"priority drain inverted: interactive p50 {ip50}ms > "
            f"batch p50 {bp50}ms",
        )
        print(
            "serve_smoke: tenants "
            + ", ".join(
                f"{t}: {st['completed']}/{st['requests']} ok "
                f"shed={st['shed']} "
                f"p50={roll[t]['latency_p50_ms'] and round(roll[t]['latency_p50_ms'], 1)}ms"
                for t, st in sorted(observed.items())
            )
        )
    if args.prewarm:
        # The prewarmed tier must have compiled NOTHING: hydration is
        # snapshot deserialization (zero compile-cache consultations),
        # and every storm dispatch runs an installed AOT executable
        # (zero jit fallbacks — the only path that can reach XLA).
        check(
            serve_cache["requests"] == 0,
            f"prewarmed hydration consulted the compile cache "
            f"{serve_cache['requests']} times (misses="
            f"{serve_cache['misses']}) — snapshots must not compile",
        )
        serving = (
            [(r.replica_id, r.engine) for r in replicas]
            if replicas is not None
            else [(0, engine)]
        )
        for rid, eng in serving:
            counts = eng.dispatch_counts
            check(
                counts["jit"] == 0,
                f"replica {rid} dispatch provenance {counts}: a "
                "prewarmed storm must run entirely through installed "
                "AOT executables",
            )
        check(
            sum(e.dispatch_counts["aot"] for _, e in serving) > 0,
            "prewarmed storm never exercised an AOT executable",
        )
        if replicas is not None:
            for r in replicas:
                ws = r.warm_stats or {}
                check(
                    ws.get("source") == "snapshot"
                    and ws.get("misses") == 0
                    and not ws.get("skipped"),
                    f"replica {r.replica_id} warm_stats {ws}: expected "
                    "a clean snapshot hydration",
                )
            warms = [
                e for e in events if e.get("event") == "replica_warm"
            ]
            check(
                {e["replica"] for e in warms}
                == {r.replica_id for r in replicas}
                and all(e["source"] == "snapshot" for e in warms),
                f"replica_warm events malformed: {warms}",
            )

    if publisher is not None:
        # Live-metrics-plane assertions (ISSUE 14 acceptance).
        from gnot_tpu.obs import events as events_registry
        from gnot_tpu.obs.metrics import summary_agrees

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import metrics_report

        snaps = [e for e in events if e.get("event") == "metrics_snapshot"]
        alerts = [e for e in events if e.get("event") == "slo_alert"]
        check(
            len(snaps) >= 2,
            f"metrics plane published {len(snaps)} snapshots; need the "
            "mid-storm tick plus the final post-drain one at minimum",
        )
        for rec in snaps + alerts:
            check(
                events_registry.validate_record(rec) == [],
                f"metrics record fails registry validation: {rec}",
            )
        check(
            mid_snap is not None
            and mid_snap["pool"]["completed"] > 0
            and (mid_snap["pool"]["p99_ms"] or 0) > 0,
            f"mid-storm snapshot must report a nonzero live pool p99 "
            f"BEFORE drain: {mid_snap and mid_snap['pool']}",
        )
        agree_problems = summary_agrees(summary, final_snap)
        check(
            not agree_problems,
            f"final snapshot disagrees with serve_summary: "
            f"{agree_problems}",
        )
        _, alert_problems = metrics_report.breach_intervals(events)
        check(
            not alert_problems,
            f"slo_alert stream is not edge-disciplined: {alert_problems}",
        )
        if "slow_request" in args.inject_fault and args.deadline_ms:
            # The injected straggler's deadline sheds breach the shed
            # SLO exactly once — one fire edge mid-storm, one clear
            # edge after the quiet post-drain window, never spam — IF
            # the breach was real at slow-window scale (the storm's
            # overall shed fraction exceeded the objective). A blip
            # the slow window correctly suppressed must stay silent:
            # that suppression is the design, not a miss.
            frac = sum(summary["shed"].values()) / max(
                1, summary["requests"]
            )
            states = [
                a["state"] for a in alerts
                if a["objective"] == "shed_fraction"
            ]
            want = (
                ["fire", "clear"] if frac > args.slo_shed_frac else []
            )
            check(
                states == want,
                f"shed SLO edges {states} != {want} (storm shed "
                f"fraction {frac:.4f} vs objective "
                f"{args.slo_shed_frac})",
            )
        stem = os.path.splitext(metrics_path)[0]
        rows = metrics_report.load_rows(f"{stem}.series.jsonl")
        check(
            len(rows) == publisher.seq and rows[-1]["seq"] == publisher.seq,
            f"series file rows ({len(rows)}) != published snapshots "
            f"({publisher.seq})",
        )
        check(
            os.path.exists(f"{stem}.prom")
            and "serve_request_latency_ms_count" in open(f"{stem}.prom").read(),
            "Prometheus exposition file missing or incomplete",
        )
        print(
            f"serve_smoke: metrics plane {publisher.seq} snapshots, "
            f"{len(alerts)} alert edges, mid-storm p99="
            f"{round(mid_snap['pool']['p99_ms'], 1)}ms"
        )

    if tracer is not None:
        # Trace-file assertions (ISSUE 5 acceptance): every completed
        # request's trace carries the full lifecycle chain under ONE
        # trace_id, and trace_report derives a per-bucket queue/device
        # breakdown from the file.
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_report

        from gnot_tpu.obs import tracing

        spans = trace_report.load_spans(args.trace_path)
        by_trace: dict = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], set()).add(s["name"])
        chain = set(tracing.SERVE_SPANS)
        complete = [t for t, names in by_trace.items() if chain <= names]
        # Head sampling is deterministic (floor-counter rule) and every
        # submit calls start_trace exactly once, so the sampled-trace
        # count is exact; at rate 1.0 every completed request must also
        # have a whole chain (a sampled shed chain legitimately stops
        # early, so below 1.0 only the bound holds).
        sampled = math.floor(args.n * args.trace_sample_rate)
        check(
            len(by_trace) == sampled,
            f"{len(by_trace)} sampled traces != floor(n*rate) = {sampled}",
        )
        if args.trace_sample_rate >= 1.0:
            # Trace ids assign in submit order at rate 1.0, so result i
            # is trace t(i+1): every COMPLETED request must have a
            # whole chain. Requests failed by an injected nan_output
            # also carry whole chains (they reached resolve), so the
            # ok set is a subset, not an equality.
            ok_traces = {
                f"t{i + 1:06d}" for i, r in enumerate(results) if r.ok
            }
            check(
                ok_traces <= set(complete),
                f"completed requests missing whole chains: "
                f"{sorted(ok_traces - set(complete))}",
            )
        else:
            check(
                len(complete) <= sampled,
                f"{len(complete)} complete chains exceed {sampled} "
                "sampled traces",
            )
        # Every bucket has queue-wait numbers; buckets that only ever
        # shed (no dispatch reached the device) legitimately carry no
        # device time, so require device numbers on at least one — and
        # only when some sampled request actually completed (at low
        # rates the lone sampled trace can be the injected straggler's
        # shed request, which never reaches the device). At rates low
        # enough that floor(n*rate) == 0 an empty breakdown is the
        # configured behavior — nothing to check.
        bb = trace_report.bucket_breakdown(spans)
        if sampled:
            check(
                bool(bb)
                and all(v["queue_p50_ms"] is not None for v in bb.values())
                and (
                    not complete
                    or any(
                        v["device_p50_ms"] is not None for v in bb.values()
                    )
                ),
                f"trace_report bucket breakdown empty/malformed: {bb}",
            )
        check(
            summary.get("queue_device_by_bucket") is not None,
            "serve_summary missing queue_device_by_bucket with tracing on",
        )
        print(
            f"serve_smoke: trace {args.trace_path}: {len(spans)} spans, "
            f"{len(complete)} complete chains, buckets={sorted(bb)}"
        )

    p50, p99 = summary["latency_p50_ms"], summary["latency_p99_ms"]
    if args.rollout and summary.get("sessions"):
        print(f"serve_smoke: sessions rollup {summary['sessions']}")
    print(
        f"serve_smoke: {n_ok}/{args.n} ok, shed={summary['shed']}, "
        f"p50={p50 if p50 is None else round(p50, 1)}ms "
        f"p99={p99 if p99 is None else round(p99, 1)}ms, "
        f"buckets={sorted(buckets)}, compiled={summary['compiled_shapes']}, "
        f"{len(packed_d)} packed / {len(padded_d)} padded dispatches, "
        f"{summary['requests_per_s']:.1f} req/s"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary["failures"] = failures
    return summary


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
