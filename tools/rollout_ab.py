"""Chaos A/B: fault-tolerant rollout serving vs a no-migration twin.

The ISSUE 13 acceptance artifact: a storm of concurrent K-step
autoregressive rollout sessions (serve/rollout.py) over a 2-replica
pool, with replica 0 KILLED mid-storm (``replica_kill@N`` — its worker
dies, every in-system request fails ``error_replica_dead``). Two arms,
identical traffic, identical fault:

* ``migration`` — ``session_migration=True`` (the default): the router
  re-places every orphaned session on the surviving replica from its
  last host-side snapshot and replays forward. Bar: **0 lost
  sessions**, and every served rollout matches the offline engine-only
  K-step loop (``offline_rollout``) to <= 1e-5 per step — at-least-once
  replay is EXACT, not approximately recovered.
* ``no_migration`` — the twin with migration disabled: sessions
  resident on the killed replica resolve with the failure. Bar:
  **measured losses > 0** (the kill genuinely orphaned sessions — the
  migration arm's zero is an achievement, not a vacuous storm).

Writes JSONL to ``--out`` (committed as
``docs/artifacts/rollout_ab.jsonl``; schema pinned by
``tests/test_artifacts.py::test_rollout_ab_artifact_schema``).

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/rollout_ab.py --out docs/artifacts/rollout_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BAR_NUMERIC = 1e-5


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", type=str, required=True, help="JSONL output")
    p.add_argument("--sessions", type=int, default=12)
    p.add_argument("--steps", type=int, default=6)
    p.add_argument(
        "--kill_at_step", type=int, default=8,
        help="replica 0 dies before dispatching its Nth rollout step "
             "(1-indexed per-server step admission ordinal) — mid-storm"
    )
    p.add_argument(
        "--snapshot_every", type=int, default=2,
        help="session snapshot cadence > 1, so migration exercises a "
             "REAL replay (steps past the snapshot re-execute)"
    )
    p.add_argument("--max_batch", type=int, default=2)
    p.add_argument(
        "--quick", action="store_true",
        help="smaller storm for the in-process test-suite smoke"
    )
    args = p.parse_args(argv)
    if args.quick:
        args.sessions, args.steps, args.kill_at_step = 6, 4, 4

    import jax

    import serve_smoke

    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.serve import (
        ReplicaRouter,
        build_replicas,
        offline_rollout,
        rollout,
    )
    from gnot_tpu.utils.metrics import MetricsSink

    engine = serve_smoke.build_engine(max_batch=args.max_batch)
    traffic = serve_smoke.mixed_traffic(
        args.sessions, seed=7, mesh_lo=100, mesh_hi=300
    )
    engine.warmup(traffic, rows=args.max_batch)
    records: list[dict] = []
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        if not ok:
            failures.append(msg)

    # The offline engine-only reference trajectories (no serve stack).
    reference = [
        offline_rollout(engine, s, args.steps, rows=args.max_batch)
        for s in traffic
    ]

    arm_stats: dict[str, dict] = {}
    arm_results: dict[str, list] = {}
    for arm, migrate in (("migration", True), ("no_migration", False)):
        replicas = build_replicas(
            engine.model,
            engine.params,
            2,
            batch_size=args.max_batch,
            devices=jax.devices()[:2],
        )
        for r in replicas:
            r.warm(traffic, rows=args.max_batch)
        sink_path = f"{args.out}.{arm}.events.jsonl"
        with MetricsSink(sink_path) as sink:
            router = ReplicaRouter(
                replicas,
                sink=sink,
                max_batch=args.max_batch,
                max_wait_ms=2.0,
                session_snapshot_every=args.snapshot_every,
                session_migration=migrate,
                faults={
                    0: FaultInjector.from_spec(
                        f"replica_kill@{args.kill_at_step}"
                    )
                },
            ).start()
            futures = [
                router.submit_rollout(s, args.steps) for s in traffic
            ]
            results = [f.result(timeout=120) for f in futures]
            summary = router.drain()
        sess = summary.get("sessions") or {}
        lost = [r for r in results if not r.ok]
        check(
            len(results) == args.sessions,
            f"{arm}: {len(results)} futures resolved != {args.sessions}",
        )
        check(
            sess.get("lost", 0) == len(lost),
            f"{arm}: rollup lost={sess.get('lost')} != observed "
            f"{len(lost)}",
        )
        events = [json.loads(l) for l in open(sink_path) if l.strip()]
        kills = [
            e for e in events
            if e.get("event") == "replica_health"
            and e.get("reason") == "dead"
        ]
        check(
            bool(kills),
            f"{arm}: replica 0 never read dead — the kill didn't land",
        )
        arm_stats[arm] = {
            "arm": arm,
            "sessions": args.sessions,
            "steps": args.steps,
            "snapshot_every": args.snapshot_every,
            "killed_replica": 0,
            "kill_at_step": args.kill_at_step,
            "completed": sess.get("completed", 0),
            "lost": len(lost),
            "lost_reasons": sorted({r.reason for r in lost}),
            "migrated": sess.get("migrated", 0),
            "drained": sess.get("drained", 0),
            "shed": sess.get("shed", 0),
            "steps_committed": sum(r.steps_completed for r in results),
            "step_latency_p50_ms": sess.get("step_latency_p50_ms"),
            "step_latency_p99_ms": sess.get("step_latency_p99_ms"),
        }
        records.append(arm_stats[arm])
        arm_results[arm] = results
        os.remove(sink_path)

    # The bars: zero lost with migration, measured losses without.
    mig, nomig = arm_stats["migration"], arm_stats["no_migration"]
    check(
        mig["lost"] == 0,
        f"migration arm lost {mig['lost']} sessions (must be 0)",
    )
    check(
        mig["completed"] == args.sessions,
        f"migration arm completed {mig['completed']}/{args.sessions}",
    )
    check(mig["migrated"] >= 1, "migration arm never migrated a session")
    check(
        nomig["lost"] >= 1,
        "no-migration twin lost nothing — the kill was vacuous",
    )

    # Parity: every served rollout (migrated sessions included) matches
    # the offline engine-only loop per step, at the original tolerance.
    worst = 0.0
    for r, ref in zip(arm_results["migration"], reference):
        if not r.ok:
            continue
        worst = max(
            worst, rollout.parity_check(r.outputs, ref, atol=BAR_NUMERIC)
        )
    check(
        worst <= BAR_NUMERIC,
        f"served rollouts drifted {worst} from the offline loop "
        f"(bar {BAR_NUMERIC})",
    )
    records.append(
        {
            "probe": "parity",
            "sessions_checked": sum(
                r.ok for r in arm_results["migration"]
            ),
            "steps": args.steps,
            "max_abs_diff": worst,
            "bar": BAR_NUMERIC,
        }
    )

    summary_rec = {
        "summary": "rollout_ab",
        "quick": args.quick,
        "sessions": args.sessions,
        "steps": args.steps,
        "snapshot_every": args.snapshot_every,
        "kill_at_step": args.kill_at_step,
        "lost_migration": mig["lost"],
        "lost_no_migration": nomig["lost"],
        "migrated": mig["migrated"],
        "max_abs_diff": worst,
        "bar_numeric": BAR_NUMERIC,
        "bar_lost_migration": 0,
    }
    records.append(summary_rec)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(
        f"rollout_ab: migration lost={mig['lost']} "
        f"(migrated={mig['migrated']}) vs no_migration "
        f"lost={nomig['lost']}; parity max |diff| = {worst:.2e} "
        f"(bar {BAR_NUMERIC}); wrote {args.out}"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary_rec = dict(summary_rec)
    summary_rec["failures"] = failures
    return summary_rec


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
