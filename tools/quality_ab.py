"""Full-scale quality A/B: torch reference vs jax, all BASELINE configs.

Runs the reference-default GNOT architecture (4 layers / 256 wide /
3 experts / 8 heads — /root/reference/main.py:16-22) on any of the five
BASELINE.json benchmark configs at the reference training regime
(AdamW 1e-3, per-epoch OneCycle with the reference's stepping bug,
batch 4) from the SAME initial weights (torch.manual_seed(0) ->
state_dict_to_flax) and the SAME per-epoch batch composition, and
writes one JSONL line per epoch: {"backend", "epoch", "train_loss",
"test_metric"}.

Padding: every batch is padded to ONE dataset-wide fixed shape
(``fixed_pad_lengths`` over train+test, bucketed).  Both backends see
the identical padded arrays, so the parity variant compares
implementations — not padding policies — head to head, and the jax
side keeps its one-dispatch-per-epoch stacked path even on the ragged
configs (elasticity / inductor2d / heatsink3d).  On the uniform
Darcy 64x64 grid the fixed pad equals the sample length, so the
original darcy artifact regime is unchanged.

One backend per invocation so the slow torch-CPU side can run in the
background while jax variants run on the TPU:

  python tools/quality_ab.py --backend torch --config ns2d --out ab.jsonl
  python tools/quality_ab.py --backend jax --config ns2d --variant parity_f32 --out ab.jsonl
  python tools/quality_ab.py --backend jax --config ns2d --variant masked_tanh_bf16 --out ab.jsonl

Committed artifacts live at docs/artifacts/quality_ab_<config>.jsonl
(darcy64 keeps its round-4 name); the summary table is in
docs/performance.md. tests/test_quality_gate.py pins each artifact's
final-epoch gap; ::test_full_scale_quality_ab_rerun re-runs darcy64
end to end when RUN_SLOW_AB=1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VARIANTS = {
    # attention_mode, gelu, dtype
    "parity_f32": ("parity", "erf", "float32"),
    "masked_erf_f32": ("masked", "erf", "float32"),
    "masked_tanh_f32": ("masked", "tanh", "float32"),
    "masked_tanh_bf16": ("masked", "tanh", "bfloat16"),
}


def build_setup(args):
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader, collate, fixed_pad_lengths
    from gnot_tpu.train.schedule import make_lr_fn

    gen = datasets.SYNTHETIC[args.config]
    # --size maps to each generator's own size kwarg (grid_n /
    # n_points / base_points — datasets._SIZE_KWARG); --grid_n is the
    # darcy-specific spelling kept for the committed darcy64 artifact.
    size_kw = {"grid_n": args.grid_n} if args.config == "darcy2d" else {}
    if args.size:
        size_kw = {datasets._SIZE_KWARG[args.config]: args.size}
    train = gen(args.n_train, seed=11, **size_kw)
    test = gen(args.n_test, seed=12, **size_kw)
    dims = datasets.infer_model_dims(train)
    # One dataset-wide static shape: identical pads for both backends
    # (head-to-head parity under the same pollution) and a single XLA
    # program for the stacked jax path, ragged configs included.
    pad_n, pad_f = fixed_pad_lengths(list(train) + list(test), bucket=True)

    rng = np.random.default_rng(7)
    epoch_batches = []
    for _ in range(args.epochs):
        order = rng.permutation(len(train))
        epoch_batches.append(
            [
                collate(
                    [train[i] for i in order[s : s + args.batch]],
                    pad_nodes=pad_n,
                    pad_funcs=pad_f,
                )
                for s in range(0, len(train), args.batch)
            ]
        )
    test_batches = list(
        Loader(test, args.batch, prefetch=0, pad_nodes=pad_n, pad_funcs=pad_f)
    )
    optim = OptimConfig()
    lr_fn = make_lr_fn(
        optim, steps_per_epoch=len(epoch_batches[0]), epochs=args.epochs
    )
    return dims, epoch_batches, test_batches, optim, lr_fn


def log_line(out, **kw):
    with open(out, "a") as f:
        f.write(json.dumps(kw) + "\n")
    print(json.dumps(kw), flush=True)


def run_torch(args):
    import torch

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.interop.torch_oracle import build_reference_model, torch_rel_l2

    torch.set_num_threads(os.cpu_count() or 1)
    dims, epoch_batches, test_batches, optim, lr_fn = build_setup(args)
    mc = ModelConfig(**dims, attention_mode="parity")

    def tt(b):
        return (
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        )

    torch.manual_seed(0)
    model = build_reference_model(mc)
    opt = torch.optim.AdamW(model.parameters(), lr=optim.lr)
    for epoch in range(args.epochs):
        lr = lr_fn(0, epoch)
        for g in opt.param_groups:
            g["lr"] = lr
        losses = []
        for b in epoch_batches[epoch]:
            loss = torch_rel_l2(
                model(*tt(b)), torch.from_numpy(b.y), torch.from_numpy(b.node_mask)
            )
            opt.zero_grad()
            loss.backward()
            opt.step()
            losses.append(float(loss))
        with torch.no_grad():
            metric = float(
                np.mean(
                    [
                        float(
                            torch_rel_l2(
                                model(*tt(b)),
                                torch.from_numpy(b.y),
                                torch.from_numpy(b.node_mask),
                            )
                        )
                        for b in test_batches
                    ]
                )
            )
        log_line(
            args.out,
            backend="torch",
            variant="parity_f32",
            epoch=epoch,
            train_loss=float(np.mean(losses)),
            test_metric=metric,
        )


def run_jax(args):
    import jax
    import jax.numpy as jnp
    import torch

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import (
        TrainState,
        make_eval_step,
        make_multi_eval_step,
        make_multi_train_step,
        make_optimizer,
        stack_batches,
    )

    mode, gelu, dtype = VARIANTS[args.variant]
    dims, epoch_batches, test_batches, optim, lr_fn = build_setup(args)
    mc = ModelConfig(**dims, attention_mode=mode, gelu=gelu, dtype=dtype)

    # Same init as the torch run: the reference model's own initializer.
    torch.manual_seed(0)
    init_mc = ModelConfig(**dims, attention_mode="parity")
    params = jax.tree.map(
        jnp.asarray,
        state_dict_to_flax(build_reference_model(init_mc).state_dict(), init_mc),
    )
    model = GNOT(mc)
    tx = make_optimizer(optim, optim.lr)
    state = TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )
    # One dispatch per epoch (all batches share a shape on the regular
    # grid) — the tunnel-latency lever; numerically identical to
    # per-step dispatch (tests pin it).
    multi_step = make_multi_train_step(model, optim, "rel_l2")
    multi_eval = make_multi_eval_step(model, "rel_l2")
    stacked_test = stack_batches(test_batches)

    for epoch in range(args.epochs):
        lrs = jnp.full((len(epoch_batches[epoch]),), lr_fn(0, epoch), jnp.float32)
        state, losses = multi_step(state, stack_batches(epoch_batches[epoch]), lrs)
        metric = float(np.mean(np.asarray(multi_eval(state.params, stacked_test))))
        log_line(
            args.out,
            backend="jax",
            variant=args.variant,
            epoch=epoch,
            train_loss=float(np.mean(np.asarray(losses))),
            test_metric=metric,
        )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--backend", choices=["torch", "jax"], required=True)
    p.add_argument("--variant", choices=sorted(VARIANTS), default="parity_f32")
    p.add_argument(
        "--config",
        choices=["darcy2d", "ns2d", "elasticity", "inductor2d", "heatsink3d"],
        default="darcy2d",
    )
    p.add_argument("--grid_n", type=int, default=64, help="darcy2d grid edge")
    p.add_argument(
        "--size", type=int, default=None,
        help="generator size knob for any config (datasets._SIZE_KWARG); "
        "overrides --grid_n",
    )
    p.add_argument("--n_train", type=int, default=32)
    p.add_argument("--n_test", type=int, default=16)
    p.add_argument("--epochs", type=int, default=24)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--out", type=str, required=True)
    args = p.parse_args()
    if args.backend == "torch":
        run_torch(args)
    else:
        run_jax(args)


if __name__ == "__main__":
    main()
