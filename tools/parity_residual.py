"""On-chip parity residual vs the torch CPU oracle, with an error budget.

Parity mode now pins jax.default_matmul_precision('highest')
(models/gnot.py), so the full-f32 forward on TPU should agree with the
torch CPU reference to the same order as CPU-vs-CPU. This script
measures the end-to-end residual on the default platform and
decomposes the remaining floor per op class:

* matmul: chip f32 dot (highest precision) vs numpy f64-rounded-f32;
* erf-GELU: chip jax.nn.gelu(approximate=False) vs torch nn.GELU;
* feature softmax: chip f32 softmax vs torch F.softmax.

Usage: python tools/parity_residual.py [--grid_n 16] [--small_arch]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--grid_n", type=int, default=16)
    p.add_argument("--small_arch", action="store_true",
                   help="2 layers / 64 wide (the round-3 probe config) "
                        "instead of the reference default")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import torch

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.interop.torch_oracle import build_reference_model, state_dict_to_flax
    from gnot_tpu.models.gnot import GNOT

    dev = jax.devices()[0]
    print(f"platform: {dev.platform} ({getattr(dev, 'device_kind', '?')})")

    samples = datasets.synth_darcy2d(2, seed=9, grid_n=args.grid_n)
    b = collate(samples, bucket=False)
    arch = (
        dict(n_attn_layers=2, n_attn_hidden_dim=64, n_mlp_num_layers=2,
             n_mlp_hidden_dim=64, n_input_hidden_dim=64, n_expert=2, n_head=4)
        if args.small_arch
        else {}
    )
    mc = ModelConfig(
        **datasets.infer_model_dims(samples), **arch, attention_mode="parity"
    )

    torch.manual_seed(4)
    ref = build_reference_model(mc)
    ref.eval()
    with torch.no_grad():
        want = ref(
            torch.from_numpy(b.coords),
            torch.from_numpy(b.theta),
            [torch.from_numpy(f) for f in b.funcs],
        ).numpy()

    params = state_dict_to_flax(ref.state_dict(), mc)
    got = np.asarray(
        jax.jit(
            lambda p, c, t, f: GNOT(mc).apply({"params": p}, c, t, f)
        )(params, b.coords, b.theta, b.funcs)
    )
    resid = float(np.max(np.abs(got - want)))
    scale = float(np.max(np.abs(want)))
    print(f"full-model forward residual (parity mode, auto-highest): "
          f"{resid:.3e} abs  ({resid / scale:.3e} of max |out|)")

    # ---- error budget -----------------------------------------------------
    rng = np.random.default_rng(0)
    # matmul at the model's hot shape
    m, k, n = 4096 if not args.small_arch else 512, 256, 256
    A = rng.normal(size=(m, k)).astype(np.float32)
    B = rng.normal(size=(k, n)).astype(np.float32)
    exact = (A.astype(np.float64) @ B.astype(np.float64)).astype(np.float32)
    for prec in ("default", "highest"):
        with jax.default_matmul_precision(prec):
            chip = np.asarray(jax.jit(jnp.dot)(A, B))  # graftlint: disable=GL003 — two-precision diagnostic: compiles exactly twice by design
        print(f"matmul [{m}x{k}x{n}] f32 {prec}: max|err| = "
              f"{np.max(np.abs(chip - exact)):.3e} "
              f"(rel {np.max(np.abs(chip - exact)) / np.max(np.abs(exact)):.3e})")

    x = rng.normal(size=(1 << 16,)).astype(np.float32) * 3
    t_gelu = torch.nn.GELU()(torch.from_numpy(x)).numpy()
    j_gelu = np.asarray(jax.jit(lambda v: jax.nn.gelu(v, approximate=False))(x))
    print(f"erf-GELU: chip vs torch max|err| = {np.max(np.abs(j_gelu - t_gelu)):.3e}")

    xs = rng.normal(size=(1024, 32)).astype(np.float32)
    t_sm = torch.nn.functional.softmax(torch.from_numpy(xs), dim=-1).numpy()
    j_sm = np.asarray(jax.jit(lambda v: jax.nn.softmax(v, axis=-1))(xs))
    print(f"feature softmax (D=32): chip vs torch max|err| = "
          f"{np.max(np.abs(j_sm - t_sm)):.3e}")


if __name__ == "__main__":
    main()
