"""Deploy-time AOT prewarm CLI: compile the whole serving program
family before any replica serves.

Enumerates every bucket and ``PackPlan`` program the given traffic
shape needs (``serve/aot.py``), ``jit(...).lower().compile()``s each of
them for EVERY replica slice of the target topology into the
persistent compile cache, serializes the executables as warm-replica
snapshots, and writes the deploy manifest — program keys, per-program
compile seconds, snapshot bytes, cache-dir occupancy. A serving
process (or a live scale-out) then hydrates replicas from the manifest
(``ReplicaRouter.prewarm_from`` / ``--serve_prewarm``) and answers its
first request without a single XLA compile.

Usage::

    JAX_PLATFORMS=cpu python tools/aot_prewarm.py \
        --replicas 4 --n 16 --snapshot_dir /tmp/snap \
        --manifest /tmp/snap/manifest.json --metrics_path /tmp/aot.jsonl

With ``--metrics_path`` the run also emits the ``aot_prewarm`` event
and a ``run.json`` manifest whose ``aot_prewarm`` block carries the
compile/cache stats (docs/serving.md "Deploy-time prewarm").
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--replicas", type=int, default=1,
        help="target serving topology: programs are compiled (and "
             "snapshotted) per replica slice — XLA executables are "
             "device-bound, so the manifest must match the topology "
             "the deployment will serve"
    )
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument(
        "--n", type=int, default=16,
        help="representative traffic size (the bucket family is "
             "derived from it — same generator as serve_smoke)"
    )
    p.add_argument("--mesh_lo", type=int, default=300)
    p.add_argument("--mesh_hi", type=int, default=700)
    p.add_argument("--packed", action="store_true",
                   help="also compile the PackPlan program")
    p.add_argument("--pack_chunk", type=int, default=64)
    p.add_argument(
        "--serve_dtype", type=str, default="float32",
        choices=["float32", "bfloat16"],
        help="serving compute dtype the deployment will run at "
             "(models/precision.py): programs, keys and the manifest "
             "are dtype-bound — a bf16 deployment refuses an f32 "
             "manifest wholesale, so prewarm at the dtype you serve"
    )
    p.add_argument("--snapshot_dir", type=str, required=True)
    p.add_argument(
        "--manifest", type=str, default="",
        help="manifest path (default: <snapshot_dir>/manifest.json)"
    )
    p.add_argument("--metrics_path", type=str, default="")
    args = p.parse_args(argv)
    manifest_path = args.manifest or os.path.join(
        args.snapshot_dir, "manifest.json"
    )
    if "jax" not in sys.modules:
        # Standalone CLI on a bare host: virtual CPU devices for the
        # replica slices, same idiom as serve_bench (a no-op when jax
        # is already imported — the in-process test path).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            flags += (
                " --xla_force_host_platform_device_count="
                f"{max(8, args.replicas)}"
            )
        os.environ["XLA_FLAGS"] = flags.strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from gnot_tpu.serve import aot, build_replicas
    from gnot_tpu.utils.cache import enable_compile_cache
    from gnot_tpu.utils.metrics import MetricsSink
    from serve_smoke import build_engine, mixed_traffic

    cache_dir = enable_compile_cache()
    engine = build_engine(max_batch=args.max_batch, dtype=args.serve_dtype)
    traffic = mixed_traffic(
        args.n, mesh_lo=args.mesh_lo, mesh_hi=args.mesh_hi
    )
    pack_plan = None
    if args.packed:
        from gnot_tpu.data.batch import PackPlan

        pack_plan = PackPlan.from_samples(
            traffic, chunk=args.pack_chunk, batch_size=args.max_batch
        )
    if args.replicas > 1:
        replicas = build_replicas(
            engine.model, engine.params, args.replicas,
            batch_size=args.max_batch, dtype=args.serve_dtype,
        )
        engines = [(r.replica_id, r.engine) for r in replicas]
    else:
        engines = [(0, engine)]

    sink = MetricsSink(args.metrics_path) if args.metrics_path else None
    try:
        doc = aot.prewarm_deployment(
            engines,
            traffic,
            rows=args.max_batch,
            pack_plan=pack_plan,
            snapshot_dir=args.snapshot_dir,
            manifest_path=manifest_path,
            sink=sink,
        )
        if sink is not None:
            from gnot_tpu.obs import manifest as manifest_lib

            manifest_lib.write_manifest(
                manifest_lib.manifest_path_for(args.metrics_path),
                argv=list(argv) if argv is not None else sys.argv[1:],
                extra={
                    "kind": "aot_prewarm",
                    "aot_prewarm": {
                        "manifest": manifest_path,
                        "replicas": doc["replicas"],
                        "program_keys": doc["program_keys"],
                        "compile_s": doc["compile_s"],
                        "snapshot_bytes": doc["snapshot_bytes"],
                        "cache": doc["cache"],
                        "cache_dir": doc["cache_dir"],
                    },
                },
            )
    finally:
        if sink is not None:
            sink.close()
    n_prog = len(doc["program_keys"]) * doc["replicas"]
    print(
        f"aot_prewarm: {n_prog} programs "
        f"({len(doc['program_keys'])} keys x {doc['replicas']} replicas) "
        f"compiled in {doc['compile_s']:.2f}s, cache {cache_dir} "
        f"(misses={doc['cache']['misses']}), snapshots "
        f"{doc['snapshot_bytes']} bytes -> {manifest_path}"
    )
    for key in doc["program_keys"]:
        secs = [
            p["compile_s"]
            for b in doc["per_replica"].values()
            for p in b["programs"]
            if p["key"] == key
        ]
        print(f"  {key}: {min(secs):.3f}-{max(secs):.3f}s per replica")
    return doc


def main(argv=None) -> int:
    run(argv)
    return 0


if __name__ == "__main__":
    sys.exit(main())
