"""Packing A/B: pad-waste and throughput, padded vs packed, both hot paths.

The ISSUE 6 acceptance evidence for "pack, don't pad" end-to-end
(docs/performance.md "Pack, don't pad"): on a mixed small-mesh ragged
workload,

* **train** — tokens/s (REAL node tokens per second) with the padded
  ``Loader`` vs the packed ``PackedLoader`` layout, same samples, same
  model, interleaved best-of-N timed windows (the telemetry_ab
  methodology, so ambient load drift hits both arms alike);
* **serve** — requests/s through the REAL ``InferenceServer`` storm
  (tools/serve_smoke.py, submit -> last resolve) with per-bucket padded
  dispatch vs ``--serve_packed`` pack-plan dispatch, same traffic
  generator, same weights (seeded build);
* **numerics** — every request's packed output vs its own solo padded
  dispatch, max |diff| <= 1e-5 (the packed layout is a layout change,
  never a semantics change).

Pad waste is measured, not modeled: real node tokens vs the compiled
programs' token capacity, from the batch masks (train) and the
``serve_summary.pad_waste_by_bucket`` rollup (serve).

Usage::

    JAX_PLATFORMS=cpu python tools/pack_ab.py \
        --out docs/artifacts/pack_ab.jsonl

Emits one JSONL record per arm plus a summary record; committed as
docs/artifacts/pack_ab.jsonl and schema-checked by
tests/test_artifacts.py::test_pack_ab_artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def _token_counts(batch) -> tuple[int, int]:
    """(real, capacity) node tokens of one dispatch's static shape."""
    real = int(np.asarray(batch.node_mask).sum())
    capacity = int(batch.coords.shape[0] * batch.coords.shape[1])
    return real, capacity


def train_ab(config: str, n_points: int, batch_size: int, pack_chunk: int,
             steps: int, repeats: int) -> tuple[dict, dict]:
    """Interleaved best-of-``repeats`` timed windows for the padded and
    packed train steps over the SAME sample set (bench.build guarantees
    the generator and ModelConfig match)."""
    import bench

    dev = jax.devices()[0]
    lr = jnp.asarray(1e-3, jnp.float32)
    arms = {}
    for packed in (False, True):
        step, state, batch, _mc = bench.build(
            "float32", n_points=n_points, batch_size=batch_size,
            config=config, packed=packed, pack_chunk=pack_chunk,
        )
        real, capacity = _token_counts(batch)
        arms[packed] = {
            "step": step, "state": state, "batch": batch,
            "real": real, "capacity": capacity, "best": float("inf"),
        }
    for _ in range(max(1, repeats)):
        for packed in (False, True):  # interleaved: drift hits both arms
            a = arms[packed]
            a["best"] = min(
                a["best"],
                bench.time_steps(
                    a["step"], a["state"], a["batch"], lr, 2, steps, dev,
                ),
            )
    out = []
    for packed in (False, True):
        a = arms[packed]
        out.append({
            "arm": "train_packed" if packed else "train_padded",
            "config": config, "n_points": n_points,
            "batch_size": batch_size,
            "pack_chunk": pack_chunk if packed else None,
            "ms_per_step": round(a["best"] * 1e3, 4),
            "real_tokens": a["real"], "capacity_tokens": a["capacity"],
            "pad_waste_frac": round(1.0 - a["real"] / a["capacity"], 4),
            "tokens_per_s": round(a["real"] / a["best"], 1),
        })
    return out[0], out[1]


def _serve_waste(summary: dict) -> float:
    """Aggregate measured pad waste over every executed dispatch."""
    pw = summary.get("pad_waste_by_bucket") or {}
    real = sum(v["real_tokens"] for v in pw.values())
    cap = sum(v["capacity_tokens"] for v in pw.values())
    return 1.0 - real / cap if cap else 0.0


def serve_ab(n: int, max_batch: int, pack_chunk: int, mesh_lo: int,
             mesh_hi: int, repeats: int) -> tuple[dict, dict]:
    """Best-of-``repeats`` serve_smoke storms per arm, interleaved.
    Every storm must pass ALL the smoke's own assertions (bucket
    discipline, everything resolves) — a fast-but-wrong arm is a
    failure, not a win."""
    import serve_smoke

    base = [
        "--n", str(n), "--max_batch", str(max_batch),
        "--inject_fault", "none", "--deadline_ms", "10000",
        "--mesh_lo", str(mesh_lo), "--mesh_hi", str(mesh_hi),
    ]
    arms = {False: None, True: None}
    for _ in range(max(1, repeats)):
        for packed in (False, True):
            argv = base + (
                ["--packed", "--pack_chunk", str(pack_chunk)] if packed else []
            )
            s = serve_smoke.run(argv)
            if s["failures"]:
                raise RuntimeError(
                    f"serve_smoke arm packed={packed} failed its own "
                    f"assertions: {s['failures']}"
                )
            best = arms[packed]
            if best is None or s["requests_per_s"] > best["requests_per_s"]:
                arms[packed] = s
    out = []
    for packed in (False, True):
        s = arms[packed]
        out.append({
            "arm": "serve_packed" if packed else "serve_unpacked",
            "n_requests": n, "max_batch": max_batch,
            "pack_chunk": pack_chunk if packed else None,
            "mesh_lo": mesh_lo, "mesh_hi": mesh_hi,
            "requests_per_s": round(s["requests_per_s"], 2),
            "dispatches": s["dispatches"],
            "latency_p50_ms": s["latency_p50_ms"],
            "latency_p99_ms": s["latency_p99_ms"],
            "pad_waste_frac": round(_serve_waste(s), 4),
        })
    return out[0], out[1]


def numerics_check(n: int, max_batch: int, pack_chunk: int, mesh_lo: int,
                   mesh_hi: int) -> float:
    """max over requests of max |packed output - solo padded output|:
    the <= 1e-5 per-request acceptance bar, measured through the real
    engine on the same traffic the serve A/B uses."""
    import serve_smoke

    from gnot_tpu.data.batch import PackPlan, pack_prefix

    engine = serve_smoke.build_engine(max_batch=max_batch)
    traffic = serve_smoke.mixed_traffic(n, mesh_lo=mesh_lo, mesh_hi=mesh_hi)
    plan = PackPlan.from_samples(traffic, chunk=pack_chunk,
                                 batch_size=max_batch)
    solo = []
    for s in traffic:
        pn, pf = engine.bucket_key(s)
        solo.append(
            engine.infer([s], pad_nodes=pn, pad_funcs=pf, rows=max_batch)[0]
        )
    packed_outs: list[np.ndarray] = []
    rest = list(traffic)
    while rest:
        placements = pack_prefix([s.coords.shape[0] for s in rest], plan)
        k = max(1, len(placements))
        packed_outs.extend(
            engine.infer_packed(rest[:k], plan, placements=placements[:k])
        )
        rest = rest[k:]
    return float(
        max(
            np.abs(p - s).max()
            for p, s in zip(packed_outs, solo)
        )
    )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", type=str, default="elasticity",
                   help="train-arm workload (a ragged bench config)")
    p.add_argument("--n_points", type=int, default=256,
                   help="train-arm base mesh size (elasticity spreads "
                        "sizes around it — the ragged mix)")
    p.add_argument("--batch_size", type=int, default=16,
                   help="train-arm samples per dispatch")
    p.add_argument("--pack_chunk", type=int, default=64)
    p.add_argument("--steps", type=int, default=8,
                   help="train-arm steps per timed window")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--serve_n", type=int, default=32,
                   help="serve-arm storm size")
    p.add_argument("--serve_max_batch", type=int, default=4)
    p.add_argument("--mesh_lo", type=int, default=40)
    p.add_argument("--mesh_hi", type=int, default=200,
                   help="serve-arm ragged sizes: the mixed SMALL-mesh "
                        "workload packing exists for")
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    platform = jax.devices()[0].platform
    t0 = time.perf_counter()
    tr_pad, tr_pack = train_ab(
        args.config, args.n_points, args.batch_size, args.pack_chunk,
        args.steps, args.repeats,
    )
    sv_pad, sv_pack = serve_ab(
        args.serve_n, args.serve_max_batch, args.pack_chunk,
        args.mesh_lo, args.mesh_hi, args.repeats,
    )
    max_diff = numerics_check(
        args.serve_n, args.serve_max_batch, args.pack_chunk,
        args.mesh_lo, args.mesh_hi,
    )
    records = [tr_pad, tr_pack, sv_pad, sv_pack]
    for r in records:
        r["platform"] = platform
    records.append({
        "summary": "pack_ab",
        "platform": platform,
        "train_tokens_per_s_padded": tr_pad["tokens_per_s"],
        "train_tokens_per_s_packed": tr_pack["tokens_per_s"],
        "train_speedup": round(
            tr_pack["tokens_per_s"] / tr_pad["tokens_per_s"], 3
        ),
        "train_pad_waste_padded": tr_pad["pad_waste_frac"],
        "train_pad_waste_packed": tr_pack["pad_waste_frac"],
        "serve_requests_per_s_unpacked": sv_pad["requests_per_s"],
        "serve_requests_per_s_packed": sv_pack["requests_per_s"],
        "serve_speedup": round(
            sv_pack["requests_per_s"] / sv_pad["requests_per_s"], 3
        ),
        "serve_pad_waste_unpacked": sv_pad["pad_waste_frac"],
        "serve_pad_waste_packed": sv_pack["pad_waste_frac"],
        "max_abs_diff": max_diff,
        "elapsed_s": round(time.perf_counter() - t0, 1),
        "bar": "pad waste down and throughput up on BOTH paths; "
               "max_abs_diff <= 1e-5",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
