"""Render a live-metrics time series (obs/metrics.py) and cross-check
the final snapshot against the drain-time ``serve_summary``.

The metrics plane writes one JSONL row per publish cycle
(``<metrics-stem>.series.jsonl`` under ``--metrics_interval_s``); this
tool is its ``trace_report``-style reader:

* **per-series history** — for every histogram series: windowed p50 /
  p99 / rate (observations per second) over time, computed from the
  cumulative bucket deltas between consecutive rows; for counters: the
  per-interval rate; for gauges: the level.
* **pool size over time** — the ``pool_replicas`` gauge's trajectory
  (transitions + endpoints) and its step-integral in replica-seconds:
  the autoscaler's membership changes and the capacity actually paid
  for, read straight off the time series.
* **SLO breach intervals** — the fire->clear windows reconstructed
  from the ``slo_alert`` edges in the event stream (``--metrics`` JSONL
  from the same run), asserted to alternate (edge discipline: a second
  ``fire`` without an intervening ``clear`` is a bug, not load).
* **final-snapshot cross-check** — the last series row against the
  ``serve_summary`` event, number-for-number: counters exactly,
  percentiles within the documented histogram estimate bound
  (``summary_agrees`` — the same check ``main.py --serve`` runs at
  drain).

Usage::

    python tools/metrics_report.py run/serve.series.jsonl \
        --metrics run/serve.jsonl

Exit code 0 iff the series parses, the alert stream is edge-
disciplined, and (when ``--metrics`` has a serve_summary) the final
snapshot agrees with it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gnot_tpu.obs.metrics import (  # noqa: E402
    LogHistogram,
    summary_agrees,
)


def load_rows(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def series_history(rows: list[dict]) -> dict[str, list[dict]]:
    """Per-series derived history: one entry per row transition with
    windowed stats (histograms: delta p50/p99 + rate; counters: rate;
    gauges: level)."""
    out: dict[str, list[dict]] = {}
    prev: dict | None = None
    for row in rows:
        t = row["t"]
        dt = (t - prev["t"]) if prev is not None else None
        for key, st in row["series"].items():
            hist = out.setdefault(key, [])
            kind = st["type"]
            entry: dict = {"seq": row["seq"], "t": t, "type": kind}
            if kind == "histogram":
                base = (prev or {}).get("series", {}).get(key)
                delta = LogHistogram.delta(st, base)
                entry.update(
                    count=st["count"],
                    window_n=delta.count,
                    p50_ms=delta.percentile(0.50),
                    p99_ms=delta.percentile(0.99),
                    rate=(delta.count / dt) if dt else None,
                )
            elif kind == "counter":
                base = (prev or {}).get("series", {}).get(key)
                d = st["value"] - (base["value"] if base else 0)
                entry.update(
                    value=st["value"], rate=(d / dt) if dt else None
                )
            else:
                entry.update(value=st["value"])
            hist.append(entry)
        prev = row
    return out


def pool_size_series(rows: list[dict]) -> list[dict]:
    """Pool-size-over-time from the ``pool_replicas`` gauge the router
    registers: one ``{"seq", "t", "replicas"}`` entry per snapshot that
    carries the gauge (empty when the run never registered it — a
    single-server tier has no pool gauge)."""
    out: list[dict] = []
    for row in rows:
        for st in row["series"].values():
            if st["type"] == "gauge" and st["name"] == "pool_replicas":
                out.append(
                    {
                        "seq": row["seq"],
                        "t": row["t"],
                        "replicas": int(st["value"]),
                    }
                )
                break
    return out


def replica_seconds(series: list[dict]) -> float:
    """Step-integral of the pool size over the snapshot timeline — the
    capacity actually paid for (the autoscale A/B's efficiency axis,
    as reconstructable from the time series alone)."""
    total = 0.0
    for a, b in zip(series, series[1:]):
        total += (b["t"] - a["t"]) * a["replicas"]
    return total


def breach_intervals(events: list[dict]) -> tuple[list[dict], list[str]]:
    """(fire->clear intervals per objective, edge-discipline problems)
    from the ``slo_alert`` records of a metrics-event JSONL."""
    alerts = [e for e in events if e.get("event") == "slo_alert"]
    open_at: dict[str, dict] = {}
    intervals: list[dict] = []
    problems: list[str] = []
    for a in alerts:
        name = a["objective"]
        if a["state"] == "fire":
            if name in open_at:
                problems.append(
                    f"objective {name!r}: second fire without a clear"
                )
            open_at[name] = a
        elif a["state"] == "clear":
            start = open_at.pop(name, None)
            if start is None:
                problems.append(
                    f"objective {name!r}: clear without a prior fire"
                )
                continue
            intervals.append(
                {
                    "objective": name,
                    "kind": a["kind"],
                    "fired_ts": start.get("ts"),
                    "cleared_ts": a.get("ts"),
                    "peak_burn_fast": start["burn_fast"],
                }
            )
        else:
            problems.append(f"unknown slo_alert state {a['state']!r}")
    for name, a in open_at.items():
        intervals.append(
            {
                "objective": name,
                "kind": a["kind"],
                "fired_ts": a.get("ts"),
                "cleared_ts": None,  # still burning at end of stream
                "peak_burn_fast": a["burn_fast"],
            }
        )
    return intervals, problems


def tenant_breakdown(events: list[dict]) -> dict[str, dict]:
    """Per-tenant isolation rollup from a metrics-event JSONL
    (docs/serving.md "Multi-tenant isolation"): the final
    ``serve_summary``'s ``tenants`` block (requests / completed / shed
    / latency percentiles) joined with the ``tenant_quota_shed``
    admission events (``quota_shed_events``) and any tenant-scoped
    ``slo_alert`` edges (``slo_edges``). Empty when the run never
    carried a tenant tag — the single-tenant path emits none of
    these."""
    summaries = [
        e
        for e in events
        if e.get("event") == "serve_summary" and e.get("tenants")
    ]
    # Prefer the pool-level rollup when a router emitted both tiers.
    pool = [e for e in summaries if "per_replica" in e or "routing" in e]
    roll = ((pool or summaries)[-1]["tenants"] if summaries else {}) or {}
    out = {t: dict(st) for t, st in roll.items()}
    for e in events:
        if e.get("event") == "tenant_quota_shed":
            st = out.setdefault(e["tenant"], {})
            st["quota_shed_events"] = st.get("quota_shed_events", 0) + 1
        elif e.get("event") == "slo_alert" and e.get("tenant"):
            st = out.setdefault(e["tenant"], {})
            st.setdefault("slo_edges", []).append(
                (e["objective"], e["state"])
            )
    return out


def host_breakdown(rows: list[dict]) -> dict[str, dict]:
    """Per-host slice of a federated series (docs/distributed.md): the
    ``ClusterRouter`` publishes every host's registry snapshot under
    ``host<i>/<series_key>`` merged keys; this splits the LAST row back
    into per-host rollups — series count, counter totals by name, gauge
    levels, histogram observation totals. Empty for a single-host
    series (no prefixed keys)."""
    out: dict[str, dict] = {}
    if not rows:
        return out
    for key, st in rows[-1]["series"].items():
        host, sep, _rest = key.partition("/")
        if not sep or not host.startswith("host"):
            continue
        h = out.setdefault(
            host,
            {"series": 0, "counters": {}, "gauges": {}, "observations": 0},
        )
        h["series"] += 1
        name = st.get("name", key)
        if st["type"] == "counter":
            h["counters"][name] = h["counters"].get(name, 0) + st["value"]
        elif st["type"] == "gauge":
            h["gauges"][name] = st["value"]
        elif st["type"] == "histogram":
            h["observations"] += st.get("count", 0)
    return out


def cluster_crosscheck(events: list[dict]) -> tuple[dict | None, list[str]]:
    """(final ``cluster_summary`` or None, problems): the controller's
    drain-time ledger checked against the raw federation event stream
    it claims to roll up — one ``host_dead`` event per counted death,
    one ``session_remigrate`` per counted re-migration, heartbeats
    observed from every member host, and internal coherence (every
    one-shot resolved, lost bounded by sessions)."""
    summaries = [e for e in events if e.get("event") == "cluster_summary"]
    if not summaries:
        return None, []
    s = summaries[-1]
    problems: list[str] = []
    deaths = [e for e in events if e.get("event") == "host_dead"]
    remigs = [e for e in events if e.get("event") == "session_remigrate"]
    hb_hosts = {
        e["host"] for e in events if e.get("event") == "host_heartbeat"
    }
    if s["hosts_dead"] != len(deaths):
        problems.append(
            f"cluster_summary hosts_dead={s['hosts_dead']} != "
            f"{len(deaths)} host_dead events"
        )
    if s["remigrated"] != len(remigs):
        problems.append(
            f"cluster_summary remigrated={s['remigrated']} != "
            f"{len(remigs)} session_remigrate events"
        )
    if hb_hosts and s["hosts"] != len(hb_hosts):
        problems.append(
            f"cluster_summary hosts={s['hosts']} != heartbeats observed "
            f"from {sorted(hb_hosts)}"
        )
    if s["completed"] + s["shed"] != s["requests"]:
        problems.append(
            f"one-shot ledger incoherent: completed {s['completed']} + "
            f"shed {s['shed']} != requests {s['requests']}"
        )
    if s["lost"] > s["sessions"]:
        problems.append(
            f"lost {s['lost']} exceeds sessions {s['sessions']}"
        )
    return s, problems


def run(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("series", help="the <stem>.series.jsonl time series")
    p.add_argument(
        "--metrics", default="",
        help="the run's metrics-event JSONL (for slo_alert intervals "
             "and the serve_summary cross-check)",
    )
    p.add_argument(
        "--tail", type=int, default=5,
        help="history rows to print per series",
    )
    args = p.parse_args(argv)

    rows = load_rows(args.series)
    if not rows:
        print(f"FAIL: {args.series} is empty")
        return 1
    failures: list[str] = []
    seqs = [r["seq"] for r in rows]
    if seqs != sorted(set(seqs)):
        failures.append(f"snapshot seq not strictly increasing: {seqs}")

    hist = series_history(rows)
    print(f"{args.series}: {len(rows)} snapshots, {len(hist)} series\n")
    for key in sorted(hist):
        entries = hist[key][-args.tail:]
        kind = entries[-1]["type"]
        print(f"  {key} [{kind}]")
        for e in entries:
            if kind == "histogram":
                p50 = e["p50_ms"]
                p99 = e["p99_ms"]
                rate = e["rate"]
                print(
                    f"    seq {e['seq']:>4}  n={e['window_n']:>6}  "
                    f"p50={p50 if p50 is None else round(p50, 2)}ms  "
                    f"p99={p99 if p99 is None else round(p99, 2)}ms  "
                    f"rate={rate if rate is None else round(rate, 2)}/s"
                )
            elif kind == "counter":
                rate = e["rate"]
                print(
                    f"    seq {e['seq']:>4}  total={e['value']:>8}  "
                    f"rate={rate if rate is None else round(rate, 2)}/s"
                )
            else:
                print(f"    seq {e['seq']:>4}  value={e['value']}")

    per_host = host_breakdown(rows)
    if per_host:
        print(f"\nPer-host breakdown ({len(per_host)} hosts):")
        for host, st in sorted(per_host.items()):
            counters = ", ".join(
                f"{k}={v}" for k, v in sorted(st["counters"].items())
            )
            print(
                f"  {host}: {st['series']} series, "
                f"{st['observations']} observations"
                + (f", {counters}" if counters else "")
            )

    pool = pool_size_series(rows)
    if pool:
        sizes = [p["replicas"] for p in pool]
        print(
            f"\nPool size over time ({len(pool)} snapshots, "
            f"min={min(sizes)} max={max(sizes)}, "
            f"{replica_seconds(pool):.1f} replica-seconds):"
        )
        # Print the transitions (and the endpoints), not every row —
        # a long steady stretch is one line, not a page.
        last = None
        for i, p in enumerate(pool):
            if p["replicas"] != last or i in (0, len(pool) - 1):
                print(
                    f"  seq {p['seq']:>4}  t={p['t']:.3f}  "
                    f"replicas={p['replicas']}"
                )
                last = p["replicas"]

    if args.metrics:
        events = load_rows(args.metrics)
        intervals, problems = breach_intervals(events)
        failures.extend(problems)
        print(f"\nSLO breach intervals ({len(intervals)}):")
        for iv in intervals:
            end = (
                "open"
                if iv["cleared_ts"] is None
                else f"cleared @{iv['cleared_ts']:.3f}"
            )
            print(
                f"  {iv['objective']} [{iv['kind']}] fired "
                f"@{iv['fired_ts']:.3f} -> {end} "
                f"(burn_fast {iv['peak_burn_fast']})"
            )
        tb = tenant_breakdown(events)
        if tb:
            print(f"\nPer-tenant breakdown ({len(tb)} tenants):")
            for t, st in sorted(tb.items()):
                shed = st.get("shed") or {}
                p50, p99 = st.get("latency_p50_ms"), st.get("latency_p99_ms")
                print(
                    f"  {t}: requests={st.get('requests', 0)} "
                    f"completed={st.get('completed', 0)} "
                    f"shed={dict(sorted(shed.items()))} "
                    f"p50={p50 if p50 is None else round(p50, 2)}ms "
                    f"p99={p99 if p99 is None else round(p99, 2)}ms "
                    f"quota_shed_events={st.get('quota_shed_events', 0)}"
                )
                for obj, state in st.get("slo_edges", []):
                    print(f"    slo_alert {obj}: {state}")
                # Admission coherence: the fast-fail event stream and
                # the summary's shed counter are two views of the same
                # decisions — they must agree per tenant.
                n_ev = st.get("quota_shed_events", 0)
                n_sum = shed.get("shed_tenant_quota", 0)
                if "requests" in st and n_ev != n_sum:
                    failures.append(
                        f"tenant {t}: {n_ev} tenant_quota_shed events "
                        f"!= summary shed_tenant_quota {n_sum}"
                    )
        cluster, cluster_problems = cluster_crosscheck(events)
        if cluster is not None:
            failures.extend(cluster_problems)
            if not cluster_problems:
                print(
                    "\ncluster_summary agrees with the federation event "
                    f"stream (hosts={cluster['hosts']}, "
                    f"requests={cluster['requests']}, "
                    f"sessions={cluster['sessions']}, "
                    f"remigrated={cluster['remigrated']}, "
                    f"hosts_dead={cluster['hosts_dead']}, "
                    f"lost={cluster['lost']})"
                )
        summaries = [
            e
            for e in events
            if e.get("event") == "serve_summary" and "routing" not in e
        ] or [e for e in events if e.get("event") == "serve_summary"]
        # A federated run's serve_summary events are PER-HOST (each
        # covers one pool's slice of the storm); the merged series can
        # only be checked against the cluster ledger above.
        if summaries and cluster is None:
            # Prefer the pool-level summary when a router emitted both
            # tiers (per-replica summaries cover a subset each).
            pool = [e for e in events if e.get("event") == "serve_summary"
                    and ("per_replica" in e or "routing" in e)]
            summary = (pool or summaries)[-1]
            problems = summary_agrees(summary, rows[-1])
            if problems:
                failures.extend(
                    f"final snapshot vs serve_summary: {p}" for p in problems
                )
            else:
                print(
                    "\nfinal snapshot agrees with serve_summary "
                    f"(requests={summary['requests']}, "
                    f"completed={summary['completed']}, "
                    f"p99={summary['latency_p99_ms']})"
                )

    for msg in failures:
        print(f"FAIL: {msg}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(run())
