"""Chaos A/B: topology-honest federation vs a no-failover twin, plus
the federation-off single-host byte-pin.

The ISSUE 18 acceptance artifact, three probes over identical traffic:

* ``chaos`` — a 2-host loopback federation (serve/federation.py)
  running a storm of concurrent K-step rollout sessions; once a
  session is mid-trajectory the OWNER HOST IS KILLED (silent death —
  no goodbye frame, the lease just stops renewing). Bars: **0 lost
  sessions** (every orphan re-migrates to the survivor from its
  persisted SessionStore snapshot), ``remigrated >= 1``, and every
  served rollout matches the offline engine-only K-step loop
  (``offline_rollout``) to <= 1e-5 per step — at-least-once replay
  across hosts is EXACT.
* ``no_failover`` — the twin with ``failover=False``: the dead host's
  sessions resolve ``host_dead`` instead of re-placing. Bar:
  **measured losses >= 1** (the kill genuinely orphaned sessions; the
  chaos arm's zero is an achievement, not a vacuous storm).
* ``single_host_pin`` — the federation-off path (``--hosts 1`` never
  touches federation.py): the SAME serial one-shot storm through a
  plain single-replica ``ReplicaRouter``, twice. Bars: per-request
  outputs **byte-identical** across the runs (batcher level) and the
  deterministic ``serve_summary`` ledger fields equal (summary level)
  — growing the federation plane perturbed nothing underneath.

Writes JSONL to ``--out`` (committed as
``docs/artifacts/federation_ab.jsonl``; schema pinned by
``tests/test_artifacts.py::test_federation_ab_artifact_schema``).

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/federation_ab.py --out docs/artifacts/federation_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BAR_NUMERIC = 1e-5

#: serve_summary fields that are deterministic under a SERIAL storm
#: (no batching races, no wall-clock): the summary-level pin set.
PIN_FIELDS = (
    "requests", "admitted", "completed", "shed", "dispatches",
    "reloads", "breaker_trips", "compiled_shapes",
)


def _federated_storm(args, engine, traffic, *, failover: bool) -> dict:
    """One federated rollout storm with the owner of a mid-flight
    session killed. Returns results + the cluster ledger + victim."""
    import jax

    from gnot_tpu.serve import build_replica
    from gnot_tpu.serve.federation import build_local_federation
    from gnot_tpu.serve.rollout import SessionStore
    from gnot_tpu.utils.metrics import MetricsSink

    devs = jax.devices()
    groups = [
        [
            build_replica(
                engine.model, engine.params, 0, [devs[h % len(devs)]],
                batch_size=args.max_batch,
            )
        ]
        for h in range(args.hosts)
    ]
    tmp = tempfile.mkdtemp(prefix="federation_ab_")
    sink = MetricsSink(os.path.join(tmp, "events.jsonl"))
    cluster, agents = build_local_federation(
        groups,
        sink=sink,
        failover=failover,
        suspect_after_s=0.25,
        dead_after_s=0.6,
        session_store=SessionStore(os.path.join(tmp, "sessions")),
        router_kwargs=dict(
            max_batch=args.max_batch,
            max_wait_ms=2.0,
            session_snapshot_every=args.snapshot_every,
        ),
    )
    for a in agents.values():
        a.router.start()
    for g in groups:
        for r in g:
            r.warm(traffic, rows=args.max_batch)
    with sink:
        futs = [
            cluster.submit_rollout(s, args.steps, name=f"s{i:03d}")
            for i, s in enumerate(traffic)
        ]
        # Kill the owner of the first session caught mid-trajectory —
        # after real progress (snapshots exist), before the tail (the
        # kill cannot be a no-op).
        victim = None
        deadline = time.time() + 60
        while victim is None and time.time() < deadline:
            cluster.tick()
            for s in cluster._sessions.values():
                if 2 <= s.streamed < args.steps - 2:
                    victim = s.owner
                    break
            time.sleep(0.01)
        assert victim is not None, "no session reached the kill window"
        agents[victim].kill()
        stop = threading.Event()

        def _ticker():
            while not stop.is_set():
                cluster.tick()
                stop.wait(0.02)

        t = threading.Thread(target=_ticker, daemon=True)
        t.start()
        results = [f.result(timeout=180) for f in futs]
        stop.set()
        t.join(timeout=5)
        summary = cluster.drain()
    for a in agents.values():
        a.stop()
    return {"results": results, "summary": summary, "victim": victim}


def _single_host_storm(args, engine, traffic) -> dict:
    """The federation-off path: a serial one-shot storm through a
    plain single-replica ReplicaRouter (exactly what ``--hosts 1``
    runs). Returns per-request output bytes + the drain summary."""
    import jax

    from gnot_tpu.serve import ReplicaRouter, build_replicas

    replicas = build_replicas(
        engine.model, engine.params, 1,
        batch_size=args.max_batch, devices=jax.devices()[:1],
    )
    for r in replicas:
        r.warm(traffic, rows=args.max_batch)
    router = ReplicaRouter(
        replicas, max_batch=args.max_batch, max_wait_ms=2.0
    ).start()
    outs = []
    for s in traffic:
        res = router.submit(s).result(timeout=60)
        assert res.ok, f"single-host pin request failed: {res.reason}"
        outs.append(res.output.tobytes())
    summary = router.drain()
    return {"outputs": outs, "summary": summary}


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", type=str, required=True, help="JSONL output")
    p.add_argument("--hosts", type=int, default=2)
    p.add_argument("--sessions", type=int, default=8)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument(
        "--snapshot_every", type=int, default=2,
        help="session snapshot cadence > 1, so a re-migration exercises "
             "a REAL cross-host replay from the persisted cursor"
    )
    p.add_argument("--max_batch", type=int, default=2)
    p.add_argument(
        "--quick", action="store_true",
        help="smaller storm for the in-process test-suite smoke"
    )
    args = p.parse_args(argv)
    if args.quick:
        args.sessions, args.steps = 4, 8

    import serve_smoke

    from gnot_tpu.serve import offline_rollout
    from gnot_tpu.serve.rollout import parity_check

    engine = serve_smoke.build_engine(max_batch=args.max_batch)
    traffic = serve_smoke.mixed_traffic(
        args.sessions, seed=7, mesh_lo=100, mesh_hi=300
    )
    engine.warmup(traffic, rows=args.max_batch)
    records: list[dict] = []
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        if not ok:
            failures.append(msg)

    # The offline engine-only reference trajectories (no serve stack,
    # no federation) every served session must match.
    reference = [
        offline_rollout(engine, s, args.steps, rows=args.max_batch)
        for s in traffic
    ]

    arm_stats: dict[str, dict] = {}
    arm_out: dict[str, dict] = {}
    for arm, failover in (("chaos", True), ("no_failover", False)):
        out = _federated_storm(args, engine, traffic, failover=failover)
        results, summary = out["results"], out["summary"]
        lost = [r for r in results if not r.ok]
        check(
            len(results) == args.sessions,
            f"{arm}: {len(results)} futures resolved != {args.sessions}",
        )
        check(
            summary["lost"] == len(lost),
            f"{arm}: ledger lost={summary['lost']} != observed "
            f"{len(lost)}",
        )
        check(
            summary["hosts_dead"] == 1,
            f"{arm}: hosts_dead={summary['hosts_dead']} — the kill "
            "didn't land as ONE dead host",
        )
        arm_stats[arm] = {
            "arm": arm,
            "hosts": args.hosts,
            "failover": failover,
            "sessions": args.sessions,
            "steps": args.steps,
            "snapshot_every": args.snapshot_every,
            "killed_host": out["victim"],
            "completed": summary["completed"],
            "lost": len(lost),
            "lost_reasons": sorted({r.reason for r in lost}),
            "remigrated": summary["remigrated"],
            "hosts_dead": summary["hosts_dead"],
            "protocol_errors": summary["protocol_errors"],
            "steps_committed": sum(r.steps_completed for r in results),
        }
        records.append(arm_stats[arm])
        arm_out[arm] = out

    chaos, nofail = arm_stats["chaos"], arm_stats["no_failover"]
    check(
        chaos["lost"] == 0,
        f"chaos arm lost {chaos['lost']} sessions (must be 0)",
    )
    check(
        chaos["remigrated"] >= 1,
        "chaos arm never re-migrated a session — the kill was vacuous",
    )
    check(
        chaos["completed"] == args.sessions,
        f"chaos arm completed {chaos['completed']}/{args.sessions}",
    )
    check(
        chaos["protocol_errors"] == 0,
        f"chaos arm counted {chaos['protocol_errors']} protocol errors",
    )
    check(
        nofail["lost"] >= 1,
        "no-failover twin lost nothing — the host kill was vacuous",
    )
    check(
        nofail["lost_reasons"] == ["host_dead"],
        f"no-failover losses must read host_dead, got "
        f"{nofail['lost_reasons']}",
    )

    # Parity: every chaos-arm rollout (re-migrated sessions included)
    # matches the offline loop per step, at the original tolerance.
    worst = 0.0
    for r, ref in zip(arm_out["chaos"]["results"], reference):
        worst = max(worst, parity_check(r.outputs, ref, atol=BAR_NUMERIC))
    check(
        worst <= BAR_NUMERIC,
        f"federated rollouts drifted {worst} from the offline loop "
        f"(bar {BAR_NUMERIC})",
    )
    records.append(
        {
            "probe": "parity",
            "sessions_checked": sum(
                r.ok for r in arm_out["chaos"]["results"]
            ),
            "steps": args.steps,
            "max_abs_diff": worst,
            "bar": BAR_NUMERIC,
        }
    )

    # The federation-off byte-pin: two identical single-host runs.
    pin_a = _single_host_storm(args, engine, traffic)
    pin_b = _single_host_storm(args, engine, traffic)
    byte_identical = pin_a["outputs"] == pin_b["outputs"]
    check(
        byte_identical,
        "single-host outputs differ between identical runs — the "
        "federation-off batcher path is no longer deterministic",
    )
    pin_view_a = {k: pin_a["summary"].get(k) for k in PIN_FIELDS}
    pin_view_b = {k: pin_b["summary"].get(k) for k in PIN_FIELDS}
    check(
        pin_view_a == pin_view_b,
        f"single-host serve_summary ledgers diverged: {pin_view_a} "
        f"vs {pin_view_b}",
    )
    records.append(
        {
            "probe": "single_host_pin",
            "requests": len(traffic),
            "byte_identical": byte_identical,
            "summary_match": pin_view_a == pin_view_b,
            "summary_fields": list(PIN_FIELDS),
            "ledger": pin_view_a,
        }
    )

    summary_rec = {
        "summary": "federation_ab",
        "quick": args.quick,
        "hosts": args.hosts,
        "sessions": args.sessions,
        "steps": args.steps,
        "snapshot_every": args.snapshot_every,
        "lost_chaos": chaos["lost"],
        "lost_no_failover": nofail["lost"],
        "remigrated": chaos["remigrated"],
        "max_abs_diff": worst,
        "single_host_byte_identical": byte_identical,
        "bar_numeric": BAR_NUMERIC,
        "bar_lost_chaos": 0,
    }
    records.append(summary_rec)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(
        f"federation_ab: chaos lost={chaos['lost']} "
        f"(remigrated={chaos['remigrated']}) vs no_failover "
        f"lost={nofail['lost']}; parity max |diff| = {worst:.2e} "
        f"(bar {BAR_NUMERIC}); single-host pin "
        f"byte_identical={byte_identical}; wrote {args.out}"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary_rec = dict(summary_rec)
    summary_rec["failures"] = failures
    return summary_rec


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
