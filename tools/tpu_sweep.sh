#!/usr/bin/env bash
# Hardware validation sweep: drives the CLI across the feature matrix
# on the attached accelerator (one run per feature; ~1-5 min each, the
# persistent compile cache makes re-runs much faster). Exits nonzero if
# any configuration fails. Logs land in ${SWEEP_LOG_DIR:-/tmp}.
#
# The pytest suite pins itself to 8 virtual CPU devices, so this script
# is the hardware-side complement (same role as
# tools/validate_tpu_kernels.py for the pallas kernels).
set -u
# Private log dir by default: predictable world-shared /tmp names would
# collide (or be squattable) for the second user on a shared host.
LOGDIR="${SWEEP_LOG_DIR:-$(mktemp -d -t gnot_sweep.XXXXXX)}"
echo "sweep logs: $LOGDIR"
ARCH="--n_attn_layers 2 --n_attn_hidden_dim 64 --n_mlp_num_layers 2
      --n_mlp_hidden_dim 64 --n_input_hidden_dim 64 --n_head 4
      --epochs 2 --n_train 8 --n_test 4"
CKPT="$LOGDIR/sweep_ckpt.$$"
fail=0
run() {
  name="$1"; shift
  if timeout 600 python -m gnot_tpu.main $ARCH "$@" > "$LOGDIR/sweep_$name.log" 2>&1; then
    best=$(grep -E "Best Test Metric|Eval \(best" "$LOGDIR/sweep_$name.log" | tail -1)
    echo "OK   $name  ($best)"
  else
    echo "FAIL $name (see $LOGDIR/sweep_$name.log)"; fail=1
  fi
}
run darcy_f32      --synthetic darcy2d
run ns2d_bf16      --synthetic ns2d --dtype bfloat16
run elas_remat     --synthetic elasticity --remat
run induct_scan    --synthetic inductor2d --scan_layers
run heat_k4        --synthetic heatsink3d --steps_per_dispatch 4 --batch_size 4
run darcy_parity   --synthetic darcy2d --attention_mode parity --no_bucket
run ns2d_ffnpallas --synthetic ns2d --ffn_impl pallas
run ns2d_flat      --synthetic ns2d --flat_params --dtype bfloat16
run elas_packed    --synthetic elasticity --packed --dtype bfloat16 --batch_size 8
run darcy_ckpt     --synthetic darcy2d --checkpoint_dir "$CKPT" --checkpoint_every 1 \
                   --predict_out "$LOGDIR/sweep_preds.pkl" --export_torch "$LOGDIR/sweep_model.pth"
run darcy_resume   --synthetic darcy2d --checkpoint_dir "$CKPT" --eval_only
rm -rf "$CKPT"
exit $fail
