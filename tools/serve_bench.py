"""Open-loop serving load bench: 1 vs N engine replicas.

Closed-loop benches (submit, wait, repeat) hide queueing collapse — the
bench slows down with the server and never observes overload. This one
is OPEN-LOOP: request arrivals are a Poisson process at a configured
offered load (exponential inter-arrival gaps, pre-drawn from a seeded
rng), submitted on schedule whether or not the pool is keeping up, so
sustained requests/s and p50/p99 vs offered load mean what they say.

Method (interleaved arms, one offered-load ladder shared by both):

1. Measure one warmed dispatch to estimate the single-replica capacity
   ``max_batch / dispatch_s``; the ladder is fractions/multiples of it.
2. For each offered load, run the 1-replica arm then the N-replica arm
   (same traffic, same seed, same duration). Each arm is a
   ``ReplicaRouter`` over ``build_replicas`` engines pinned one device
   each — the arms differ ONLY in replica count; N=1 pays the same
   router overhead.
3. A run SUSTAINS its load when shed_frac <= ``--max_shed_frac`` and
   p99 <= ``--slo_p99_ms``; per arm, sustained rps is the best achieved
   rps over sustaining runs — "equal p99" means both arms are held to
   the same p99 SLO.
4. Numerics: every distinct traffic sample is replayed through the
   N-replica pool at idle and through a solo engine; the summary
   records the max per-request |replicated - solo|.

Writes JSONL (one record per run + one summary) for the committed
artifact ``docs/artifacts/serve_bench.jsonl``
(tests/test_artifacts.py::test_serve_bench_artifact_schema pins the
acceptance bar: N-replica sustained >= 2.5x single at equal p99,
numerics <= 1e-5). With ``--trace_path`` the heaviest N-replica run is
traced and the per-replica queue-vs-device breakdown
(tools/trace_report.py) is printed — the bottleneck, named per replica.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_bench.py \
        --out docs/artifacts/serve_bench.jsonl --replicas 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import loadgen
import serve_smoke


def _ensure_xla_flags(n_replicas: int) -> None:
    """Pin the CPU backend to ONE intra-op thread per dispatch (and
    enough virtual devices), BEFORE jax initializes.

    Rationale: with multi-threaded eigen, a single dispatch steals
    every host core — the 1-replica arm's capacity is then an artifact
    of intra-op parallelism and the N-replica arm measures threadpool
    thrash, not the replica tier (and runs show multi-second p99
    outliers from scheduling collapse). One intra-op thread per device
    is the honest CPU proxy for per-replica hardware (a TPU replica
    owns its chip), applied IDENTICALLY to both arms. No-op when jax is
    already initialized (the flags would silently not apply) — the
    standalone CLI is the measurement vehicle."""
    import importlib.util
    import sys as _sys

    if "jax" in _sys.modules:
        print(
            "serve_bench: note — jax already imported; XLA flags "
            "unchanged (in-process smoke, not a measurement run)"
        )
        return
    assert importlib.util.find_spec("jax") is not None
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={max(8, n_replicas)}"
    if "xla_cpu_multi_thread_eigen" not in flags:
        flags += (
            " --xla_cpu_multi_thread_eigen=false"
            " intra_op_parallelism_threads=1"
        )
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def make_replicas(model, params, n_replicas, *, max_batch, traffic,
                  dtype="float32"):
    """N warmed replicas, one device each (the arms differ ONLY in
    replica count). Warm compiles are the expensive part — callers
    build replicas once per arm and put a FRESH router over them per
    run (jitted executables persist on the engines). ``dtype`` is the
    serving compute dtype (the low-precision A/B arms differ only in
    it — tools/lowprec_ab.py). Returns (replicas, warm_stats)."""
    import jax

    from gnot_tpu.serve import build_replicas
    from gnot_tpu.utils.cache import compile_cache_probe

    devices = jax.devices()
    if n_replicas > len(devices):
        raise ValueError(
            f"{n_replicas} replicas > {len(devices)} devices; raise "
            "--xla_force_host_platform_device_count"
        )
    replicas = build_replicas(
        model, params, n_replicas,
        batch_size=max_batch, devices=devices[:n_replicas],
        dtype=dtype,
    )
    with compile_cache_probe() as warm_stats:
        warmed = sum(r.warm(traffic, rows=max_batch) for r in replicas)
    return replicas, {"programs_warmed": warmed, **warm_stats}


def fresh_router(replicas, *, max_batch, queue_limit=256, max_wait_ms=4.0,
                 sink=None, tracer=None):
    """A new router over already-warm replicas (routers drain once;
    engines and their compiled programs are reusable)."""
    from gnot_tpu.serve import ReplicaRouter

    return ReplicaRouter(
        replicas,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        queue_limit=queue_limit,
        sink=sink,
        tracer=tracer,
    )


def run_arm(router, traffic, *, offered_rps, duration_s, seed) -> dict:
    """One open-loop run: Poisson arrivals at ``offered_rps`` for
    ``duration_s`` (tools/loadgen.py ``steady`` trace — the shared
    seeded generator, so arms replay identical schedules), submitted on
    schedule (never throttled by responses), then wait for every Future
    and drain."""
    times = loadgen.trace_times(
        "steady", base_rps=offered_rps, duration_s=duration_s, seed=seed
    )
    router.start()
    t0 = time.perf_counter()
    futures = loadgen.replay(
        lambda i: router.submit(traffic[i % len(traffic)]), times
    )
    results = [f.result(timeout=300) for f in futures]
    last_done = time.perf_counter()
    summary = router.drain()
    elapsed = last_done - t0
    completed = sum(r.ok for r in results)
    shed = summary["shed"]
    return {
        "offered_rps": offered_rps,
        "duration_s": round(duration_s, 3),
        "submitted": len(futures),
        "completed": completed,
        "shed": shed,
        "shed_frac": (
            round(sum(shed.values()) / len(futures), 4) if futures else 0.0
        ),
        "achieved_rps": round(completed / elapsed, 2) if elapsed > 0 else None,
        "p50_ms": (
            round(summary["latency_p50_ms"], 2)
            if summary["latency_p50_ms"] is not None
            else None
        ),
        "p99_ms": (
            round(summary["latency_p99_ms"], 2)
            if summary["latency_p99_ms"] is not None
            else None
        ),
        "dispatches": summary["dispatches"],
        "compiled_shapes": summary["compiled_shapes"],
        "spills": summary["routing"]["spills"],
    }


def numerics_check(model, params, replicas, traffic, *, max_batch) -> float:
    """Max per-request |replicated - solo| over the distinct traffic
    set: every request replayed through an idle N-replica pool AND a
    plain solo engine (default placement). The replicated-vs-solo
    acceptance number."""
    from gnot_tpu.serve import InferenceEngine

    router = fresh_router(replicas, max_batch=max_batch)
    router.start()
    futs = [router.submit(s) for s in traffic]
    results = [f.result(timeout=120) for f in futs]
    router.drain()
    solo = InferenceEngine(model, params, batch_size=max_batch)
    solo.warmup(traffic, rows=max_batch)
    worst = 0.0
    for s, r in zip(traffic, results):
        assert r.ok, f"numerics replay shed a request: {r.reason}"
        key = solo.bucket_key(s)
        ref = solo.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=max_batch
        )[0]
        worst = max(worst, float(np.max(np.abs(ref - r.output))))
    return worst


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=4,
                   help="N for the N-replica arm (vs the 1-replica arm)")
    p.add_argument("--n_traffic", type=int, default=16,
                   help="distinct request samples cycled by the arrival "
                        "process (mixed Darcy64 + ragged buckets)")
    # Mesh sizes + model width sized so a dispatch is COMPUTE-heavy
    # (tens of ms inside XLA with the GIL released): that is the regime
    # where replica workers genuinely run concurrently on CPU — a
    # 2-3 ms dispatch is mostly GIL-held host work and replicas can't
    # scale it (measured; on TPU slices the compute fraction is higher
    # still, so CPU is the conservative proxy).
    p.add_argument("--mesh_lo", type=int, default=600)
    p.add_argument("--mesh_hi", type=int, default=1000)
    p.add_argument("--hidden", type=int, default=96)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--queue_limit", type=int, default=256)
    p.add_argument("--duration_s", type=float, default=6.0,
                   help="open-loop window per run")
    p.add_argument("--loads", type=str, default="0.5,0.8,1.2,2.0,2.6,3.2",
                   help="offered-load ladder as multiples of the "
                        "measured single-replica dispatch capacity")
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="p99 SLO a run must meet to count as sustained "
                        "(0 = auto: 12x the measured solo dispatch time)")
    p.add_argument("--max_shed_frac", type=float, default=0.02)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default="",
                   help="JSONL output path (the committed artifact)")
    p.add_argument("--trace_path", type=str, default="",
                   help="trace the heaviest N-replica run and print the "
                        "per-replica breakdown (trace_report.py)")
    p.add_argument("--quick", action="store_true",
                   help="tiny ladder + short windows (CI smoke, not the "
                        "committed artifact)")
    args = p.parse_args(argv)
    if args.quick:
        args.duration_s = min(args.duration_s, 2.0)
        args.loads = "0.6,2.4"

    _ensure_xla_flags(args.replicas)

    from gnot_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()

    model, params = _build_model(args)
    traffic = serve_smoke.mixed_traffic(
        args.n_traffic, seed=args.seed, mesh_lo=args.mesh_lo,
        mesh_hi=args.mesh_hi,
    )

    # Capacity probe: one warmed solo engine, median dispatch time.
    from gnot_tpu.serve import InferenceEngine

    probe = InferenceEngine(model, params, batch_size=args.max_batch)
    probe.warmup(traffic, rows=args.max_batch)
    keys = [probe.bucket_key(s) for s in traffic]
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for s, k in zip(traffic[:8], keys[:8]):
            probe.infer([s], pad_nodes=k[0], pad_funcs=k[1],
                        rows=args.max_batch)
        times.append((time.perf_counter() - t0) / 8)
    dispatch_s = float(np.median(times))
    cap1 = args.max_batch / dispatch_s
    slo = args.slo_p99_ms or round(12 * dispatch_s * 1e3, 1)
    print(
        f"serve_bench: dispatch {dispatch_s * 1e3:.1f} ms -> est. "
        f"1-replica capacity {cap1:.0f} req/s, p99 SLO {slo} ms"
    )

    loads = [float(x) for x in args.loads.split(",")]
    records: list[dict] = []
    # Build + warm each arm's replicas ONCE (compiles are the dominant
    # cost); each run gets a fresh router over the same warm engines.
    pools = {}
    for n in (1, args.replicas):
        pools[n] = make_replicas(
            model, params, n, max_batch=args.max_batch, traffic=traffic
        )
        warm = pools[n][1]
        print(
            f"  warmed n={n}: {warm['programs_warmed']} programs, "
            f"cache hits={warm.get('hits')} misses={warm.get('misses')}"
        )
    for li, mult in enumerate(loads):
        offered = mult * cap1
        for n in (1, args.replicas):  # interleaved arms per load
            replicas_n, warm = pools[n]
            router = fresh_router(
                replicas_n, max_batch=args.max_batch,
                queue_limit=args.queue_limit,
            )
            rec = run_arm(
                router, traffic, offered_rps=offered,
                duration_s=args.duration_s, seed=args.seed + li,
            )
            rec = {
                "arm": f"replicas_{n}", "replicas": n,
                "load_mult": mult, **rec,
                "warm_cache_hits": warm.get("hits"),
                "warm_cache_misses": warm.get("misses"),
            }
            records.append(rec)
            print(
                f"  n={n} offered={offered:7.1f}/s -> "
                f"achieved={rec['achieved_rps']}/s "
                f"p50={rec['p50_ms']}ms p99={rec['p99_ms']}ms "
                f"shed={rec['shed_frac']:.1%}"
            )

    def sustained(n):
        ok = [
            r for r in records
            if r["replicas"] == n
            and r["shed_frac"] <= args.max_shed_frac
            and r["p99_ms"] is not None
            and r["p99_ms"] <= slo
        ]
        best = max(ok, key=lambda r: r["achieved_rps"], default=None)
        return best

    best1, bestn = sustained(1), sustained(args.replicas)
    worst = numerics_check(
        model, params, pools[args.replicas][0], traffic,
        max_batch=args.max_batch,
    )
    summary = {
        "summary": "serve_bench",
        "replicas_n": args.replicas,
        "slo_p99_ms": slo,
        "max_shed_frac": args.max_shed_frac,
        "dispatch_ms": round(dispatch_s * 1e3, 3),
        "sustained_rps_1": best1["achieved_rps"] if best1 else None,
        "p99_at_sustained_1": best1["p99_ms"] if best1 else None,
        "sustained_rps_n": bestn["achieved_rps"] if bestn else None,
        "p99_at_sustained_n": bestn["p99_ms"] if bestn else None,
        "speedup": (
            round(bestn["achieved_rps"] / best1["achieved_rps"], 3)
            if best1 and bestn and best1["achieved_rps"]
            else None
        ),
        "max_abs_diff": worst,
        "bar_speedup": 2.5,
        "bar_numeric": 1e-5,
        "quick": bool(args.quick),
    }
    records.append(summary)
    print(
        f"serve_bench: sustained {summary['sustained_rps_1']} req/s (n=1) "
        f"vs {summary['sustained_rps_n']} req/s (n={args.replicas}) at "
        f"p99<={slo}ms -> speedup {summary['speedup']}x; "
        f"max |replicated-solo| {worst:.2e}"
    )

    if args.trace_path and bestn is not None:
        _traced_run(args, pools[args.replicas][0], traffic, bestn, cap1)

    if args.out:
        if d := os.path.dirname(args.out):
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        print(f"serve_bench: wrote {len(records)} records to {args.out}")
    return summary


def _traced_run(args, replicas, traffic, bestn, cap1) -> None:
    """Re-run the best sustained N-replica load with the span tracer on
    and print the per-replica breakdown — the 'name the bottleneck per
    replica' view."""
    import trace_report

    from gnot_tpu.obs.tracing import Tracer

    tracer = Tracer(path=args.trace_path)
    router = fresh_router(
        replicas, max_batch=args.max_batch,
        queue_limit=args.queue_limit, tracer=tracer,
    )
    run_arm(
        router, traffic, offered_rps=bestn["load_mult"] * cap1,
        duration_s=min(args.duration_s, 3.0), seed=args.seed,
    )
    tracer.flush()
    rep = trace_report.report(args.trace_path)
    trace_report.print_report(rep)


def _build_model(args):
    """A mid-size GNOT on the Darcy operator schema — big enough that a
    dispatch is compute-bound (see the --mesh_lo help), untrained
    (serving throughput is about plumbing, not accuracy)."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(args.max_batch, seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=args.layers,
        n_attn_hidden_dim=args.hidden,
        n_mlp_num_layers=2,
        n_mlp_hidden_dim=args.hidden,
        n_input_hidden_dim=args.hidden,
        n_expert=2,
        n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    return model, init_params(model, collate(samples), args.seed)


def main(argv=None) -> int:
    s = run(argv)
    ok = (
        s["speedup"] is not None
        and s["speedup"] >= s["bar_speedup"]
        and s["max_abs_diff"] <= s["bar_numeric"]
    )
    if not ok:
        print(f"FAIL: acceptance bar not met: {s}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
