"""Distributed-tracing overhead A/B: federated request latency with the
cluster tracing plane OFF vs ON.

The acceptance bar for the distributed-tracing subsystem
(docs/observability.md "Distributed tracing") is <=2% per-request
regression at the default sample rate (1.0 — every request traced)
WITH the flight recorder armed on every host. The ON arm runs the real
plane end to end: a cluster ``Tracer`` deciding head sampling, the
``trace_ctx`` wire field on every placement, per-host tracers adopting
the decision, per-host ``FlightRecorder`` rings shadow-recording every
span, and the drain-time ``trace_pull`` stitch + merged-file write
(the stitch is OUTSIDE the timed windows — it is a drain cost, not a
steady-state one, same rationale as capacity_ab keeping cost capture
outside). Timed windows are best-of-N and interleaved off/on like
tools/telemetry_ab.py, so ambient machine-load drift hits both arms
alike.

Usage::

    JAX_PLATFORMS=cpu python tools/dtrace_ab.py \
        --n 32 --repeats 3 --out docs/artifacts/dtrace_overhead_ab.jsonl

Emits one JSONL record per arm plus a summary record with
``overhead_frac``; committed as docs/artifacts/dtrace_overhead_ab.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH = 2


def build_federation(tmp: str, traced: bool):
    """One 2-host in-proc federation over tiny darcy replicas; the
    ``traced`` arm gets the full plane (cluster tracer at rate 1.0,
    per-host adopters, flight recorders on every ring)."""
    import jax

    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.serve import build_replica
    from gnot_tpu.serve.federation import build_local_federation
    from gnot_tpu.train.trainer import init_params
    from gnot_tpu.utils.metrics import MetricsSink

    # Same micro-bench philosophy as tools/tracing_ab.py: reference
    # shape at half width/depth — CPU-fast, realistic RELATIVE cost.
    # The plane's absolute cost is a fixed ~0.1-0.2 ms of host work per
    # request; a toy 64-point model would make that look like 10%+ of
    # a request that no real deployment resembles.
    samples = datasets.synth_darcy2d(8, seed=0, grid_n=16)
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=128, n_mlp_num_layers=2,
        n_mlp_hidden_dim=128, n_input_hidden_dim=128, n_expert=3, n_head=4,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples[:4]), 0)
    devs = jax.devices()
    groups = [
        [build_replica(model, params, 0, [devs[h % len(devs)]],
                       batch_size=BATCH)]
        for h in range(2)
    ]
    sink = MetricsSink(os.path.join(tmp, "ab.jsonl"))
    kw = {}
    if traced:
        from gnot_tpu.obs import dtrace
        from gnot_tpu.obs.tracing import Tracer

        recorders = {
            h: dtrace.FlightRecorder(tmp, window_s=30.0, host=h)
            for h in ("controller", "host0", "host1")
        }
        kw = dict(
            cluster_tracer=Tracer(
                sample_rate=1.0, recorder=recorders["controller"]
            ),
            tracer_factory=lambda h: Tracer(recorder=recorders[h]),
            trace_path=os.path.join(tmp, "ab_trace.json"),
            recorders=recorders,
        )
    cluster, agents = build_local_federation(
        groups, sink=sink,
        router_kwargs=dict(max_batch=BATCH, max_wait_ms=2.0),
        **kw,
    )
    for a in agents.values():
        a.router.start()
    for g in groups:
        for r in g:
            r.warm(samples[:BATCH], rows=BATCH)
    return cluster, agents, sink, samples


def _window(cluster, samples, n: int) -> float:
    """One timed storm of ``n`` one-shots, submit to last resolution;
    seconds per request. A warm-up request runs outside the window."""
    cluster.submit(samples[0]).result(timeout=60)
    t0 = time.perf_counter()
    futs = [cluster.submit(samples[i % len(samples)]) for i in range(n)]
    for f in futs:
        r = f.result(timeout=60)
        assert r.ok, r.reason
    return (time.perf_counter() - t0) / n


def time_ab(n: int, repeats: int) -> tuple[float, float]:
    """Best-of-``repeats`` seconds/request for (off, on), windows
    interleaved off/on so ambient load drift cancels. Both federations
    are built (and warmed) before any window is timed."""
    tmp_off = tempfile.mkdtemp()
    tmp_on = tempfile.mkdtemp()
    fed_off = build_federation(tmp_off, traced=False)
    fed_on = build_federation(tmp_on, traced=True)
    best_off = best_on = float("inf")
    try:
        for _ in range(max(1, repeats)):
            best_off = min(best_off, _window(fed_off[0], fed_off[3], n))
            best_on = min(best_on, _window(fed_on[0], fed_on[3], n))
    finally:
        for cluster, agents, sink, _ in (fed_off, fed_on):
            with sink:
                cluster.drain()
            for a in agents.values():
                a.stop()
    return best_off, best_on


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=32)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    import jax

    platform = jax.devices()[0].platform
    sec_off, sec_on = time_ab(args.n, args.repeats)
    records = []
    for arm, sec in (("dtrace_off", sec_off), ("dtrace_on", sec_on)):
        records.append({
            "arm": arm, "ms_per_request": round(sec * 1e3, 4),
            "platform": platform, "hosts": 2, "n": args.n,
            "sample_rate": 1.0, "flight_recorder_s": 30.0,
            "repeats": args.repeats,
        })
    off, on = records[0]["ms_per_request"], records[1]["ms_per_request"]
    records.append({
        "summary": "dtrace_overhead", "config": "darcy2d_micro_2host",
        "ms_per_request_off": off, "ms_per_request_on": on,
        "overhead_frac": round(on / off - 1.0, 4),
        "bar": "overhead_frac < 0.02 with propagation + flight recorder "
               "on at sample_rate=1.0",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
